"""Request-journey forensics: the tail-sampled trace vault.

Every surface before this one is aggregate — histograms, fleet scrapes,
history rings, burn rates. When the burn alert fires, an operator can see
THAT p99 TTFT collapsed but not WHY request X was slow: the bounded span
ring (`core/trace.py`) evicts a slow request's early spans before it
finishes, and nothing joins spans, SLO verdicts, KV-stream chunk timings,
and retry/breaker/deadline/fault events into one per-request story. The
`JourneyVault` is that join, with TAIL-BASED retention:

  * **Journeys assemble from three feeds.** A trace finish listener
    (`Tracer.add_finish_listener`) buffers every finished span by trace id;
    a flight-recorder observer (`FlightRecorder.add_observer`) attaches
    resilience events (retries, breaker transitions, deadline trips, fault
    injections, torn KV streams) by request id or trace ctx; an SLO sink
    (`SLORecorder.journey_sinks`) completes the journey with the timeline's
    phase values, targets, and attainment verdict. `install()` wires all
    three onto the process defaults — the worker telemetry server and the
    API server both call it.
  * **Retention is decided at completion, tail-first.** SLO-breaching,
    errored, deadline-expired, retried, and fault-touched requests are kept
    100%; the slowest-K healthy requests per retention window ride along;
    a small reservoir fraction of the remaining healthy ones
    (`LWS_TPU_JOURNEY_SAMPLE`) keeps the baseline comparable. Everything is
    bounded (`LWS_TPU_JOURNEY_BUDGET` total span/event/annotation records)
    and every loss is counted in the same record units: `serving_journeys_retained_total{outcome}` /
    `serving_journeys_dropped_total{reason}`. Healthy pressure evicts
    sampled journeys first, then slowest ones — a retained breached
    journey is never evicted by a flood of healthy traffic.
  * **Exemplars resolve vault-first.** An SLO histogram exemplar carries a
    trace id; a breaching observation belongs to a request that fails
    attainment, so its journey is retained and `get(trace_id)` finds it
    long after the span ring wrapped — the ring is only the fallback for
    unsampled healthy traffic.

Cross-process assembly happens one level up: each process's vault holds its
LOCAL leg (keyed by the request id that rides the KV frame meta), both
servers serve `GET /debug/request/{id}`, and the API server fleet-joins the
legs via `FleetCollector.collect_journeys` into one connected tree —
rendered by `lws-tpu explain`. The module-level VAULT is the process
default, like metrics.REGISTRY and trace.TRACER.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from lws_tpu.core import metrics
from lws_tpu.utils.common import env_float as _env_float

JOURNEYS_ENV = "LWS_TPU_JOURNEYS"          # "0" disables install()
SAMPLE_ENV = "LWS_TPU_JOURNEY_SAMPLE"      # healthy reservoir fraction
BUDGET_ENV = "LWS_TPU_JOURNEY_BUDGET"      # total retained span+event records
SOURCE_BUDGET_ENV = "LWS_TPU_JOURNEY_SOURCE_BUDGET"  # per (klass, revision)
RETENTION_ENV = "LWS_TPU_JOURNEY_RETENTION_S"

DEFAULT_SAMPLE_RATE = 0.02
DEFAULT_BUDGET_RECORDS = 8192
DEFAULT_SOURCE_BUDGET_RECORDS = 2048
DEFAULT_SLOWEST_K = 16
DEFAULT_RETENTION_S = 900.0
DEFAULT_MAX_OPEN_TRACES = 512
DEFAULT_MAX_SPANS = 256

# /debug/requests?outcome= vocabulary (the index surface's 400 contract).
OUTCOMES = ("all", "breached", "errored", "deadline_expired", "retried",
            "fault", "slowest", "sampled")

# Flight-recorder event kinds that join a journey and the retention flag
# each one raises. `fault_injected` marks chaos-touched requests; the
# torn-stream/requeue/replay kinds are the at-least-once retry story.
_EVENT_FLAGS = {
    "retry": "retried",
    "kv_stream_torn": "retried",
    "kv_requeue": "retried",
    "replay_deduped": "retried",
    "circuit_breaker": "retried",
    "deadline_exceeded": "deadline_expired",
    "fault_injected": "fault",
}

# Must-keep flag priority for the journey's outcome label.
_FLAG_PRIORITY = ("errored", "deadline_expired", "breached", "retried",
                  "fault")


class _Journey:
    __slots__ = (
        "id", "trace_id", "root_span_id", "engine", "klass", "revision",
        "spans", "events", "annotations", "timeline", "flags", "outcome",
        "completed", "completed_unix", "completed_mono", "latency_s",
        "spans_dropped",
    )

    def __init__(self, rid: str) -> None:
        self.id = rid
        self.trace_id: Optional[str] = None
        # The span id the completion ctx named (the request's root span,
        # which closes AFTER the timeline finishes): once it attaches, the
        # journey's trace claim is released — several requests may share
        # one trace (a client grafting requests onto a reconcile root),
        # and a finished journey must not steal the next request's spans.
        self.root_span_id: Optional[str] = None
        self.engine = ""
        self.klass = ""
        self.revision = ""
        self.spans: list[dict] = []
        self.events: list[dict] = []
        self.annotations: dict = {}
        self.timeline: dict = {}
        self.flags: set = set()
        self.outcome = "open"
        self.completed = False
        self.completed_unix = 0.0
        self.completed_mono = 0.0
        self.latency_s = 0.0
        self.spans_dropped = 0

    def records(self) -> int:
        # Annotations (KV chunk timelines) count too: a retained streamed
        # journey's per-chunk dicts are real memory the budget must see.
        ann = sum(len(v) if isinstance(v, list) else 1
                  for v in self.annotations.values())
        return len(self.spans) + len(self.events) + ann

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "trace_id": self.trace_id,
            "engine": self.engine,
            "klass": self.klass,
            "revision": self.revision,
            "outcome": self.outcome,
            "flags": sorted(self.flags),
            "completed": self.completed,
            "completed_unix": round(self.completed_unix, 6),
            "latency_s": round(self.latency_s, 6),
            "timeline": dict(self.timeline),
            "spans": list(self.spans),
            "events": list(self.events),
            "annotations": dict(self.annotations),
            "spans_dropped": self.spans_dropped,
        }

    def digest(self) -> dict:
        """The compact index row (`/debug/requests`, watchdog dumps)."""
        return {
            "id": self.id,
            "trace_id": self.trace_id,
            "engine": self.engine,
            "klass": self.klass,
            "revision": self.revision,
            "outcome": self.outcome,
            "flags": sorted(self.flags),
            "latency_s": round(self.latency_s, 6),
            "ttft_s": self.timeline.get("ttft_s"),
            "total_s": self.timeline.get("total_s"),
            "completed_unix": round(self.completed_unix, 6),
            "spans": len(self.spans),
            "events": len(self.events),
        }


def enabled() -> bool:
    """The plane's kill switch (`LWS_TPU_JOURNEYS=0`). Gates install() AND
    the direct vault entry points the disagg workers call (`complete`,
    `annotate`), so disabling really disables — no half-on vault filling
    behind unregistered listeners."""
    return os.environ.get(JOURNEYS_ENV, "1").lower() not in ("0", "false",
                                                             "off")


def verdict(journey: dict) -> dict:
    """One-line verdict for a journey record: which phase blew the budget?
    Pure function of the journey's timeline + flags, shared by the explain
    renderer and tests. Returns {"ok", "phase", "value", "target", "text"}
    — `phase` is None when every recorded phase met its target."""
    flags = set(journey.get("flags") or [])
    tl = journey.get("timeline") or {}
    targets = tl.get("targets") or {}
    if "errored" in flags:
        err = tl.get("error") or next(
            (e.get("error") for e in journey.get("events") or []
             if e.get("error")), "request failed",
        )
        return {"ok": False, "phase": "error", "value": None, "target": None,
                "text": f"FAILED — {err}"}
    if "deadline_expired" in flags:
        return {"ok": False, "phase": "deadline", "value": None,
                "target": None,
                "text": "DEADLINE EXPIRED — the request's budget ran out "
                        "before the work finished"}
    checks = (
        ("queue_wait", tl.get("queue_wait_s"), targets.get("queue_wait_s")),
        ("ttft", tl.get("ttft_s"), targets.get("ttft_s")),
        ("itl", tl.get("worst_itl_s"), targets.get("itl_s")),
    )
    worst = None
    for phase, value, target in checks:
        if value is None or target is None or value <= target:
            continue
        overrun = value / target if target > 0 else float("inf")
        if worst is None or overrun > worst[3]:
            worst = (phase, value, target, overrun)
    if worst is not None:
        phase, value, target, _ = worst
        if phase == "ttft":
            # Compile blame: when the compile ledger annotated this journey
            # (lws_tpu/obs/device.py) and the compile seconds cover at
            # least half the TTFT overrun, recompilation IS the phase —
            # name it, so the fix is bucket tuning, not prefill capacity.
            compiles = (journey.get("annotations") or {}).get("compiles") or []
            compile_s = sum(c.get("seconds") or 0.0 for c in compiles)
            if compiles and compile_s >= 0.5 * (value - target):
                kinds = sorted({c.get("kind") or "?" for c in compiles})
                return {
                    "ok": False, "phase": "compile", "value": value,
                    "target": target,
                    "text": f"BREACHED — ttft {value:.4f}s blew the "
                            f"{target:.4f}s budget; {compile_s:.4f}s of it "
                            f"was XLA compilation ({len(compiles)} "
                            f"{'/'.join(kinds)} compile(s) — tune shape "
                            "buckets, don't add prefill capacity)",
                }
        return {
            "ok": False, "phase": phase, "value": value, "target": target,
            "text": f"BREACHED — {phase} {value:.4f}s blew the "
                    f"{target:.4f}s budget",
        }
    if "breached" in flags:
        return {"ok": False, "phase": "unknown", "value": None,
                "target": None,
                "text": "BREACHED — attainment verdict was false but no "
                        "recorded phase exceeds its target"}
    extra = " (retried)" if "retried" in flags else ""
    return {"ok": True, "phase": None, "value": None, "target": None,
            "text": f"ok — every recorded phase within target{extra}"}


def _closes_tree(j: _Journey, record: dict) -> bool:
    """True when `record` is an ancestor the journey's spans already point
    at but the journey doesn't hold — the late-closing parent chain of a
    completed request whose ctx named no root span. Anything else arriving
    on a completed journey's trace belongs to a different request."""
    sid = record.get("span_id")
    if sid is None:
        return False
    held = {s.get("span_id") for s in j.spans}
    if sid in held:
        return False
    return any(s.get("parent_id") == sid for s in j.spans)


class JourneyVault:
    def __init__(
        self,
        budget_records: Optional[int] = None,
        source_budget_records: Optional[int] = None,
        slowest_k: int = DEFAULT_SLOWEST_K,
        sample_rate: Optional[float] = None,
        retention_s: Optional[float] = None,
        max_open_traces: int = DEFAULT_MAX_OPEN_TRACES,
        max_spans_per_journey: int = DEFAULT_MAX_SPANS,
        registry=None,
        rng: Optional[Callable[[], float]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        """`budget_records` bounds the TOTAL retained span/event/annotation
        records (env LWS_TPU_JOURNEY_BUDGET); `source_budget_records` bounds
        each (klass, revision) source's share of it (env
        LWS_TPU_JOURNEY_SOURCE_BUDGET, 0 disables) so one hot class at
        fleet scale cannot evict every other source's tail evidence through
        the global budget; `slowest_k` the healthy slow set;
        `sample_rate` the healthy reservoir fraction (env
        LWS_TPU_JOURNEY_SAMPLE); `retention_s` ages completed journeys out
        (env LWS_TPU_JOURNEY_RETENTION_S). `rng`/`clock` are injectable so
        retention tests are deterministic."""
        self.budget_records = int(
            budget_records if budget_records is not None
            else _env_float(BUDGET_ENV, DEFAULT_BUDGET_RECORDS)
        )
        self.source_budget_records = int(
            source_budget_records if source_budget_records is not None
            else _env_float(SOURCE_BUDGET_ENV, DEFAULT_SOURCE_BUDGET_RECORDS)
        )
        self.slowest_k = max(0, int(slowest_k))
        self.sample_rate = (
            sample_rate if sample_rate is not None
            else _env_float(SAMPLE_ENV, DEFAULT_SAMPLE_RATE)
        )
        self.retention_s = (
            retention_s if retention_s is not None
            else _env_float(RETENTION_ENV, DEFAULT_RETENTION_S)
        )
        self.max_open_traces = max(1, int(max_open_traces))
        self.max_spans_per_journey = max(1, int(max_spans_per_journey))
        self._registry = registry
        self._rng = rng if rng is not None else random.random
        self._clock = clock
        self._lock = threading.Lock()
        # trace_id -> buffered finished spans (requests still in flight,
        # before any completion names them). LRU-bounded: evictions are
        # counted — this is where the span ring's wrap problem is solved,
        # so its own bound must be visible too.
        self._open_traces: "OrderedDict[str, list]" = OrderedDict()  # guarded-by: _lock
        # trace_id -> buffered resilience events for requests still in
        # flight whose events carry only a trace ctx (resilience.call's
        # retry events have no request id): joined at complete(), so a
        # mid-request retry still raises the must-keep `retried` flag.
        # Bounded exactly like the open-span buffers.
        self._open_events: "OrderedDict[str, list]" = OrderedDict()  # guarded-by: _lock
        # trace_id -> journey that claimed it (spans arriving after the
        # claim — the root serve.request span closes last — attach direct).
        self._trace_owner: dict[str, _Journey] = {}  # guarded-by: _lock
        # request_id -> journey opened by an event/annotation before its
        # completion arrived (bounded like the open traces).
        self._pending: "OrderedDict[str, _Journey]" = OrderedDict()  # guarded-by: _lock
        self._kept: "OrderedDict[str, _Journey]" = OrderedDict()  # guarded-by: _lock
        self._records = 0  # guarded-by: _lock — span+event records in _kept
        # (klass, revision) -> retained records charged to that source; the
        # fairness ledger behind source_budget_records.
        self._source_records: dict = {}  # guarded-by: _lock
        # Disambiguates trace-derived keys when several requests complete
        # on one shared trace (engine paths have no wire request id).
        self._trace_seq = 0  # guarded-by: _lock

    # ---- metrics ---------------------------------------------------------
    def _inc(self, name: str, labels: dict, value: float = 1.0) -> None:
        reg = self._registry if self._registry is not None else metrics.REGISTRY
        reg.inc(name, labels, value)  # vet: ignore[metric-name-literal]: forwarding shim — the retention paths pass the literal vault names the catalogue anchors on

    def _retained(self, outcome: str) -> None:  # holds-lock: _lock
        self._inc("serving_journeys_retained_total", {"outcome": outcome})

    def _dropped(self, reason: str, n: int = 1) -> None:  # holds-lock: _lock
        self._inc("serving_journeys_dropped_total", {"reason": reason},
                  float(n))

    # ---- source ledger ---------------------------------------------------
    @staticmethod
    def _source_of(j: _Journey) -> tuple:
        return (j.klass or "", j.revision or "")

    def _bump_source_locked(self, j: _Journey, n: int) -> None:  # holds-lock: _lock
        """Adjust the (klass, revision) ledger by `n` records. klass and
        revision are fixed at complete() before retention, so post-retention
        record growth (late spans, events, annotations) charges the same
        bucket the retention charge opened."""
        if self.source_budget_records <= 0 or n == 0:
            return
        key = self._source_of(j)
        total = self._source_records.get(key, 0) + n
        if total > 0:
            self._source_records[key] = total
        else:
            self._source_records.pop(key, None)

    # ---- feeds -----------------------------------------------------------
    def on_span(self, record: dict) -> None:
        """Trace finish listener: buffer the span under its trace id (or
        attach it straight to the journey that already claimed the trace).
        This is the decode hot path's recurring cost — one lock, one dict
        lookup, one append (`benchmarks/journey_overhead_bench.py` budgets
        it under 2% of decode throughput). Listener contract: runs on the
        finishing span's own thread, so a vault bug must surface as a lost
        journey record, never as an exception into that thread."""
        try:
            self._on_span(record)
        except Exception:  # vet: ignore[hazard-exception-swallow]: a vault bug must never break span accounting on the finishing thread (purity-observer-raise)
            pass

    def _on_span(self, record: dict) -> None:
        tid = record.get("trace_id")
        if not tid:
            return
        with self._lock:
            owner = self._trace_owner.get(tid)
            # A COMPLETED journey only accepts its own root span (the span
            # the completion ctx named, which closes after the timeline
            # finishes — everything below it already closed by then). Any
            # OTHER span arriving on a finished journey's trace belongs to
            # a different request re-using the trace (client grafting onto
            # a reconcile root, or a worker that completed a dropped
            # request against the client's wire ctx whose root will never
            # close HERE) — buffer it fresh instead of letting a finished
            # journey steal it.
            if owner is not None and owner.completed \
                    and record.get("span_id") != owner.root_span_id \
                    and not (owner.root_span_id is None
                             and _closes_tree(owner, record)):
                owner = None
            if owner is not None:
                if len(owner.spans) < self.max_spans_per_journey:
                    owner.spans.append(record)
                    if owner.completed:
                        self._records += 1
                        self._bump_source_locked(owner, 1)
                        self._enforce_budget_locked()
                else:
                    owner.spans_dropped += 1
                    self._dropped("journey_span_cap")
                # The completed journey's own root closed: release the
                # trace so the NEXT request sharing this trace id buffers
                # its spans fresh instead of feeding a finished journey.
                if owner.completed and self._trace_owner.get(tid) is owner \
                        and (record.get("span_id") == owner.root_span_id
                             if owner.root_span_id is not None
                             else record.get("parent_id") is None):
                    del self._trace_owner[tid]
                return
            bucket = self._open_traces.get(tid)
            if bucket is None:
                while len(self._open_traces) >= self.max_open_traces:
                    _, evicted = self._open_traces.popitem(last=False)
                    self._dropped("open_evicted", len(evicted) or 1)
                bucket = self._open_traces[tid] = []
            else:
                self._open_traces.move_to_end(tid)
            if len(bucket) < self.max_spans_per_journey:
                bucket.append(record)
            else:
                self._dropped("journey_span_cap")

    def on_event(self, event: dict) -> None:
        """Flight-recorder observer: attach resilience/chaos events to the
        journey they belong to — by explicit `request_id` field first, by
        the event's recorded trace ctx second. Unjoinable events are
        ignored (the ring still has them). Same containment contract as
        on_span: the recording thread never sees a vault exception."""
        try:
            self._on_event(event)
        except Exception:  # vet: ignore[hazard-exception-swallow]: a vault bug must cost one journey join, not the recording thread (purity-observer-raise)
            pass

    def _on_event(self, event: dict) -> None:
        flag = _EVENT_FLAGS.get(event.get("kind", ""))
        if flag is None:
            return
        rid = str(event.get("request_id") or event.get("id") or "")
        ctx = event.get("trace") or {}
        tid = ctx.get("trace_id") if isinstance(ctx, dict) else None
        with self._lock:
            j = None
            if rid:
                j = self._kept.get(rid) or self._pending.get(rid)
            if j is None and tid:
                owner = self._trace_owner.get(tid)
                if owner is not None and not owner.completed:
                    j = owner
            if j is None:
                if not rid:
                    if tid:
                        # Trace-only event for a request still in flight
                        # (resilience.call's retry events carry no id):
                        # buffer under the trace, joined at complete() —
                        # a mid-request retry must still raise the
                        # must-keep `retried` flag.
                        self._buffer_event_locked(tid, event)
                    return
                j = self._open_pending_locked(rid)
                if j is None:
                    return
                if tid:
                    j.trace_id = tid
                    self._trace_owner.setdefault(tid, j)
            if len(j.events) >= self.max_spans_per_journey:
                self._dropped("journey_event_cap")
                return
            j.events.append(dict(event))
            j.flags.add(flag)
            if j.completed:
                self._records += 1
                self._bump_source_locked(j, 1)
                # A must-keep signal arriving after a sampled/slowest
                # retention upgrades the journey's eviction class.
                if j.outcome in ("sampled", "slowest"):
                    j.outcome = self._outcome_locked(j)
                self._enforce_budget_locked()

    def on_timeline(self, summary: dict) -> None:
        """SLO sink (`SLORecorder.journey_sinks`): a request timeline
        finished — complete the journey with its phase values and verdict.
        Contained like the other feeds (the sink loop in slo.py also
        wraps, but the vault does not rely on every dispatcher doing so)."""
        try:
            self._on_timeline(summary)
        except Exception:  # vet: ignore[hazard-exception-swallow]: a vault bug must not fail a request's SLO completion (purity-observer-raise)
            pass

    def _on_timeline(self, summary: dict) -> None:
        phases = {
            k: summary.get(k)
            for k in ("queue_wait_s", "ttft_s", "worst_itl_s", "total_s",
                      "tokens", "good_tokens")
            if summary.get(k) is not None
        }
        self.complete(
            str(summary.get("request_id") or ""),
            trace=summary.get("trace"),
            engine=str(summary.get("engine") or ""),
            klass=str(summary.get("klass") or ""),
            revision=str(summary.get("revision") or ""),
            ok=bool(summary.get("ok", True)),
            phases=phases,
            targets=summary.get("targets"),
        )

    def annotate(self, request_id: str, **fields) -> None:
        """Attach structured extras (the KV-stream chunk timelines) to a
        journey by request id — pre- or post-completion."""
        rid = str(request_id or "")
        if not rid or not enabled():
            return
        with self._lock:
            j = self._kept.get(rid) or self._pending.get(rid)
            if j is None:
                j = self._open_pending_locked(rid)
                if j is None:
                    return
            tracked = self._kept.get(rid) is j
            before = j.records() if tracked else 0
            j.annotations.update(fields)
            if tracked:
                # Kept journeys are budget-tracked: annotation payloads
                # attached after retention adjust the record count.
                delta = j.records() - before
                self._records += delta
                self._bump_source_locked(j, delta)
                self._enforce_budget_locked()

    # ---- completion + retention ------------------------------------------
    def complete(
        self,
        request_id: str,
        trace: Optional[dict] = None,
        engine: str = "",
        klass: str = "",
        revision: str = "",
        ok: bool = True,
        outcome: Optional[str] = None,
        error: Optional[str] = None,
        phases: Optional[dict] = None,
        targets: Optional[dict] = None,
        now: Optional[float] = None,
    ) -> Optional[str]:
        """A request finished in THIS process: join its buffered spans and
        events, grade it, and decide retention. `outcome` forces a verdict
        class for the non-timeline completions (`errored`,
        `deadline_expired`); returns the retention outcome, or None when
        the journey was not kept."""
        if not enabled():
            return None
        tid = (trace or {}).get("trace_id") if isinstance(trace, dict) else None
        rid = str(request_id or "") or (tid or "")
        if not rid:
            with self._lock:
                self._dropped("unidentified")
            return None
        if now is None:
            now = self._clock()
        with self._lock:
            self._sweep_locked(now)
            j = self._pending.pop(rid, None)
            if j is None:
                j = self._kept.get(rid)
                if j is not None:
                    if j.completed and not request_id:
                        # The key was TRACE-derived (engine paths carry no
                        # wire id): a completed journey under it means a
                        # SECOND request finishing on a shared trace, not
                        # an idempotent re-finish — grade it fresh under a
                        # distinct key instead of discarding its verdict.
                        self._trace_seq += 1
                        rid = f"{rid}#{self._trace_seq}"
                        j = None
                    else:
                        del self._kept[rid]
            if j is None:
                j = _Journey(rid)
            if j.completed:
                # Already graded (idempotent finish): re-keep as-is.
                self._kept[rid] = j
                return j.outcome
            if tid:
                j.trace_id = tid
                j.root_span_id = (trace or {}).get("span_id")
                buffered = self._open_traces.pop(tid, None)
                if buffered:
                    room = self.max_spans_per_journey - len(j.spans)
                    j.spans.extend(buffered[:room])
                    if len(buffered) > room:
                        j.spans_dropped += len(buffered) - room
                        self._dropped("journey_span_cap",
                                      len(buffered) - room)
                # Trace-only resilience events buffered while the request
                # was in flight (mid-request retries) join here and raise
                # their must-keep flags before retention is decided.
                for ev in self._open_events.pop(tid, ()):
                    if len(j.events) >= self.max_spans_per_journey:
                        self._dropped("journey_event_cap")
                        break
                    j.events.append(ev)
                    ev_flag = _EVENT_FLAGS.get(ev.get("kind", ""))
                    if ev_flag:
                        j.flags.add(ev_flag)
                self._trace_owner[tid] = j
            j.engine = engine or j.engine
            j.klass = klass or j.klass
            j.revision = revision or j.revision
            if phases:
                j.timeline.update(phases)
            if targets:
                j.timeline["targets"] = dict(targets)
            if error:
                j.timeline["error"] = str(error)[:300]
            if outcome in ("errored",):
                j.flags.add("errored")
            if outcome in ("deadline_expired",):
                j.flags.add("deadline_expired")
            if not ok:
                j.flags.add("breached")
            j.completed = True
            j.completed_unix = time.time()
            j.completed_mono = now
            j.latency_s = max(
                float(j.timeline.get("total_s") or 0.0),
                float(j.timeline.get("ttft_s") or 0.0),
            )
            verdict_outcome = self._decide_locked(j)
            if verdict_outcome is None:
                # Not retained: release the trace claim so the vault holds
                # no reference (late spans re-open a bucket that ages out).
                if tid and self._trace_owner.get(tid) is j:
                    del self._trace_owner[tid]
                self._dropped("not_sampled", max(j.records(), 1))
                return None
            j.outcome = verdict_outcome
            self._kept[rid] = j
            self._records += j.records()
            self._bump_source_locked(j, j.records())
            self._retained(verdict_outcome)
            self._enforce_budget_locked()
            return verdict_outcome

    def _outcome_locked(self, j: _Journey) -> str:  # holds-lock: _lock
        for flag in _FLAG_PRIORITY:
            if flag in j.flags:
                return flag
        return j.outcome if j.outcome not in ("open",) else "sampled"

    def _decide_locked(self, j: _Journey) -> Optional[str]:  # holds-lock: _lock
        """The tail-sampling decision. Must-keep flags win outright; then
        the slowest-K healthy set; then the reservoir roll."""
        for flag in _FLAG_PRIORITY:
            if flag in j.flags:
                return flag
        if self.slowest_k > 0:
            slow = [k for k in self._kept
                    if self._kept[k].outcome == "slowest"]
            if len(slow) < self.slowest_k:
                return "slowest"
            floor_key = min(slow, key=lambda k: self._kept[k].latency_s)
            if j.latency_s > self._kept[floor_key].latency_s:
                evicted = self._kept.pop(floor_key)
                self._records -= evicted.records()
                self._bump_source_locked(evicted, -evicted.records())
                self._release_locked(evicted)
                self._dropped("displaced", max(evicted.records(), 1))
                return "slowest"
        if self._rng() < self.sample_rate:
            return "sampled"
        return None

    def _buffer_event_locked(self, tid: str, event: dict) -> None:  # holds-lock: _lock
        bucket = self._open_events.get(tid)
        if bucket is None:
            while len(self._open_events) >= self.max_open_traces:
                _, evicted = self._open_events.popitem(last=False)
                self._dropped("open_evicted", len(evicted) or 1)
            bucket = self._open_events[tid] = []
        else:
            self._open_events.move_to_end(tid)
        if len(bucket) < self.max_spans_per_journey:
            bucket.append(dict(event))
        else:
            self._dropped("journey_event_cap")

    def _open_pending_locked(self, rid: str) -> Optional[_Journey]:  # holds-lock: _lock
        j = self._pending.get(rid)
        if j is not None:
            self._pending.move_to_end(rid)
            return j
        while len(self._pending) >= self.max_open_traces:
            _, evicted = self._pending.popitem(last=False)
            self._release_locked(evicted)
            self._dropped("open_evicted", max(evicted.records(), 1))
        j = self._pending[rid] = _Journey(rid)
        return j

    def _release_locked(self, j: _Journey) -> None:  # holds-lock: _lock
        if j.trace_id and self._trace_owner.get(j.trace_id) is j:
            del self._trace_owner[j.trace_id]

    def _sweep_locked(self, now: float) -> None:  # holds-lock: _lock
        cutoff = now - self.retention_s
        for rid in [r for r, j in self._kept.items()
                    if j.completed_mono < cutoff]:
            evicted = self._kept.pop(rid)
            self._records -= evicted.records()
            self._bump_source_locked(evicted, -evicted.records())
            self._release_locked(evicted)
            self._dropped("aged", max(evicted.records(), 1))

    def _enforce_budget_locked(self) -> None:  # holds-lock: _lock
        """Evict down to the record budget, cheapest truth first: sampled
        healthy journeys, then the slowest set, and only then — when the
        must-keep class ALONE exceeds the budget — the oldest flagged
        journeys. A healthy-request flood can therefore never evict a
        retained breached journey. The per-source fairness bound is
        enforced after the global one with the same pass order."""
        if self._records > self.budget_records:
            for klass_pass in ("sampled", "slowest", None):
                victims = [
                    rid for rid, j in self._kept.items()
                    if klass_pass is None or j.outcome == klass_pass
                ]
                for rid in victims:
                    if self._records <= self.budget_records:
                        break
                    evicted = self._kept.pop(rid)
                    self._records -= evicted.records()
                    self._bump_source_locked(evicted, -evicted.records())
                    self._release_locked(evicted)
                    self._dropped("budget", max(evicted.records(), 1))
                if self._records <= self.budget_records:
                    break
        self._enforce_source_budget_locked()

    def _enforce_source_budget_locked(self) -> None:  # holds-lock: _lock
        """Per-(klass, revision) fairness: at 1,000 instances × classes ×
        revisions one hot source can stay under the GLOBAL budget while
        monopolising it. Sources over their share evict within-source in
        the same cheapest-truth-first order; losses count under the
        existing drop convention as reason="source_budget"."""
        if self.source_budget_records <= 0:
            return
        over = [key for key, n in self._source_records.items()
                if n > self.source_budget_records]
        for key in over:
            for klass_pass in ("sampled", "slowest", None):
                if (self._source_records.get(key, 0)
                        <= self.source_budget_records):
                    break
                victims = [
                    rid for rid, j in self._kept.items()
                    if self._source_of(j) == key
                    and (klass_pass is None or j.outcome == klass_pass)
                ]
                for rid in victims:
                    if (self._source_records.get(key, 0)
                            <= self.source_budget_records):
                        break
                    evicted = self._kept.pop(rid)
                    self._records -= evicted.records()
                    self._bump_source_locked(evicted, -evicted.records())
                    self._release_locked(evicted)
                    self._dropped("source_budget", max(evicted.records(), 1))

    # ---- views -----------------------------------------------------------
    def get(self, key: str) -> Optional[dict]:
        """A retained journey by REQUEST id or TRACE id (the exemplar
        resolution path) — None when the vault never kept it."""
        with self._lock:
            # Read-time sweep: the age bound must hold on a quiet process
            # too, not only while completions keep arriving.
            self._sweep_locked(self._clock())
            j = self._kept.get(key)
            if j is None or j.trace_id == key:
                # Newest first: several requests may share one trace (a
                # client grafting onto a reconcile root) — an exemplar's
                # trace id should resolve to the most recent of them, even
                # when the oldest one's key IS the trace id (engine paths).
                for cand in reversed(self._kept.values()):
                    if cand.trace_id == key:
                        j = cand
                        break
            if j is None:
                j = self._pending.get(key)
            return j.to_dict() if j is not None else None

    def spans_for_trace(self, trace_id: str) -> list[dict]:
        """Every span this process holds for `trace_id`: a kept journey's
        subtree, or the in-flight open-trace buffer — the local leg the
        fleet join pulls even when no completion ran here (the API-server
        process's client/reconcile spans)."""
        with self._lock:
            owner = self._trace_owner.get(trace_id)
            if owner is not None:
                return list(owner.spans)
            return list(self._open_traces.get(trace_id, ()))

    def index(self, outcome: str = "all", klass: str = "",
              limit: int = 32, revision: str = "") -> list[dict]:
        """Digest rows for `/debug/requests`, worst-first: `slowest` sorts
        by latency, everything else newest-first. `revision` filters to one
        serving revision's journeys (`explain --breached --revision`).
        Unknown outcomes raise ValueError (the debug surfaces answer 400)."""
        if outcome not in OUTCOMES:
            raise ValueError(
                f"outcome must be one of {', '.join(OUTCOMES)}, got {outcome!r}"
            )
        with self._lock:
            self._sweep_locked(self._clock())
            rows = [j for j in self._kept.values() if j.completed]
            if klass:
                rows = [j for j in rows if j.klass == klass]
            if revision:
                rows = [j for j in rows if j.revision == revision]
            if outcome == "slowest":
                rows.sort(key=lambda j: -j.latency_s)
            else:
                if outcome != "all":
                    rows = [j for j in rows
                            if j.outcome == outcome or outcome in j.flags]
                rows.sort(key=lambda j: -j.completed_unix)
            if limit >= 0:
                rows = rows[:limit] if limit else []
            # Digest under the lock: on_event() mutates a kept journey's
            # flags set, and sorted() over a set racing an add() raises.
            return [j.digest() for j in rows]

    def worst(self, limit: int = 8) -> list[dict]:
        """The flight-recorder dump embed: the window's worst journeys —
        every flagged one (newest first), padded with the slowest healthy
        ones."""
        with self._lock:
            self._sweep_locked(self._clock())
            kept = [j for j in self._kept.values() if j.completed]
            flagged = sorted(
                (j for j in kept if j.flags), key=lambda j: -j.completed_unix
            )
            healthy = sorted(
                (j for j in kept if not j.flags), key=lambda j: -j.latency_s
            )
            return [j.digest() for j in (flagged + healthy)[:max(0, limit)]]

    def stats(self) -> dict:
        with self._lock:
            return {
                "kept": len(self._kept),
                "records": self._records,
                "budget_records": self.budget_records,
                "source_budget_records": self.source_budget_records,
                "sources": len(self._source_records),
                "open_traces": len(self._open_traces),
                "pending": len(self._pending),
            }

    def clear(self) -> None:
        with self._lock:
            self._open_traces.clear()
            self._open_events.clear()
            self._trace_owner.clear()
            self._pending.clear()
            self._kept.clear()
            self._records = 0
            self._source_records.clear()


# ---------------------------------------------------------------------------
# Process-default vault + feed wiring.

VAULT = JourneyVault()

_INSTALL_LOCK = threading.Lock()
_INSTALLED = False


def install(vault: Optional[JourneyVault] = None) -> Optional[JourneyVault]:
    """Wire `vault` (default: the process VAULT) onto the process-default
    tracer, flight recorder, and SLO recorder. Idempotent — both servers
    call it at startup; LWS_TPU_JOURNEYS=0 disables the plane entirely
    (listeners never registered: the only residual cost is the empty
    listener-list iteration, covered by the trace-overhead budget)."""
    global _INSTALLED
    if not enabled():
        return None
    target = vault if vault is not None else VAULT
    with _INSTALL_LOCK:
        if _INSTALLED and vault is None:
            return VAULT
        from lws_tpu.core import flightrecorder, slo, trace

        trace.TRACER.add_finish_listener(target.on_span)
        flightrecorder.RECORDER.add_observer(target.on_event)
        if target.on_timeline not in slo.RECORDER.journey_sinks:
            slo.RECORDER.journey_sinks.append(target.on_timeline)
        if vault is None:
            _INSTALLED = True
    return target


def local_journey(key: str, span_limit: int = 512) -> Optional[dict]:
    """The `/debug/request/{id}` body for THIS process: the vault's journey
    (by request OR trace id) first; the bounded span ring second — the ring
    fallback keeps unsampled healthy traffic explainable while it is still
    young, and the vault keeps the tail explainable forever. None when the
    process knows nothing about the id."""
    from lws_tpu.core import trace

    journey = VAULT.get(key)
    if journey is not None:
        journey["source"] = "vault"
        return journey
    # Open, uncompleted trace buffers (a request still in flight).
    spans = VAULT.spans_for_trace(key)
    if spans:
        return {"id": key, "trace_id": key, "outcome": "open",
                "completed": False, "flags": [], "timeline": {},
                "events": [], "annotations": {}, "spans": spans,
                "source": "vault"}
    ring = [
        s for s in trace.TRACER.spans(span_limit)
        if s.get("trace_id") == key
        or (s.get("attrs") or {}).get("request_id") == key
    ]
    if ring:
        tid = ring[0].get("trace_id")
        return {"id": key, "trace_id": tid, "outcome": "open",
                "completed": False, "flags": [], "timeline": {},
                "events": [], "annotations": {}, "spans": ring,
                "source": "ring"}
    return None
