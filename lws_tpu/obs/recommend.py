"""Autoscaling recommender: ROADMAP item 4's decision plane.

Consumes the history plane exactly as the roadmap prescribes — prefill
desired-replicas from TTFT/queue-wait burn, decode from ITL burn and
KV-occupancy trend — and publishes DECISIONS, not actions:

  * `serving_scale_recommendation{role}` — the desired replica count per
    DS role, a gauge on the normal metrics surface (rides /metrics/fleet,
    rendered by `lws-tpu monitor`);
  * `serving_slo_burn_rate{engine,klass,window}` — the short-window burn of
    each tier per SLO series, the raw paging signal;
  * edge-triggered `burn_rate` Watchdog alerts: while a series' fast tier
    fires, the recommender holds a `burn_rate:{engine}[/{klass}]` heartbeat
    at depth 1 (the `circuit_open` convention) so the stock Watchdog rule
    produces ONE alert + diagnostics dump per burn episode — and the ring
    event recorded on the firing edge embeds the offending error-series
    window, so the dump carries the evidence, not just the verdict.

The `AnnotationAdapter` is the actuation seam: it writes the
recommendation into the existing `METRIC_ANNOTATION_PREFIX` pod-annotation
contract (`metrics.lws.tpu/<metric>` on ready leader pods — normalized so
the HPA math reproduces the recommendation exactly), which the stock
`AutoscalerReconciler` already consumes. Since the decision-provenance PR
the loop is CLOSED by default for DisaggregatedSet roles: the
`ScaleActuator` (obs/decisions.py) drives this adapter per evaluation,
records the full provenance chain in the `DecisionLedger`, and honors the
`LWS_TPU_ACTUATION_DISABLE=scale` kill switch — with the switch set, the
evaluation below is once again a pure recommendation.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from lws_tpu.core import flightrecorder, metrics
from lws_tpu.core.slo import SLOTargets
from lws_tpu.obs import signals
from lws_tpu.obs.history import HistoryRing
from lws_tpu.utils.common import env_float as _env_float

ATTAINMENT_TARGET_ENV = "LWS_TPU_SLO_BURN_TARGET"
DEFAULT_ATTAINMENT_TARGET = 0.99

# Per-role phase signals: the roadmap's sensor assignment. Prefill owns the
# arrival side (TTFT, queue wait); decode owns the steady-state side (ITL).
ROLE_PHASES = {
    "prefill": (
        ("serving_ttft_seconds_bucket", "ttft_s"),
        ("serving_queue_wait_seconds_bucket", "queue_wait_s"),
    ),
    "decode": (
        ("serving_itl_seconds_bucket", "itl_s"),
    ),
}

# KV-pool occupancy bands for the decode recommendation: above `high` the
# pool itself is the bottleneck (scale out even before latency burns);
# below `low` the pool is idle enough to consider scaling in.
KV_OCCUPANCY_HIGH = 0.85
KV_OCCUPANCY_LOW = 0.50

# Scale-up severity is bounded: one evaluation never recommends more than
# this factor over current (the HPA controller's own clamps still apply).
MAX_SCALE_FACTOR = 4.0

# Points embedded in the firing-edge ring event: enough to read the
# episode, bounded so a dump stays a dump.
EVENT_WINDOW_POINTS = 64


@dataclass
class Recommendation:
    """One evaluation's full verdict — JSON-shaped for reports/traces."""

    at: float
    desired: dict = field(default_factory=dict)      # role -> replicas
    current: dict = field(default_factory=dict)      # role -> replicas
    reasons: dict = field(default_factory=dict)      # role -> short text
    burns: list = field(default_factory=list)        # per-series tier dicts
    firing: list = field(default_factory=list)       # "engine[/klass]" keys

    def to_dict(self) -> dict:
        return {
            "at": self.at,
            "desired": dict(self.desired),
            "current": dict(self.current),
            "reasons": dict(self.reasons),
            "burns": list(self.burns),
            "firing": list(self.firing),
        }


def _burn_key(labels: dict) -> str:
    engine = labels.get("engine", "-")
    klass = labels.get("klass", "")
    return f"{engine}/{klass}" if klass else engine


class ScaleRecommender:
    def __init__(
        self,
        ring: HistoryRing,
        targets: Optional[SLOTargets] = None,
        class_targets: Optional[dict] = None,
        attainment_target: Optional[float] = None,
        windows: Optional[tuple] = None,
        current: Optional[dict] = None,
        min_replicas: int = 1,
        max_replicas: int = 8,
        registry=None,
        recorder: Optional[flightrecorder.FlightRecorder] = None,
    ) -> None:
        """`targets`/`class_targets` grade the phase histograms (defaults:
        env, like core/slo.py). `attainment_target` sets the error budget
        (`LWS_TPU_SLO_BURN_TARGET`, default 0.99); `windows` the burn tiers
        (default `signals.burn_windows()`, env-scalable to the ring's
        resolution). `current` maps role -> current replicas (the
        baseline the recommendation scales from; default 1 each).
        `registry` receives the recommendation/burn gauges (default the
        process registry); `recorder` the flight recorder whose heartbeat
        table the Watchdog's `burn_rate` rule reads (default the process
        one)."""
        self.ring = ring
        self.targets = targets if targets is not None else SLOTargets.from_env()
        self.class_targets = dict(class_targets or {})
        self.attainment_target = (
            attainment_target if attainment_target is not None
            else _env_float(ATTAINMENT_TARGET_ENV, DEFAULT_ATTAINMENT_TARGET)
        )
        self.windows = windows if windows is not None else signals.burn_windows()
        self.current = dict(current or {"prefill": 1, "decode": 1})
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self._registry = registry
        self._recorder = recorder if recorder is not None else flightrecorder.RECORDER
        self._lock = threading.Lock()
        self._firing: set = set()  # guarded-by: _lock
        # Burn-gauge label sets published on the previous evaluation: a
        # series whose goodput pair left the ring (retired worker, aged-out
        # class) must RETIRE its gauge, not freeze at the last burn — the
        # same staleness contract core/slo.py applies to attainment.
        self._published_burns: set = set()  # guarded-by: _lock

    # ---- plumbing --------------------------------------------------------
    def _reg(self):
        return self._registry if self._registry is not None else metrics.REGISTRY

    def _targets_for(self, klass: str) -> SLOTargets:
        if klass and klass in self.class_targets:
            return self.class_targets[klass]
        return self.targets

    def _fast(self) -> signals.BurnWindow:
        return self.windows[0]

    def _goodput_pairs(self) -> list:
        """[(labels, good points, total points)] for every token-ledger
        series, matched by exact label set. A total series WITHOUT a
        goodput twin means zero tokens ever landed on time (core/slo.py
        only creates the goodput counter on the first on-time token) —
        that's the worst burn there is, not a missing signal."""
        goods = {
            tuple(sorted(labels.items())): pts
            for _, labels, _, pts, _ in self.ring.series(
                "serving_goodput_tokens_total")
        }
        return [
            (labels, goods.get(tuple(sorted(labels.items())), []), pts)
            for _, labels, _, pts, _ in self.ring.series("serving_tokens_total")
        ]

    def _bucket_groups(self, family: str) -> dict:
        """{labels-minus-le tuple: {le: points}} for one histogram family's
        retained bucket series."""
        groups: dict = {}
        for _, labels, _, pts, _ in self.ring.series(family):
            le = labels.get("le")
            if le is None:
                continue
            rest = tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le"
            ))
            groups.setdefault(rest, {})[le] = pts
        return groups

    def occupancy_points(self, labels_subset: Optional[dict] = None) -> list:
        """Pointwise KV-pool occupancy series live/(free+live+parked) from
        the state-labelled block gauge, aligned on sample times (summed
        across matching engines/instances per timestamp)."""
        states: dict = {}
        for _, labels, _, pts, _ in self.ring.series(
                "serving_kv_pool_blocks", labels_subset):
            state = labels.get("state")
            if state not in ("free", "live", "parked"):
                continue
            for t, v in pts:
                slot = states.setdefault(t, {})
                slot[state] = slot.get(state, 0.0) + v
        out = []
        for t in sorted(states):
            slot = states[t]
            pool = sum(slot.values())
            if pool > 0 and "live" in slot:
                out.append((t, slot["live"] / pool))
        return out

    # ---- the evaluation --------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> Recommendation:
        """One evaluation pass (pure — the ScaleActuator acts on the result):
        burn every SLO series, derive per-role desired replicas, publish
        the gauges, and drive the edge-triggered alert feed. Deterministic under an injected `now`."""
        if now is None:
            now = time.monotonic()
        rec = Recommendation(at=now, current=dict(self.current))
        reg = self._reg()
        fast = self._fast()

        # 1. Error-budget burn per goodput series (the canonical
        #    `serving_slo_burn_rate` surface + the alert feed). On a
        #    fleet-fed ring the same (engine, klass) exists once PER
        #    INSTANCE; the gauge publishes the WORST instance's burn —
        #    last-write-wins would let a calm worker mask a burning one.
        firing_now: set = set()
        worst: dict = {}  # gauge label tuple -> max short burn
        for labels, good, total in self._goodput_pairs():
            target = self.attainment_target
            verdicts = signals.multiwindow_burn(
                good, total, target, self.windows, now
            )
            key = _burn_key(labels)
            burn_labels = {
                k: v for k, v in labels.items() if k in ("engine", "klass")
            }
            for v in verdicts:
                if v.short_burn is not None:
                    gauge_labels = tuple(sorted(
                        {**burn_labels, "window": v.window}.items()
                    ))
                    if v.short_burn > worst.get(gauge_labels, -1.0):
                        worst[gauge_labels] = v.short_burn
                rec.burns.append({
                    "series": key, "instance": labels.get("instance", ""),
                    "window": v.window,
                    "short_burn": v.short_burn, "long_burn": v.long_burn,
                    "threshold": v.threshold, "firing": v.firing,
                })
            if verdicts and verdicts[0].firing:  # the fast (page) tier
                firing_now.add(key)
                if key not in rec.firing:
                    rec.firing.append(key)
                self._hold_alert(labels, good, total, verdicts[0], now)
        for gauge_labels, burn in worst.items():
            reg.set("serving_slo_burn_rate", burn, dict(gauge_labels))
        self._clear_alerts(firing_now, now)
        # Retire burn gauges whose feeding series left the ring or stopped
        # being evaluable — a frozen 20x burn is a phantom incident.
        with self._lock:
            stale_burns = self._published_burns - set(worst)
            self._published_burns = set(worst)
        for labels_t in stale_burns:
            reg.clear_gauge("serving_slo_burn_rate", dict(labels_t),
                            exact=True)

        # 2. Per-role desired replicas from the phase burns + KV trend.
        for role, phases in ROLE_PHASES.items():
            cur = int(self.current.get(role, 1))
            burn_short = None
            burn_firing = False
            for family, target_field in phases:
                for rest, buckets in self._bucket_groups(family).items():
                    labels = dict(rest)
                    target = getattr(
                        self._targets_for(labels.get("klass", "")), target_field
                    )
                    budget = 1.0 - self.attainment_target
                    short = signals.breach_fraction(
                        buckets, target, fast.short_s, now)
                    long_ = signals.breach_fraction(
                        buckets, target, fast.long_s, now)
                    if short is None or budget <= 0:
                        continue
                    short /= budget
                    if burn_short is None or short > burn_short:
                        burn_short = short
                    if long_ is not None and short >= fast.threshold \
                            and long_ / budget >= fast.threshold:
                        burn_firing = True
            occ = occ_slope = None
            if role == "decode":
                occ_pts = self.occupancy_points()
                occ = signals.mean(occ_pts, fast.long_s, now)
                occ_slope = signals.slope(occ_pts, fast.short_s, now)
            desired, reason = self._desired(
                cur, burn_short, burn_firing, occ, occ_slope, fast
            )
            rec.desired[role] = desired
            rec.reasons[role] = reason
            reg.set("serving_scale_recommendation", float(desired),
                    {"role": role})
        return rec

    def _desired(self, cur: int, burn_short, burn_firing: bool,
                 occ, occ_slope, fast) -> tuple:
        """The policy, spelled out: scale up when the phase burn
        fires (severity-proportional, bounded), bump decode when the KV
        pool itself is the bottleneck, scale in one step only when every
        signal is both evaluable-or-absent and calm. No data ≠ calm."""
        if burn_firing and burn_short is not None:
            severity = min(MAX_SCALE_FACTOR, burn_short / fast.threshold)
            desired = max(cur + 1, math.ceil(cur * severity))
            return (min(self.max_replicas, desired),
                    f"burn {burn_short:.1f}x over threshold {fast.threshold:g}")
        if occ is not None and (
            occ >= KV_OCCUPANCY_HIGH
            or (occ_slope is not None and occ_slope > 0
                and occ + occ_slope * fast.short_s >= KV_OCCUPANCY_HIGH)
        ):
            return (min(self.max_replicas, cur + 1),
                    f"kv occupancy {occ:.0%} (slope {occ_slope or 0:+.3f}/s)")
        calm_burn = burn_short is not None and burn_short < 1.0
        calm_occ = occ is None or occ < KV_OCCUPANCY_LOW
        if calm_burn and calm_occ and cur > self.min_replicas:
            return (max(self.min_replicas, cur - 1),
                    f"calm: burn {burn_short:.2f}x, budget intact")
        return cur, ("steady" if burn_short is not None else "no signal")

    # ---- edge-triggered alert feed ---------------------------------------
    def _hold_alert(self, labels: dict, good, total, verdict, now: float) -> None:
        """While a series' fast tier fires, hold its `burn_rate:*` heartbeat
        at depth 1 with a pinned progress counter (the `circuit_open`
        convention: the Watchdog's sustained-depth rule fires once per
        episode). The NEW-episode edge also records a ring event embedding
        the offending error-series window — the next watchdog dump then
        ships the evidence inside its event ring."""
        key = _burn_key(labels)
        with self._lock:
            new_edge = key not in self._firing
            self._firing.add(key)
        self._recorder.beat(f"burn_rate:{key}", progress=0.0, depth=1.0,
                            now=now)
        if new_edge:
            window = signals.error_series(good, total)[-EVENT_WINDOW_POINTS:]
            self._recorder.record(
                "burn_rate_fired",
                series=key,
                engine=labels.get("engine", ""),
                klass=labels.get("klass", ""),
                window=verdict.window,
                short_burn=verdict.short_burn,
                long_burn=verdict.long_burn,
                threshold=verdict.threshold,
                error_window=[[t, v] for t, v in window],
            )

    def _clear_alerts(self, firing_now: set, now: float) -> None:
        with self._lock:
            cleared = self._firing - firing_now
            self._firing = set(firing_now)
        for key in cleared:
            # Advancing progress while dropping depth clears the sustained-
            # depth rule on the next watchdog pass (edge -> inactive).
            self._recorder.beat(f"burn_rate:{key}", progress=1.0, depth=0.0,
                                now=now)


def role_replicas_from_store(store) -> dict:
    """{role name: spec replicas} summed over every DisaggregatedSet in the
    store — the REAL per-role baseline the control plane's recommender
    scales from (a hardcoded baseline of 1 would both understate desired
    counts under burn and invite a calm 'scale to 1' against a wide
    fleet). Empty when no DS exists (single-LWS deployments have no
    prefill/decode roles to recommend for)."""
    out: dict = {}
    for ds in store.list("DisaggregatedSet"):
        for role in getattr(ds.spec, "roles", None) or []:
            name = getattr(role, "name", "")
            if name:
                out[name] = out.get(name, 0) + int(getattr(role, "replicas", 0) or 0)
    return out


# Process-default recommender over the process history ring: the control
# plane evaluates it per fleet-history ingest (runtime/server.py), syncing
# `current` from the store's DS roles first, so the recommendation/burn
# gauges and the `burn_rate` alert feed exist on every live deployment
# without any wiring. The same ingest step hands the verdict to the
# default ScaleActuator (obs/decisions.py), which actuates DS roles
# through the AnnotationAdapter below unless LWS_TPU_ACTUATION_DISABLE
# says otherwise.
RECOMMENDER: Optional[ScaleRecommender] = None
_RECOMMENDER_LOCK = threading.Lock()


def default_recommender(store=None) -> ScaleRecommender:
    """The process-default recommender; with `store`, its `current`
    baseline re-syncs to the store's actual per-role replica counts before
    the caller evaluates."""
    global RECOMMENDER
    with _RECOMMENDER_LOCK:
        if RECOMMENDER is None:
            from lws_tpu.obs.history import HISTORY

            RECOMMENDER = ScaleRecommender(HISTORY)
        if store is not None:
            replicas = role_replicas_from_store(store)
            if replicas:
                RECOMMENDER.current = {**RECOMMENDER.current, **replicas}
        return RECOMMENDER


# ---------------------------------------------------------------------------
# The opt-in actuation seam


class AnnotationAdapter:
    """Write a recommendation into the existing pod-annotation metric
    contract (`metrics.lws.tpu/<metric>` on ready leader pods) that
    `controllers/autoscaler_controller.py` already consumes.

    The value is NORMALIZED so the HPA math reproduces the recommendation
    exactly: each of the `n` ready leaders reports `(desired - 0.5) / n`,
    and an `Autoscaler` with `spec.metric == adapter.metric` and
    `spec.target_value == 1.0` computes
    `ceil(n * avg / target) = ceil(desired - 0.5) = desired` — the half
    offset makes the ceil land on `desired` for EVERY (desired, n) pair
    (a bare `desired/n` share overshoots by one whenever the float
    round-trip lands epsilon above the integer, e.g. desired=25, n=11).
    The Autoscaler's own min/max clamps and scale-down stabilization stay
    the operator's guardrails. Driven per evaluation by the default
    `ScaleActuator` (obs/decisions.py) for DS roles; still usable directly
    for manual or out-of-tree wiring."""

    def __init__(self, store, namespace: str, target: str,
                 metric: str = "scale_recommendation") -> None:
        self.store = store
        self.namespace = namespace
        self.target = target
        self.metric = metric

    def leader_pods(self) -> list:
        from lws_tpu.api import contract
        from lws_tpu.utils.podutils import pod_running_and_ready

        return [
            p for p in self.store.list(
                "Pod", self.namespace,
                labels={
                    contract.SET_NAME_LABEL_KEY: self.target,
                    contract.WORKER_INDEX_LABEL_KEY: "0",
                },
            )
            if pod_running_and_ready(p)
        ]

    def publish(self, desired: int) -> int:
        """Annotate every ready leader with the normalized recommendation;
        returns the number of leaders annotated (0 = nothing to feed the
        controller yet — the caller retries on its own cadence)."""
        from lws_tpu.api.autoscaler import METRIC_ANNOTATION_PREFIX
        from lws_tpu.core.store import ConflictError

        leaders = self.leader_pods()
        if not leaders:
            return 0
        share = (float(desired) - 0.5) / len(leaders)
        annotated = 0
        for pod in leaders:
            for _ in range(3):  # optimistic-concurrency retries, like /report-metric
                try:
                    fresh = self.store.get("Pod", pod.meta.namespace, pod.meta.name)
                    fresh.meta.annotations[
                        METRIC_ANNOTATION_PREFIX + self.metric
                    ] = str(share)
                    self.store.update(fresh)
                    annotated += 1
                    break
                except ConflictError:
                    continue
        return annotated
