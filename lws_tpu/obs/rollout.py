"""Rollout intelligence plane: the sensor-and-verdict half of canarying.

Three pieces turn a rolling update from a config mutation into an
observable, judgeable process:

  * **RolloutLedger** — a bounded, retention-aware timeline of control-plane
    state transitions (group revision flips, partition movement, DS
    lockstep steps, scale changes, drains, pod churn), fed by a store
    watcher plus a flight-recorder observer in the manager's reconcile
    path. Snapshotable at `GET /debug/rollout` and embedded in every
    watchdog dump, so a canary alert ships the rollout timeline that led
    to it.
  * **revision folds** — pure `signals.py`-style functions over the
    `HistoryRing`: the fleet exposition already labels every series with
    `revision` (and PR 15 threads the same label through worker-local
    series via LWS_TPU_REVISION), so per-(engine, revision) burn,
    attainment, TTFT/ITL quantiles, and GOOD%/SPEC%/PFX% are one
    `ring.series(family, {"revision": r})` away.
  * **CanaryAnalyzer** — promote/hold/rollback verdicts
    (`lws_rollout_canary_verdict{lws,revision}`: +1/0/-1) from
    baseline-vs-canary burn deltas, with minimum-sample and
    minimum-duration guards: NO DATA IS NOT PROMOTE — a revision that
    hasn't served enough tokens for long enough holds, it never promotes.
    While a revision's regression fires, the analyzer holds a
    `canary:{lws}/{revision}` heartbeat at depth 1 (the `circuit_open`
    convention) so the stock Watchdog `canary_regression` rule produces
    ONE alert + diagnostics dump per episode — and the firing-edge ring
    event embeds both the offending revision's error-series window and the
    ledger window, so the dump carries the evidence, not just the verdict.

`RolloutActuationAdapter` is the actuation seam: it can pause the stock
rollout controller (freeze the partition) or roll the template back to the
baseline revision via the existing ControllerRevision machinery. Since the
decision-provenance PR the edge-triggered `RolloutActuator`
(obs/decisions.py) drives it by default when a canary regression fires,
recording the full evidence chain in the decision ledger — behind the
`LWS_TPU_ACTUATION_DISABLE=rollout` kill switch, which restores the old
verdict-only behavior.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Optional

from lws_tpu.core import flightrecorder, metrics
from lws_tpu.obs import signals
from lws_tpu.obs.history import HistoryRing
from lws_tpu.utils.common import env_float as _env_float

# ---- guards (env-tunable per deployment; tests pass explicit values) -------
# Tokens a revision must have delivered before it is judgeable at all.
MIN_SAMPLES_ENV = "LWS_TPU_CANARY_MIN_SAMPLES"
DEFAULT_MIN_SAMPLES = 50.0
# Seconds of retained series a revision must span before it is judgeable.
MIN_DURATION_ENV = "LWS_TPU_CANARY_MIN_DURATION_S"
DEFAULT_MIN_DURATION_S = 60.0
# How many burn multiples HOTTER than the best other revision the fast
# short-window burn must run before a firing revision is attributed (and
# rolled back) rather than held as a fleet-wide problem.
DELTA_ENV = "LWS_TPU_CANARY_DELTA"
DEFAULT_DELTA = 2.0

# Verdict gauge encoding: promote / hold / rollback.
VERDICT_VALUES = {"promote": 1.0, "hold": 0.0, "rollback": -1.0}

# Points/entries embedded in the firing-edge ring event: enough to read the
# episode, bounded so a dump stays a dump.
EVENT_WINDOW_POINTS = 64
EVENT_LEDGER_ENTRIES = 32

DEFAULT_LEDGER_CAPACITY = 512
DEFAULT_LEDGER_RETENTION_S = 3600.0

# Flight-recorder event kinds worth a rollout-timeline entry (drains,
# restarts, alerts, chaos, actuations); everything else in the ring is
# request-scale noise at rollout timescales.
LEDGER_EVENT_KINDS = frozenset((
    "drain_requested", "drain_ignored", "watchdog_alert",
    "fault_injected", "burn_rate_fired", "canary_regression_fired",
    "actuation", "actuation_flap", "autoscaler_scaled",
))


# ---------------------------------------------------------------------------
# The rollout ledger


class RolloutLedger:
    """Bounded, retention-aware timeline of control-plane transitions.

    Entries are plain dicts (`{"at", "unix", "kind", "object", "revision",
    "detail"}`) so snapshots serve straight from `GET /debug/rollout` and
    embed in watchdog dumps. Fed two ways: `observe_store_event` diffs
    tracked objects on every store watch event (the manager's reconcile
    path mutates the store, so every rollout decision lands here), and
    `observe_recorder_event` mirrors the flight-recorder kinds that matter
    at rollout timescale. `clock` is injectable for deterministic tests."""

    def __init__(self, capacity: int = DEFAULT_LEDGER_CAPACITY,
                 retention_s: float = DEFAULT_LEDGER_RETENTION_S,
                 clock=time.monotonic, registry=None,
                 capacity_per_kind: Optional[int] = None) -> None:
        """`capacity` bounds the whole timeline; `capacity_per_kind`
        (default capacity // 4, floor 64) bounds any ONE kind's share so
        fleet-scale pod churn cannot flush the partition moves and
        revision flips out of the window. Capacity evictions are counted
        (`lws_rollout_ledger_dropped_total{kind}`) — a silently shortened
        timeline reads as a quiet rollout."""
        self.retention_s = retention_s
        self.capacity = max(1, int(capacity))
        self.capacity_per_kind = (
            int(capacity_per_kind) if capacity_per_kind is not None
            else max(64, self.capacity // 4)
        )
        self._entries: deque = deque()  # guarded-by: _lock
        self._per_kind: dict = {}  # guarded-by: _lock — entry count by kind
        self._lock = threading.Lock()
        self._clock = clock
        self._registry = registry
        # Last-seen tracked fields per (kind, namespace, name): the diff
        # base observe_store_event derives transitions from. LRU-bounded —
        # a ledger must never grow with fleet size unbounded.
        self._state: OrderedDict = OrderedDict()  # guarded-by: _lock

    def _reg(self):
        return self._registry if self._registry is not None else metrics.REGISTRY

    # ---- feeds -----------------------------------------------------------
    def record(self, kind: str, obj: str = "", revision: str = "",
               now: Optional[float] = None, **detail) -> dict:
        if now is None:
            now = self._clock()
        entry = {
            "at": round(now, 6),
            "unix": round(time.time(), 6),
            "kind": kind,
            "object": obj,
            "revision": revision,
            "detail": {k: v for k, v in detail.items() if v is not None},
        }
        with self._lock:
            self._entries.append(entry)
            self._per_kind[kind] = self._per_kind.get(kind, 0) + 1
            dropped = self._evict_locked(kind)
        self._reg().inc("lws_rollout_ledger_events_total", {"kind": kind})
        for dkind, n in dropped.items():
            self._reg().inc("lws_rollout_ledger_dropped_total",
                            {"kind": dkind}, float(n))
        return entry

    def _evict_locked(self, kind: str) -> dict:  # holds-lock: _lock
        """Enforce the per-kind then the global capacity, oldest first;
        returns {kind: evicted count} for the caller to count OUTSIDE the
        lock (registry has its own lock — no nesting)."""
        dropped: dict = {}

        def _forget(victim: dict) -> None:
            vkind = victim["kind"]
            left = self._per_kind.get(vkind, 0) - 1
            if left > 0:
                self._per_kind[vkind] = left
            else:
                self._per_kind.pop(vkind, None)
            dropped[vkind] = dropped.get(vkind, 0) + 1

        if (self.capacity_per_kind > 0
                and self._per_kind.get(kind, 0) > self.capacity_per_kind):
            for victim in self._entries:
                if victim["kind"] == kind:
                    self._entries.remove(victim)
                    _forget(victim)
                    break
        while len(self._entries) > self.capacity:
            _forget(self._entries.popleft())
        return dropped

    def observe_store_event(self, ev) -> None:
        """Store watch feed: diff the tracked fields of rollout-relevant
        kinds and record the transitions. Never raises — a broken observer
        must never break the reconcile path it observes."""
        try:
            self._observe_store_event(ev)
        except Exception:  # vet: ignore[hazard-exception-swallow]: observer must never break the watched store's notify loop (BLE001 intended)
            pass

    def _observe_store_event(self, ev) -> None:
        obj = ev.obj
        kind = getattr(obj, "kind", "") or type(obj).__name__
        handler = {
            "LeaderWorkerSet": self._track_lws,
            "GroupSet": self._track_groupset,
            "DisaggregatedSet": self._track_ds,
            "Pod": self._track_pod,
            "Node": self._track_node,
        }.get(kind)
        if handler is None:
            return
        name = f"{obj.meta.namespace}/{obj.meta.name}"
        key = (kind, name)
        if ev.type == "DELETED":
            with self._lock:
                prev = self._state.pop(key, None)
            if kind == "Pod":
                self._record_pod_gone(obj, prev)
            elif prev is not None:
                self.record("deleted", obj=f"{kind} {name}",
                            revision=str(prev.get("revision", "")))
            return
        state = handler(obj, name, ev.type)
        with self._lock:
            self._state[key] = state
            self._state.move_to_end(key)
            while len(self._state) > 4096:
                self._state.popitem(last=False)

    def _prev(self, kind: str, name: str) -> Optional[dict]:
        with self._lock:
            return self._state.get((kind, name))

    def _track_lws(self, obj, name: str, ev_type: str) -> dict:
        ru = getattr(obj.spec.rollout_strategy, "rolling_update_configuration",
                     None)
        state = {
            "partition": int(getattr(ru, "partition", 0) or 0),
            "replicas": int(obj.spec.replicas),
            "updated": int(getattr(obj.status, "updated_replicas", 0) or 0),
            "ready": int(getattr(obj.status, "ready_replicas", 0) or 0),
        }
        prev = self._prev("LeaderWorkerSet", name)
        label = f"LeaderWorkerSet {name}"
        if prev is None:
            if ev_type == "ADDED":
                self.record("created", obj=label, replicas=state["replicas"])
            return state
        if state["partition"] != prev["partition"]:
            self.record("partition_move", obj=label,
                        from_partition=prev["partition"],
                        to_partition=state["partition"])
        if state["replicas"] != prev["replicas"]:
            self.record("scale", obj=label, from_replicas=prev["replicas"],
                        to_replicas=state["replicas"])
        if (state["updated"], state["ready"]) != (prev["updated"], prev["ready"]):
            self.record("rollout_progress", obj=label,
                        updated=state["updated"], ready=state["ready"],
                        replicas=state["replicas"])
        return state

    def _track_groupset(self, obj, name: str, ev_type: str) -> dict:
        from lws_tpu.api import contract

        state = {
            "revision": obj.meta.labels.get(contract.REVISION_LABEL_KEY, ""),
            "partition": int(getattr(obj.spec.update_strategy, "partition", 0)
                             or 0),
        }
        prev = self._prev("GroupSet", name)
        label = f"GroupSet {name}"
        if prev is None:
            if ev_type == "ADDED" and state["revision"]:
                self.record("group_created", obj=label,
                            revision=state["revision"])
            return state
        if state["revision"] != prev["revision"]:
            self.record("revision_flip", obj=label,
                        revision=state["revision"],
                        from_revision=prev["revision"])
        if state["partition"] != prev["partition"]:
            self.record("partition_move", obj=label,
                        revision=state["revision"],
                        from_partition=prev["partition"],
                        to_partition=state["partition"])
        return state

    def _track_ds(self, obj, name: str, ev_type: str) -> dict:
        roles = tuple(
            (getattr(r, "name", ""), int(getattr(r, "replicas", 0) or 0))
            for r in (getattr(obj.spec, "roles", None) or [])
        )
        state = {
            "revision": getattr(obj.status, "current_revision", "") or "",
            "roles": roles,
        }
        prev = self._prev("DisaggregatedSet", name)
        label = f"DisaggregatedSet {name}"
        if prev is None:
            return state
        if state["revision"] != prev["revision"]:
            self.record("ds_revision_flip", obj=label,
                        revision=state["revision"],
                        from_revision=prev["revision"])
        if state["roles"] != prev["roles"]:
            self.record("ds_lockstep_step", obj=label,
                        revision=state["revision"],
                        from_roles=dict(prev["roles"]),
                        to_roles=dict(roles))
        return state

    def _pod_revision(self, obj) -> str:
        # Same precedence as the fleet scraper's labels (runtime/fleet.py):
        # the DS per-role revision first, the LWS template revision second.
        from lws_tpu.api import contract, disagg

        return (obj.meta.labels.get(disagg.DS_REVISION_LABEL_KEY)
                or obj.meta.labels.get(contract.REVISION_LABEL_KEY) or "")

    def _track_pod(self, obj, name: str, ev_type: str) -> dict:
        phase = str(getattr(obj.status, "phase", "") or "")
        state = {"revision": self._pod_revision(obj), "phase": phase}
        prev = self._prev("Pod", name)
        label = f"Pod {name}"
        if prev is None:
            if ev_type == "ADDED":
                self.record("pod_created", obj=label,
                            revision=state["revision"])
            return state
        if phase != prev["phase"] and phase in ("Failed", "Succeeded"):
            self.record("pod_phase", obj=label, revision=state["revision"],
                        phase=phase)
        return state

    def _record_pod_gone(self, obj, prev: Optional[dict]) -> None:
        self.record("pod_deleted",
                    obj=f"Pod {obj.meta.namespace}/{obj.meta.name}",
                    revision=(prev or {}).get("revision",
                                              self._pod_revision(obj)))

    def _track_node(self, obj, name: str, ev_type: str) -> dict:
        state = {"unschedulable": bool(getattr(obj.spec, "unschedulable",
                                               False))}
        prev = self._prev("Node", name)
        if prev is not None and state["unschedulable"] != prev["unschedulable"]:
            self.record("cordon" if state["unschedulable"] else "uncordon",
                        obj=f"Node {obj.meta.name}")
        return state

    def observe_recorder_event(self, event: dict) -> None:
        """Flight-recorder feed: mirror the event kinds that matter at
        rollout timescale (drains, alerts, chaos) into the timeline."""
        try:
            kind = event.get("kind", "")
            if kind not in LEDGER_EVENT_KINDS:
                return
            detail = {
                k: v for k, v in event.items()
                if k not in ("kind", "ts", "trace", "revision",
                             "error_window", "ledger_window")
                and isinstance(v, (str, int, float, bool))
            }
            self.record(kind,
                        obj=str(event.get("series") or event.get("source")
                                or event.get("point") or ""),
                        revision=str(event.get("revision", "")), **detail)
        except Exception:  # vet: ignore[hazard-exception-swallow]: observer must never break event recording (BLE001 intended)
            pass

    # ---- views -----------------------------------------------------------
    def _sweep(self, now: float) -> None:
        cutoff = now - self.retention_s
        with self._lock:
            while self._entries and self._entries[0]["at"] < cutoff:
                aged = self._entries.popleft()
                left = self._per_kind.get(aged["kind"], 0) - 1
                if left > 0:
                    self._per_kind[aged["kind"]] = left
                else:
                    self._per_kind.pop(aged["kind"], None)

    def snapshot(self, limit: int = 256,
                 now: Optional[float] = None) -> list:
        """The newest `limit` retained entries, oldest first — the
        `GET /debug/rollout` body and the watchdog dump embed."""
        self._sweep(self._clock() if now is None else now)
        with self._lock:
            out = list(self._entries)
        return out[-limit:] if limit else []

    def window(self, since_s: float, now: Optional[float] = None) -> list:
        """Entries from the trailing `since_s` seconds — the slice a canary
        alert embeds next to the offending revision's error series."""
        if now is None:
            now = self._clock()
        self._sweep(now)
        cutoff = now - since_s
        with self._lock:
            return [e for e in self._entries if e["at"] >= cutoff]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._per_kind.clear()
            self._state.clear()


# Process-default ledger: the control plane wires its store watch + the
# process flight recorder into THIS instance (runtime/harness.py install()),
# and the watchdog dump / debug endpoint snapshot it.
LEDGER = RolloutLedger()

_INSTALL_LOCK = threading.Lock()
_RECORDER_OBSERVED = False


def _default_recorder_observer(event: dict) -> None:
    LEDGER.observe_recorder_event(event)


def install(store=None):
    """Wire the process-default ledger: subscribe it to the process flight
    recorder (once) and, with `store`, to that store's watch feed. Returns
    the store-watch unsubscribe callable (None without a store)."""
    global _RECORDER_OBSERVED
    with _INSTALL_LOCK:
        if not _RECORDER_OBSERVED:
            flightrecorder.RECORDER.add_observer(_default_recorder_observer)
            _RECORDER_OBSERVED = True
    if store is not None:
        return store.watch(LEDGER.observe_store_event)
    return None


# ---------------------------------------------------------------------------
# Revision-dimension folds: pure functions over a ring, signals.py style.


def _subset(revision: str, engine: Optional[str] = None) -> dict:
    sub = {"revision": revision}
    if engine:
        sub["engine"] = engine
    return sub


def revision_values(ring: HistoryRing) -> list:
    """Sorted revisions present on the token ledger — the judgeable set."""
    revs = {
        labels["revision"]
        for _, labels, _, _, _ in ring.series("serving_tokens_total")
        if labels.get("revision")
    }
    return sorted(revs)


def revision_goodput_pairs(ring: HistoryRing, revision: str,
                           engine: Optional[str] = None) -> list:
    """[(labels, good points, total points)] for one revision's token
    ledger, matched by exact label set — same contract as the recommender's
    `_goodput_pairs`: a total series WITHOUT a goodput twin is a 100% error
    series (core/slo.py only mints the goodput counter on the first
    on-time token), not a missing signal."""
    sub = _subset(revision, engine)
    goods = {
        tuple(sorted(labels.items())): pts
        for _, labels, _, pts, _ in ring.series(
            "serving_goodput_tokens_total", sub)
    }
    return [
        (labels, goods.get(tuple(sorted(labels.items())), []), pts)
        for _, labels, _, pts, _ in ring.series("serving_tokens_total", sub)
    ]


def revision_burn(ring: HistoryRing, revision: str, target: float,
                  windows: Optional[tuple] = None,
                  now: Optional[float] = None,
                  engine: Optional[str] = None) -> list:
    """[BurnVerdict per tier] for one revision: the WORST instance's burn
    per tier (worst short-window burn wins; a calm worker must never mask
    a burning one — same rule as the fleet burn gauge)."""
    tiers = windows if windows is not None else signals.burn_windows()
    worst: list = [None] * len(tiers)
    for _, good, total in revision_goodput_pairs(ring, revision, engine):
        for i, v in enumerate(signals.multiwindow_burn(
                good, total, target, tiers, now)):
            cur = worst[i]
            if cur is None or (v.short_burn or -1.0) > (cur.short_burn or -1.0):
                worst[i] = v
    return [
        v if v is not None else signals.BurnVerdict(
            window=w.name, short_burn=None, long_burn=None,
            threshold=w.threshold)
        for v, w in zip(worst, tiers)
    ]


def revision_samples(ring: HistoryRing, revision: str,
                     now: Optional[float] = None,
                     engine: Optional[str] = None) -> tuple:
    """(tokens delivered, seconds of series span) for one revision over the
    full retained window — the minimum-sample / minimum-duration guard
    inputs. (0.0, 0.0) for an unseen revision."""
    tokens = 0.0
    span = 0.0
    for _, _, total in revision_goodput_pairs(ring, revision, engine):
        tokens += signals.increase(total) or 0.0
        if len(total) >= 2:
            span = max(span, total[-1][0] - total[0][0])
    return tokens, span


def revision_attainment(ring: HistoryRing, revision: str,
                        window_s: Optional[float] = None,
                        now: Optional[float] = None,
                        engine: Optional[str] = None) -> Optional[float]:
    """Worst (minimum) time-weighted attainment across one revision's
    `serving_slo_attainment` gauges — per-(engine, revision) attainment
    with the same worst-instance pessimism as the burn fold."""
    vals = [
        signals.mean(pts, window_s, now)
        for _, _, _, pts, _ in ring.series("serving_slo_attainment",
                                           _subset(revision, engine))
    ]
    vals = [v for v in vals if v is not None]
    return min(vals) if vals else None


def revision_quantile(ring: HistoryRing, family: str, q: float,
                      revision: str, window_s: Optional[float] = None,
                      now: Optional[float] = None,
                      engine: Optional[str] = None) -> Optional[float]:
    """Worst per-instance windowed quantile of one revision's histogram
    family (e.g. `serving_ttft_seconds_bucket`): per bucket-group
    `quantile_over_window`, max across groups."""
    groups: dict = {}
    for _, labels, _, pts, _ in ring.series(family, _subset(revision, engine)):
        le = labels.get("le")
        if le is None:
            continue
        rest = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
        groups.setdefault(rest, {})[le] = pts
    vals = [
        signals.quantile_over_window(buckets, q, window_s, now)
        for buckets in groups.values()
    ]
    vals = [v for v in vals if v is not None]
    return max(vals) if vals else None


def _family_increase(ring: HistoryRing, family: str, sub: dict,
                     window_s: Optional[float],
                     now: Optional[float]) -> Optional[float]:
    total = None
    for _, _, _, pts, _ in ring.series(family, sub):
        inc = signals.increase(pts, window_s, now)
        if inc is not None:
            total = (total or 0.0) + inc
    return total


def revision_good_fraction(ring: HistoryRing, revision: str,
                           window_s: Optional[float] = None,
                           now: Optional[float] = None,
                           engine: Optional[str] = None) -> Optional[float]:
    """GOOD% for one revision: goodput tokens / tokens over the window."""
    sub = _subset(revision, engine)
    tokens = _family_increase(ring, "serving_tokens_total", sub, window_s, now)
    if not tokens:
        return None
    good = _family_increase(ring, "serving_goodput_tokens_total", sub,
                            window_s, now) or 0.0
    return max(0.0, min(1.0, good / tokens))


def revision_spec_fraction(ring: HistoryRing, revision: str,
                           window_s: Optional[float] = None,
                           now: Optional[float] = None,
                           engine: Optional[str] = None) -> Optional[float]:
    """SPEC% for one revision: accepted / drafted speculative tokens."""
    sub = _subset(revision, engine)
    drafted = _family_increase(ring, "serving_spec_tokens_total",
                               {**sub, "kind": "drafted"}, window_s, now)
    if not drafted:
        return None
    accepted = _family_increase(ring, "serving_spec_tokens_total",
                                {**sub, "kind": "accepted"}, window_s,
                                now) or 0.0
    return max(0.0, min(1.0, accepted / drafted))


def revision_prefix_fraction(ring: HistoryRing, revision: str,
                             window_s: Optional[float] = None,
                             now: Optional[float] = None,
                             engine: Optional[str] = None) -> Optional[float]:
    """PFX% for one revision: prefix-cache hits / (hits + misses)."""
    sub = _subset(revision, engine)
    hits = _family_increase(ring, "serving_prefix_cache_hits_total", sub,
                            window_s, now)
    misses = _family_increase(ring, "serving_prefix_cache_misses_total", sub,
                              window_s, now)
    if hits is None and misses is None:
        return None
    lookups = (hits or 0.0) + (misses or 0.0)
    if lookups <= 0:
        return None
    return (hits or 0.0) / lookups


# ---------------------------------------------------------------------------
# The canary analyzer


@dataclass
class RevisionVerdict:
    """One revision's judgement — JSON-shaped for reports."""

    revision: str
    verdict: str                       # promote | hold | rollback
    reason: str
    samples: float = 0.0
    duration_s: float = 0.0
    short_burn: Optional[float] = None
    long_burn: Optional[float] = None
    baseline_burn: Optional[float] = None
    firing: bool = False

    @property
    def value(self) -> float:
        return VERDICT_VALUES[self.verdict]

    def to_dict(self) -> dict:
        return {
            "revision": self.revision, "verdict": self.verdict,
            "value": self.value, "reason": self.reason,
            "samples": round(self.samples, 3),
            "duration_s": round(self.duration_s, 3),
            "short_burn": self.short_burn, "long_burn": self.long_burn,
            "baseline_burn": self.baseline_burn, "firing": self.firing,
        }


@dataclass
class CanaryReport:
    """One evaluation across every judgeable revision."""

    at: float
    lws: str
    baseline: str = ""
    verdicts: dict = field(default_factory=dict)  # revision -> RevisionVerdict

    def to_dict(self) -> dict:
        return {
            "at": self.at, "lws": self.lws, "baseline": self.baseline,
            "verdicts": {r: v.to_dict() for r, v in self.verdicts.items()},
        }


class CanaryAnalyzer:
    def __init__(
        self,
        ring: HistoryRing,
        lws: str = "-",
        attainment_target: Optional[float] = None,
        windows: Optional[tuple] = None,
        min_samples: Optional[float] = None,
        min_duration_s: Optional[float] = None,
        delta: Optional[float] = None,
        ledger: Optional[RolloutLedger] = None,
        registry=None,
        recorder: Optional[flightrecorder.FlightRecorder] = None,
    ) -> None:
        """`lws` labels the verdict gauge (the deployment under analysis;
        `default_canary_analyzer` syncs it from the store). Guards default
        from env (`LWS_TPU_CANARY_MIN_SAMPLES` / `_MIN_DURATION_S` /
        `_DELTA`); `windows` the burn tiers (default
        `signals.burn_windows()`, env-scalable); `ledger` contributes the
        timeline window a firing-edge event embeds; `registry`/`recorder`
        default to the process ones, injectable for deterministic tests."""
        from lws_tpu.obs.recommend import (
            ATTAINMENT_TARGET_ENV,
            DEFAULT_ATTAINMENT_TARGET,
        )

        self.ring = ring
        self.lws = lws
        self.attainment_target = (
            attainment_target if attainment_target is not None
            else _env_float(ATTAINMENT_TARGET_ENV, DEFAULT_ATTAINMENT_TARGET)
        )
        self.windows = windows if windows is not None else signals.burn_windows()
        self.min_samples = (
            min_samples if min_samples is not None
            else _env_float(MIN_SAMPLES_ENV, DEFAULT_MIN_SAMPLES)
        )
        self.min_duration_s = (
            min_duration_s if min_duration_s is not None
            else _env_float(MIN_DURATION_ENV, DEFAULT_MIN_DURATION_S)
        )
        self.delta = delta if delta is not None else _env_float(
            DELTA_ENV, DEFAULT_DELTA)
        self.ledger = ledger
        self._registry = registry
        self._recorder = (recorder if recorder is not None
                          else flightrecorder.RECORDER)
        self._lock = threading.Lock()
        self._firing: set = set()             # guarded-by: _lock
        self._published_verdicts: set = set()  # guarded-by: _lock
        self._published_burns: set = set()     # guarded-by: _lock
        self._last_verdicts: dict = {}         # guarded-by: _lock

    def _reg(self):
        return self._registry if self._registry is not None else metrics.REGISTRY

    # ---- the evaluation --------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> CanaryReport:
        """One evaluation pass (pure — the RolloutActuator acts on the
        result): burn every revision, apply the guards, judge
        baseline-vs-canary deltas, publish the verdict + revision-burn
        gauges, and drive the edge-triggered `canary:*` alert feed.
        Deterministic under an injected `now`."""
        if now is None:
            now = time.monotonic()
        report = CanaryReport(at=now, lws=self.lws)
        reg = self._reg()
        fast = self.windows[0]

        stats: dict = {}
        burn_gauges: dict = {}  # label tuple -> worst short burn
        for rev in revision_values(self.ring):
            samples, duration = revision_samples(self.ring, rev, now)
            verdicts = revision_burn(self.ring, rev, self.attainment_target,
                                     self.windows, now)
            fast_v = verdicts[0]
            stats[rev] = {
                "samples": samples, "duration": duration, "fast": fast_v,
                "judgeable": (samples >= self.min_samples
                              and duration >= self.min_duration_s),
            }
            # The revision-scoped burn twin, per (engine, revision, window):
            # worst instance wins, same as serving_slo_burn_rate.
            for labels, good, total in revision_goodput_pairs(self.ring, rev):
                for v in signals.multiwindow_burn(
                        good, total, self.attainment_target, self.windows,
                        now):
                    if v.short_burn is None:
                        continue
                    gauge_labels = tuple(sorted({
                        "engine": labels.get("engine", ""),
                        "revision": rev, "window": v.window,
                    }.items()))
                    if v.short_burn > burn_gauges.get(gauge_labels, -1.0):
                        burn_gauges[gauge_labels] = v.short_burn

        firing_now: set = set()
        for rev, st in stats.items():
            fast_v = st["fast"]
            others = [
                s["fast"].short_burn for r, s in stats.items()
                if r != rev and s["judgeable"]
                and s["fast"].short_burn is not None
            ]
            baseline_burn = min(others) if others else None
            if not st["judgeable"]:
                rv = RevisionVerdict(
                    rev, "hold",
                    f"insufficient data ({st['samples']:.0f} tokens over "
                    f"{st['duration']:.1f}s; need >= {self.min_samples:g} "
                    f"over {self.min_duration_s:g}s)",
                )
            elif fast_v.firing and baseline_burn is not None and \
                    (fast_v.short_burn or 0.0) - baseline_burn >= self.delta:
                rv = RevisionVerdict(
                    rev, "rollback",
                    f"fast burn {fast_v.short_burn:.1f}x vs baseline "
                    f"{baseline_burn:.1f}x (delta >= {self.delta:g})",
                    firing=True,
                )
            elif fast_v.firing:
                rv = RevisionVerdict(
                    rev, "hold",
                    "burning but not revision-attributable (no healthy "
                    "baseline to compare against)",
                    firing=True,
                )
            else:
                rv = RevisionVerdict(
                    rev, "promote",
                    f"within budget (fast burn "
                    f"{fast_v.short_burn if fast_v.short_burn is not None else 0:.2f}x)",
                )
            rv.samples = st["samples"]
            rv.duration_s = st["duration"]
            rv.short_burn = fast_v.short_burn
            rv.long_burn = fast_v.long_burn
            rv.baseline_burn = baseline_burn
            report.verdicts[rev] = rv
            if rv.verdict == "rollback":
                firing_now.add(rev)
                self._hold_alert(rev, rv, now)

        # Deterministic baseline: the judgeable revision with the most
        # delivered tokens (ties break lexicographically) — the incumbent.
        judgeable = [r for r, s in stats.items() if s["judgeable"]]
        if judgeable:
            report.baseline = min(
                judgeable, key=lambda r: (-stats[r]["samples"], r))

        verdict_gauges = {
            tuple(sorted({"lws": self.lws, "revision": r}.items())): v.value
            for r, v in report.verdicts.items()
        }
        for labels_t, value in verdict_gauges.items():
            reg.set("lws_rollout_canary_verdict", value, dict(labels_t))
        for labels_t, burn in burn_gauges.items():
            reg.set("serving_slo_burn_rate_by_revision", burn, dict(labels_t))
        self._clear_alerts(firing_now, now)
        # Retire gauges whose revision left the ring (aged-out canary, torn
        # down fleet) — a frozen rollback verdict is a phantom incident.
        with self._lock:
            stale_verdicts = self._published_verdicts - set(verdict_gauges)
            self._published_verdicts = set(verdict_gauges)
            stale_burns = self._published_burns - set(burn_gauges)
            self._published_burns = set(burn_gauges)
            changed = {
                r: v.verdict for r, v in report.verdicts.items()
                if self._last_verdicts.get(r) != v.verdict
            }
            self._last_verdicts = {
                r: v.verdict for r, v in report.verdicts.items()
            }
        for labels_t in stale_verdicts:
            reg.clear_gauge("lws_rollout_canary_verdict", dict(labels_t),
                            exact=True)
        for labels_t in stale_burns:
            reg.clear_gauge("serving_slo_burn_rate_by_revision",
                            dict(labels_t), exact=True)
        if self.ledger is not None:
            for rev, verdict in changed.items():
                self.ledger.record("canary_verdict", obj=self.lws,
                                   revision=rev, now=now, verdict=verdict,
                                   reason=report.verdicts[rev].reason)
        return report

    # ---- edge-triggered alert feed ---------------------------------------
    def _hold_alert(self, rev: str, rv: RevisionVerdict, now: float) -> None:
        """While a revision's regression verdict holds, pin its
        `canary:{lws}/{revision}` heartbeat at depth 1 (the `circuit_open`
        convention: the Watchdog's `canary_regression` rule fires once per
        episode). The NEW-episode edge records a ring event embedding the
        offending revision's error-series window AND the rollout-ledger
        window — the next watchdog dump ships the full evidence."""
        key = f"{self.lws}/{rev}"
        with self._lock:
            new_edge = key not in self._firing
            self._firing.add(key)
        self._recorder.beat(f"canary:{key}", progress=0.0, depth=1.0, now=now)
        if new_edge:
            window: list = []
            for _, good, total in revision_goodput_pairs(self.ring, rev):
                series = signals.error_series(good, total)
                if len(series) > len(window):
                    window = series
            ledger_window = (
                self.ledger.snapshot(limit=EVENT_LEDGER_ENTRIES, now=now)
                if self.ledger is not None else []
            )
            self._recorder.record(
                "canary_regression_fired",
                lws=self.lws,
                revision=rev,
                baseline_burn=rv.baseline_burn,
                short_burn=rv.short_burn,
                long_burn=rv.long_burn,
                threshold=self.windows[0].threshold,
                error_window=[[t, v] for t, v
                              in window[-EVENT_WINDOW_POINTS:]],
                ledger_window=ledger_window,
            )

    def _clear_alerts(self, firing_now: set, now: float) -> None:
        with self._lock:
            cleared = self._firing - {f"{self.lws}/{r}" for r in firing_now}
            self._firing = {f"{self.lws}/{r}" for r in firing_now}
        for key in cleared:
            self._recorder.beat(f"canary:{key}", progress=1.0, depth=0.0,
                                now=now)


# Process-default analyzer over the process history ring + ledger: the
# control plane evaluates it per fleet-history ingest (runtime/server.py),
# so the verdict/burn gauges and the `canary_regression` alert feed exist
# on every live deployment without wiring. The analyzer itself never
# mutates the store: acting on its reports is the RolloutActuator's job
# (obs/decisions.py — on by default, LWS_TPU_ACTUATION_DISABLE=rollout to
# record only).
ANALYZER: Optional[CanaryAnalyzer] = None
_ANALYZER_LOCK = threading.Lock()


def default_canary_analyzer(store=None) -> CanaryAnalyzer:
    """The process-default analyzer; with `store`, its `lws` target label
    re-syncs to the store's first LeaderWorkerSet before the caller
    evaluates."""
    global ANALYZER
    with _ANALYZER_LOCK:
        if ANALYZER is None:
            from lws_tpu.obs.history import HISTORY

            ANALYZER = CanaryAnalyzer(HISTORY, ledger=LEDGER)
        if store is not None:
            names = sorted(
                f"{o.meta.namespace}/{o.meta.name}"
                for o in store.list("LeaderWorkerSet")
            )
            if names:
                ANALYZER.lws = names[0]
        return ANALYZER


# ---------------------------------------------------------------------------
# The opt-in actuation seam


class RolloutActuationAdapter:
    """Act on a rollback verdict through the stock rollout machinery:
    `pause()` freezes the rolling update by raising the partition to the
    replica count (groups below the partition are never updated — the
    existing canary/xPyD semantics), and `rollback(revision_key)` restores
    the template from the named ControllerRevision via the same
    `utils/revision.py` path the controller uses, so the rollout controller
    itself walks the fleet back. Driven by the edge-triggered
    `RolloutActuator` (obs/decisions.py) when a canary regression fires —
    behind the `LWS_TPU_ACTUATION_DISABLE=rollout` kill switch; still
    usable directly for manual rollbacks."""

    def __init__(self, store, namespace: str, target: str) -> None:
        self.store = store
        self.namespace = namespace
        self.target = target

    def _retry_update(self, mutate) -> bool:
        from lws_tpu.core.store import ConflictError

        for _ in range(3):  # optimistic-concurrency retries
            lws = self.store.get("LeaderWorkerSet", self.namespace,
                                 self.target)
            if lws is None:
                return False
            if not mutate(lws):
                return False
            try:
                self.store.update(lws)
                return True
            except ConflictError:
                continue
        return False

    def pause(self) -> bool:
        """Freeze the rollout where it stands: partition = replicas means
        every group index is below the partition, so no further group is
        updated until an operator (or a rollback) moves it."""
        def mutate(lws) -> bool:
            ru = lws.spec.rollout_strategy.rolling_update_configuration
            ru.partition = int(lws.spec.replicas)
            return True

        return self._retry_update(mutate)

    def rollback(self, revision_key: str) -> bool:
        """Restore the LWS template from the named ControllerRevision and
        release the partition — the stock controller then rolls every
        group back to the restored (now-current) template."""
        from lws_tpu.utils import revision as revisionutils

        def mutate(lws) -> bool:
            rev = revisionutils.get_revision(self.store, lws, revision_key)
            if rev is None:
                return False
            restored = revisionutils.apply_revision(lws, rev)
            lws.spec.leader_worker_template = \
                restored.spec.leader_worker_template
            lws.spec.network_config = restored.spec.network_config
            lws.spec.rollout_strategy.rolling_update_configuration.partition = 0
            return True

        return self._retry_update(mutate)

    def apply(self, report: CanaryReport) -> dict:
        """Act on a CanaryReport: when any non-baseline revision's verdict
        is `rollback` and a judged baseline exists, pause the rollout and
        restore the baseline revision. Returns what was done."""
        offenders = [
            r for r, v in report.verdicts.items()
            if v.verdict == "rollback" and r != report.baseline
        ]
        if not offenders or not report.baseline:
            return {"acted": False, "offenders": offenders}
        paused = self.pause()
        rolled_back = self.rollback(report.baseline)
        return {
            "acted": rolled_back, "paused": paused,
            "rolled_back_to": report.baseline, "offenders": offenders,
        }
