"""Pure derived signals over retained series: the math between sensor and
actuator.

Every function here is a pure fold over `[(t, value), ...]` point lists
(the `HistoryRing`'s window shape) with an explicit `now` — no clocks, no
I/O, no registries — so the recommender's decisions and the monitor's
columns are unit-testable from canned points. Counter-shaped inputs are
assumed RESET-ADJUSTED (the ring guarantees it), which is why `rate` and
`increase` clamp at zero instead of guessing at resets themselves.

The burn-rate functions implement SRE-workbook multi-window multi-burn-rate
alerting over the serving SLO series: an error budget of `1 - target`
burning at rate B exhausts in `window/B`; paging fires only when BOTH a
fast window (default 5m, threshold 14.4x) and its long confirmation window
(1h) burn hot — a blip trips neither, a real incident trips both within
minutes. The canonical windows are wall-scale; `LWS_TPU_BURN_WINDOW_SCALE`
(or an explicit `scale=`) shrinks them proportionally to the ring's
resolution — CPU tests and second-scale scenario runs use the same math at
1/100th the wall clock.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, replace
from typing import Optional

Points = list  # [(t_seconds, value)] — the HistoryRing window shape


def clip(points: Points, window_s: Optional[float],
         now: Optional[float]) -> Points:
    """The trailing `window_s` of `points` (all of them when unbounded)."""
    if window_s is None or now is None:
        return list(points)
    cutoff = now - window_s
    return [p for p in points if p[0] >= cutoff]


def last(points: Points) -> Optional[float]:
    return points[-1][1] if points else None


def increase(points: Points, window_s: Optional[float] = None,
             now: Optional[float] = None) -> Optional[float]:
    """Total growth of a (reset-adjusted) cumulative series over the
    window: last - first, clamped at zero. None below two points — one
    sample carries no delta, and fabricating 0.0 would render a fake calm
    column (the `lws-tpu top` first-frame bug this plane cures)."""
    pts = clip(points, window_s, now)
    if len(pts) < 2:
        return None
    return max(0.0, pts[-1][1] - pts[0][1])


def rate(points: Points, window_s: Optional[float] = None,
         now: Optional[float] = None) -> Optional[float]:
    """Per-second growth over the window (increase / observed span). The
    denominator is the span actually covered by samples, so a skipped
    scrape widens the window instead of corrupting the rate."""
    pts = clip(points, window_s, now)
    if len(pts) < 2:
        return None
    span = pts[-1][0] - pts[0][0]
    if span <= 0:
        return None
    return max(0.0, pts[-1][1] - pts[0][1]) / span


def mean(points: Points, window_s: Optional[float] = None,
         now: Optional[float] = None) -> Optional[float]:
    """Time-weighted mean of a gauge over the window (each value holds
    until the next sample; simple mean would over-weight scrape bursts)."""
    pts = clip(points, window_s, now)
    if not pts:
        return None
    if len(pts) == 1:
        return pts[0][1]
    acc = 0.0
    for (t0, v0), (t1, _) in zip(pts, pts[1:]):
        acc += v0 * (t1 - t0)
    span = pts[-1][0] - pts[0][0]
    if span <= 0:
        return pts[-1][1]
    return acc / span


def ewma(points: Points, tau_s: float, window_s: Optional[float] = None,
         now: Optional[float] = None) -> Optional[float]:
    """Exponentially-weighted moving average with time constant `tau_s`
    (irregular sampling handled per-gap: alpha = 1 - exp(-dt/tau)) — the
    smoothing the monitor's trend columns use so one noisy scrape doesn't
    flip a recommendation."""
    import math

    pts = clip(points, window_s, now)
    if not pts:
        return None
    acc = pts[0][1]
    for (t0, _), (t1, v1) in zip(pts, pts[1:]):
        alpha = 1.0 - math.exp(-max(0.0, t1 - t0) / tau_s) if tau_s > 0 else 1.0
        acc += alpha * (v1 - acc)
    return acc


def slope(points: Points, window_s: Optional[float] = None,
          now: Optional[float] = None) -> Optional[float]:
    """Least-squares trend of a gauge in value/second — the KV-occupancy
    "filling vs draining" signal the decode recommendation consumes."""
    pts = clip(points, window_s, now)
    if len(pts) < 2:
        return None
    n = len(pts)
    mt = sum(t for t, _ in pts) / n
    mv = sum(v for _, v in pts) / n
    denom = sum((t - mt) ** 2 for t, _ in pts)
    if denom <= 0:
        return None
    return sum((t - mt) * (v - mv) for t, v in pts) / denom


def error_series(good: Points, total: Points) -> Points:
    """Pointwise error-fraction series from two cumulative counters: at
    each successive TOTAL sample pair, 1 - dgood/dtotal (skipping gaps
    where nothing was delivered). The good series is carried forward
    between its samples and defaults to zero when absent entirely — an
    all-late workload never creates the goodput counter at all, and that
    is a 100% error series, not a missing one. This is the series a burn
    alert embeds in its flight-recorder dump — the offending window,
    legible."""
    goods = sorted(good)
    out: Points = []
    prev: Optional[tuple] = None
    gi = 0
    g = 0.0
    for t, tot in sorted(total):
        while gi < len(goods) and goods[gi][0] <= t:
            g = goods[gi][1]
            gi += 1
        if prev is not None:
            dg, dt = g - prev[1], tot - prev[2]
            if dt > 0:
                out.append((t, max(0.0, min(1.0, 1.0 - dg / dt))))
        prev = (t, g, tot)
    return out


# ---------------------------------------------------------------------------
# Histogram folds


def histogram_quantile(buckets: list, q: float) -> Optional[float]:
    """Estimate a quantile from cumulative `(le, count)` pairs — the PromQL
    histogram_quantile shape, linear within the winning bucket. (`lws-tpu
    top` renders its p95 columns through this same function.)"""
    if not buckets:
        return None
    buckets = sorted(buckets, key=lambda b: b[0])
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_cum = 0.0, 0.0
    for le, cum in buckets:
        if cum >= rank:
            if le == float("inf"):
                return prev_le  # open-ended bucket: report its lower bound
            span = cum - prev_cum
            frac = (rank - prev_cum) / span if span > 0 else 1.0
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = le, cum
    return buckets[-1][0]


def quantile_over_window(bucket_points: dict, q: float,
                         window_s: Optional[float] = None,
                         now: Optional[float] = None) -> Optional[float]:
    """Quantile of the observations that arrived WITHIN the window:
    `bucket_points` maps the `le` label (str) to that bucket's retained
    cumulative-count points; per-bucket `increase` over the window rebuilds
    the window's own cumulative histogram. A lifetime quantile can't sag
    back down after one bad hour — this one can."""
    buckets = []
    for le, pts in bucket_points.items():
        inc = increase(pts, window_s, now)
        if inc is None:
            continue
        le_f = float("inf") if le in ("+Inf", "inf") else float(le)
        buckets.append((le_f, inc))
    return histogram_quantile(buckets, q)


def breach_fraction(bucket_points: dict, target: float,
                    window_s: Optional[float] = None,
                    now: Optional[float] = None) -> Optional[float]:
    """Fraction of the window's observations that EXCEEDED `target`,
    from bucket increases: 1 - (count in the smallest bucket covering the
    target) / (total count). The per-phase error rate (TTFT over target,
    queue wait over target) the role recommendations burn against;
    conservative when the target falls between bucket bounds (the covering
    bucket may admit some over-target samples)."""
    total = None
    covering: Optional[tuple] = None
    widest: Optional[tuple] = None
    for le, pts in bucket_points.items():
        inc = increase(pts, window_s, now)
        if inc is None:
            continue
        le_f = float("inf") if le in ("+Inf", "inf") else float(le)
        if le_f == float("inf"):
            total = inc
            continue
        if le_f >= target and (covering is None or le_f < covering[0]):
            covering = (le_f, inc)
        if widest is None or le_f > widest[0]:
            widest = (le_f, inc)
    if total is None or total <= 0:
        return None
    if covering is None:
        # Target past every finite bucket: everything the widest bucket
        # counted is certainly within target; only the open-ended tail
        # MIGHT breach — still counted, staying conservative.
        covering = widest
    good = covering[1] if covering is not None else 0.0
    return max(0.0, min(1.0, 1.0 - good / total))


# ---------------------------------------------------------------------------
# Multi-window multi-burn-rate (SRE-workbook shape)


BURN_WINDOW_SCALE_ENV = "LWS_TPU_BURN_WINDOW_SCALE"


@dataclass(frozen=True)
class BurnWindow:
    """One page/ticket tier: a short window that reacts and a long window
    that confirms; both must burn past `threshold` to fire."""

    name: str
    short_s: float
    long_s: float
    threshold: float

    def scaled(self, scale: float) -> "BurnWindow":
        return replace(self, short_s=self.short_s * scale,
                       long_s=self.long_s * scale)


# The SRE-workbook page tier (5m/1h at 14.4x: 2% of a 30-day budget in an
# hour) and ticket tier (1h/6h at 6x), wall-scale.
DEFAULT_BURN_WINDOWS = (
    BurnWindow("fast", 300.0, 3600.0, 14.4),
    BurnWindow("slow", 3600.0, 21600.0, 6.0),
)


def burn_windows(scale: Optional[float] = None) -> tuple:
    """The default tiers scaled to the deployment's ring resolution:
    `scale` (or LWS_TPU_BURN_WINDOW_SCALE) multiplies both windows of each
    tier; thresholds are scale-free (a burn RATE is already normalized by
    its window)."""
    if scale is None:
        try:
            scale = float(os.environ.get(BURN_WINDOW_SCALE_ENV, 1.0))
        except ValueError:
            scale = 1.0
    if scale == 1.0:
        return DEFAULT_BURN_WINDOWS
    return tuple(w.scaled(scale) for w in DEFAULT_BURN_WINDOWS)


def burn_rate_from_counters(good: Points, total: Points, target: float,
                            window_s: float,
                            now: Optional[float] = None) -> Optional[float]:
    """Error-budget burn over one window from the goodput ledger pair:
    (error fraction of the window's tokens) / (1 - target). Burn 1.0 means
    the budget exhausts exactly at the SLO horizon; 14.4 means 2% of a
    30-day budget per hour — page territory."""
    budget = 1.0 - target
    if budget <= 0:
        return None
    dtotal = increase(total, window_s, now)
    if not dtotal:
        return None
    dgood = increase(good, window_s, now) or 0.0
    err = max(0.0, min(1.0, 1.0 - dgood / dtotal))
    return err / budget


def burn_rate_from_gauge(err_points: Points, target: float, window_s: float,
                         now: Optional[float] = None) -> Optional[float]:
    """Burn over one window from an error-fraction gauge series (e.g.
    `1 - serving_slo_attainment` samples): mean error over the window /
    budget. The attainment-series twin of `burn_rate_from_counters`."""
    budget = 1.0 - target
    if budget <= 0:
        return None
    err = mean(err_points, window_s, now)
    if err is None:
        return None
    return max(0.0, err) / budget


@dataclass(frozen=True)
class BurnVerdict:
    window: str
    short_burn: Optional[float]
    long_burn: Optional[float]
    threshold: float

    @property
    def firing(self) -> bool:
        """Both windows must burn past the threshold — the blip-proof AND
        of the multi-window rule. An unevaluable window (too few points)
        never fires: alerting on absence of data is the watchdog rules'
        job, not the burn math's."""
        return (
            self.short_burn is not None and self.long_burn is not None
            and self.short_burn >= self.threshold
            and self.long_burn >= self.threshold
        )


def multiwindow_burn(good: Points, total: Points, target: float,
                     windows: Optional[tuple] = None,
                     now: Optional[float] = None) -> list:
    """[BurnVerdict per tier] over a goodput counter pair: the full
    page/ticket evaluation one (engine, klass) series feeds. Callers fold
    `any(v.firing for v in ...)` into alerts and recommendations."""
    out = []
    for w in (windows if windows is not None else burn_windows()):
        out.append(BurnVerdict(
            window=w.name,
            short_burn=burn_rate_from_counters(good, total, target, w.short_s, now),
            long_burn=burn_rate_from_counters(good, total, target, w.long_s, now),
            threshold=w.threshold,
        ))
    return out
