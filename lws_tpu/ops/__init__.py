"""TPU kernels: pallas flash attention (single-chip hot path) and ring
attention over a context-parallel mesh axis (long-context). Reference jnp
implementations back every kernel for CPU testing and GSPMD paths."""

from lws_tpu.ops.attention import flash_attention, reference_attention  # noqa: F401
from lws_tpu.ops.ring import ring_attention  # noqa: F401
