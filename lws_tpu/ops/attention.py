"""Flash attention (pallas, TPU): online-softmax tiling so the [S, S] score
matrix never materializes in HBM — scores live in VMEM tiles feeding the MXU.

Layout: q [B, S, H, D], k/v [B, S, Hkv, D] (GQA: Hkv | H). Grid is
(B, H, S/block_q); each program streams K/V blocks for its (b, kv-head) with
f32 accumulators. Causal programs stop at their diagonal block (no wasted
FLOPs on the upper triangle).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


NEG_INF = -1e30


def reference_attention(q, k, v, causal: bool = True):
    """jnp GQA attention (f32 softmax) — numerics oracle + CPU/GSPMD path."""
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    qg = q.reshape(B, S, Hkv, G, D)
    scores = jnp.einsum("bqkgh,bskh->bkgqs", qg, k).astype(jnp.float32) * D**-0.5
    if causal:
        Sk = k.shape[1]
        mask = jnp.arange(S)[:, None] >= jnp.arange(Sk)[None, :]
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgqs,bskh->bqkgh", probs, v)
    return out.reshape(B, S, H, D)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, scale: float, causal: bool):
    from jax.experimental import pallas as pl

    block_q = q_ref.shape[2]
    seq_k = k_ref.shape[2]  # k_ref block is [1, 1, Skv, D]
    d = q_ref.shape[-1]
    qi = pl.program_id(2)
    q_start = qi * block_q

    q = q_ref[0, 0].astype(jnp.float32) * scale  # [bq, d]

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    n_kb = seq_k // block_k
    if causal:
        # Only blocks up to (and including) the diagonal contribute.
        upper = jax.lax.div(q_start + block_q + block_k - 1, block_k)
        upper = jnp.minimum(upper, n_kb)
    else:
        upper = n_kb

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)  # [bk, d]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
):
    """q [B,S,H,D], k/v [B,Skv,Hkv,D] -> [B,S,H,D]. Pads S/Skv to block
    multiples internally (padded keys are masked out)."""
    from jax.experimental import pallas as pl

    B, S, H, D = q.shape
    Skv, Hkv = k.shape[1], k.shape[2]
    G = H // Hkv
    block_q = min(block_q, _round_up(S, 128))
    block_k = min(block_k, _round_up(Skv, 128))

    s_pad = _round_up(S, block_q)
    skv_pad = _round_up(Skv, block_k)
    if s_pad != S:
        q = jnp.pad(q, ((0, 0), (0, s_pad - S), (0, 0), (0, 0)))
    if skv_pad != Skv:
        # Padded keys sit at positions >= Skv; with causal masking every real
        # query (pos < S <= Skv under self-attention) ignores them. For
        # non-causal, mask via a huge negative bias trick: zero K works only
        # with explicit masking, so pad K with zeros and rely on causal; the
        # non-causal path requires Skv % block_k == 0.
        if not causal:
            raise ValueError("non-causal flash requires Skv divisible by block_k")
        k = jnp.pad(k, ((0, 0), (0, skv_pad - Skv), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, skv_pad - Skv), (0, 0), (0, 0)))

    grid = (B, H, s_pad // block_q)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, scale=D**-0.5, causal=causal
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, H, s_pad, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, skv_pad, D), lambda b, h, i, G=G: (b, h // G, 0, 0)),
            pl.BlockSpec((1, 1, skv_pad, D), lambda b, h, i, G=G: (b, h // G, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
        interpret=interpret,
        # all inputs indexed as [B, heads, S, D]
    )(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3))
    out = out.transpose(0, 2, 1, 3)  # [B, s_pad, H, D]
    return out[:, :S]


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def attention(q, k, v, causal: bool = True, impl: str = "auto"):
    """Dispatch: pallas flash on TPU backends, reference elsewhere."""
    if impl == "reference":
        return reference_attention(q, k, v, causal)
    if impl == "flash":
        return flash_attention(q, k, v, causal)
    backend = jax.default_backend()
    if backend in ("tpu", "axon"):
        return flash_attention(q, k, v, causal)
    return reference_attention(q, k, v, causal)
