"""Decode attention over an int8 KV cache (pallas).

The XLA int8-KV path dequantizes the ENTIRE cache view into a bf16 copy
every step (models/llama.py _block_with_cache kv_quant branch) — reading
int8 and then writing+rereading bf16 spends ~3x the bandwidth the
quantization saved, which is why int8 KV measured slower than bf16
(2633 tok/s @ B=32 vs 2681 @ B=16). This kernel DMAs the int8 tiles
straight out of the cache's native [B, T, Hkv, hd] layout (strided block
specs — no transposed or dequantized copies ever hit HBM), dequantizes
in-register per (token, kv-head) scale, and fuses the whole decode
attention for one (batch, kv-head) pair. Grouped-query: the G = H/Hkv query
heads sharing a kv head are processed together, so each K/V tile is loaded
once and reused G times.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _kernel(q_ref, kq_ref, ks_ref, vq_ref, vs_ref, bias_ref, o_ref, *, scale: float):
    q = q_ref[0, 0].astype(jnp.float32)                        # [G, hd]
    k = kq_ref[0, :, 0, :].astype(jnp.float32) * ks_ref[0, :, 0][:, None]  # [T, hd]
    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale                                                  # [G, T]
    scores = scores + bias_ref[0]                              # [T] broadcasts
    probs = jax.nn.softmax(scores, axis=-1)
    v = vq_ref[0, :, 0, :].astype(jnp.float32) * vs_ref[0, :, 0][:, None]
    o_ref[0, 0] = jnp.dot(probs, v, preferred_element_type=jnp.float32).astype(
        o_ref.dtype
    )


def int8_decode_attention(
    q: jax.Array,        # [B, 1, H, hd] (compute dtype)
    kq: jax.Array,       # [B, T, Hkv, hd] int8 (cache-native layout)
    k_scale: jax.Array,  # [B, T, Hkv] f32
    vq: jax.Array,
    v_scale: jax.Array,
    pos,                 # scalar or [B]: the CURRENT write position (attendable)
    interpret: bool = False,
) -> jax.Array:
    """Returns [B, 1, H, hd] in q.dtype. Key positions > pos are masked
    (same contract as models.llama._cached_attention with S=1)."""
    from jax.experimental import pallas as pl

    B, S, H, hd = q.shape
    assert S == 1, "decode kernel: single query position"
    T, Hkv = kq.shape[1], kq.shape[2]
    G = H // Hkv
    key_pos = jnp.arange(T)
    bias = jnp.where(
        key_pos[None, :] <= jnp.reshape(pos, (-1, 1)), 0.0, -1e30
    ).astype(jnp.float32)
    bias = jnp.broadcast_to(bias, (B, T))

    qg = q[:, 0].reshape(B, Hkv, G, hd)  # tiny; fine to materialize

    out = pl.pallas_call(
        functools.partial(_kernel, scale=hd**-0.5),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, hd), q.dtype),
        grid=(B, Hkv),
        in_specs=[
            pl.BlockSpec((1, 1, G, hd), lambda b, h: (b, h, 0, 0)),
            pl.BlockSpec((1, T, 1, hd), lambda b, h: (b, 0, h, 0)),
            pl.BlockSpec((1, T, 1), lambda b, h: (b, 0, h)),
            pl.BlockSpec((1, T, 1, hd), lambda b, h: (b, 0, h, 0)),
            pl.BlockSpec((1, T, 1), lambda b, h: (b, 0, h)),
            pl.BlockSpec((1, T), lambda b, h: (b, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, G, hd), lambda b, h: (b, h, 0, 0)),
        interpret=interpret,
    )(qg, kq, k_scale, vq, v_scale, bias)
    return out.reshape(B, 1, H, hd)
