"""Fused int8-weight matmul for decode (pallas).

Decode streams every weight byte each step, so int8 weights should halve the
HBM time — but XLA's `x @ q.astype(bf16)` materializes a full dequantized
copy of each weight in HBM-adjacent buffers, spending the bandwidth it was
supposed to save (measured: int8 via XLA LOST to bf16, 2633 vs 2681 tok/s).
This kernel reads the int8 tile into VMEM, converts in-register (VPU), feeds
the MXU in bf16, and applies the per-output-channel scale on the f32
accumulator — weight HBM traffic stays int8 end to end.

Layout contract matches models.quant.QuantizedArray: q int8 [D, F], scale
f32 [F] over output channels, so out = (x @ q) * scale exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Tile sizes: q tile Kb x Fb int8 = 128 KB VMEM; x tile Tm x Kb bf16 <= 256 KB.
_KB = 512
_FB = 256
_TM_MAX = 256


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_k: int):
    from jax.experimental import pallas as pl

    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    xb = x_ref[:]
    wb = q_ref[:].astype(xb.dtype)  # int8 -> compute dtype, in-register
    acc_ref[:] += jnp.dot(xb, wb, preferred_element_type=jnp.float32)

    @pl.when(k == n_k - 1)
    def _finish():
        o_ref[:] = (acc_ref[:] * s_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def supported(m: int, d: int, f: int) -> bool:
    """Shapes this kernel handles; callers fall back to XLA otherwise.
    m <= _TM_MAX gates it to DECODE-shaped matmuls — prefill is
    compute-bound, where XLA's native scheduling wins."""
    return m <= _TM_MAX and d % _KB == 0 and f % _FB == 0


def int8_matmul(x: jax.Array, q: jax.Array, scale: jax.Array, interpret: bool = False):
    """x [..., D] x (q int8 [D, F], scale f32 [F]) -> [..., F] in x.dtype."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    *lead, d = x.shape
    f = q.shape[1]
    x2 = x.reshape(-1, d)
    m = x2.shape[0]
    tm = m if m >= 8 else 8
    tm = min(_TM_MAX, -(-tm // 8) * 8)
    m_pad = -(-m // tm) * tm
    if m_pad != m:
        x2 = jnp.pad(x2, ((0, m_pad - m), (0, 0)))
    n_k = d // _KB
    out = pl.pallas_call(
        functools.partial(_kernel, n_k=n_k),
        out_shape=jax.ShapeDtypeStruct((m_pad, f), x.dtype),
        grid=(m_pad // tm, f // _FB, n_k),
        in_specs=[
            pl.BlockSpec((tm, _KB), lambda i, j, k: (i, k)),
            pl.BlockSpec((_KB, _FB), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, _FB), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((tm, _FB), lambda i, j, k: (i, j)),
        scratch_shapes=[pltpu.VMEM((tm, _FB), jnp.float32)],
        interpret=interpret,
    )(x2, q, scale.reshape(1, f))
    return out[:m].reshape(*lead, f)
