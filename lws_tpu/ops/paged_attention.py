"""Paged decode attention over a block-pool KV cache (pallas).

The XLA paged path materializes every slot's FULL logical cache view per
layer per step (`k_l[block_table]` gather in models/llama.py
forward_decode_paged) — random-access gather traffic that made 128 paged
slots run at ~40% of the dense Engine's throughput. This kernel reads each
slot's KV blocks IN PLACE from the pool:

  * the block table and per-slot positions are scalar-prefetched, and the
    K/V index maps resolve (layer, pool_block) per grid step — the DMA
    fetches exactly the addressed [block_size, Hkv, hd] tile, nothing else;
  * grid = (B, max_blocks) with the block index innermost; chunks past a
    slot's live length map to its LAST live block, so the pipeline's
    revisiting logic elides their copies — HBM traffic is the LIVE tokens,
    not slots x max_len;
  * flash-style online softmax (running max / sum / accumulator in VMEM
    scratch) across a slot's chunks; grouped-query heads share each K/V
    tile load.

Same contract as models.llama._cached_attention with S=1: key positions
<= pos are attendable (pos = the slot's current write position).
vLLM's PagedAttention is the competitor shape
(/root/reference/docs/examples/vllm/TPU/lws.yaml:22-34); this is the
TPU-native re-design, not a translation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _kernel(
    table_ref,  # [B, max_blocks] int32 (SMEM, scalar-prefetch)
    pos_ref,    # [B] int32
    layer_ref,  # [1] int32
    q_ref,      # [1, Hkv, G, hd]
    k_ref,      # [1, 1, bs, Hkv, hd]
    v_ref,      # [1, 1, bs, Hkv, hd]
    *rest,      # [ks_ref, vs_ref,] o_ref, m_ref, l_ref, acc_ref
    scale: float,
    block_size: int,
    quant: bool,
):
    from jax.experimental import pallas as pl

    if quant:  # int8 pool: per-(token, head) scales ride along
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest

    b = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    pos = pos_ref[b]
    n_live = pos // block_size + 1  # blocks holding attendable tokens

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(j < n_live)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32) * scale          # [Hkv, G, hd]
        k = k_ref[0, 0].astype(jnp.float32)               # [bs, Hkv, hd]
        if quant:  # dequantize in-register; int8 is what crossed HBM
            k = k * ks_ref[0, 0][..., None]
        kt = k.transpose(1, 2, 0)                         # [Hkv, hd, bs]
        s = jax.lax.dot_general(
            q, kt, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )                                                 # [Hkv, G, bs]
        token_idx = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, block_size), 2
        )
        s = jnp.where(token_idx <= pos, s, _NEG_INF)
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))  # [Hkv, G]
        alpha = jnp.exp(m_prev - m_new)                   # j==0: exp(-1e30-m)=0
        p = jnp.exp(s - m_new[..., None])                 # [Hkv, G, bs]
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=-1)
        v = v_ref[0, 0].astype(jnp.float32)               # [bs, Hkv, hd]
        if quant:
            v = v * vs_ref[0, 0][..., None]
        vt = v.transpose(1, 0, 2)                         # [Hkv, bs, hd]
        pv = jax.lax.dot_general(
            p, vt, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )                                                 # [Hkv, G, hd]
        acc_ref[...] = acc_ref[...] * alpha[..., None] + pv
        m_ref[...] = m_new

    @pl.when(j == nj - 1)
    def _finish():
        o_ref[0] = (acc_ref[...] / l_ref[...][..., None]).astype(o_ref.dtype)


def paged_decode_attention(
    q: jax.Array,            # [B, 1, H, hd] (compute dtype)
    k_pool: jax.Array,       # [L, num_blocks, bs, Hkv, hd] (cache pool, whole)
    v_pool: jax.Array,       # same
    block_table: jax.Array,  # [B, max_blocks] int32 (slot -> pool blocks)
    pos_b: jax.Array,        # [B] int32: each slot's current write position
    layer_idx,               # int (unrolled loop) or int32 scalar
    k_scale: jax.Array | None = None,  # [L, num_blocks, bs, Hkv] f32 (int8 pool)
    v_scale: jax.Array | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Returns [B, 1, H, hd] in q.dtype. The pool is passed WHOLE (no
    per-layer slice — a slice operand would make XLA materialize a layer
    copy, re-creating the traffic this kernel exists to kill); the layer is
    resolved inside the index maps. With k_scale/v_scale the pool is int8
    and dequantization happens in-register per tile — int8 is what crosses
    HBM, composing paged density with KV quantization."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, hd = q.shape
    assert S == 1, "decode kernel: single query position"
    _, _, bs, Hkv, _ = k_pool.shape
    max_blocks = block_table.shape[1]
    G = H // Hkv
    quant = k_scale is not None

    qg = q[:, 0].reshape(B, Hkv, G, hd)  # tiny; fine to materialize
    layer_arr = jnp.asarray(layer_idx, jnp.int32).reshape(1)
    table = block_table.astype(jnp.int32)
    pos_arr = pos_b.astype(jnp.int32).reshape(B)

    def kv_index(b, j, table_ref, pos_ref, layer_ref):
        # Dead chunks (j >= live blocks) revisit the last live block: the
        # pipeline elides the repeated copy, so they cost no HBM traffic.
        n_live = pos_ref[b] // bs + 1
        jj = jnp.minimum(j, n_live - 1)
        return (layer_ref[0], table_ref[b, jj], 0, 0, 0)

    def scale_index(b, j, table_ref, pos_ref, layer_ref):
        n_live = pos_ref[b] // bs + 1
        jj = jnp.minimum(j, n_live - 1)
        return (layer_ref[0], table_ref[b, jj], 0, 0)

    in_specs = [
        pl.BlockSpec((1, Hkv, G, hd), lambda b, j, *_: (b, 0, 0, 0)),
        pl.BlockSpec((1, 1, bs, Hkv, hd), kv_index),
        pl.BlockSpec((1, 1, bs, Hkv, hd), kv_index),
    ]
    operands = [qg, k_pool, v_pool]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, bs, Hkv), scale_index),
            pl.BlockSpec((1, 1, bs, Hkv), scale_index),
        ]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, max_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Hkv, G, hd), lambda b, j, *_: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G), jnp.float32),
            pltpu.VMEM((Hkv, G), jnp.float32),
            pltpu.VMEM((Hkv, G, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, scale=hd**-0.5, block_size=bs, quant=quant),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, hd), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(table, pos_arr, layer_arr, *operands)
    return out.reshape(B, 1, H, hd)
