"""Ring attention: exact causal attention over a context-parallel mesh axis.

Sequence is sharded over `axis` (each rank holds S/n contiguous tokens of
q/k/v). K/V chunks rotate around the ICI ring via ppermute; each rank folds
every chunk into its online-softmax accumulators, so memory stays O(S/n) per
chip and the [S, S] matrix never exists anywhere. This is the long-context
first-class path (SURVEY §5 "long-context / sequence parallelism": cp is a
jax Mesh axis within a slice; the orchestration contract already guarantees
rank order == ICI neighbor order via TPU_WORKER_ID).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _chunk_attention(q, k, v, q_offset, k_offset, causal):
    """Partial (unnormalized) attention of a q chunk against one k/v chunk.
    Returns (m, l, acc): row max, row sum, weighted values — f32."""
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    qg = q.reshape(B, Sq, Hkv, G, D).astype(jnp.float32) * D**-0.5
    scores = jnp.einsum("bqkgh,bskh->bkgqs", qg, k.astype(jnp.float32))
    if causal:
        q_pos = q_offset + jnp.arange(Sq)
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None, None], scores, NEG_INF)
    m = scores.max(axis=-1)  # [B,Hkv,G,Sq]
    p = jnp.exp(scores - m[..., None])
    l = p.sum(axis=-1)
    acc = jnp.einsum("bkgqs,bskh->bkgqh", p, v.astype(jnp.float32))
    return m, l, acc


def ring_attention_inner(q, k, v, axis_name: str, causal: bool = True):
    """To be called INSIDE shard_map: q/k/v are this rank's sequence chunks."""
    n = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    chunk = Sq  # equal chunking
    q_offset = rank * chunk

    m = jnp.full((B, Hkv, G, Sq), NEG_INF, jnp.float32)
    l = jnp.zeros((B, Hkv, G, Sq), jnp.float32)
    acc = jnp.zeros((B, Hkv, G, Sq, D), jnp.float32)

    def step(i, carry):
        m, l, acc, k_cur, v_cur = carry
        src_rank = (rank - i) % n
        k_offset = src_rank * chunk
        cm, cl, cacc = _chunk_attention(q, k_cur, v_cur, q_offset, k_offset, causal)
        m_new = jnp.maximum(m, cm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(cm - m_new)
        l_new = l * alpha + cl * beta
        acc_new = acc * alpha[..., None] + cacc * beta[..., None]
        # Rotate k/v to the next rank around the ring (ICI neighbor hop).
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m_new, l_new, acc_new, k_nxt, v_nxt

    m, l, acc, _, _ = jax.lax.fori_loop(0, n, step, (m, l, acc, k, v))
    l = jnp.maximum(l, 1e-30)  # fully-masked rows (never in causal self-attn)
    out = (acc / l[..., None]).astype(q.dtype)  # [B,Hkv,G,Sq,D]
    return out.transpose(0, 3, 1, 2, 4).reshape(B, Sq, H, D)


def ring_attention(
    q,
    k,
    v,
    mesh=None,
    axis: str = "cp",
    causal: bool = True,
    batch_axis=None,
    head_axis=None,
):
    """q [B,S,H,D], k/v [B,S,Hkv,D]; runs the ring over `axis` and returns
    [B,S,H,D] sharded the same way. `mesh=None` uses the ambient mesh context
    (composable inside a GSPMD-jitted model). `batch_axis`/`head_axis`
    optionally co-shard batch (dp) and heads (tp) so ring attention slots into
    a dp x cp x tp layout."""
    from jax import shard_map

    spec = P(batch_axis, axis, head_axis, None)
    inner = functools.partial(ring_attention_inner, axis_name=axis, causal=causal)
    fn = shard_map(
        inner,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
