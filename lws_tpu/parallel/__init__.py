"""Compute-plane parallelism: env-contract bootstrap -> jax.distributed,
mesh construction (dp/pp/tp axes; sp rides tp via sequence sharding, ep rides
tp via expert sharding), and sharding helpers.

This is the consumer side of the orchestration contract: the pod webhook
writes LWS_*/TPU_*/JAX_* into containers (SURVEY §3.3); this package turns
them into an initialized runtime and a device mesh whose axes map onto the
group topology (group = slice, subgroup = sub-slice stage).
"""

from lws_tpu.parallel.bootstrap import BootstrapInfo, bootstrap_info_from_env, initialize_from_env  # noqa: F401
from lws_tpu.parallel.mesh import MeshSpec, build_mesh, mesh_from_bootstrap  # noqa: F401
