"""Distributed bootstrap from the injected env contract.

`jax.distributed.initialize()` needs (coordinator, num_processes, process_id);
the pod webhook already published exactly these as JAX_COORDINATOR_ADDRESS /
JAX_NUM_PROCESSES / JAX_PROCESS_ID (with LWS_LEADER_ADDRESS / LWS_GROUP_SIZE /
LWS_WORKER_INDEX as the underlying generic contract, ref
pkg/utils/pod/pod_utils.go:131-179). The reference leaves this glue to the
workload (Ray in docs/examples/vllm/TPU/lws.yaml:30-34); here it is one call.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from lws_tpu.api import contract


@dataclass(frozen=True)
class BootstrapInfo:
    coordinator_address: str
    num_processes: int
    process_id: int
    subgroup_size: Optional[int] = None
    subgroup_index: Optional[int] = None

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


def bootstrap_info_from_env(env: Optional[dict[str, str]] = None) -> BootstrapInfo:
    e = os.environ if env is None else env
    coordinator = e.get(contract.JAX_COORDINATOR_ADDRESS)
    if coordinator is None:
        leader = e.get(contract.LWS_LEADER_ADDRESS)
        coordinator = (
            f"{leader}:{contract.JAX_COORDINATOR_PORT_DEFAULT}" if leader else "localhost:0"
        )
    num = int(e.get(contract.JAX_NUM_PROCESSES, e.get(contract.LWS_GROUP_SIZE, "1")))
    pid = int(e.get(contract.JAX_PROCESS_ID, e.get(contract.LWS_WORKER_INDEX, "0")))
    sub_size = e.get(contract.LWS_SUBGROUP_SIZE)
    sub_index = e.get(contract.LWS_SUBGROUP_INDEX)
    return BootstrapInfo(
        coordinator_address=coordinator,
        num_processes=num,
        process_id=pid,
        subgroup_size=int(sub_size) if sub_size is not None else None,
        subgroup_index=int(sub_index) if sub_index is not None else None,
    )


def assert_platform_from_env(env: Optional[dict[str, str]] = None) -> None:
    """Honor an explicit JAX_PLATFORMS from the pod env even when a site-wide
    accelerator plugin overrode platform selection via jax.config at
    interpreter start (observed with relay-backed TPU plugins): the env
    contract must win inside workers. Call before first backend use."""
    import jax

    platforms = (os.environ if env is None else env).get("JAX_PLATFORMS")
    if platforms:
        try:
            jax.config.update("jax_platforms", platforms)
        except Exception:  # vet: ignore[hazard-exception-swallow]: best-effort platform pin; backend may already be fixed (BLE001 intended)
            pass


def initialize_from_env(env: Optional[dict[str, str]] = None) -> BootstrapInfo:
    """Initialize jax.distributed from the env contract (no-op single-host)."""
    info = bootstrap_info_from_env(env)
    assert_platform_from_env(env)

    if info.is_distributed:
        import jax
        jax.distributed.initialize(
            coordinator_address=info.coordinator_address,
            num_processes=info.num_processes,
            process_id=info.process_id,
        )
    return info
