"""Device mesh construction: orchestration shape -> jax.sharding.Mesh.

Axis conventions used across models/ops:
  dp — data parallel (LWS replica-internal batch split)
  pp — pipeline stages (subgroups map here: subgroup i = stage i, sub-slice
       exclusive topology keeps each stage on its own ICI island)
  tp — tensor parallel (within a subgroup / slice; ICI all-reduces)
Sequence parallelism (sp) shards activations' sequence dim over `tp` between
blocks; expert parallelism (ep) shards the experts dim over `tp`. Context
parallelism for ring attention uses a dedicated `cp` axis (see ops.ring).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np


@dataclass(frozen=True)
class MeshSpec:
    dp: int = 1
    pp: int = 1
    tp: int = 1
    # Context parallelism (ring attention over sequence chunks). Kept as a
    # distinct axis from tp: cp shards the SEQUENCE through attention itself
    # (ppermute ring), tp shards heads/features.
    cp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.pp * self.tp * self.cp

    def axis_names(self) -> tuple[str, ...]:
        return ("dp", "pp", "cp", "tp")


def auto_meshspec(n_devices: int, prefer_tp: int = 0, pp: int = 1, cp: int = 1) -> MeshSpec:
    """Factor n_devices into (dp, pp, cp, tp): tp gets the largest power-of-two
    up to prefer_tp (or up to n/(pp*cp) if unset), dp absorbs the rest."""
    assert n_devices % (pp * cp) == 0, f"{n_devices} devices not divisible by pp*cp={pp * cp}"
    rest = n_devices // (pp * cp)
    tp = prefer_tp or rest
    while rest % tp != 0:
        tp //= 2
    tp = max(1, tp)
    return MeshSpec(dp=rest // tp, pp=pp, cp=cp, tp=tp)


def build_mesh(spec: MeshSpec, devices: Optional[Sequence] = None):
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) != spec.size:
        raise ValueError(f"mesh spec {spec} needs {spec.size} devices, have {len(devs)}")
    arr = np.array(devs).reshape(spec.dp, spec.pp, spec.cp, spec.tp)
    return Mesh(arr, spec.axis_names())


def mesh_from_bootstrap(
    info, devices: Optional[Sequence] = None, pp_from_subgroups: bool = True, cp: int = 1
):
    """Build the group-wide mesh from the bootstrap contract: with subgroups,
    pp = number of subgroups (sub-slice stages) and tp = chips per subgroup;
    otherwise tp = all chips of the slice. `cp` carves a context-parallel
    axis out of tp for long-context ring attention (the production path to
    cp > 1 — pair with cfg.context_parallel)."""
    import jax

    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if n % cp != 0:
        raise ValueError(f"{n} devices not divisible by cp={cp}")
    if pp_from_subgroups and info.subgroup_size and info.num_processes > info.subgroup_size:
        n_subgroups = info.num_processes // info.subgroup_size
        if n % (n_subgroups * cp) != 0:
            # Never silently drop the pp axis: subgroup i = stage i is the
            # documented bootstrap contract.
            raise ValueError(
                f"{n} devices not divisible by subgroups({n_subgroups}) x cp({cp}); "
                "adjust cp or the subgroup layout"
            )
        return build_mesh(
            MeshSpec(dp=1, pp=n_subgroups, cp=cp, tp=n // n_subgroups // cp), devs
        )
    return build_mesh(MeshSpec(dp=1, pp=1, cp=cp, tp=n // cp), devs)
