"""Device mesh construction: orchestration shape -> jax.sharding.Mesh.

Axis conventions used across models/ops:
  dp — data parallel (LWS replica-internal batch split)
  pp — pipeline stages (subgroups map here: subgroup i = stage i, sub-slice
       exclusive topology keeps each stage on its own ICI island)
  tp — tensor parallel (within a subgroup / slice; ICI all-reduces)
Sequence parallelism (sp) shards activations' sequence dim over `tp` between
blocks; expert parallelism (ep) shards the experts dim over `tp`. Context
parallelism for ring attention uses a dedicated `cp` axis (see ops.ring).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np


@dataclass(frozen=True)
class MeshSpec:
    dp: int = 1
    pp: int = 1
    tp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.pp * self.tp

    def axis_names(self) -> tuple[str, ...]:
        return ("dp", "pp", "tp")


def auto_meshspec(n_devices: int, prefer_tp: int = 0, pp: int = 1) -> MeshSpec:
    """Factor n_devices into (dp, pp, tp): tp gets the largest power-of-two
    up to prefer_tp (or up to n/pp if unset), dp absorbs the rest."""
    assert n_devices % pp == 0, f"{n_devices} devices not divisible by pp={pp}"
    rest = n_devices // pp
    tp = prefer_tp or rest
    while rest % tp != 0:
        tp //= 2
    tp = max(1, tp)
    return MeshSpec(dp=rest // tp, pp=pp, tp=tp)


def build_mesh(spec: MeshSpec, devices: Optional[Sequence] = None):
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) != spec.size:
        raise ValueError(f"mesh spec {spec} needs {spec.size} devices, have {len(devs)}")
    arr = np.array(devs).reshape(spec.dp, spec.pp, spec.tp)
    return Mesh(arr, spec.axis_names())


def mesh_from_bootstrap(info, devices: Optional[Sequence] = None, pp_from_subgroups: bool = True):
    """Build the group-wide mesh from the bootstrap contract: with subgroups,
    pp = number of subgroups (sub-slice stages) and tp = chips per subgroup;
    otherwise tp = all chips of the slice."""
    import jax

    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if pp_from_subgroups and info.subgroup_size and info.num_processes > info.subgroup_size:
        n_subgroups = info.num_processes // info.subgroup_size
        if n % n_subgroups == 0:
            return build_mesh(MeshSpec(dp=1, pp=n_subgroups, tp=n // n_subgroups), devs)
    return build_mesh(MeshSpec(dp=1, pp=1, tp=n), devs)
