"""Runtime: the assembled control plane + cluster backends.

- harness.ControlPlane: store + admission + all controllers + scheduler wired
  into a Manager (≈ cmd/main.go setup, SURVEY §3.1).
- FakeKubelet: drives pod status like a node agent would (test/e2e backends).
"""

from lws_tpu.runtime.harness import ControlPlane, FakeKubelet  # noqa: F401
