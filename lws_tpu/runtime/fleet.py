"""Fleet metric aggregation: one scrape surface for every worker.

The control plane already knows where the workers are — pod records carry
a published address and the pod spec declares the telemetry port
(LWS_TPU_METRICS_PORT, the containerPort analog — same discovery contract
as the KV endpoint's LWS_TPU_KV_PORT). The FleetCollector walks READY pods
with a declared port, scrapes each `http://addr:port/metrics`, injects
`instance` (pod name) plus `role`/`revision` labels where the pod carries
them, and merges everything — control-plane registries included, as
instance "control-plane" — into ONE parser-valid exposition served at
`GET /metrics/fleet` (runtime/server.py).

Operators get fleet-level latency distributions instead of per-process
averages (the serving-at-scale case PAPERS.md makes): a PromQL quantile
over the merged `serving_ttft_seconds` IS the fleet TTFT distribution, and
`lws-tpu top` renders the same surface live. Scrapes are bounded (short
per-worker timeout, cached for `cache_ttl` so a dashboard refresh loop
can't DOS the data plane) and failures degrade per instance:
`lws_fleet_scrape_errors_total{instance}` counts them, the merged view
carries whatever answered."""

from __future__ import annotations

import os
import threading
import time
import urllib.request
from http.client import HTTPException
from typing import Optional

from lws_tpu.api import contract
from lws_tpu.core import metrics, trace
from lws_tpu.runtime.telemetry import METRICS_PORT_ENV, METRICS_TOKEN_ENV


def pod_metrics_endpoint(pod) -> Optional[tuple[str, int]]:
    """(host, port) when the pod declares a telemetry port, else None.
    Mirrors kv_transport.discover_role_endpoint: the published address is
    used VERBATIM (LocalBackend publishes 127.0.0.1; a rendezvous FQDN
    resolves through cluster DNS). An unresolvable address fails that one
    instance's scrape — never silently rewritten to loopback, which off
    this host would scrape the wrong process under the pod's label.
    Public: the scale actuator (obs/decisions.py) resolves the same
    endpoint to drain a scale-in victim's worker before the pod goes."""
    for container in pod.spec.containers:
        for env in container.env:
            if env.name == METRICS_PORT_ENV and env.value:
                return pod.status.address or "127.0.0.1", int(env.value)
    return None


def _pod_scrape_labels(pod) -> dict[str, str]:
    from lws_tpu.api import disagg

    labels = {"instance": pod.meta.name}
    role = pod.meta.labels.get(disagg.DS_ROLE_LABEL_KEY)
    if role:
        labels["role"] = role
    revision = pod.meta.labels.get(disagg.DS_REVISION_LABEL_KEY) or \
        pod.meta.labels.get(contract.REVISION_LABEL_KEY)
    if revision:
        labels["revision"] = revision
    return labels


class FleetCollector:
    def __init__(
        self,
        store,
        control_registries: tuple = (),
        timeout_s: float = 2.0,
        cache_ttl_s: float = 1.0,
        max_label_sets: int = 512,
        metrics_registry=None,
        backoff_base_s: float = 2.0,
        backoff_cap_s: float = 60.0,
        shard_size: int = 64,
    ) -> None:
        """`control_registries` join the merge as instance "control-plane";
        `metrics_registry` receives the collector's own health metrics
        (defaults to the first control registry, else the process one).
        `backoff_base_s`/`backoff_cap_s` shape the per-instance scrape
        backoff: a failing instance doubles its skip window per consecutive
        miss up to the cap — the collector's circuit-breaker-lite.
        `shard_size` bounds one shard collector's member count in the
        two-tier scrape tree (one shard per role-slice of at most this many
        instances): scrape wall-clock then grows with shard depth, not
        fleet width."""
        self.store = store
        self.control_registries = control_registries
        self.timeout_s = timeout_s
        self.cache_ttl_s = cache_ttl_s
        self.max_label_sets = max_label_sets
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.shard_size = max(1, shard_size)
        self._own_metrics = (
            metrics_registry if metrics_registry is not None
            else (control_registries[0] if control_registries else metrics.REGISTRY)
        )
        self._lock = threading.Lock()
        self._refill_lock = threading.Lock()
        # Per-shard merged expositions, shard_id -> {"text", "at" (monotonic),
        # "members" (instance-name tuple, so membership churn invalidates),
        # "scraped"/"failed"/"skipped" counts}: the TTL cache now lives at
        # shard granularity — a dashboard refresh re-renders the fleet view
        # from cached shard texts without re-dialing anyone, and the fleet
        # text itself is never cached whole (streaming bound).
        self._shard_cache: dict[str, dict] = {}  # guarded-by: _lock
        # Instances currently failing to scrape, with per-instance backoff
        # state ({"failures": n, "until": monotonic}): a down worker is
        # SKIPPED until its backoff expires instead of being re-dialed (and
        # re-timed-out) on every cache refill. Ring events fire on the
        # healthy->failing edge only (the counter still counts every real
        # miss). Mutated from the scrape pool's threads, so it shares
        # _lock: two concurrent misses for one instance must produce ONE
        # edge event, and lock-free mutation under churn can corrupt it.
        self._failing: dict[str, dict] = {}  # guarded-by: _lock

    # ---- discovery + scrape ----------------------------------------------
    def targets(self) -> list[tuple[dict, tuple[str, int]]]:
        """[(labels, (host, port))] for every READY pod declaring a
        telemetry port — k8s Endpoints semantics, same readiness gate as
        the KV endpoint discovery."""
        out = []
        for pod in self.store.list("Pod"):
            if not getattr(pod.status, "ready", False):
                continue
            endpoint = pod_metrics_endpoint(pod)
            if endpoint is None:
                continue
            out.append((_pod_scrape_labels(pod), endpoint))
        return out

    @staticmethod
    def _scrape_headers(accept: Optional[str] = None) -> dict:
        """Shared worker-scrape headers: optional Accept negotiation plus
        the same-deployment bearer token (one token, CP + workers)."""
        headers = {"Accept": accept} if accept else {}
        token = os.environ.get(METRICS_TOKEN_ENV)
        if token:
            headers["Authorization"] = f"Bearer {token}"
        return headers

    def _scrape_one(self, host: str, port: int) -> str:
        from lws_tpu.core import faults

        faults.fire("fleet.scrape")
        # Negotiate OpenMetrics: the merge must carry the workers' trace
        # exemplars (classic text-format responses have them stripped).
        req = urllib.request.Request(
            f"http://{host}:{port}/metrics",
            headers=self._scrape_headers(metrics.OPENMETRICS_CONTENT_TYPE),
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return resp.read().decode()

    def _backoff_s(self, failures: int) -> float:  # holds-lock: _lock
        return min(self.backoff_cap_s,
                   self.backoff_base_s * (2.0 ** max(0, failures - 1)))

    def in_backoff(self, instance: str, now: float) -> bool:
        with self._lock:
            state = self._failing.get(instance)
            return state is not None and now < state["until"]

    def _scrape_target(self, labels: dict, host: str, port: int,
                       now: Optional[float] = None) -> Optional[str]:
        instance = labels["instance"]
        if now is None:
            now = time.monotonic()
        started = time.perf_counter()
        try:
            text = self._scrape_one(host, port)
            # Validate HERE, inside the per-instance guard: one worker
            # answering with garbage (port reused mid-restart, truncated
            # body) must not blank the whole fleet view when the merge
            # parses it later.
            metrics.parse_exposition(text)
            with self._lock:
                recovered = self._failing.pop(instance, None) is not None
            if recovered:
                from lws_tpu.core import flightrecorder

                flightrecorder.record("fleet_scrape_recovered",
                                      instance=instance)
            return text
        except (OSError, ValueError, HTTPException) as e:
            self._own_metrics.inc(
                "lws_fleet_scrape_errors_total", {"instance": instance},
            )
            # The failure is also a flight-recorder event — but only on the
            # healthy->failing EDGE: a dead worker re-scraped every cache
            # TTL would otherwise flood the bounded ring and evict the rare
            # notable events the black box exists to retain. The test-and-
            # set runs under _lock: this method executes on the scrape
            # pool's threads, and two lock-free concurrent misses could
            # both pass the membership test and double-record the edge.
            # Each consecutive miss doubles the instance's backoff window
            # (collect() skips it until `until` passes).
            # Anchor the window at the FAILURE time, not collect-start: a
            # timing-out scrape otherwise consumes its own backoff window
            # (timeout_s ~= backoff_base_s) and gets re-dialed every cache
            # refill anyway. `now` stays the injected base so tests remain
            # deterministic; the elapsed scrape time rides on top.
            failed_at = now + (time.perf_counter() - started)
            with self._lock:
                state = self._failing.get(instance)
                newly_failing = state is None
                failures = 1 if newly_failing else state["failures"] + 1
                self._failing[instance] = {
                    "failures": failures,
                    "until": failed_at + self._backoff_s(failures),
                }
            if newly_failing:
                from lws_tpu.core import flightrecorder

                flightrecorder.record(
                    "fleet_scrape_error",
                    instance=instance, error=repr(e)[:200],
                )
            return None

    # ---- two-tier scrape tree --------------------------------------------
    def _shards(self, discovered) -> list[tuple[str, list]]:
        """Partition discovered targets into shard collectors: role-major,
        then slices of at most `shard_size` instances, members name-sorted
        so a stable fleet yields stable shard membership (and the per-shard
        cache actually hits). Shard ids are `{role}-{slice_index}`."""
        by_role: dict[str, list] = {}
        for labels, endpoint in discovered:
            by_role.setdefault(labels.get("role") or "default", []).append(
                (labels, endpoint)
            )
        shards: list[tuple[str, list]] = []
        for role in sorted(by_role):
            members = sorted(by_role[role], key=lambda t: t[0]["instance"])
            for i in range(0, len(members), self.shard_size):
                shards.append(
                    (f"{role}-{i // self.shard_size}",
                     members[i:i + self.shard_size])
                )
        return shards

    def _prune_backoff(self, discovered) -> None:
        """Prune backoff state for instances that LEFT the ready set: a pod
        that restarted under the same name re-enters with a clean slate
        (it went unready in between), and names that never return must
        not accumulate in _failing forever."""
        live_names = {labels["instance"] for labels, _ in discovered}
        with self._lock:
            for stale in [i for i in self._failing if i not in live_names]:
                del self._failing[stale]

    def _scrape_shard(self, shard_id: str, members: list,
                      now: float) -> tuple[list, int, int]:
        """One shard collector's pass: backoff-filter its members, scrape
        the rest concurrently, time the whole thing. Returns
        ([(labels, text)], n_failed, n_skipped). Failure isolation stays
        per shard: a shard of timing-out instances burns ITS wall-clock
        budget while its siblings proceed on the root pool."""
        live = []
        skipped = 0
        for labels, endpoint in members:
            if self.in_backoff(labels["instance"], now):
                self._own_metrics.inc(
                    "lws_fleet_scrape_skipped_total",
                    {"instance": labels["instance"]},
                )
                skipped += 1
                continue
            live.append((labels, endpoint))
        sources: list[tuple[dict, str]] = []
        started = time.perf_counter()
        if live:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=min(8, len(live))) as pool:
                scraped = pool.map(
                    lambda t: self._scrape_target(t[0], *t[1], now=now),
                    live,
                )
                sources = [
                    (labels, text)
                    for (labels, _), text in zip(live, scraped)
                    if text is not None
                ]
        self._own_metrics.observe(
            "lws_fleet_shard_scrape_seconds",
            time.perf_counter() - started,
            {"shard": shard_id},
        )
        return sources, len(live) - len(sources), skipped

    def _scrape_tree(self, now: float) -> list[tuple[str, list]]:
        """The full two-tier pass: discovery, backoff pruning, shard
        fan-out on a root pool (each shard fans out to its members on its
        own pool), fleet gauges. Returns [(shard_id, [(labels, text)])]."""
        discovered = self.targets()
        self._prune_backoff(discovered)
        shards = self._shards(discovered)
        results: list[tuple[str, list]] = []
        n_scraped = n_failed = n_backoff = 0
        with trace.span("fleet.scrape", instances=len(discovered),
                        shards=len(shards)):
            if shards:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(max_workers=min(8, len(shards))) as root:
                    out = root.map(
                        lambda s: self._scrape_shard(s[0], s[1], now), shards,
                    )
                    for (shard_id, _), (sources, failed, skipped) in zip(shards, out):
                        results.append((shard_id, sources))
                        n_scraped += len(sources)
                        n_failed += failed
                        n_backoff += skipped
        self._set_fleet_gauges(n_scraped, n_failed, n_backoff)
        return results

    def _set_fleet_gauges(self, n_scraped: int, n_failed: int,
                          n_backoff: int) -> None:
        # Unlabeled total = merged instance count (the historical series
        # dashboards already watch); the state breakdown rides alongside,
        # zeros included so a recovering fleet visibly drains failed/backoff.
        self._own_metrics.set("lws_fleet_instances", float(n_scraped))
        self._own_metrics.set("lws_fleet_instances", float(n_scraped),
                              {"state": "scraped"})
        self._own_metrics.set("lws_fleet_instances", float(n_failed),
                              {"state": "failed"})
        self._own_metrics.set("lws_fleet_instances", float(n_backoff),
                              {"state": "backoff"})

    def collect(self, now: Optional[float] = None) -> list[tuple[dict, str]]:
        """One scrape pass over the ready fleet: [(labels, exposition)].
        Control-plane registries ride along as instance "control-plane" so
        the fleet view is genuinely ONE surface. Per-instance failures are
        counted and skipped — a dead worker must not blank the fleet — and
        a KNOWN-failing instance is not even dialed until its backoff
        expires (each consecutive miss doubles the window up to the cap),
        so a dead pod costs one timeout per backoff window, not one per
        cache refill. `now` (monotonic seconds) is injectable so the
        backoff regression tests drive time deterministically. The pass
        runs the two-tier shard tree under the hood (a partitioned worker
        costs one timeout of SHARD wall clock, overlapped with its sibling
        shards) and flattens the result for callers that want per-instance
        sources."""
        if now is None:
            now = time.monotonic()
        sources: list[tuple[dict, str]] = [
            src
            for _, shard_sources in self._scrape_tree(now)
            for src in shard_sources
        ]
        # Render the control plane LAST: this pass's own health metrics
        # (instance gauge, scrape-error counts) must appear in THIS pass's
        # merged view, not trail one scrape behind.
        if self.control_registries:
            sources.insert(0, (
                {"instance": "control-plane"},
                metrics.render_exposition(*self.control_registries),
            ))
        return sources

    # ---- continuous-profiling fan-in (GET /debug/profile/fleet) ----------
    def _scrape_profile(self, labels: dict, host: str, port: int,
                        limit: int) -> Optional[dict]:
        """One worker's /debug/profile snapshot, or None on failure (counted
        under the same per-instance scrape-error counter as /metrics; no
        flight-recorder edge event — profile scrapes are operator-driven
        one-shots, not the periodic refresh whose re-fire flood the
        _failing edge logic exists to suppress). The generic debug-JSON
        scrape plus the profile shape check."""
        snap = self._scrape_debug_json(
            labels, host, port, f"/debug/profile?limit={int(limit)}",
            missing_ok=False,
        )
        if snap is not None and (not isinstance(snap, dict)
                                 or "stacks" not in snap):
            self._own_metrics.inc(
                "lws_fleet_scrape_errors_total", {"instance": labels["instance"]},
            )
            return None
        return snap

    def collect_profiles(self, limit: int = 512) -> list[tuple[dict, dict]]:
        """[(labels, profile snapshot)] over the ready fleet plus this
        process as instance "control-plane" — the /debug/profile analog of
        collect(). Operator-driven (no cache: `lws-tpu profile` polls at
        human rates, and snapshots are cumulative anyway)."""
        from lws_tpu.core import profile as profmod

        sources: list[tuple[dict, dict]] = [
            ({"instance": "control-plane"}, profmod.PROFILER.snapshot(limit))
        ]
        targets = self.targets()
        if targets:
            from concurrent.futures import ThreadPoolExecutor

            with profmod.phase("fleet.profile_scrape"):
                with ThreadPoolExecutor(max_workers=min(8, len(targets))) as pool:
                    scraped = pool.map(
                        lambda t: self._scrape_profile(t[0], *t[1], limit),
                        targets,
                    )
                    sources.extend(
                        (labels, snap)
                        for (labels, _), snap in zip(targets, scraped)
                        if snap is not None
                    )
        return sources

    # ---- request-journey fan-in (GET /debug/request[s]) ------------------
    def _scrape_debug_json(self, labels: dict, host: str, port: int,
                           path: str, missing_ok: bool = True):
        """One worker's JSON debug body, or None when the worker has
        nothing for it (with `missing_ok`, a 404 — a request that never
        touched that instance — is an answer, not an error; real failures
        count under the usual per-instance scrape-error counter)."""
        import json
        import urllib.error

        req = urllib.request.Request(
            f"http://{host}:{port}{path}", headers=self._scrape_headers(),
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            if missing_ok and e.code == 404:
                return None
            self._own_metrics.inc(
                "lws_fleet_scrape_errors_total", {"instance": labels["instance"]},
            )
            return None
        except (OSError, ValueError, HTTPException):
            self._own_metrics.inc(
                "lws_fleet_scrape_errors_total", {"instance": labels["instance"]},
            )
            return None

    def collect_journeys(self, request_id: str) -> Optional[dict]:
        """Fleet-join one request's journey legs: every ready worker's
        `GET /debug/request/{id}` plus this process's local leg (the
        client/reconcile spans live HERE), merged into one record whose
        span set should form one connected tree — the trace ctx rode the
        KV frame meta, so prefill's and decode's subtrees share the
        client's trace id. None when no instance knows the id."""
        from urllib.parse import quote

        from lws_tpu.core import trace
        from lws_tpu.core.trace import connected_tree
        from lws_tpu.obs import journey as journeymod

        legs: list[tuple[dict, dict]] = []
        local = journeymod.local_journey(request_id)
        if local is not None:
            legs.append(({"instance": "control-plane"}, local))
        targets = self.targets()
        if targets:
            from concurrent.futures import ThreadPoolExecutor

            path = f"/debug/request/{quote(str(request_id), safe='')}"
            with trace.span("fleet.journey_scrape", instances=len(targets)):
                with ThreadPoolExecutor(max_workers=min(8, len(targets))) as pool:
                    scraped = pool.map(
                        lambda t: self._scrape_debug_json(t[0], *t[1], path),
                        targets,
                    )
                    legs.extend(
                        (labels, leg)
                        for (labels, _), leg in zip(targets, scraped)
                        if isinstance(leg, dict)
                    )
        if not legs:
            return None
        trace_id = next(
            (leg.get("trace_id") for _, leg in legs if leg.get("trace_id")),
            None,
        )
        if local is None and trace_id:
            # The workers named the trace: pull this process's leg of it
            # (the request root + reconcile spans the client opened here).
            extra = journeymod.VAULT.spans_for_trace(trace_id) or [
                s for s in trace.TRACER.spans()
                if s.get("trace_id") == trace_id
            ]
            if extra:
                legs.insert(0, ({"instance": "control-plane"}, {
                    "id": request_id, "trace_id": trace_id,
                    "outcome": "open", "completed": False, "flags": [],
                    "timeline": {}, "events": [], "annotations": {},
                    "spans": extra,
                }))
        spans: list[dict] = []
        seen_spans: set = set()
        events: list[dict] = []
        annotations: dict = {}
        flags: set = set()
        for labels, leg in legs:
            for s in leg.get("spans") or []:
                sid = s.get("span_id")
                if sid in seen_spans:
                    continue
                seen_spans.add(sid)
                spans.append({**s, "instance": labels.get("instance", "-")})
            events.extend(leg.get("events") or [])
            annotations.update(leg.get("annotations") or {})
            flags.update(leg.get("flags") or [])
        # Worst leg verdict wins the joined outcome label (a breached
        # decode leg must not be masked by a healthy prefill leg).
        outcome = "open"
        for want in ("errored", "deadline_expired", "breached", "retried",
                     "fault", "slowest", "sampled"):
            if any(leg.get("outcome") == want for _, leg in legs):
                outcome = want
                break
        return {
            "id": request_id,
            "trace_id": trace_id,
            "outcome": outcome,
            "flags": sorted(flags),
            "spans": spans,
            "events": sorted(events, key=lambda e: e.get("ts", 0.0)),
            "annotations": annotations,
            "legs": [
                {"labels": labels,
                 "journey": {k: v for k, v in leg.items() if k != "spans"}}
                for labels, leg in legs
            ],
            "connected": connected_tree(spans) if spans else False,
        }

    def collect_request_index(self, outcome: str = "all", klass: str = "",
                              limit: int = 32,
                              revision: str = "") -> list[dict]:
        """Fleet-joined `/debug/requests` index: every ready worker's
        retained-journey digests plus this process's, instance-labelled and
        merged worst-first. Unknown outcomes raise ValueError BEFORE any
        scrape (the caller answers 400). `revision` narrows every leg to
        journeys that completed under that serving revision."""
        from lws_tpu.obs import journey as journeymod

        rows = [
            {**row, "instance": "control-plane"}
            for row in journeymod.VAULT.index(outcome=outcome, klass=klass,
                                              limit=limit, revision=revision)
        ]
        targets = self.targets()
        if targets:
            from concurrent.futures import ThreadPoolExecutor
            from urllib.parse import urlencode

            query = urlencode({"outcome": outcome, "klass": klass,
                               "limit": int(limit), "revision": revision})
            path = f"/debug/requests?{query}"
            with ThreadPoolExecutor(max_workers=min(8, len(targets))) as pool:
                scraped = pool.map(
                    lambda t: self._scrape_debug_json(t[0], *t[1], path),
                    targets,
                )
                for (labels, _), got in zip(targets, scraped):
                    if isinstance(got, list):
                        rows.extend(
                            {**row, "instance": labels.get("instance", "-")}
                            for row in got if isinstance(row, dict)
                        )
        if outcome == "slowest":
            rows.sort(key=lambda r: -(r.get("latency_s") or 0.0))
        else:
            rows.sort(key=lambda r: -(r.get("completed_unix") or 0.0))
        if limit >= 0:
            rows = rows[:limit] if limit else []
        return rows

    def collect_prefix_index(self, limit: int = 512) -> dict:
        """Fleet-merged prefix-cache digest index (ISSUE 18, the remote
        tier's discovery half): every ready worker's `GET /debug/prefixes`
        advertisement folded into digest-hex -> {instance, host, port,
        tier}, where (host, port) is the sibling's KV wire endpoint a
        `fetch_prefix` should dial. Arena-backed entries win over
        HBM-resident ones for the same digest: the default fetch provider
        serves the host arena, so those are the fetchable copies. Instances
        that advertise no KV port contribute nothing fetchable and are
        skipped."""
        from lws_tpu.core import trace

        index: dict[str, dict] = {}
        targets = self.targets()
        if not targets:
            return {"digests": {}, "instances": 0}
        from concurrent.futures import ThreadPoolExecutor

        path = f"/debug/prefixes?limit={int(limit)}"
        answered = 0
        with trace.span("fleet.prefix_scrape", instances=len(targets)):
            with ThreadPoolExecutor(max_workers=min(8, len(targets))) as pool:
                scraped = pool.map(
                    lambda t: self._scrape_debug_json(
                        t[0], *t[1], path, missing_ok=False
                    ),
                    targets,
                )
                for (labels, (host, _mport)), got in zip(targets, scraped):
                    if not isinstance(got, dict):
                        continue
                    answered += 1
                    kv_port = got.get("kv_port")
                    if not kv_port:
                        continue
                    for tier, key in (("hbm", "digests"),
                                      ("host", "arena_digests")):
                        for hexd in got.get(key) or []:
                            have = index.get(hexd)
                            if have is None or (
                                tier == "host" and have["tier"] == "hbm"
                            ):
                                index[hexd] = {
                                    "instance": labels.get("instance", "-"),
                                    "host": host,
                                    "port": int(kv_port),
                                    "tier": tier,
                                }
        return {"digests": index, "instances": answered}

    def prefix_lookup(self, limit: int = 512):
        """A `RemotePrefixSource`-shaped lookup closure over a fresh
        digest index snapshot: digest_hex -> (host, kv_port) | None."""
        snapshot = self.collect_prefix_index(limit)["digests"]

        def lookup(digest_hex: str):
            entry = snapshot.get(digest_hex)
            if entry is None:
                return None
            return entry["host"], entry["port"]

        return lookup

    # ---- compile-ledger fan-in (GET /debug/compile/fleet) ----------------
    def collect_compiles(self, limit: int = 256) -> dict:
        """Fleet-merged compile-ledger view: every ready worker's
        `GET /debug/compile` plus this process's own ledger as instance
        "control-plane", each under its instance labels, with a cross-fleet
        `executables` fold (per-executable first/recompile/seconds summed
        over instances — the "which executable storms fleet-wide" answer).
        Operator-driven like collect_profiles (no cache: `lws-tpu devices`
        polls at human rates, and ledger counters are cumulative anyway)."""
        from lws_tpu.core import trace
        from lws_tpu.obs import device as devicemod

        instances: list[dict] = [{
            "labels": {"instance": "control-plane"},
            "compile": devicemod.debug_compile(limit),
        }]
        targets = self.targets()
        if targets:
            from concurrent.futures import ThreadPoolExecutor

            path = f"/debug/compile?limit={int(limit)}"
            with trace.span("fleet.compile_scrape", instances=len(targets)):
                with ThreadPoolExecutor(max_workers=min(8, len(targets))) as pool:
                    scraped = pool.map(
                        lambda t: self._scrape_debug_json(
                            t[0], *t[1], path, missing_ok=False
                        ),
                        targets,
                    )
                    instances.extend(
                        {"labels": labels, "compile": got}
                        for (labels, _), got in zip(targets, scraped)
                        if isinstance(got, dict)
                    )
        executables: dict[str, dict] = {}
        for entry in instances:
            for name, counts in (entry["compile"].get("executables")
                                 or {}).items():
                agg = executables.setdefault(
                    name, {"first": 0, "recompiles": 0, "seconds": 0.0,
                           "instances": 0})
                agg["first"] += int(counts.get("first") or 0)
                agg["recompiles"] += int(counts.get("recompiles") or 0)
                agg["seconds"] = round(
                    agg["seconds"] + float(counts.get("seconds") or 0.0), 6)
                agg["instances"] += 1
        return {"instances": instances, "executables": executables}

    def collect_shard_texts(self, force: bool = False,
                            now: Optional[float] = None) -> list[tuple[str, str]]:
        """[(shard_id, merged shard exposition)] over the ready fleet, the
        control plane first as pseudo-shard "control-plane" (rendered fresh
        every call: this pass's own health metrics must appear in this
        pass's view). Shard texts are cached for `cache_ttl_s` keyed by
        shard membership, and refills are single-flight: concurrent cache
        misses wait for the one in-progress pass instead of each launching
        their own scrape storm. Only STALE shards are re-scraped. The
        per-family cardinality cap applies HERE, per shard — the root
        streaming merge runs uncapped, because a fleet-wide cap would need
        fleet-wide seen-label-set memory and void the O(largest shard)
        streaming bound."""
        if now is None:
            now = time.monotonic()
        with self._refill_lock:
            discovered = self.targets()  # vet: ignore[lock-held-blocking]: single-flight by design — _refill_lock exists so ONE scrape pass runs while concurrent misses wait on it
            self._prune_backoff(discovered)
            shards = self._shards(discovered)
            wall = time.monotonic()
            stale: list[tuple[str, list]] = []
            with self._lock:
                live_ids = {shard_id for shard_id, _ in shards}
                for gone in [s for s in self._shard_cache if s not in live_ids]:
                    del self._shard_cache[gone]
                for shard_id, members in shards:
                    names = tuple(labels["instance"] for labels, _ in members)
                    entry = self._shard_cache.get(shard_id)
                    if (force or entry is None or entry["members"] != names
                            or wall - entry["at"] >= self.cache_ttl_s):
                        stale.append((shard_id, members))
            if stale:
                from concurrent.futures import ThreadPoolExecutor

                with trace.span("fleet.scrape", instances=sum(
                        len(m) for _, m in stale), shards=len(stale)):
                    with ThreadPoolExecutor(
                            max_workers=min(8, len(stale))) as root:
                        out = root.map(  # vet: ignore[lock-held-blocking]: same single-flight refill — the scrape tree runs once under _refill_lock
                            lambda s: self._scrape_shard(s[0], s[1], now),
                            stale,
                        )
                        refreshed = {
                            shard_id: (sources, failed, skipped)
                            for (shard_id, _), (sources, failed, skipped)
                            in zip(stale, out)
                        }
                refreshed_at = time.monotonic()
                with self._lock:
                    for (shard_id, members) in stale:
                        sources, failed, skipped = refreshed[shard_id]
                        self._shard_cache[shard_id] = {
                            "text": metrics.merge_expositions(
                                sources, max_label_sets=self.max_label_sets),
                            "at": refreshed_at,
                            "members": tuple(
                                labels["instance"] for labels, _ in members),
                            "scraped": len(sources),
                            "failed": failed,
                            "skipped": skipped,
                        }
            with self._lock:
                entries = [(shard_id, self._shard_cache[shard_id])
                           for shard_id, _ in shards
                           if shard_id in self._shard_cache]
                texts = [(shard_id, e["text"]) for shard_id, e in entries]
                counts = [(e["scraped"], e["failed"], e["skipped"])
                          for _, e in entries]
            # Gauges reflect the whole tree — cached shards included —
            # so a partial refresh never under-reports fleet size.
            totals = [sum(c) for c in zip(*counts)] if counts else [0, 0, 0]
            self._set_fleet_gauges(*totals)
        if self.control_registries:
            # The pseudo-shard goes through the same per-shard merge as a
            # real one so its samples carry instance="control-plane" (the
            # root streaming merge injects nothing).
            texts.insert(0, ("control-plane", metrics.merge_expositions(
                [({"instance": "control-plane"},
                  metrics.render_exposition(*self.control_registries))],
                max_label_sets=self.max_label_sets,
            )))
        return texts

    def render_fleet_chunks(self, force: bool = False):
        """The fleet exposition as a chunk generator: shard texts (cached,
        single-flight — collect_shard_texts) fed through an UNCAPPED
        streaming merge, so /metrics/fleet writes to the wire with peak
        merge memory O(largest shard) and the whole-fleet text never
        materializes. A shard whose cached text fails validation is dropped
        whole (counted) instead of poisoning the view."""
        shard_texts = self.collect_shard_texts(force=force)
        merger = metrics.StreamingMerger(drop_malformed=True)
        yield from merger.merge([({}, text) for _, text in shard_texts])
        if merger.dropped_sources:
            self._own_metrics.inc(
                "lws_fleet_shards_dropped_total",
                value=float(len(merger.dropped_sources)),
            )

    def render_fleet(self, force: bool = False) -> str:
        """The merged exposition as ONE string — the convenience join of
        render_fleet_chunks for callers that genuinely need the whole text
        (history-ring ingest, CLI one-shots, tests). The serving path
        (runtime/server.py /metrics/fleet) streams the chunks instead."""
        return "".join(self.render_fleet_chunks(force=force))
