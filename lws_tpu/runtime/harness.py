"""Assembled control plane (≈ cmd/main.go:72-250 startup + watch wiring).

Everything is wired into one Manager over one Store; `run_until_stable()`
drains all workqueues to a fixed point (deterministic, no sleeps), `start()`
runs them on background threads for live use.
"""

from __future__ import annotations

import collections
from typing import Optional

from lws_tpu.api import contract
from lws_tpu.api.node import Node
from lws_tpu.api.pod import Pod, PodPhase
from lws_tpu.core.events import EventRecorder
from lws_tpu.core.manager import Manager, Result
from lws_tpu.core.store import Key, Store
from lws_tpu.controllers.groupset_controller import GroupSetReconciler
from lws_tpu.controllers.lws_controller import LWSReconciler
from lws_tpu.controllers.pod_controller import PodReconciler
from lws_tpu.sched.provider import make_scheduler_provider
from lws_tpu.sched.scheduler import Scheduler
from lws_tpu.webhooks import register_lws_webhooks, register_pod_webhooks
from lws_tpu.webhooks.ds_webhook import register_ds_webhooks


class FakeKubelet:
    """Node-agent stand-in: pods that land on a node start Running+ready.

    With require_binding=False it also runs unbound pods — handy for control
    plane tests that don't model a fleet (the envtest trick, SURVEY §4.2,
    except our tests get it automatically)."""

    name = "kubelet"

    def __init__(self, store: Store, require_binding: bool = False) -> None:
        self.store = store
        self.require_binding = require_binding

    def reconcile(self, key: Key) -> Result | None:
        pod = self.store.try_get("Pod", key[1], key[2])
        if pod is None or not isinstance(pod, Pod):
            return None
        if pod.status.phase != PodPhase.PENDING:
            return None
        if self.require_binding and not pod.spec.node_name:
            return None
        pod.status.phase = PodPhase.RUNNING
        pod.status.ready = True
        pod.status.address = f"{pod.meta.name}.{pod.spec.subdomain}.{pod.meta.namespace}"
        self.store.update_status(pod)
        return None


class ControlPlane:
    def __init__(
        self,
        scheduler_provider: Optional[str] = None,
        enable_scheduler: bool = False,
        auto_ready: bool = False,
        require_binding: bool = False,
        store: Optional[Store] = None,
        leader_election: bool = False,
        identity: Optional[str] = None,
        **election_kw,
    ) -> None:
        from lws_tpu.core.metrics import MetricsRegistry

        # A pre-existing store = controller restart over live state; call
        # resync() after construction.
        self.store = store if store is not None else Store()
        self.recorder = EventRecorder()
        self.metrics = MetricsRegistry()

        provider = make_scheduler_provider(scheduler_provider, self.store)
        register_lws_webhooks(self.store)
        register_pod_webhooks(self.store, provider)
        register_ds_webhooks(self.store)

        self.manager = Manager(self.store, metrics=self.metrics)

        # HA: with leader_election on, this manager reconciles only while it
        # holds the cluster Lease (reference cmd/main.go:95-106 semantics —
        # standbys watch but stay passive until the lease expires).
        self.elector = None
        if leader_election:
            import os
            import uuid

            from lws_tpu.core.election import LeaderElector

            self.elector = LeaderElector(
                self.store,
                identity=identity or f"{os.getpid()}-{uuid.uuid4().hex[:8]}",
                **election_kw,
            )
            # Gate ALL dispatch (deterministic and threaded) on holding the
            # lease — a standby that reconciled would be a split brain.
            self.manager.gate = self.elector.is_leader
        store = self.store

        def lws_key_by_label(obj) -> list[Key]:
            name = obj.meta.labels.get(contract.SET_NAME_LABEL_KEY)
            return [("LeaderWorkerSet", obj.meta.namespace, name)] if name else []

        def leader_pods_of_lws(obj) -> list[Key]:
            name = obj.meta.labels.get(contract.SET_NAME_LABEL_KEY)
            if not name:
                return []
            return store.list_keys(
                "Pod",
                obj.meta.namespace,
                labels={contract.SET_NAME_LABEL_KEY: name, contract.WORKER_INDEX_LABEL_KEY: "0"},
            )

        def groupset_owner_of_pod(obj) -> list[Key]:
            owner = obj.meta.controller_owner()
            if owner is not None and owner.kind == "GroupSet":
                return [("GroupSet", obj.meta.namespace, owner.name)]
            return []

        _lws_fanout_gen: "collections.OrderedDict" = collections.OrderedDict()

        def pods_of_lws(obj) -> list[Key]:
            # LWS SPEC changes (size, template) flow through leader pods.
            # Status-only updates keep meta.generation, and during a fleet
            # rollout the LWS status churns once per group — fanning every
            # one of those out to every leader pod was the dominant source
            # of no-op pod reconciles (CONTROL_r04 rollout). Pods requeue
            # themselves through their own direct watch; this mapper only
            # needs to fire on generation edges. Deleted dependents are
            # repaired by the owner_pod_of_deleted / leader_pod_of_groupset
            # DELETED-only mappers below, not by this side channel.
            # Memo keyed by uid: a deleted-and-recreated LWS restarts its
            # generation counter and must not inherit the old memo. Bounded
            # LRU (DS rollouts churn uniquely-named child LWSes forever):
            # move-to-end on hit so long-lived LWSes survive eviction —
            # insertion-order eviction dropped exactly the live fleet
            # entries the gate targets (ADVICE r4).
            memo_key = (obj.key(), obj.meta.uid)
            gen = obj.meta.generation
            prev = _lws_fanout_gen.get(memo_key)
            if prev is not None:
                _lws_fanout_gen.move_to_end(memo_key)
                if prev == gen:
                    return []
            _lws_fanout_gen[memo_key] = gen
            while len(_lws_fanout_gen) > 8192:
                _lws_fanout_gen.popitem(last=False)
            return store.list_keys(
                "Pod",
                obj.meta.namespace,
                labels={contract.SET_NAME_LABEL_KEY: obj.meta.name, contract.WORKER_INDEX_LABEL_KEY: "0"},
            )

        self.lws_controller = LWSReconciler(self.store, self.recorder, metrics=self.metrics)
        self.manager.register(
            self.lws_controller,
            {
                "LeaderWorkerSet": lambda o: [o.key()],
                "GroupSet": lws_key_by_label,
                "Service": lws_key_by_label,
                "Pod": lws_key_by_label,
            },
        )

        from lws_tpu.core.manager import deleted_only

        @deleted_only
        def leader_pod_of_groupset(obj) -> list[Key]:
            # Worker groupsets are named after their leader pod; deleting one
            # must requeue that leader directly so the pod controller
            # recreates it (previously this recovery rode the LWS
            # status-churn side channel, which the generation gate above
            # rightly cuts). DELETED-only: firing on every creation/status
            # write would reintroduce the no-op churn the gate removed.
            if contract.GROUP_INDEX_LABEL_KEY in obj.meta.labels:
                return [("Pod", obj.meta.namespace, obj.meta.name)]
            return []

        @deleted_only
        def owner_pod_of_deleted(obj) -> list[Key]:
            # Per-replica Services and gang PodGroups are owned by their
            # leader pod; deleting one requeues that pod so its reconcile
            # recreates the dependent (same repair edge as above).
            owner = obj.meta.controller_owner()
            if owner is not None and owner.kind == "Pod":
                return [("Pod", obj.meta.namespace, owner.name)]
            return []

        self.pod_controller = PodReconciler(self.store, self.recorder, provider)
        self.manager.register(
            self.pod_controller,
            {
                "Pod": lambda o: [o.key()],
                "ControllerRevision": leader_pods_of_lws,
                "Node": lambda o: [],  # placeholder; exclusive placement keys off pod binding
                "LeaderWorkerSet": pods_of_lws,
                "GroupSet": leader_pod_of_groupset,
                "Service": owner_pod_of_deleted,
                "PodGroup": owner_pod_of_deleted,
            },
        )

        self.groupset_controller = GroupSetReconciler(self.store, self.recorder)
        self.manager.register(
            self.groupset_controller,
            {
                "GroupSet": lambda o: [o.key()],
                "Pod": groupset_owner_of_pod,
            },
        )

        from lws_tpu.api import disagg
        from lws_tpu.controllers.disagg import DSReconciler

        def ds_key_by_label(obj) -> list[Key]:
            name = obj.meta.labels.get(disagg.DS_NAME_LABEL_KEY)
            return [("DisaggregatedSet", obj.meta.namespace, name)] if name else []

        self.ds_controller = DSReconciler(self.store, self.recorder)
        self.manager.register(
            self.ds_controller,
            {
                "DisaggregatedSet": lambda o: [o.key()],
                "LeaderWorkerSet": ds_key_by_label,
            },
        )

        from lws_tpu.controllers.autoscaler_controller import AutoscalerReconciler

        def autoscalers_watching(obj) -> list[Key]:
            # Leader pod metric annotations / LWS changes retrigger autoscalers.
            return [
                asc.key()
                for asc in store.list("Autoscaler", obj.meta.namespace)
                if asc.spec.target == obj.meta.labels.get(contract.SET_NAME_LABEL_KEY, obj.meta.name)
            ]

        self.autoscaler_controller = AutoscalerReconciler(self.store, self.recorder)
        self.manager.register(
            self.autoscaler_controller,
            {
                "Autoscaler": lambda o: [o.key()],
                "Pod": autoscalers_watching,
                "LeaderWorkerSet": autoscalers_watching,
            },
        )

        if enable_scheduler:
            # The scheduler subscribes its own store watch for its incremental
            # pod indexes (binding state, gang membership, pending set).
            self.scheduler = Scheduler(self.store, self.recorder)

            def pending_work(obj) -> list[Key]:
                # Node added/uncordoned or PodGroup created: requeue one
                # representative per waiting gang + waiting solo pods
                # (was: every unbound pod — O(pods) keys per event).
                return self.scheduler.pending_representatives()

            self.manager.register(
                self.scheduler,
                {
                    "Pod": lambda o: [o.key()],
                    "Node": pending_work,
                    "PodGroup": pending_work,
                },
            )
            from lws_tpu.controllers.node_monitor import NodeMonitor

            self.node_monitor = NodeMonitor(self.store, self.recorder)
            self.manager.register(self.node_monitor, {"Node": lambda o: [o.key()]})

        if auto_ready:
            self.kubelet = FakeKubelet(self.store, require_binding=require_binding)
            self.manager.register(self.kubelet, {"Pod": lambda o: [o.key()]})

        # Fleet telemetry plane: the collector merges every ready worker's
        # /metrics into /metrics/fleet (this registry + the process serving
        # registry ride along as instance "control-plane"); the watchdog
        # evaluates stall/hot-loop/backlog rules over the process flight
        # recorder's heartbeats. run_until_stable ticks it deterministically;
        # start() runs it on a thread.
        from lws_tpu.core.flightrecorder import Watchdog
        from lws_tpu.core.metrics import REGISTRY as _process_registry
        from lws_tpu.runtime.fleet import FleetCollector

        control_regs = (
            (self.metrics,) if self.metrics is _process_registry
            else (self.metrics, _process_registry)
        )
        self.fleet = FleetCollector(self.store, control_registries=control_regs)
        self.watchdog = Watchdog(registries=(self.metrics,))

        # Rollout intelligence plane: the process-default ledger observes
        # this store's watch feed plus the process flight recorder, so
        # every revision flip, partition move, DS lockstep step, drain,
        # and pod churn the reconcile path produces lands on the timeline
        # (`GET /debug/rollout`, watchdog dumps, `lws-tpu rollout`).
        from lws_tpu.obs import rollout as rolloutmod

        self.rollout = rolloutmod.LEDGER
        rolloutmod.install(self.store)

        # Decision plane: the provenance ledger (`GET /debug/decisions`,
        # `lws-tpu why`) plus the synchronous DS replica writeback that
        # lets the stock autoscaler move a DS child LWS without the DS
        # reconciler fighting it (lws_tpu/obs/decisions.py).
        from lws_tpu.obs import decisions as decisionsmod

        self.decisions = decisionsmod.DECISIONS
        decisionsmod.install(self.store)

    # ------------------------------------------------------------------
    def run_until_stable(self, max_iterations: int = 10000) -> int:
        if self.elector is not None:
            self.elector.tick()
        n = self.manager.run_until_stable(max_iterations)
        # Deterministic watchdog tick: non-threaded control planes (the
        # dominant test shape) still get alert evaluation after each drain.
        self.watchdog.check_now()
        return n

    def start(self) -> None:
        """Threaded mode: election loop (if configured) + controller workers.
        The manager's gate keeps standby workers passive until elected."""
        if self.elector is not None:
            self.elector.start()
        self.manager.start()
        self.watchdog.start()

    def stop(self) -> None:
        self.watchdog.stop()
        self.manager.stop()
        if self.elector is not None:
            self.elector.stop()

    def resync(self) -> None:
        """Cold-start cache resync: enqueue every stored object to every
        watching controller — required when standing up a fresh control plane
        over pre-existing state (level-triggered restart semantics)."""
        if getattr(self, "scheduler", None) is not None:
            self.scheduler.rebuild_from_store()
        self.manager.resync()

    def add_nodes(self, nodes: list[Node]) -> None:
        for node in nodes:
            self.store.create(node)

    def create(self, obj):
        return self.store.create(obj)
