"""LocalBackend: runs pods as real local processes.

The "kubelet" of single-host deployments and e2e tests: when a pod appears it
spawns the container's command with the pod's injected env (the full
LWS_*/TPU_*/JAX_* bootstrap contract), marks the pod Running+ready, tracks the
process, and reports exits back into pod status — a Failed exit increments
container_restarts, which is exactly what trips the all-or-nothing restart
policy (SURVEY §3.5) for real workloads.

FQDN rewriting: rendezvous names like `<leader>.<subdomain>.<ns>` resolve via
cluster DNS in a fleet; locally every pod is on this host, so values of
address-bearing env vars get their host part rewritten to 127.0.0.1.
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Optional

from lws_tpu.api import contract
from lws_tpu.api.pod import Pod, PodPhase
from lws_tpu.core.manager import Result
from lws_tpu.core.store import Key, Store

ADDRESS_ENV_VARS = (contract.LWS_LEADER_ADDRESS, contract.JAX_COORDINATOR_ADDRESS)


class LocalBackend:
    name = "local-backend"

    def __init__(
        self,
        store: Store,
        env_overrides: Optional[dict[str, str]] = None,
        env_drop: tuple[str, ...] = (),
        default_command: Optional[list[str]] = None,
        log_dir: Optional[str] = None,
    ) -> None:
        self.store = store
        self.env_overrides = env_overrides or {}
        self.env_drop = env_drop
        self.default_command = default_command or ["sleep", "infinity"]
        self.log_dir = log_dir
        self._procs: dict[str, subprocess.Popen] = {}  # pod uid -> process
        self._lock = threading.Lock()

    def pod_logs(self, namespace: str, name: str) -> Optional[str]:
        """Captured stdout/stderr of the CURRENT pod incarnation (logs are
        keyed by uid so a recreated pod never shows its predecessor's output)."""
        if self.log_dir is None:
            return None
        pod = self.store.try_get("Pod", namespace, name)
        if pod is None:
            return None
        path = self._log_path(pod)
        if path is None or not os.path.exists(path):
            return None
        with open(path, errors="replace") as f:
            return f.read()

    def _log_path(self, pod: Pod) -> Optional[str]:
        if self.log_dir is None:
            return None
        return os.path.join(
            self.log_dir, f"{pod.meta.namespace}_{pod.meta.name}_{pod.meta.uid}.log"
        )

    # ------------------------------------------------------------------
    def reconcile(self, key: Key) -> Result | None:
        pod = self.store.try_get("Pod", key[1], key[2])
        if pod is None or not isinstance(pod, Pod):
            self._kill_orphans()
            return None
        with self._lock:
            proc = self._procs.get(pod.meta.uid)
        if proc is None:
            if pod.status.phase == PodPhase.PENDING:
                self._spawn(pod)
            return None
        code = proc.poll()
        if code is None:
            return None
        # Process exited: report status (once).
        if code == 0 and pod.status.phase != PodPhase.SUCCEEDED:
            pod.status.phase = PodPhase.SUCCEEDED
            pod.status.ready = False
            self.store.update_status(pod)
        elif code != 0 and pod.status.phase != PodPhase.FAILED:
            pod.status.phase = PodPhase.FAILED
            pod.status.ready = False
            pod.status.container_restarts += 1
            pod.status.message = f"process exited with code {code}"
            self.store.update_status(pod)
        return None

    # ------------------------------------------------------------------
    def _spawn(self, pod: Pod) -> None:
        container = pod.spec.containers[0]
        command = container.command or self.default_command
        env = {k: v for k, v in os.environ.items() if k not in self.env_drop}
        for e in container.env:
            value = e.value.replace("$(POD_NAME)", pod.meta.name)  # downward-API-lite
            if e.name in ADDRESS_ENV_VARS:
                value = _localize(value)
            env[e.name] = value
        env["POD_NAME"] = pod.meta.name
        env.update(self.env_overrides)
        stdout = None
        if self.log_dir is not None:
            os.makedirs(self.log_dir, exist_ok=True)
            stdout = open(self._log_path(pod), "ab")  # noqa: SIM115 — owned by the child process
        try:
            proc = subprocess.Popen(command, env=env, stdout=stdout, stderr=stdout)
        except OSError as err:
            pod.status.phase = PodPhase.FAILED
            pod.status.message = f"spawn failed: {err}"
            self.store.update_status(pod)
            return
        with self._lock:
            self._procs[pod.meta.uid] = proc
        pod.status.phase = PodPhase.RUNNING
        pod.status.ready = True
        pod.status.address = "127.0.0.1"
        self.store.update_status(pod)

    def _kill_orphans(self) -> None:
        """Kill processes whose pods no longer exist (group teardown)."""
        live_uids = {p.meta.uid for p in self.store.list("Pod")}
        with self._lock:
            dead = [uid for uid in self._procs if uid not in live_uids]
            for uid in dead:
                proc = self._procs.pop(uid)
                if proc.poll() is None:
                    proc.terminate()

    def poll_all(self) -> None:
        """Re-examine every tracked process (call from a ticker or tests)."""
        for pod in self.store.list("Pod"):
            self.reconcile(pod.key())
        self._kill_orphans()

    def shutdown(self) -> None:
        with self._lock:
            for proc in self._procs.values():
                if proc.poll() is None:
                    proc.terminate()
            self._procs.clear()


def _localize(value: str) -> str:
    """Rewrite `host[:port]` to `127.0.0.1[:port]`."""
    if ":" in value:
        _, port = value.rsplit(":", 1)
        if port.isdigit():
            return f"127.0.0.1:{port}"
    return "127.0.0.1"
