"""LocalBackend: runs pods as real local processes.

The "kubelet" of single-host deployments and e2e tests: when a pod appears it
spawns the container's command with the pod's injected env (the full
LWS_*/TPU_*/JAX_* bootstrap contract), marks the pod Running+ready, tracks the
process, and reports exits back into pod status — a Failed exit increments
container_restarts, which is exactly what trips the all-or-nothing restart
policy (SURVEY §3.5) for real workloads.

FQDN rewriting: rendezvous names like `<leader>.<subdomain>.<ns>` resolve via
cluster DNS in a fleet; locally every pod is on this host, so values of
address-bearing env vars get their host part rewritten to 127.0.0.1.
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Optional

from lws_tpu.api import contract
from lws_tpu.api.pod import Pod, PodPhase
from lws_tpu.core.manager import Result
from lws_tpu.core.store import Key, Store

ADDRESS_ENV_VARS = (contract.LWS_LEADER_ADDRESS, contract.JAX_COORDINATOR_ADDRESS)

# Pid of the pod's process, recorded so a restarted backend can re-adopt it.
PID_ANNOTATION_KEY = "local.lws.tpu/pid"


class _ReadoptedProcess:
    """Handle to a process spawned by a PREVIOUS backend incarnation: alive
    checks via signal 0; an exit while unowned reads as failure (we cannot
    reap its true status), which correctly trips the restart policy."""

    def __init__(self, pid: int) -> None:
        self.pid = pid

    def poll(self) -> Optional[int]:
        try:
            os.kill(self.pid, 0)
            return None
        except ProcessLookupError:
            return 1
        except PermissionError:
            return None

    def terminate(self) -> None:
        try:
            os.kill(self.pid, 15)
        except (ProcessLookupError, PermissionError):
            pass

    kill = terminate


def _pid_belongs_to_pod(pid: int, pod_name: str) -> bool:
    """Guard against pid reuse: the process env must carry our POD_NAME."""
    try:
        with open(f"/proc/{pid}/environ", "rb") as f:
            env = f.read().split(b"\0")
        return f"POD_NAME={pod_name}".encode() in env
    except OSError:
        return False


class LocalBackend:
    name = "local-backend"

    def __init__(
        self,
        store: Store,
        env_overrides: Optional[dict[str, str]] = None,
        env_drop: tuple[str, ...] = (),
        default_command: Optional[list[str]] = None,
        log_dir: Optional[str] = None,
    ) -> None:
        self.store = store
        self.env_overrides = env_overrides or {}
        self.env_drop = env_drop
        self.default_command = default_command or ["sleep", "infinity"]
        self.log_dir = log_dir
        self._procs: dict[str, subprocess.Popen] = {}  # guarded-by: _lock — pod uid -> process
        self._lock = threading.Lock()

    def pod_logs(self, namespace: str, name: str) -> Optional[str]:
        """Captured stdout/stderr of the CURRENT pod incarnation (logs are
        keyed by uid so a recreated pod never shows its predecessor's output)."""
        if self.log_dir is None:
            return None
        pod = self.store.try_get("Pod", namespace, name)
        if pod is None:
            return None
        path = self._log_path(pod)
        if path is None or not os.path.exists(path):
            return None
        with open(path, errors="replace") as f:
            return f.read()

    def _log_path(self, pod: Pod) -> Optional[str]:
        if self.log_dir is None:
            return None
        return os.path.join(
            self.log_dir, f"{pod.meta.namespace}_{pod.meta.name}_{pod.meta.uid}.log"
        )

    # ------------------------------------------------------------------
    def reconcile(self, key: Key) -> Result | None:
        pod = self.store.try_get("Pod", key[1], key[2])
        if pod is None or not isinstance(pod, Pod):
            self._kill_orphans()
            return None
        with self._lock:
            proc = self._procs.get(pod.meta.uid)
        if proc is None:
            if pod.status.phase == PodPhase.PENDING:
                self._spawn(pod)
                return None
            if pod.status.phase == PodPhase.RUNNING:
                # Control-plane restart: re-adopt the live process (or report
                # it dead so the restart policy recreates the group).
                self._readopt(pod)
            return None
        code = proc.poll()
        if code is None:
            if pod.status.phase == PodPhase.PENDING:
                # Level-triggered repair: an earlier Running write lost its
                # optimistic-concurrency race; apply it now.
                self._mark_running(pod.meta.namespace, pod.meta.name, pod.meta.uid, proc.pid)
            return None
        # Process exited: report status (once).
        if code == 0 and pod.status.phase != PodPhase.SUCCEEDED:
            pod.status.phase = PodPhase.SUCCEEDED
            pod.status.ready = False
            self.store.update_status(pod)
        elif code != 0 and pod.status.phase != PodPhase.FAILED:
            pod.status.phase = PodPhase.FAILED
            pod.status.ready = False
            pod.status.container_restarts += 1
            pod.status.message = f"process exited with code {code}"
            self.store.update_status(pod)
        return None

    # ------------------------------------------------------------------
    def _spawn(self, pod: Pod) -> None:
        # A PENDING pod may still own a live pre-restart process (the snapshot
        # predated _mark_running's writes): adopt it instead of double-spawning
        # two workers onto the same chips/ports.
        raw_pid = pod.meta.annotations.get(PID_ANNOTATION_KEY)
        if raw_pid and raw_pid.isdigit():
            pid = int(raw_pid)
            if _pid_belongs_to_pod(pid, pod.meta.name):
                with self._lock:
                    self._procs[pod.meta.uid] = _ReadoptedProcess(pid)
                self._mark_running(pod.meta.namespace, pod.meta.name, pod.meta.uid, pid)
                return
        container = pod.spec.containers[0]
        command = container.command or self.default_command
        env = {k: v for k, v in os.environ.items() if k not in self.env_drop}
        for e in container.env:
            value = e.value.replace("$(POD_NAME)", pod.meta.name)  # downward-API-lite
            if e.name in ADDRESS_ENV_VARS:
                value = _localize(value)
            env[e.name] = value
        env["POD_NAME"] = pod.meta.name
        env["POD_NAMESPACE"] = pod.meta.namespace  # downward-API parity
        env.update(self.env_overrides)
        stdout = None
        if self.log_dir is not None:
            os.makedirs(self.log_dir, exist_ok=True)
            stdout = open(self._log_path(pod), "ab")  # noqa: SIM115 — owned by the child process
        try:
            proc = subprocess.Popen(command, env=env, stdout=stdout, stderr=stdout)
        except OSError as err:
            pod.status.phase = PodPhase.FAILED
            pod.status.message = f"spawn failed: {err}"
            self.store.update_status(pod)
            return
        with self._lock:
            self._procs[pod.meta.uid] = proc
        self._mark_running(pod.meta.namespace, pod.meta.name, pod.meta.uid, proc.pid)

    def _mark_running(self, namespace: str, name: str, uid: str, pid: int) -> None:
        """Record pid + Running status on the EXACT pod incarnation we spawned
        for; retries update races (further repair happens level-triggered in
        reconcile). A same-name/new-uid pod (group recreated mid-flight) must
        never inherit this process."""
        from lws_tpu.core.store import ConflictError

        for _ in range(5):
            fresh = self.store.try_get("Pod", namespace, name)
            if fresh is None or fresh.meta.uid != uid:
                # Our pod incarnation is gone: the process is an orphan.
                with self._lock:
                    proc = self._procs.pop(uid, None)
                if proc is not None and proc.poll() is None:
                    proc.terminate()
                return
            try:
                fresh.meta.annotations[PID_ANNOTATION_KEY] = str(pid)
                fresh = self.store.update(fresh)
                fresh.status.phase = PodPhase.RUNNING
                fresh.status.ready = True
                fresh.status.address = "127.0.0.1"
                self.store.update_status(fresh)
                return
            except ConflictError:
                continue

    def _readopt(self, pod: Pod) -> None:
        raw_pid = pod.meta.annotations.get(PID_ANNOTATION_KEY)
        pid = int(raw_pid) if raw_pid and raw_pid.isdigit() else None
        if pid is not None and _pid_belongs_to_pod(pid, pod.meta.name):
            with self._lock:
                self._procs[pod.meta.uid] = _ReadoptedProcess(pid)
            return
        # Process gone or unverifiable: make sure it is not merely
        # unverifiable-but-alive (pid reuse aside, an unreadable /proc entry)
        # before the restart policy spawns a replacement next to it.
        if pid is not None:
            try:
                os.kill(pid, 15)
            except (ProcessLookupError, PermissionError):
                pass
        pod.status.phase = PodPhase.FAILED
        pod.status.ready = False
        pod.status.message = "process lost across control-plane restart"
        pod.status.container_restarts += 1
        self.store.update_status(pod)

    def _kill_orphans(self) -> None:
        """Kill processes whose pods no longer exist (group teardown)."""
        live_uids = {p.meta.uid for p in self.store.list("Pod")}  # vet: ignore[purity-fleet-scan]: the orphan sweep needs the COMPLETE live-uid set by definition; runs on the slow poll ticker
        with self._lock:
            dead = [uid for uid in self._procs if uid not in live_uids]
            for uid in dead:
                proc = self._procs.pop(uid)
                if proc.poll() is None:
                    proc.terminate()

    def poll_all(self) -> None:
        """Re-examine every tracked process (call from a ticker or tests)."""
        for pod in self.store.list("Pod"):
            self.reconcile(pod.key())
        self._kill_orphans()

    def shutdown(self) -> None:
        with self._lock:
            for proc in self._procs.values():
                if proc.poll() is None:
                    proc.terminate()
            self._procs.clear()


def _localize(value: str) -> str:
    """Rewrite `host[:port]` to `127.0.0.1[:port]`."""
    if ":" in value:
        _, port = value.rsplit(":", 1)
        if port.isdigit():
            return f"127.0.0.1:{port}"
    return "127.0.0.1"
