"""HTTP API server: the remote face of the control plane
(≈ the apiserver+webhook endpoint + healthz/readyz + metrics of
cmd/main.go:252-262,336-348 rolled into one in-process server).

Endpoints:
  GET  /healthz | /readyz             liveness/readiness
  GET  /metrics                       Prometheus text (control plane +
                                      process serving registry, one valid
                                      exposition)
  GET  /metrics/fleet                 the AGGREGATED fleet exposition: every
                                      ready worker's /metrics scraped and
                                      merged with instance/role/revision
                                      labels (runtime/fleet.py)
  GET  /debug/traces[?limit=N]        recent spans from the process tracer
                                      (reconcile -> serving trace spine)
  GET  /debug/flightrecorder[?limit=N] flight-recorder snapshot: event ring,
                                      heartbeats, active watchdog alerts,
                                      and the last alert's diagnostics dump
  GET  /debug/profile[?limit=N&format=json|collapsed]
                                      this process's collapsed-stack profile
                                      (core/profile.py; collapsed = raw
                                      flamegraph.pl input)
  GET  /debug/profile/fleet           every ready worker's /debug/profile,
                                      merged with instance/role labels
                                      (runtime/fleet.py)
  GET  /debug/history[?limit=N]       the process history ring: retained
                                      per-series time series sampled from
                                      the /metrics and /metrics/fleet
                                      surfaces (lws_tpu/obs/history.py)
  GET  /debug/decisions[?limit=N]     the decision ledger: provenance
                                      records for every recommender/canary
                                      evaluation with guards, actuation
                                      outcome, and convergence timing
                                      (lws_tpu/obs/decisions.py)
  GET  /debug/compile[?limit=N]       this process's compile ledger:
                                      backend-compile provenance records,
                                      per-executable counters, active storm
                                      windows (lws_tpu/obs/device.py)
  GET  /debug/compile/fleet           every ready worker's /debug/compile
                                      plus the control-plane leg, instance-
                                      labelled, with a cross-fleet
                                      executables fold (runtime/fleet.py)
  GET  /debug/faults                  armed fault points + hit/trip counters
  POST /debug/faults                  arm/disarm deterministic fault
                                      schedules in this process
                                      (core/faults.py; bearer-gated like
                                      every other mutating endpoint)
  POST /apply                         YAML/JSON manifest (create-or-update)
  GET  /apis/{kind}                   list (JSON manifests)
  GET  /apis/{kind}/{ns}/{name}       get
  DELETE /apis/{kind}/{ns}/{name}     delete
  POST /scale/{ns}/{name}             {"replicas": N} on a LeaderWorkerSet
  POST /report-metric/{ns}/{pod}      {"metric": value} -> pod annotation (autoscaler)
  POST /cordon/{node}                 {"unschedulable": bool} (default true)
  POST /drain/{node}                  cordon + evict (groups recreate elsewhere)
  GET  /logs/{ns}/{pod}               captured pod stdout/stderr
  GET  /events[?namespace=&name=]     controller decision trace (k8s Events)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from lws_tpu.core.store import (
    AdmissionError,
    AlreadyExistsError,
    ConflictError,
    FieldManagerConflict,
    NotFoundError,
)
from lws_tpu.manifest import from_manifest, to_manifest


_CANONICAL_KINDS = (
    "LeaderWorkerSet", "DisaggregatedSet", "GroupSet", "Pod", "Node",
    "Service", "PodGroup", "ControllerRevision", "PersistentVolumeClaim",
    "Autoscaler", "Lease",
)
_KIND_ALIASES = {
    **{k.lower(): k for k in _CANONICAL_KINDS},
    **{k.lower() + "s": k for k in _CANONICAL_KINDS},
    "lws": "LeaderWorkerSet",
    "ds": "DisaggregatedSet",
    "pvc": "PersistentVolumeClaim",
    "pvcs": "PersistentVolumeClaim",
    "revision": "ControllerRevision",
    "revisions": "ControllerRevision",
}


def _kind(raw: str) -> str:
    """kubectl-style kind resolution: `pods`, `Pod`, `lws`, ... all work."""
    kind = _KIND_ALIASES.get(raw.lower())
    if kind is None:
        raise ValueError(
            f"unknown kind {raw!r}; one of {', '.join(sorted(_KIND_ALIASES))}"
        )
    return kind


def _retry_conflicts(attempt_fn, what: str):
    """Run a read-modify-update attempt up to 5 times across optimistic-
    concurrency races with background controllers; returns the attempt's
    result. Persistent losers surface as ConflictError → HTTP 409."""
    for _ in range(4):
        try:
            return attempt_fn()
        except (ConflictError, AlreadyExistsError):
            # AlreadyExists: a create lost a create-vs-create race; the next
            # attempt re-reads and takes the update path.
            continue
    try:
        return attempt_fn()  # last try: conflict propagates to the 409 path
    except ConflictError as e:
        raise ConflictError(f"{what}: {e}") from e


def _set_cordon(store, node_name: str, unschedulable: bool) -> None:
    from lws_tpu.api.node import CLUSTER_NAMESPACE

    def attempt():
        node = store.get("Node", CLUSTER_NAMESPACE, node_name)
        node.spec.unschedulable = unschedulable
        store.update(node)

    _retry_conflicts(attempt, f"cordon of {node_name}")


class ApiServer:
    def __init__(
        self,
        control_plane,
        port: int = 9443,
        host: str = "127.0.0.1",
        tls=None,
        watch_buffer: int = 4096,
        auth=None,
    ) -> None:
        """`tls`: an optional lws_tpu.core.certs.CertManager; when given the
        server speaks HTTPS with its (auto-generated, auto-rotated) cert.
        `watch_buffer`: events retained for /watch replay; clients that fall
        further behind are told to relist (k8s "410 Gone" semantics).
        `auth`: an optional lws_tpu.core.auth.TokenAuth; when given every
        endpoint except /healthz//readyz requires a Bearer token (ref gates
        metrics behind authn/authz filters, cmd/main.go:336-348)."""
        import collections

        self.control_plane = control_plane
        self.tls = tls
        self.auth = auth
        cp = control_plane

        # Journey-vault feeds for THIS process (span buffering, resilience
        # events, SLO completions): the API server's local leg of the
        # cross-process /debug/request assembly. Idempotent; off with
        # LWS_TPU_JOURNEYS=0.
        from lws_tpu.obs import journey as journeymod

        journeymod.install()

        # Watch plumbing (≈ the apiserver's watch cache): every store event
        # gets a server-local sequence number; /watch long-polls on it.
        events = collections.deque(maxlen=watch_buffer)
        events_cond = threading.Condition()
        seq_box = {"seq": 0}

        def _record_event(ev) -> None:
            # Store-watch observer on the committing writer's thread: a
            # manifest-encoding bug must cost one watch event, not the
            # writer. Long-pollers resync from a LIST on reconnect anyway.
            try:
                with events_cond:
                    seq_box["seq"] += 1
                    events.append(
                        {"seq": seq_box["seq"], "type": ev.type, "object": to_manifest(ev.obj)}
                    )
                    events_cond.notify_all()
            except Exception:  # vet: ignore[hazard-exception-swallow]: a broken watch-cache append must not kill the committing writer (purity-observer-raise)
                pass

        self._unwatch = cp.store.watch(_record_event)
        self._events, self._events_cond, self._seq_box = events, events_cond, seq_box

        from lws_tpu.version import user_agent

        class Handler(BaseHTTPRequestHandler):
            server_version = user_agent()  # identifies the control plane
            sys_version = ""

            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, body: str, ctype: str = "application/json"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _json(self, code: int, obj):
                self._send(code, json.dumps(obj, indent=1, default=str))

            def _send_exposition(self, text: str) -> None:
                from lws_tpu.core import metrics as metricsmod

                body, ctype = metricsmod.negotiate_exposition(
                    text, self.headers.get("Accept")
                )
                self._send(200, body, ctype)

            def _stream_exposition(self, chunks) -> None:
                """The streaming twin of _send_exposition: write exposition
                chunks to the wire as they merge, close-delimited (HTTP/1.0,
                no Content-Length) — the whole fleet text never exists
                server-side. Per-chunk exemplar stripping and the trailing
                `# EOF` replicate negotiate_exposition byte-for-byte (chunks
                hold whole lines, so the line-anchored strip regex composes)."""
                from lws_tpu.core import metrics as metricsmod

                om = metricsmod.wants_openmetrics(self.headers.get("Accept"))
                chunks = iter(chunks)
                # Pull the first chunk BEFORE committing headers: a scrape
                # pass that dies whole must 500, not truncate a 200.
                try:
                    first = next(chunks)
                except StopIteration:
                    first = "\n"
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    metricsmod.OPENMETRICS_CONTENT_TYPE if om else "text/plain",
                )
                self.end_headers()
                import itertools

                for chunk in itertools.chain((first,), chunks):
                    if not om:
                        chunk = metricsmod.strip_exemplars(chunk)
                    if chunk:
                        self.wfile.write(chunk.encode())
                if om:
                    self.wfile.write(b"# EOF\n")

            def _authorized(self) -> bool:
                if auth is None:
                    return True
                from lws_tpu.core.auth import OPEN_PATHS

                if self.path.split("?", 1)[0] in OPEN_PATHS:
                    return True
                entry = auth.authenticate(self.headers.get("Authorization"))
                if entry is None:
                    self._json(401, {"error": "unauthorized: missing or invalid bearer token"})
                    return False
                if not auth.authorize(entry, self.command):
                    self._json(403, {"error": f"forbidden: role {entry.role!r} may not {self.command}"})
                    return False
                return True

            def do_GET(self):
                if not self._authorized():
                    return
                path = self.path.split("?", 1)[0]
                parts = [p for p in path.split("/") if p]
                if self.path in ("/healthz", "/readyz"):
                    self._send(200, "ok", "text/plain")
                elif path == "/metrics":
                    # One merged exposition: the control plane's registry
                    # plus the process-default registry the serving engines
                    # report into (a live worker embedding both is
                    # inspectable from one scrape).
                    from lws_tpu.core import metrics as metricsmod
                    from lws_tpu.core import profile as profmod
                    from lws_tpu.core import slo as slomod

                    # Device-memory gauges refresh per scrape (CPU-safe
                    # no-op without allocator stats) via the shared helper
                    # — per-device + per-pool + peak/fragmentation + the
                    # hbm_pressure heartbeat, same call the worker
                    # telemetry server makes; SLO attainment windows
                    # age-evict the same way (stale-attainment guard,
                    # core/slo.py).
                    from lws_tpu.obs import device as devicemod

                    devicemod.refresh_device_memory()
                    slomod.RECORDER.refresh()
                    regs = (cp.metrics,) if cp.metrics is metricsmod.REGISTRY \
                        else (cp.metrics, metricsmod.REGISTRY)
                    text = metricsmod.render_exposition(*regs)
                    # Feed the process history ring ONLY when no fleet
                    # collector is wired (the fleet handler below is the
                    # richer source then, and two sources racing one
                    # interval gate would starve each other and flap the
                    # ring's live-series flags between shapes).
                    if getattr(cp, "fleet", None) is None:
                        from lws_tpu.obs import history as historymod

                        historymod.HISTORY.ingest_if_due(text)
                    self._send_exposition(text)
                elif path == "/metrics/fleet":
                    # The aggregated fleet view: every ready worker's
                    # /metrics merged with instance/role/revision labels
                    # under the cardinality cap (runtime/fleet.py).
                    fleet = getattr(cp, "fleet", None)
                    if fleet is None:
                        self._json(404, {"error": "fleet collector not wired"})
                        return
                    from lws_tpu.obs import history as historymod

                    # The instance-labelled fleet view is the control
                    # plane's history source: per-worker series ride the
                    # process ring (interval-gated). The thunk keeps the
                    # streaming bound honest: the whole-fleet text
                    # materializes only when an ingest interval is actually
                    # due (at most once per interval), never per scrape.
                    # Each fresh ingest also evaluates the process-default
                    # recommender, so
                    # `serving_scale_recommendation`/`serving_slo_burn_rate`
                    # and the `burn_rate` alert feed exist on every live
                    # deployment — published on the NEXT scrape, like every
                    # refresh-per-scrape gauge.
                    if historymod.HISTORY.ingest_if_due(
                            lambda: fleet.render_fleet()):
                        from lws_tpu.obs import decisions as decisionsmod

                        try:
                            # The closed-loop decision step: evaluate the
                            # recommender (`current` re-synced from the
                            # store's DS roles) and the canary analyzer,
                            # actuate both planes through the defaults
                            # (kill-switched by LWS_TPU_ACTUATION_DISABLE),
                            # and sweep convergence. Every verdict lands in
                            # the decision ledger either way.
                            decisionsmod.evaluate_and_actuate(cp.store)
                        except Exception:  # vet: ignore[hazard-exception-swallow]: a decision-plane hiccup must never 500 the fleet scrape (BLE001 intended)
                            pass
                    self._stream_exposition(fleet.render_fleet_chunks())
                elif path == "/debug/traces":
                    from urllib.parse import parse_qs, urlparse

                    from lws_tpu.core import trace as tracemod
                    from lws_tpu.runtime.telemetry import parse_limit

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        limit = parse_limit(q)
                    except ValueError as e:
                        # 400, never a 500: non-integer AND negative limits
                        # are both caller errors.
                        self._json(400, {"error": f"bad limit: {e}"})
                        return
                    self._json(200, tracemod.TRACER.spans(limit))
                elif path == "/debug/flightrecorder":
                    from urllib.parse import parse_qs, urlparse

                    from lws_tpu.core import flightrecorder as frmod
                    from lws_tpu.runtime.telemetry import parse_limit

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        limit = parse_limit(q)
                    except ValueError as e:
                        self._json(400, {"error": f"bad limit: {e}"})
                        return
                    self._json(200, frmod.debug_snapshot(
                        limit, getattr(cp, "watchdog", None)
                    ))
                elif path in ("/debug/profile", "/debug/profile/fleet"):
                    from urllib.parse import parse_qs, urlparse

                    from lws_tpu.core import profile as profmod
                    from lws_tpu.runtime.telemetry import (
                        parse_limit,
                        parse_profile_format,
                    )

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        limit = parse_limit(q, default=512)
                        fmt = parse_profile_format(q)
                    except ValueError as e:
                        self._json(400, {"error": f"bad query: {e}"})
                        return
                    if path == "/debug/profile":
                        if fmt == "collapsed":
                            self._send(200, profmod.PROFILER.collapsed(limit),
                                       "text/plain")
                        else:
                            self._json(200, profmod.PROFILER.snapshot(limit))
                        return
                    # Fleet-merged: every ready worker's /debug/profile,
                    # instance/role-labelled like /metrics/fleet.
                    fleet = getattr(cp, "fleet", None)
                    if fleet is None:
                        self._json(404, {"error": "fleet collector not wired"})
                        return
                    sources = fleet.collect_profiles(limit)
                    if fmt == "collapsed":
                        self._send(200, profmod.merge_collapsed(sources),
                                   "text/plain")
                    else:
                        self._json(200, {"instances": [
                            {"labels": labels, "profile": snap}
                            for labels, snap in sources
                        ]})
                elif path in ("/debug/compile", "/debug/compile/fleet"):
                    from urllib.parse import parse_qs, urlparse

                    from lws_tpu.obs import device as devicemod
                    from lws_tpu.runtime.telemetry import parse_limit

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        limit = parse_limit(q)
                    except ValueError as e:
                        self._json(400, {"error": f"bad limit: {e}"})
                        return
                    if path == "/debug/compile":
                        self._json(200, devicemod.debug_compile(limit))
                        return
                    # Fleet-merged: every ready worker's /debug/compile
                    # plus the control plane's own leg, instance-labelled
                    # like /metrics/fleet, with a cross-fleet executables
                    # fold (runtime/fleet.py).
                    fleet = getattr(cp, "fleet", None)
                    if fleet is None:
                        self._json(404, {"error": "fleet collector not wired"})
                        return
                    self._json(200, fleet.collect_compiles(limit))
                elif path == "/debug/history":
                    from urllib.parse import parse_qs, urlparse

                    from lws_tpu.obs import history as historymod
                    from lws_tpu.runtime.telemetry import parse_limit

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        limit = parse_limit(q)
                    except ValueError as e:
                        self._json(400, {"error": f"bad limit: {e}"})
                        return
                    self._json(200, historymod.HISTORY.snapshot(limit))
                elif path == "/debug/rollout":
                    from urllib.parse import parse_qs, urlparse

                    from lws_tpu.obs import rollout as rolloutmod
                    from lws_tpu.runtime.telemetry import parse_limit

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        limit = parse_limit(q)
                    except ValueError as e:
                        self._json(400, {"error": f"bad limit: {e}"})
                        return
                    self._json(200, rolloutmod.LEDGER.snapshot(limit))
                elif path == "/debug/decisions":
                    from urllib.parse import parse_qs, urlparse

                    from lws_tpu.obs import decisions as decisionsmod
                    from lws_tpu.runtime.telemetry import parse_limit

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        limit = parse_limit(q)
                    except ValueError as e:
                        self._json(400, {"error": f"bad limit: {e}"})
                        return
                    self._json(200, decisionsmod.DECISIONS.snapshot(limit))
                elif path == "/debug/requests":
                    from urllib.parse import parse_qs, urlparse

                    from lws_tpu.obs import journey as journeymod
                    from lws_tpu.runtime.telemetry import parse_limit

                    q = parse_qs(urlparse(self.path).query)
                    outcome = q.get("outcome", ["all"])[0]
                    klass = q.get("klass", [""])[0]
                    revision = q.get("revision", [""])[0]
                    fleet = getattr(cp, "fleet", None)
                    try:
                        limit = parse_limit(q, default=32)
                        if fleet is not None:
                            # Fleet-joined index: every ready worker's
                            # retained journeys plus this process's, one
                            # worst-first table (runtime/fleet.py).
                            rows = fleet.collect_request_index(
                                outcome, klass, limit, revision=revision
                            )
                        else:
                            rows = journeymod.VAULT.index(
                                outcome=outcome, klass=klass, limit=limit,
                                revision=revision,
                            )
                    except ValueError as e:
                        # 400, never 500: bad limit/outcome are caller
                        # errors (parse_limit contract, both servers).
                        self._json(400, {"error": str(e)})
                        return
                    self._json(200, rows)
                elif path.startswith("/debug/request/"):
                    from urllib.parse import unquote

                    from lws_tpu.obs import journey as journeymod

                    key = unquote(path[len("/debug/request/"):])
                    fleet = getattr(cp, "fleet", None)
                    if fleet is not None:
                        # Cross-process assembly: the trace ctx rode the KV
                        # frame meta, so every worker's local leg joins by
                        # request id into one connected tree.
                        body = fleet.collect_journeys(key)
                    else:
                        body = journeymod.local_journey(key)
                    if body is None:
                        self._json(404, {"error": f"no journey for {key!r}"})
                        return
                    self._json(200, body)
                elif path == "/debug/faults":
                    from lws_tpu.core import faults as faultsmod

                    self._json(200, faultsmod.INJECTOR.snapshot())
                elif len(parts) == 2 and parts[0] == "apis":
                    try:
                        objs = cp.store.list(_kind(parts[1]))
                    except ValueError as e:
                        self._json(404, {"error": str(e)})
                        return
                    self._json(200, [to_manifest(o) for o in objs])
                elif len(parts) == 4 and parts[0] == "apis":
                    try:
                        obj = cp.store.try_get(_kind(parts[1]), parts[2], parts[3])
                    except ValueError as e:
                        self._json(404, {"error": str(e)})
                        return
                    if obj is None:
                        self._json(404, {"error": f"{parts[1]} {parts[2]}/{parts[3]} not found"})
                    else:
                        self._json(200, to_manifest(obj))
                elif parts[:1] == ["events"]:
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    ns = q.get("namespace", [None])[0]
                    name = q.get("name", [None])[0]
                    out = []
                    for ev in list(cp.recorder.events):  # snapshot: threads append
                        kind, ens, ename = ev.object_key
                        if ns is not None and ens != ns:
                            continue
                        if name is not None and ename != name:
                            continue
                        out.append({
                            "object": f"{kind}/{ens}/{ename}",
                            "type": ev.type,
                            "reason": ev.reason,
                            "message": ev.message,
                            "timestamp": ev.timestamp,
                        })
                    self._json(200, out)
                elif parts[:1] == ["watch"]:
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        since = int(q.get("since", ["0"])[0])
                        timeout = min(float(q.get("timeout", ["30"])[0]), 60.0)
                    except ValueError as e:
                        self._json(400, {"error": f"bad watch params: {e}"})
                        return
                    with events_cond:
                        if since < 0:  # bookmark request: where is "now"?
                            self._json(200, {"events": [], "next": seq_box["seq"]})
                            return
                        oldest = events[0]["seq"] if events else seq_box["seq"] + 1
                        if since > seq_box["seq"] or (
                            since + 1 < oldest and seq_box["seq"] > since
                        ):
                            # Bookmark from the future (server restarted) or
                            # fallen out of the ring: client must relist
                            # (k8s 410 Gone on an unknown resourceVersion).
                            self._json(200, {"expired": True, "next": seq_box["seq"]})
                            return
                        if seq_box["seq"] <= since:
                            events_cond.wait(timeout)
                        batch = [e for e in events if e["seq"] > since]
                    nxt = batch[-1]["seq"] if batch else since
                    self._json(200, {"events": batch, "next": nxt})
                elif len(parts) == 3 and parts[0] == "logs":
                    provider = getattr(cp, "log_provider", None)
                    logs = provider(parts[1], parts[2]) if provider else None
                    if logs is None:
                        self._json(404, {"error": f"no logs for {parts[1]}/{parts[2]}"})
                    else:
                        self._send(200, logs, "text/plain")
                else:
                    self._json(404, {"error": "unknown path"})

            def do_DELETE(self):
                if not self._authorized():
                    return
                path = self.path.split("?", 1)[0]
                parts = [p for p in path.split("/") if p]
                if len(parts) == 4 and parts[0] == "apis":
                    try:
                        cp.store.delete(_kind(parts[1]), parts[2], parts[3])
                    except (ValueError, NotFoundError) as e:
                        self._json(404, {"error": str(e)})
                        return
                    self._json(200, {"deleted": f"{parts[1]}/{parts[2]}/{parts[3]}"})
                else:
                    self._json(404, {"error": "unknown path"})

            def do_POST(self):
                if not self._authorized():
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length).decode()
                path = self.path.split("?", 1)[0]
                parts = [p for p in path.split("/") if p]
                if path == "/debug/faults":
                    from lws_tpu.core import faults as faultsmod

                    try:
                        payload = json.loads(body) if body else {}
                        result = faultsmod.apply_control(payload)
                    except ValueError as e:
                        # 400, never 500: bad specs/JSON are caller errors
                        # (same contract as the other debug surfaces).
                        self._json(400, {"error": str(e)})
                        return
                    self._json(200, result)
                    return
                try:
                    if (len(parts) == 5 and parts[0] == "apis"
                            and parts[4] == "apply"):
                        # Server-side apply (k8s PATCH application/apply-patch
                        # analog): body = partial plain field tree; query
                        # carries fieldManager + force. 409 on field
                        # conflicts so clients can distinguish them from rv
                        # races.
                        from urllib.parse import parse_qs

                        q = (parse_qs(self.path.split("?", 1)[1])
                             if "?" in self.path else {})
                        manager = (q.get("fieldManager") or ["default"])[0]
                        force = (q.get("force") or ["false"])[0].lower() == "true"
                        try:
                            stored = cp.store.apply(
                                _kind(parts[1]), parts[2], parts[3],
                                json.loads(body), field_manager=manager,
                                force=force,
                            )
                        except FieldManagerConflict as e:
                            self._json(409, {"error": str(e), "conflicts": [
                                {"field": ".".join(pth), "manager": owner}
                                for pth, owner in e.conflicts
                            ]})
                            return
                        self._json(200, to_manifest(stored))
                    elif parts[:1] == ["apply"]:
                        import yaml

                        applied = []
                        for doc in yaml.safe_load_all(body):
                            if not doc:
                                continue
                            obj = from_manifest(doc)

                            def attempt(obj=obj):
                                existing = cp.store.try_get(
                                    obj.kind, obj.meta.namespace, obj.meta.name
                                )
                                if existing is None:
                                    return cp.store.create(obj)
                                obj.meta.resource_version = existing.meta.resource_version
                                obj.meta.uid = existing.meta.uid
                                # Spec-only apply: never wipe live status.
                                if hasattr(existing, "status"):
                                    obj.status = existing.status
                                return cp.store.update(obj)

                            stored = _retry_conflicts(
                                attempt, f"apply of {obj.kind}/{obj.meta.name}"
                            )
                            applied.append(f"{stored.kind}/{stored.meta.name}")
                        self._json(200, {"applied": applied})
                    elif len(parts) == 3 and parts[0] == "scale":
                        replicas = int(json.loads(body)["replicas"])

                        def attempt():
                            lws = cp.store.get("LeaderWorkerSet", parts[1], parts[2])
                            lws.spec.replicas = replicas
                            cp.store.update(lws)

                        _retry_conflicts(attempt, f"scale of {parts[2]}")
                        self._json(200, {"scaled": parts[2], "replicas": replicas})
                    elif len(parts) == 2 and parts[0] == "cordon":
                        payload = json.loads(body) if body else {}
                        if not isinstance(payload, dict):
                            raise ValueError("cordon body must be a JSON object")
                        unschedulable = payload.get("unschedulable", True)
                        if not isinstance(unschedulable, bool):
                            raise ValueError(
                                "cordon field 'unschedulable' must be a JSON bool"
                            )
                        _set_cordon(cp.store, parts[1], unschedulable)
                        self._json(200, {"node": parts[1], "unschedulable": unschedulable})
                    elif len(parts) == 2 and parts[0] == "drain":
                        # Cordon + evict: pods on the node are failed so their
                        # groups recreate onto other capacity (slice
                        # maintenance; same path preemption takes).
                        from lws_tpu.controllers.node_monitor import evict_pods_on_node

                        _set_cordon(cp.store, parts[1], True)
                        evicted = evict_pods_on_node(
                            cp.store, parts[1], f"drained from node {parts[1]}",
                            recorder=cp.recorder, reason="Drained",
                        )
                        self._json(200, {"node": parts[1], "evicted": evicted})
                    elif len(parts) == 3 and parts[0] == "report-metric":
                        # Workload-side metric push: annotates the pod so the
                        # autoscaler's HPA loop can read it.
                        from lws_tpu.api.autoscaler import METRIC_ANNOTATION_PREFIX

                        payload = json.loads(body)
                        if not isinstance(payload, dict) or not all(
                            isinstance(v, (int, float)) for v in payload.values()
                        ):
                            raise ValueError(
                                "report-metric body must be a JSON object of numbers"
                            )
                        def attempt():
                            pod = cp.store.get("Pod", parts[1], parts[2])
                            for metric, value in payload.items():
                                pod.meta.annotations[METRIC_ANNOTATION_PREFIX + metric] = str(
                                    float(value)
                                )
                            cp.store.update(pod)

                        _retry_conflicts(attempt, "metric report")
                        self._json(200, {"reported": payload})
                    else:
                        self._json(404, {"error": "unknown path"})
                except (AdmissionError, ValueError) as e:
                    self._json(422, {"error": str(e)})
                except (ConflictError, AlreadyExistsError) as e:
                    self._json(409, {"error": str(e)})
                except NotFoundError as e:
                    self._json(404, {"error": str(e)})
                except (TypeError, KeyError, AttributeError) as e:
                    # Malformed manifest/payload shapes must come back as a
                    # JSON error, not a dropped connection.
                    self._json(400, {"error": f"{type(e).__name__}: {e}"})

        if tls is None:
            self._httpd = ThreadingHTTPServer((host, port), Handler)
        else:
            # Wrap per-accepted-connection, not the listening socket: rotation
            # (CertManager regenerating at 2/3 lifetime) must reach clients
            # without a server restart, and a baked-in listener context would
            # pin the original cert forever.
            class _TLSHTTPServer(ThreadingHTTPServer):
                _ctx = tls.server_context()

                def get_request(inner):
                    sock, addr = ThreadingHTTPServer.get_request(inner)
                    if tls.needs_rotation():
                        type(inner)._ctx = tls.server_context()  # re-ensures
                    # Defer the handshake to the per-connection thread (first
                    # read) and bound it: a client that connects and stalls
                    # must not block the accept loop for everyone else.
                    sock.settimeout(60)
                    wrapped = inner._ctx.wrap_socket(
                        sock, server_side=True, do_handshake_on_connect=False
                    )
                    return wrapped, addr

            self._httpd = _TLSHTTPServer((host, port), Handler)
        self.port = self._httpd.server_port

    def start(self) -> None:
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._unwatch()  # stop serializing store events into a dead buffer
