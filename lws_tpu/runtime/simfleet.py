"""Fleet-scale simulation harness: hundreds of store-backed instances on
one CPU, each serving a REAL telemetry surface.

The observability plane's scale claims (hierarchical scrape fan-in,
streaming exposition merge, per-source budgets) need a fleet to be proven
against, and a real 1,000-pod deployment is not a test fixture. SimFleet
builds the next best thing from the SAME parts the production path uses:

  * every `SimInstance` owns a private `MetricsRegistry` and a real
    `TelemetryServer(registry=...)` on an ephemeral loopback port — the
    fleet scraper dials genuine HTTP, negotiates OpenMetrics, and parses
    genuine expositions, not canned strings;
  * `tick()` advances schema-faithful synthetic series (the SLO ledger's
    `serving_tokens_total{engine,klass,revision}` twins, TTFT/ITL/queue
    histograms with occasional trace exemplars, attainment gauges) from a
    per-instance `random.Random(f"{seed}:{name}")` — byte-reproducible
    across runs, disjoint across instances;
  * with a `store`, each instance is a READY Pod carrying the same
    role/revision labels and LWS_TPU_METRICS_PORT env the production
    discovery contract reads (runtime/fleet.py `targets()`), so the
    two-tier scrape tree shards the simulated fleet exactly as it would a
    real one;
  * `SimFleetTarget` speaks the loadgen open-loop target protocol
    (submit/step/poll), so `lws_tpu/loadgen/` schedules drive synthetic
    traffic across the fleet;
  * `seed_groups()` mass-creates steady-state group records for the
    reconcile-at-scale benchmarks.

`respond_delay_s` is the simulation's stand-in for DCN RTT + remote render
time: handler-thread sleeps overlap, so flat-vs-tree scrape wall-clock is
measurable on one GIL-bound host (benchmarks/fleet_scale_bench.py).
"""

from __future__ import annotations

import random
from typing import Optional, Sequence

from lws_tpu.core.metrics import MetricsRegistry
from lws_tpu.runtime.telemetry import METRICS_PORT_ENV, TelemetryServer

DEFAULT_ROLES = ("prefill", "decode")
DEFAULT_CLASSES = ("chat", "batch")
DEFAULT_REVISIONS = ("rev-a",)


class SimInstance:
    """One simulated serving worker: a seeded synthetic series generator
    behind a real telemetry server. The series it advances are the ones
    the SLO plane (core/slo.py) emits for a live engine, with the same
    label composition — the fleet scraper, history ring, and canary folds
    cannot tell it from a worker."""

    def __init__(self, name: str, role: str, revision: str,
                 klass: str = "chat", seed: int = 0,
                 respond_delay_s: float = 0.0) -> None:
        self.name = name
        self.role = role
        self.revision = revision
        self.klass = klass
        self.registry = MetricsRegistry()
        self.rng = random.Random(f"{seed}:{name}")
        self.server = TelemetryServer(
            port=0, host="127.0.0.1", registry=self.registry,
            respond_delay_s=respond_delay_s,
        )
        self.port = self.server.port
        self.requests = 0
        self._compiles_first = 0
        self._labels = {"engine": self.role, "klass": self.klass,
                        "revision": self.revision}

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop()

    def tick(self, n_requests: int = 1) -> None:
        """Advance the synthetic series by `n_requests` completed requests.
        Deterministic per (seed, name, call sequence)."""
        reg, rng = self.registry, self.rng
        eng = {"engine": self.role}
        for _ in range(n_requests):
            self.requests += 1
            reg.inc("serving_requests_total", eng)
            exemplar = None
            if rng.random() < 0.125:
                exemplar = {"trace_id": f"{self.name}-{self.requests:06d}"}
            reg.observe("serving_queue_wait_seconds",
                        0.002 + rng.random() * 0.03, eng)
            reg.observe("serving_ttft_seconds", 0.05 + rng.random() * 0.2,
                        self._labels, exemplar=exemplar)
            reg.observe("serving_itl_seconds", 0.004 + rng.random() * 0.02,
                        self._labels)
            tokens = 16 + rng.randrange(48)
            good = tokens if rng.random() < 0.95 else max(0, tokens - 8)
            reg.inc("serving_tokens_total", self._labels, float(tokens))
            if good:
                reg.inc("serving_goodput_tokens_total", self._labels,
                        float(good))
        reg.set("serving_slo_attainment",
                round(0.9 + 0.1 * rng.random(), 4), self._labels)
        reg.set("serving_active_slots", float(rng.randrange(8)), eng)
        # Device-runtime series (lws_tpu/obs/device.py twins): every
        # instance paid one first compile per executable at warm-up; a
        # small minority of ticks recompile (bucket misses) — exercises
        # the CMP column, the fleet compile folds, and top-k bounding.
        if self.requests and self._compiles_first == 0:
            self._compiles_first = 1
            reg.inc("serving_compiles_total", {**eng, "kind": "first"})
            reg.observe("serving_compile_seconds",
                        0.2 + rng.random() * 0.8, eng)
        if rng.random() < 0.05:
            reg.inc("serving_compiles_total", {**eng, "kind": "recompile"})
            reg.observe("serving_compile_seconds",
                        0.2 + rng.random() * 0.8, eng)
        limit = 16 * (1 << 30)
        weights = 4.2 * (1 << 30)
        kv = (2.0 + 1.5 * rng.random()) * (1 << 30)
        reg.set("serving_hbm_pool_bytes", weights, {"pool": "weights"})
        reg.set("serving_hbm_pool_bytes", kv, {"pool": "kv"})
        reg.set("serving_hbm_pool_bytes", 0.2 * (1 << 30),
                {"pool": "arena_restore"})
        reg.set("serving_hbm_pool_bytes", 0.3 * (1 << 30),
                {"pool": "workspace"})
        dev = {"device": "tpu:0"}
        reg.set("serving_hbm_bytes_in_use", weights + kv, dev)
        reg.set("serving_hbm_bytes_limit", float(limit), dev)


class SimFleet:
    """A fleet of SimInstances, optionally registered as READY pods in a
    store so `FleetCollector.targets()` discovers them through the
    production pod contract. Context-manageable: servers are real sockets
    and must be stopped."""

    def __init__(self, store=None, n_instances: int = 8,
                 roles: Sequence[str] = DEFAULT_ROLES,
                 classes: Sequence[str] = DEFAULT_CLASSES,
                 revisions: Sequence[str] = DEFAULT_REVISIONS,
                 seed: int = 0, respond_delay_s: float = 0.0,
                 namespace: str = "default",
                 name_prefix: str = "sim") -> None:
        self.store = store
        self.namespace = namespace
        self.seed = seed
        self.instances: list[SimInstance] = []
        for i in range(n_instances):
            self.instances.append(SimInstance(
                name=f"{name_prefix}-{i:04d}",
                role=roles[i % len(roles)],
                revision=revisions[i % len(revisions)],
                klass=classes[i % len(classes)],
                seed=seed,
                respond_delay_s=respond_delay_s,
            ))
        self._started = False

    def start(self) -> "SimFleet":
        for inst in self.instances:
            inst.start()
        if self.store is not None:
            for inst in self.instances:
                self._register_pod(inst)
        self._started = True
        return self

    def _register_pod(self, inst: SimInstance) -> None:
        from lws_tpu.api import disagg
        from lws_tpu.api.pod import Container, EnvVar, Pod, PodPhase, PodSpec
        from lws_tpu.core.store import new_meta

        pod = Pod(
            meta=new_meta(inst.name, namespace=self.namespace, labels={
                disagg.DS_ROLE_LABEL_KEY: inst.role,
                disagg.DS_REVISION_LABEL_KEY: inst.revision,
            }),
            spec=PodSpec(containers=[Container(
                name="w",
                command=["sleep", "1"],
                env=[EnvVar(METRICS_PORT_ENV, str(inst.port))],
            )]),
        )
        created = self.store.create(pod)
        created.status.phase = PodPhase.RUNNING
        created.status.ready = True
        created.status.address = "127.0.0.1"
        self.store.update_status(created)

    def tick(self, n_requests: int = 1) -> None:
        """Advance every instance's series by `n_requests` requests."""
        for inst in self.instances:
            inst.tick(n_requests)

    def stop(self) -> None:
        # Each server's shutdown() blocks until its serve loop polls; do
        # them concurrently or a 1,000-instance fleet takes minutes to
        # tear down.
        from concurrent.futures import ThreadPoolExecutor

        if self.instances:
            with ThreadPoolExecutor(
                    max_workers=min(64, len(self.instances))) as pool:
                list(pool.map(lambda i: i.stop(), self.instances))
        self._started = False

    def __enter__(self) -> "SimFleet":
        return self.start() if not self._started else self

    def __exit__(self, *exc) -> None:
        self.stop()


class SimFleetTarget:
    """Loadgen open-loop target over a SimFleet: each submitted request
    lands on a seeded-random instance as one synthetic completion, so a
    `loadgen.run_schedule` drives fleet-wide series exactly where a router
    would spread real traffic. Results resolve on the next poll — the
    simulation models telemetry load, not decode latency."""

    def __init__(self, fleet: SimFleet, seed: int = 0) -> None:
        self.fleet = fleet
        self._rng = random.Random(f"target:{seed}")
        self._results: dict[int, dict] = {}
        self._next_handle = 0

    def submit(self, req, arrival_wall_t: float) -> Optional[int]:
        inst = self._rng.choice(self.fleet.instances)
        inst.tick(1)
        handle = self._next_handle
        self._next_handle += 1
        self._results[handle] = {
            "n_tokens": int(getattr(req, "max_new_tokens", 0) or 16),
        }
        return handle

    def step(self) -> None:
        pass

    def poll(self, handle: int) -> Optional[dict]:
        return self._results.pop(handle, None)


def seed_groups(store, n_groups: int, namespace: str = "default",
                name_prefix: str = "simlws", group_size: int = 1,
                replicas_per_lws: int = 500) -> list:
    """Mass-create LeaderWorkerSet records sized so the fleet totals
    `n_groups` groups — the reconcile-at-scale fixture
    (benchmarks/fleet_scale_bench.py drives the controller over it).
    Creates spec records only: the reconcile pass materializes the group
    and pod children itself, which is exactly the work being measured."""
    from lws_tpu.testing import LWSBuilder

    out = []
    remaining = n_groups
    idx = 0
    while remaining > 0:
        replicas = min(replicas_per_lws, remaining)
        builder = LWSBuilder(name=f"{name_prefix}-{idx}",
                             namespace=namespace)
        out.append(store.create(
            builder.replicas(replicas).size(group_size).build()
        ))
        remaining -= replicas
        idx += 1
    return out
