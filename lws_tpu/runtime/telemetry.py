"""Worker telemetry server: the per-process /metrics surface the control
plane's fleet scraper aggregates.

A serving worker (disagg prefill/decode, or any process embedding an
engine) exposes its process-default registries over one tiny HTTP server:

  GET /metrics               process metrics.REGISTRY, Prometheus text
  GET /debug/traces?limit=N  recent spans from the process trace.TRACER
  GET /debug/flightrecorder  the process flight-recorder snapshot (ring +
                             heartbeats; ?limit=N bounds the event list)
  GET /debug/profile         the process profiler's collapsed-stack table
                             (?format=collapsed for raw flamegraph input,
                             ?limit=N keeps the heaviest N stacks)
  GET /debug/history         the process history ring: retained per-series
                             time series sampled from /metrics
                             (lws_tpu/obs/history.py; ?limit=N bounds the
                             series list, same 400 contract as the rest)
  GET /debug/decisions       the decision ledger window: provenance records
                             for the actuation planes with guards, outcome
                             and convergence (lws_tpu/obs/decisions.py;
                             ?limit=N, same 400 contract)
  GET /debug/compile         the compile ledger: backend-compile provenance
                             records, per-executable counters, active storm
                             windows (lws_tpu/obs/device.py; ?limit=N, same
                             400 contract)
  GET  /debug/faults         armed fault points + hit/trip counters
  POST /debug/faults         arm/disarm fault schedules in this process
                             ({"arm": {point: spec}}, {"disarm": [...]},
                             {"clear": true} — core/faults.py grammar)
  POST /debug/drain          request graceful drain: the worker loop stops
                             admitting, finishes in-flight work, exits
                             clean (core/resilience.py DrainGate)
  GET /healthz               liveness

Workers declare the port via LWS_TPU_METRICS_PORT in their pod env — the
containerPort analog the fleet collector (runtime/fleet.py) reads from the
pod spec, exactly like the KV endpoint's LWS_TPU_KV_PORT. Port 0 binds an
ephemeral port (tests). When LWS_TPU_METRICS_TOKEN is set (on worker AND
control plane — same-deployment convention), everything except /healthz
requires `Authorization: Bearer <token>`: the debug surface carries span
trees and request ids, the same data the API server gates behind auth.

start_from_env also runs a worker-side Watchdog over the process flight
recorder: a wedged decode ring or KV backlog in a WORKER process must trip
`lws_watchdog_*` (which ride the fleet scrape) and capture a dump, not
just beat a heartbeat table nothing evaluates."""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

METRICS_PORT_ENV = "LWS_TPU_METRICS_PORT"
METRICS_TOKEN_ENV = "LWS_TPU_METRICS_TOKEN"


def parse_limit(query: dict, default: int = 256) -> int:
    """Parse a ?limit=N value: non-integer or negative raises ValueError
    (callers answer 400 — malformed input must never 500 a debug surface)."""
    raw = query.get("limit", [str(default)])[0]
    limit = int(raw)  # ValueError on non-integer
    if limit < 0:
        raise ValueError(f"limit must be >= 0, got {limit}")
    return limit


PROFILE_FORMATS = ("json", "collapsed")


def parse_profile_format(query: dict) -> str:
    """Parse a /debug/profile ?format= value; unknown formats raise
    ValueError (same 400-never-500 contract as parse_limit)."""
    fmt = query.get("format", ["json"])[0]
    if fmt not in PROFILE_FORMATS:
        raise ValueError(
            f"format must be one of {', '.join(PROFILE_FORMATS)}, got {fmt!r}"
        )
    return fmt


class TelemetryServer:
    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 watchdog=None, token: Optional[str] = None,
                 registry=None, respond_delay_s: float = 0.0) -> None:
        """`watchdog` (a flightrecorder.Watchdog) contributes alerts and the
        last diagnostics dump to /debug/flightrecorder; `token` gates every
        path except /healthz behind `Authorization: Bearer <token>`.

        `registry` overrides the process-default metrics surface: /metrics
        serves `registry.render()` and skips the process-global side effects
        (device-memory refresh, SLO window refresh, history-ring feed) so
        hundreds of simulated instances (runtime/simfleet.py) can serve
        disjoint expositions from ONE process without cross-polluting the
        process registries. `respond_delay_s` sleeps in the handler thread
        before answering /metrics — the simulation's stand-in for DCN RTT +
        remote render time, which is what makes flat-vs-tree scrape
        wall-clock measurable on one host (sleeps overlap; GIL-bound CPU
        work would not)."""
        from lws_tpu.core import faults as faultsmod
        from lws_tpu.core import flightrecorder as frmod
        from lws_tpu.core import metrics as metricsmod
        from lws_tpu.core import profile as profmod
        from lws_tpu.core import resilience as resmod
        from lws_tpu.core import slo as slomod
        from lws_tpu.core import trace as tracemod
        from lws_tpu.obs import history as historymod
        from lws_tpu.obs import journey as journeymod

        self.watchdog = watchdog
        outer = self

        class Handler(BaseHTTPRequestHandler):
            sys_version = ""

            def log_message(self, *args):  # quiet
                pass

            def _authorized(self) -> bool:
                if token is None:
                    return True
                return self.headers.get("Authorization") == f"Bearer {token}"

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                from urllib.parse import parse_qs, urlparse

                parsed = urlparse(self.path)
                path, q = parsed.path, parse_qs(parsed.query)
                if path == "/healthz":
                    self._send(200, "ok", "text/plain")
                    return
                if not self._authorized():
                    self._send(401, json.dumps({"error": "unauthorized"}),
                               "application/json")
                    return
                if path == "/metrics":
                    if respond_delay_s > 0.0:
                        time.sleep(respond_delay_s)  # simulated remote RTT
                    if registry is not None:
                        text = registry.render()
                    else:
                        # Device-memory gauges are state, not a feed: refresh
                        # them per scrape (guarded no-op on CPU backends) via
                        # the shared helper (per-device + per-pool + peak/
                        # fragmentation + pressure heartbeat — the API server
                        # calls the same one). The SLO attainment windows
                        # age-evict the same way — a quiet engine must not
                        # advertise stale attainment.
                        from lws_tpu.obs import device as devicemod

                        devicemod.refresh_device_memory()
                        slomod.RECORDER.refresh()
                        text = metricsmod.REGISTRY.render()
                        # The scrape opportunistically feeds the history ring
                        # (interval-gated), so history accrues at scrape
                        # cadence even without the sampling thread.
                        historymod.HISTORY.ingest_if_due(text)
                    body, ctype = metricsmod.negotiate_exposition(
                        text, self.headers.get("Accept")
                    )
                    self._send(200, body, ctype)
                elif path == "/debug/profile":
                    try:
                        limit = parse_limit(q, default=512)
                        fmt = parse_profile_format(q)
                    except ValueError as e:
                        self._send(400, json.dumps({"error": f"bad query: {e}"}),
                                   "application/json")
                        return
                    if fmt == "collapsed":
                        self._send(200, profmod.PROFILER.collapsed(limit),
                                   "text/plain")
                    else:
                        self._send(200,
                                   json.dumps(profmod.PROFILER.snapshot(limit)),
                                   "application/json")
                elif path == "/debug/traces":
                    try:
                        limit = parse_limit(q)
                    except ValueError as e:
                        self._send(400, json.dumps({"error": f"bad limit: {e}"}),
                                   "application/json")
                        return
                    self._send(200, json.dumps(tracemod.TRACER.spans(limit),
                                               default=str),
                               "application/json")
                elif path == "/debug/flightrecorder":
                    try:
                        limit = parse_limit(q)
                    except ValueError as e:
                        self._send(400, json.dumps({"error": f"bad limit: {e}"}),
                                   "application/json")
                        return
                    snapshot = frmod.debug_snapshot(limit, outer.watchdog)
                    self._send(200, json.dumps(snapshot, default=str),
                               "application/json")
                elif path == "/debug/history":
                    try:
                        limit = parse_limit(q)
                    except ValueError as e:
                        self._send(400, json.dumps({"error": f"bad limit: {e}"}),
                                   "application/json")
                        return
                    self._send(200,
                               json.dumps(historymod.HISTORY.snapshot(limit)),
                               "application/json")
                elif path == "/debug/decisions":
                    # The decision ledger window: provenance records for
                    # the actuation planes (lws_tpu/obs/decisions.py) —
                    # same parse_limit/bearer contract as the API server.
                    from lws_tpu.obs import decisions as decisionsmod

                    try:
                        limit = parse_limit(q)
                    except ValueError as e:
                        self._send(400, json.dumps({"error": f"bad limit: {e}"}),
                                   "application/json")
                        return
                    self._send(200,
                               json.dumps(decisionsmod.DECISIONS.snapshot(limit)),
                               "application/json")
                elif path == "/debug/requests":
                    # The journey index: tail-retained requests by outcome
                    # (breached / slowest / errored / ...), worst first.
                    try:
                        limit = parse_limit(q, default=32)
                        rows = journeymod.VAULT.index(
                            outcome=q.get("outcome", ["all"])[0],
                            klass=q.get("klass", [""])[0],
                            limit=limit,
                            revision=q.get("revision", [""])[0],
                        )
                    except ValueError as e:
                        # 400, never 500: a bad limit or an unknown outcome
                        # is a caller error (parse_limit contract).
                        self._send(400, json.dumps({"error": str(e)}),
                                   "application/json")
                        return
                    self._send(200, json.dumps(rows, default=str),
                               "application/json")
                elif path.startswith("/debug/request/"):
                    # One request's LOCAL journey leg, by request OR trace
                    # id: the tail-sampled vault first, the bounded span
                    # ring second (lws_tpu/obs/journey.py).
                    from urllib.parse import unquote

                    key = unquote(path[len("/debug/request/"):])
                    body = journeymod.local_journey(key)
                    if body is None:
                        self._send(404, json.dumps(
                            {"error": f"no journey for {key!r}"}),
                            "application/json")
                        return
                    self._send(200, json.dumps(body, default=str),
                               "application/json")
                elif path == "/debug/prefixes":
                    # Prefix-cache digest advertisement (ISSUE 18): the
                    # engine's resident digests + its host arena's spilled
                    # digests, plus the KV port a sibling fetch_prefix
                    # should dial. The FleetCollector merges these into the
                    # digest -> instance index behind the remote tier.
                    try:
                        limit = parse_limit(q)
                    except ValueError as e:
                        self._send(400, json.dumps({"error": f"bad limit: {e}"}),
                                   "application/json")
                        return
                    # Lazy import: telemetry must stay importable (and
                    # light) in processes that never load the serving stack.
                    from lws_tpu.serving import kv_host_arena as _kha

                    self._send(200, json.dumps(_kha.debug_prefixes(limit)),
                               "application/json")
                elif path == "/debug/compile":
                    # The compile ledger: backend-compile provenance records
                    # + per-executable counters + active storm windows
                    # (lws_tpu/obs/device.py) — same parse_limit/bearer
                    # contract as the API server's twin.
                    try:
                        limit = parse_limit(q)
                    except ValueError as e:
                        self._send(400, json.dumps({"error": f"bad limit: {e}"}),
                                   "application/json")
                        return
                    from lws_tpu.obs import device as devicemod

                    self._send(200, json.dumps(devicemod.debug_compile(limit),
                                               default=str),
                               "application/json")
                elif path == "/debug/faults":
                    self._send(200, json.dumps(faultsmod.INJECTOR.snapshot()),
                               "application/json")
                else:
                    self._send(404, json.dumps({"error": "unknown path"}),
                               "application/json")

            def do_POST(self):
                from urllib.parse import urlparse

                path = urlparse(self.path).path
                if not self._authorized():
                    self._send(401, json.dumps({"error": "unauthorized"}),
                               "application/json")
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length).decode() if length else ""
                if path == "/debug/faults":
                    try:
                        payload = json.loads(body) if body else {}
                        result = faultsmod.apply_control(payload)
                    except ValueError as e:
                        # 400, never 500: bad specs/JSON are caller errors,
                        # same contract as parse_limit.
                        self._send(400, json.dumps({"error": str(e)}),
                                   "application/json")
                        return
                    self._send(200, json.dumps(result), "application/json")
                elif path == "/debug/drain":
                    accepted = resmod.DRAIN.request("debug-endpoint")
                    self._send(200, json.dumps({"draining": accepted}),
                               "application/json")
                else:
                    self._send(404, json.dumps({"error": "unknown path"}),
                               "application/json")

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            # Default backlog (5) drops SYNs when a fleet scraper's burst
            # lands while the accept loop waits on the GIL; the kernel's
            # 1s/2s/4s retransmit ladder then turns a 50ms scrape into
            # seconds. Queue the burst instead.
            request_queue_size = 128

            def handle_error(self, request, client_address):
                # A scraper hanging up mid-response (its timeout fired, the
                # pool was torn down) is the CLIENT's failure accounting —
                # `lws_fleet_scrape_errors_total` — not a server traceback;
                # everything else keeps the stock stderr report.
                import sys as _sys

                exc = _sys.exc_info()[1]
                if isinstance(exc, (BrokenPipeError, ConnectionResetError)):
                    return
                super().handle_error(request, client_address)

        self._httpd = _Server((host, port), Handler)
        self.port = self._httpd.server_port

    def start(self) -> None:
        # 0.1s poll (not serve_forever's 0.5s default): shutdown() blocks
        # until the serve loop's next poll, and simfleet stops hundreds of
        # these — 0.5s apiece turns fleet teardown into minutes.
        threading.Thread(
            target=lambda: self._httpd.serve_forever(poll_interval=0.1),
            daemon=True,
        ).start()
        if self.watchdog is not None:
            self.watchdog.start()

    def stop(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        self._httpd.shutdown()


def start_from_env() -> Optional[TelemetryServer]:
    """Start the telemetry server on the pod-declared port, with a
    worker-side Watchdog evaluating the default stall/hot-loop/backlog
    rules over this process's heartbeats; None when the env doesn't declare
    a port (telemetry is opt-in per pod spec). Also starts the continuous
    profiler when LWS_TPU_PROFILE_HZ declares a rate — its /debug/profile
    surface rides this server."""
    import os

    from lws_tpu.core import profile as profmod
    from lws_tpu.core.flightrecorder import Watchdog

    raw = os.environ.get(METRICS_PORT_ENV)
    if not raw:
        return None
    profmod.start_from_env()
    # Journey vault feeds (span buffering, resilience events, SLO
    # completions) — the tail-sampled forensics plane every worker serves
    # at /debug/request[s] (LWS_TPU_JOURNEYS=0 disables).
    from lws_tpu.obs import journey as journey_env

    journey_env.install()
    # History ring sampling thread (LWS_TPU_HISTORY_INTERVAL_S; 0 disables
    # — the /metrics handler still feeds the ring per scrape).
    from lws_tpu.obs import history as history_env

    history_env.start_from_env()
    # Compile ledger: arm the jax.monitoring backend-compile listener so
    # every compile this worker pays lands on /debug/compile with engine/
    # shape/request provenance (LWS_TPU_COMPILE_LEDGER=0 disables).
    from lws_tpu.obs import device as device_env

    device_env.arm_from_env()
    server = TelemetryServer(
        port=int(raw),
        watchdog=Watchdog(),
        token=os.environ.get(METRICS_TOKEN_ENV) or None,
    )
    server.start()
    return server
