"""Default multi-host worker workloads, driven purely by the injected env
contract — the acceptance smoke of SURVEY §7 stage 3 / BASELINE config #2:
"JAX multi-host psum smoke test, leader as coordinator".

  python -m lws_tpu.runtime.worker psum

reads JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID (injected
by the pod webhook), initializes jax.distributed with the leader as
coordinator, and all-reduces (process_id + 1) across the group. Writes
"<result>" to $LWS_TPU_RESULT_FILE when it matches n(n+1)/2.
"""

from __future__ import annotations

import os
import sys


def run_psum() -> int:
    from lws_tpu.parallel import initialize_from_env

    info = initialize_from_env()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("x",))

    n = info.num_processes
    n_local = jax.local_device_count()
    local = jnp.full((n_local,), float(info.process_id + 1)) / n_local
    arr = jax.make_array_from_process_local_data(NamedSharding(mesh, P("x")), np.asarray(local))
    total = float(jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(arr)[()])

    expected = n * (n + 1) / 2
    ok = abs(total - expected) < 1e-6
    _write_result(f"process={info.process_id} total={total} expected={expected} ok={ok}")
    print(f"[worker {info.process_id}/{n}] psum={total} expected={expected} ok={ok}")
    return 0 if ok else 1


def run_tp_forward() -> int:
    """BASELINE config #3 shape: the whole group forms ONE tensor-parallel
    mesh over all its processes' devices and runs a sharded llama forward —
    every process computes the identical replicated logits (the XLA program
    all-reduces over the tp axis spanning process boundaries)."""
    from lws_tpu.parallel import initialize_from_env

    info = initialize_from_env()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from lws_tpu.models import LlamaConfig, forward, init_params, param_shardings
    from lws_tpu.parallel import mesh_from_bootstrap

    # The canonical contract->mesh mapping (tp over the slice; subgroups
    # would become pp stages).
    mesh = mesh_from_bootstrap(info)
    n_dev = mesh.devices.size
    cfg = LlamaConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, max_seq_len=32, dtype=jnp.float32, remat=False,
    )
    with jax.set_mesh(mesh):
        shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), param_shardings(cfg))
        params = jax.jit(lambda: init_params(cfg, jax.random.key(7)), out_shardings=shardings)()
        tokens = jnp.arange(16, dtype=jnp.int32)[None, :]
        logits = jax.jit(
            lambda p, t: forward(p, t, cfg)[0], out_shardings=NamedSharding(mesh, P())
        )(params, tokens)
        checksum = float(jnp.sum(jnp.abs(logits)))

    line = (
        f"process={info.process_id}/{info.num_processes} devices={n_dev} "
        f"tp={n_dev} checksum={checksum:.4f}"
    )
    _write_result(line)
    print(f"[worker] {line}")
    return 0


def run_serve_tp() -> int:
    """BASELINE #3 serving shape: the group forms ONE tensor-parallel mesh
    across its processes and serves through the TP-sharded Engine — params
    and KV cache sharded over 'tp' spanning process boundaries, decode under
    GSPMD. Every process must sample IDENTICAL tokens (the lm-head
    all-reduce replicates the logits), which is what makes multi-host
    serving coherent: any process can answer."""
    from lws_tpu.parallel import initialize_from_env

    info = initialize_from_env()

    import jax
    import jax.numpy as jnp

    from lws_tpu.models import LlamaConfig, init_params
    from lws_tpu.parallel import mesh_from_bootstrap
    from lws_tpu.serving import Engine

    mesh = mesh_from_bootstrap(info)
    cfg = LlamaConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, max_seq_len=64, dtype=jnp.float32, remat=False,
    )
    with jax.set_mesh(mesh):
        params = jax.jit(lambda: init_params(cfg, jax.random.key(7)))()
        engine = Engine(cfg, params, batch_size=2, max_len=64, mesh=mesh)
        prompt = (jnp.arange(32, dtype=jnp.int32) % 64).reshape(2, 16)
        token, cache = engine.prefill(prompt)
        token, cache, toks = engine.decode_n(token, cache, 8)
        tokens = [int(t) for t in jax.device_get(toks).ravel()]

    line = (
        f"process={info.process_id}/{info.num_processes} "
        f"tp={mesh.devices.size} tokens={tokens}"
    )
    _write_result(line)
    print(f"[worker] {line}")
    return 0


def run_serve_paged() -> int:
    """The COMPOSED serving shape across process boundaries: the group's tp
    mesh serves a PagedBatchEngine with prefix caching and mixed
    greedy/seeded-sampled requests. Host-side allocation (slots, blocks,
    prefix map) is deterministic, and every device value that reaches the
    host comes from replicated computation — so all processes must emit
    IDENTICAL tokens and identical prefix-hit stats (multi-host coherence
    for the full density stack)."""
    from lws_tpu.parallel import initialize_from_env

    info = initialize_from_env()

    import numpy as np

    import jax
    import jax.numpy as jnp

    from lws_tpu.models import LlamaConfig, init_params
    from lws_tpu.parallel import mesh_from_bootstrap
    from lws_tpu.serving.paged_engine import PagedBatchEngine

    mesh = mesh_from_bootstrap(info)
    cfg = LlamaConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, max_seq_len=64, dtype=jnp.float32, remat=False,
    )
    with jax.set_mesh(mesh):
        params = jax.jit(lambda: init_params(cfg, jax.random.key(7)))()
        engine = PagedBatchEngine(
            cfg, params, slots=2, max_len=32, block_size=8,
            mesh=mesh, prefix_cache=True,
        )
        sys_prompt = (np.arange(16) % 64).astype(np.int32)
        a = engine.submit(np.concatenate([sys_prompt, [40, 41]]).astype(np.int32),
                          max_new_tokens=6)
        # seed=None exercises the multi-process entropy broadcast: each
        # process draws different urandom, process 0's wins — coherence.
        b = engine.submit(np.concatenate([sys_prompt, [50]]).astype(np.int32),
                          max_new_tokens=6, temperature=0.8, top_k=16, seed=None)
        engine.run_until_drained()
        tokens = engine.result(a) + engine.result(b)

    line = (
        f"process={info.process_id}/{info.num_processes} "
        f"tp={mesh.devices.size} hits={engine.stats_prefix['hit_tokens']} "
        f"tokens={tokens}"
    )
    _write_result(line)
    print(f"[worker] {line}")
    return 0


def _write_result(line: str) -> None:
    """Atomic write: readers poll for the file and must never see it empty."""
    out = os.environ.get("LWS_TPU_RESULT_FILE")
    if not out:
        return
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        f.write(line + "\n")
    os.replace(tmp, out)


def main() -> int:
    cmd = sys.argv[1] if len(sys.argv) > 1 else "psum"
    if cmd == "psum":
        return run_psum()
    if cmd == "tp_forward":
        return run_tp_forward()
    if cmd == "serve_tp":
        return run_serve_tp()
    if cmd == "serve_paged":
        return run_serve_paged()
    if cmd == "sleep":
        import time

        time.sleep(float(sys.argv[2]) if len(sys.argv) > 2 else 3600)
        return 0
    print(f"unknown worker command {cmd!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
