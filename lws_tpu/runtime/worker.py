"""Default multi-host worker workloads, driven purely by the injected env
contract — the acceptance smoke of SURVEY §7 stage 3 / BASELINE config #2:
"JAX multi-host psum smoke test, leader as coordinator".

  python -m lws_tpu.runtime.worker psum

reads JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID (injected
by the pod webhook), initializes jax.distributed with the leader as
coordinator, and all-reduces (process_id + 1) across the group. Writes
"<result>" to $LWS_TPU_RESULT_FILE when it matches n(n+1)/2.
"""

from __future__ import annotations

import os
import sys


def run_psum() -> int:
    from lws_tpu.parallel import initialize_from_env

    info = initialize_from_env()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("x",))

    n = info.num_processes
    n_local = jax.local_device_count()
    local = jnp.full((n_local,), float(info.process_id + 1)) / n_local
    arr = jax.make_array_from_process_local_data(NamedSharding(mesh, P("x")), np.asarray(local))
    total = float(jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(arr)[()])

    expected = n * (n + 1) / 2
    ok = abs(total - expected) < 1e-6
    out = os.environ.get("LWS_TPU_RESULT_FILE")
    if out:
        with open(out, "w") as f:
            f.write(f"process={info.process_id} total={total} expected={expected} ok={ok}\n")
    print(f"[worker {info.process_id}/{n}] psum={total} expected={expected} ok={ok}")
    return 0 if ok else 1


def main() -> int:
    cmd = sys.argv[1] if len(sys.argv) > 1 else "psum"
    if cmd == "psum":
        return run_psum()
    if cmd == "sleep":
        import time

        time.sleep(float(sys.argv[2]) if len(sys.argv) > 2 else 3600)
        return 0
    print(f"unknown worker command {cmd!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
