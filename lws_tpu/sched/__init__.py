"""L5 scheduling: slice-aware pod scheduler + gang-scheduling providers.

The reference delegates binding to kube-scheduler and gang admission to
Volcano (pkg/schedulerprovider/); here both are native and TPU-topology-aware:
a slice (NODE_TPU_SLICE_LABEL domain) is the atomic placement unit.
"""

from lws_tpu.sched.provider import GangSchedulerProvider, SchedulerProvider, get_pod_group_name  # noqa: F401
from lws_tpu.sched.scheduler import Scheduler  # noqa: F401
from lws_tpu.sched.topology import make_slice_nodes  # noqa: F401
