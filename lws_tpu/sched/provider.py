"""Gang scheduling provider plug-point
(≈ pkg/schedulerprovider/interface.go:39-64 + volcano_provider.go).

`create_pod_group_if_not_exists` is called by the pod controller when it sees
a leader pod; `inject_pod_group_metadata` is called by the pod webhook on every
group pod. PodGroup name: `<lws>-<groupIdx>-<revision>` so each rolling-update
generation gangs separately.
"""

from __future__ import annotations

from typing import Optional, Protocol

from lws_tpu.api import contract
from lws_tpu.api.pod import Pod
from lws_tpu.api.podgroup import PodGroup, PodGroupSpec
from lws_tpu.api.types import LeaderWorkerSet, StartupPolicy
from lws_tpu.core.store import Store, new_meta
from lws_tpu.utils.common import group_resource_total
from lws_tpu.utils.revision import get_revision_key


def get_pod_group_name(lws_name: str, group_index: str, revision_key: str) -> str:
    return f"{lws_name}-{group_index}-{revision_key}"


class SchedulerProvider(Protocol):
    def create_pod_group_if_not_exists(self, lws: LeaderWorkerSet, leader_pod: Pod) -> None: ...

    def inject_pod_group_metadata(self, pod: Pod) -> None: ...


class GangSchedulerProvider:
    """Native gang provider: one PodGroup per replica, min_member = group size
    (1 under LeaderReady startup: workers appear only after the leader runs,
    ref volcano_provider.go:58-66), min_resources = whole-group sum."""

    def __init__(self, store: Store, queue: str = "") -> None:
        self.store = store
        self.queue = queue

    def _queue_for(self, lws: LeaderWorkerSet) -> str:
        """Queue for this LWS's PodGroups; read per call so providers that
        derive it from LWS annotations stay safe under concurrent reconciles
        of different LWS (no shared-state write between them)."""
        return self.queue

    def create_pod_group_if_not_exists(self, lws: LeaderWorkerSet, leader_pod: Pod) -> None:
        group_index = leader_pod.meta.labels.get(contract.GROUP_INDEX_LABEL_KEY, "0")
        name = get_pod_group_name(lws.meta.name, group_index, get_revision_key(leader_pod))
        if self.store.try_get("PodGroup", lws.meta.namespace, name) is not None:
            return
        size = lws.spec.leader_worker_template.size
        min_member = 1 if lws.spec.startup_policy == StartupPolicy.LEADER_READY else size
        leader_template = (
            lws.spec.leader_worker_template.leader_template
            or lws.spec.leader_worker_template.worker_template
        )
        worker_template = lws.spec.leader_worker_template.worker_template

        def total(template):
            out: dict[str, int] = {}
            for c in template.spec.containers:
                for k, v in c.resources.items():
                    out[k] = out.get(k, 0) + v
            return out

        min_resources = group_resource_total(total(leader_template), total(worker_template), size)
        # Owner = the leader pod: the PodGroup is GC'd and re-created on group
        # recreation (ref volcano_provider.go:84-90).
        self.store.create(
            PodGroup(
                meta=new_meta(
                    name,
                    lws.meta.namespace,
                    labels={contract.SET_NAME_LABEL_KEY: lws.meta.name},
                    owners=[leader_pod],
                ),
                spec=PodGroupSpec(
                    min_member=min_member,
                    min_resources=min_resources,
                    queue=self._queue_for(lws),
                ),
            )
        )

    def inject_pod_group_metadata(self, pod: Pod) -> None:
        lws_name = pod.meta.labels.get(contract.SET_NAME_LABEL_KEY, "")
        group_index = pod.meta.labels.get(contract.GROUP_INDEX_LABEL_KEY, "")
        revision = pod.meta.labels.get(contract.REVISION_LABEL_KEY, "")
        pod.meta.annotations[contract.POD_GROUP_ANNOTATION_KEY] = get_pod_group_name(
            lws_name, group_index, revision
        )


# Annotation namespaces an external gang scheduler owns; inherited verbatim
# from the LWS onto PodGroups and pods (ref volcano_provider.go:49-101
# inherits queue + volcano.sh/* annotations; DS e2e checks Kueue labels).
EXTERNAL_INHERIT_PREFIXES = ("volcano.sh/", "kueue.x-k8s.io/", "scheduling.x-k8s.io/")
EXTERNAL_QUEUE_ANNOTATION = "volcano.sh/queue-name"


class ExternalSchedulerProvider(GangSchedulerProvider):
    """Compat path for clusters that already run an external gang scheduler
    (Volcano/Kueue-style): PodGroups carry the inherited queue + external
    annotations, pods are stamped with the external scheduler's name, and
    the NATIVE scheduler leaves them strictly alone — binding happens via
    the API (spec.node_name update through a client), exactly how an
    external scheduler integrates with an apiserver."""

    def __init__(self, store: Store, scheduler_name: str = "external") -> None:
        super().__init__(store)
        self.scheduler_name = scheduler_name

    def _queue_for(self, lws: LeaderWorkerSet) -> str:
        return lws.meta.annotations.get(EXTERNAL_QUEUE_ANNOTATION, "")

    def create_pod_group_if_not_exists(self, lws: LeaderWorkerSet, leader_pod: Pod) -> None:
        super().create_pod_group_if_not_exists(lws, leader_pod)
        # Inherit the external scheduler's annotation namespaces.
        group_index = leader_pod.meta.labels.get(contract.GROUP_INDEX_LABEL_KEY, "0")
        name = get_pod_group_name(lws.meta.name, group_index, get_revision_key(leader_pod))
        inherited = {
            k: v
            for k, v in lws.meta.annotations.items()
            if k.startswith(EXTERNAL_INHERIT_PREFIXES)
        }
        if not inherited:
            return
        pg = self.store.try_get("PodGroup", lws.meta.namespace, name)
        if pg is not None and not all(
            pg.meta.annotations.get(k) == v for k, v in inherited.items()
        ):
            pg.meta.annotations.update(inherited)
            from lws_tpu.core.store import ConflictError

            try:
                self.store.update(pg)
            except ConflictError:
                pass  # level-triggered: the next leader-pod reconcile retries

    def inject_pod_group_metadata(self, pod: Pod) -> None:
        super().inject_pod_group_metadata(pod)
        pod.spec.scheduler_name = self.scheduler_name


def make_scheduler_provider(name: Optional[str], store: Store) -> Optional[SchedulerProvider]:
    """≈ schedulerprovider factory (interface.go:57-64). `external[:NAME]`
    selects the external-compat provider (pods bound by a foreign scheduler
    through the API)."""
    if name in (None, ""):
        return None
    if name == "gang":
        return GangSchedulerProvider(store)
    if name == "external" or (name and name.startswith("external:")):
        _, _, sched = name.partition(":")
        return ExternalSchedulerProvider(store, scheduler_name=sched or "external")
    raise ValueError(f"unknown scheduler provider {name!r}")
