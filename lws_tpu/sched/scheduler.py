"""Slice-aware pod scheduler: binds pods to TPU-host Nodes.

Native replacement for kube-scheduler + Volcano gang admission. Honors:
  * node_selector (exclusive-placement follow-the-leader uses this,
    ref pod_controller.go:297-336),
  * chip capacity (google.com/tpu) with allocation tracking,
  * required pod affinity/anti-affinity over topology-key domains — the
    mechanism behind 1:1 group<->slice exclusive placement
    (ref pod_webhook.go:185-227),
  * gang admission: pods carrying a PodGroup annotation bind all-or-nothing
    once min_member peers exist and a joint feasible assignment is found.
"""

from __future__ import annotations

from typing import Optional

from lws_tpu.api import contract
from lws_tpu.api.node import Node
from lws_tpu.api.pod import Pod
from lws_tpu.core.events import EventRecorder
from lws_tpu.core.manager import Result
from lws_tpu.core.store import Key, Store


class Scheduler:
    name = "scheduler"

    def __init__(self, store: Store, recorder: EventRecorder) -> None:
        self.store = store
        self.recorder = recorder

    # ---- reconcile ---------------------------------------------------------
    def reconcile(self, key: Key) -> Result | None:
        pod = self.store.try_get("Pod", key[1], key[2])
        if pod is None or not isinstance(pod, Pod) or pod.spec.node_name:
            return None

        gang_name = pod.meta.annotations.get(contract.POD_GROUP_ANNOTATION_KEY)
        if gang_name:
            self._schedule_gang(pod.meta.namespace, gang_name)
        else:
            nodes = self._nodes()
            bound = self._bound_pods(pod.meta.namespace)
            node = self._feasible_node(pod, nodes, bound, extra_assigned={})
            if node is not None:
                self._bind(pod, node)
            else:
                self.recorder.event(pod, "Warning", "FailedScheduling", "no feasible node")
        return None

    # ---- gang --------------------------------------------------------------
    def _schedule_gang(self, namespace: str, gang_name: str) -> None:
        group = self.store.try_get("PodGroup", namespace, gang_name)
        if group is None:
            return  # wait for the PodGroup; its creation event retriggers us
        members = [
            p
            for p in self.store.list("Pod", namespace)
            if p.meta.annotations.get(contract.POD_GROUP_ANNOTATION_KEY) == gang_name
        ]
        pending = [p for p in members if not p.spec.node_name]
        min_member = group.spec.min_member
        if not pending:
            return
        nodes = self._nodes()
        bound = self._bound_pods(namespace)
        allowed: Optional[set[str]] = None
        members_chips = sum(p.spec.effective_tpu_chips() for p in members)
        need_chips = group.spec.min_resources.get(contract.TPU_RESOURCE_NAME, 0)
        if len(members) < min_member or members_chips < need_chips:
            # The gang's full demand is not yet represented by live pods (the
            # common LWS shape: the leader exists, workers follow only once it
            # is placed — worker groupsets gate on leader binding under
            # exclusive placement, ref pod_controller.go:162-172; LeaderReady
            # even sets min_member=1). Admit early members only if some
            # topology domain can RESERVE the whole group's min_resources;
            # otherwise a leader binding to a too-small slice deadlocks the
            # group (SURVEY §7 "gang admission on slices").
            allowed = self._reserve_for_group(group, pending[0], nodes, bound)
            if allowed is None:
                self.recorder.event(
                    group, "Warning", "GangNotSchedulable",
                    f"no topology domain can hold min_resources {group.spec.min_resources}",
                )
                return
        # Joint assignment: greedily place every pending member treating
        # earlier in-pass assignments as bound; all-or-nothing on failure.
        assignment: dict[str, str] = {}  # pod name -> node name
        extra: dict[str, Pod] = {}
        usable = nodes if allowed is None else [n for n in nodes if n.meta.name in allowed]
        for p in sorted(pending, key=lambda p: p.meta.name):
            node = self._feasible_node(p, usable, bound, extra_assigned=extra)
            if node is None:
                self.recorder.event(
                    group, "Warning", "GangNotSchedulable",
                    f"no joint assignment for {len(pending)} pending pods",
                )
                return
            assignment[p.meta.name] = node.meta.name
            placed = p.deepcopy()
            placed.spec.node_name = node.meta.name
            extra[p.meta.name] = placed
        for p in pending:
            self._bind(p, node_name=assignment[p.meta.name])
        if len(members) >= min_member and group.status.phase != "Running":
            group.status.phase = "Running"
            self.store.update_status(group)

    def _reserve_for_group(
        self, group, sample_pod: Pod, nodes: list[Node], bound: list[Pod]
    ) -> Optional[set[str]]:
        """Find a topology domain whose free chips fit the whole gang's
        min_resources; returns the node names of that domain (None if no fit).

        The domain key is the sample pod's exclusive-affinity topology key when
        present (one slice per group), else the whole cluster is one domain.
        """
        candidates = [
            n
            for n in nodes
            if all(n.meta.labels.get(k) == v for k, v in sample_pod.spec.node_selector.items())
        ]
        need = group.spec.min_resources.get(contract.TPU_RESOURCE_NAME, 0)
        topology_key = None
        if sample_pod.spec.affinity is not None and sample_pod.spec.affinity.required_affinity:
            topology_key = sample_pod.spec.affinity.required_affinity[0].topology_key
        domains: dict[str, list[Node]] = {}
        for n in candidates:
            domain = n.meta.labels.get(topology_key, "") if topology_key else ""
            if topology_key and domain == "":
                continue
            domains.setdefault(domain, []).append(n)
        for _, domain_nodes in sorted(domains.items()):
            free = sum(self._free_chips(n, bound, {}) for n in domain_nodes)
            if free >= need:
                return {n.meta.name for n in domain_nodes}
        return None

    # ---- feasibility -------------------------------------------------------
    def _nodes(self) -> list[Node]:
        # Nodes are cluster-scoped hardware (api.node.CLUSTER_NAMESPACE);
        # the fleet changes rarely next to pod churn, so the view is cached
        # on the store's Node mutation counter (scheduling is O(pods) calls
        # deep and re-listing per call dominated turnup profiles).
        version = self.store.kind_version("Node")
        cached = getattr(self, "_node_cache", None)
        if cached is not None and cached[0] == version:
            return cached[1]
        nodes = [
            n
            for n in self.store.list("Node")
            if isinstance(n, Node) and n.status.ready and not n.spec.unschedulable
        ]
        self._node_cache = (version, nodes)
        return nodes

    def _bound_pods(self, namespace: str) -> list[Pod]:
        return [p for p in self.store.list("Pod", namespace) if p.spec.node_name]

    def _free_chips(self, node: Node, bound: list[Pod], extra: dict[str, Pod]) -> int:
        used = sum(
            p.spec.effective_tpu_chips()
            for p in list(bound) + list(extra.values())
            if p.spec.node_name == node.meta.name
        )
        return node.spec.capacity.get(contract.TPU_RESOURCE_NAME, 0) - used

    def _feasible_node(
        self,
        pod: Pod,
        nodes: list[Node],
        bound: list[Pod],
        extra_assigned: dict[str, Pod],
    ) -> Optional[Node]:
        all_pods = [p for p in bound if p.meta.name != pod.meta.name] + [
            p for p in extra_assigned.values() if p.meta.name != pod.meta.name
        ]
        node_by_name = {n.meta.name: n for n in nodes}

        def domain_of(p: Pod, topology_key: str) -> Optional[str]:
            n = node_by_name.get(p.spec.node_name)
            return None if n is None else n.meta.labels.get(topology_key)

        candidates = []
        for node in nodes:
            if any(node.meta.labels.get(k) != v for k, v in pod.spec.node_selector.items()):
                continue
            chips = pod.spec.effective_tpu_chips()
            if chips > 0 and self._free_chips(node, bound, extra_assigned) < chips:
                continue
            if not self._affinity_ok(pod, node, all_pods, domain_of):
                continue
            candidates.append(node)
        if not candidates:
            return None
        # Deterministic bin-packing: prefer slices already hosting peers of the
        # same group key, then stable order.
        group_key = pod.meta.labels.get(contract.GROUP_UNIQUE_HASH_LABEL_KEY)

        def score(node: Node) -> tuple:
            slice_id = node.meta.labels.get(contract.NODE_TPU_SLICE_LABEL, "")
            peers = sum(
                1
                for p in all_pods
                if group_key
                and p.meta.labels.get(contract.GROUP_UNIQUE_HASH_LABEL_KEY) == group_key
                and domain_of(p, contract.NODE_TPU_SLICE_LABEL) == slice_id
            )
            return (-peers, slice_id, node.meta.name)

        return sorted(candidates, key=score)[0]

    def _affinity_ok(self, pod: Pod, node: Node, all_pods: list[Pod], domain_of) -> bool:
        aff = pod.spec.affinity
        if aff is None:
            return True
        for term in aff.required_affinity:
            node_domain = node.meta.labels.get(term.topology_key)
            if node_domain is None:
                return False
            matching = [p for p in all_pods if term.selector_matches(p.meta.labels)]
            if not matching:
                # Self-affinity bootstrap: first pod of the group may open a
                # new domain (kube-scheduler's special case).
                if term.selector_matches(pod.meta.labels):
                    continue
                return False
            if not any(domain_of(p, term.topology_key) == node_domain for p in matching):
                return False
        for term in aff.required_anti_affinity:
            node_domain = node.meta.labels.get(term.topology_key)
            if node_domain is None:
                continue
            for p in all_pods:
                if term.selector_matches(p.meta.labels) and domain_of(p, term.topology_key) == node_domain:
                    return False
        return True

    # ---- binding -----------------------------------------------------------
    def _bind(self, pod: Pod, node: Optional[Node] = None, node_name: str = "") -> None:
        fresh = self.store.try_get("Pod", pod.meta.namespace, pod.meta.name)
        if fresh is None or fresh.spec.node_name:
            return
        fresh.spec.node_name = node.meta.name if node is not None else node_name
        self.store.update(fresh)
        self.recorder.event(fresh, "Normal", "Scheduled", f"bound to {fresh.spec.node_name}")
