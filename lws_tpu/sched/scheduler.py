"""Slice-aware pod scheduler: binds pods to TPU-host Nodes.

Native replacement for kube-scheduler + Volcano gang admission. Honors:
  * node_selector (exclusive-placement follow-the-leader uses this,
    ref pod_controller.go:297-336),
  * chip capacity (google.com/tpu) with allocation tracking,
  * required pod affinity/anti-affinity over topology-key domains — the
    mechanism behind 1:1 group<->slice exclusive placement
    (ref pod_webhook.go:185-227),
  * gang admission: pods carrying a PodGroup annotation bind all-or-nothing
    once min_member peers exist and a joint feasible assignment is found.
"""

from __future__ import annotations

import threading
from typing import Optional

from lws_tpu.api import contract
from lws_tpu.api.node import Node
from lws_tpu.api.pod import Pod, PodPhase
from lws_tpu.core.events import EventRecorder
from lws_tpu.core.manager import Result
from lws_tpu.core.store import Key, Store


class Scheduler:
    name = "scheduler"

    def __init__(self, store: Store, recorder: EventRecorder) -> None:
        self.store = store
        self.recorder = recorder
        # Incremental pod indexes, maintained from the store watch (which
        # carries event types, so deletions purge exactly — events are
        # delivered in commit order):
        #   _pending:  key -> gang name (None = solo); consumed by
        #              pending_representatives() so a capacity event requeues
        #              ONE key per waiting gang instead of every unbound pod
        #              (the O(pods) fan-out that collapsed at fleet scale).
        #   _bound:    key -> Pod for node-bound pods; replaces the full
        #              store list that re-ran after every single bind.
        #   _by_gang:  (ns, gang) -> {key: Pod} membership.
        # A scheduler stood up over PRE-EXISTING state (restart/restore) must
        # have rebuild_from_store() called — ControlPlane.resync() does.
        self._pending: dict[Key, Optional[str]] = {}  # guarded-by: _pending_lock
        self._bound: dict[Key, Pod] = {}  # guarded-by: _pending_lock
        self._by_gang: dict[tuple[str, str], dict[Key, Pod]] = {}
        self._gang_of: dict[Key, str] = {}  # reverse map for O(1) moves/purges
        # Placement aggregates: _feasible_node used to rescan every bound pod
        # per placement — O(fleet^2) turnup at 512+ pods (CONTROL_r04 note).
        # Watch-fed counters make the common exclusive-placement terms
        # O(group) instead:
        #   _chips_by_node: node -> TPU chips of bound pods (capacity is
        #                   physical, so this one is cluster-global)
        #   _hash_nodes:    (ns, hash_label, value) -> {node: pod count}
        #   _hash_total:    (ns, hash_label) -> {node: pod count}
        self._chips_by_node: dict[str, int] = {}
        self._bound_state: dict[Key, tuple[str, int, list[tuple[str, str]]]] = {}  # guarded-by: _pending_lock
        self._hash_nodes: dict[tuple[str, str, str], dict[str, int]] = {}
        self._hash_total: dict[tuple[str, str], dict[str, int]] = {}
        self._pending_lock = threading.Lock()
        store.watch(self._observe)

    _TRACKED_HASH_KEYS = (
        contract.GROUP_UNIQUE_HASH_LABEL_KEY,
        contract.SUBGROUP_UNIQUE_HASH_LABEL_KEY,
    )

    def _unindex_bound_locked(self, key: Key) -> None:
        prev = self._bound_state.pop(key, None)
        if prev is None:
            return
        node, chips, hashes = prev
        if chips:
            left = self._chips_by_node.get(node, 0) - chips
            if left > 0:
                self._chips_by_node[node] = left
            else:
                self._chips_by_node.pop(node, None)
        ns = key[1]
        for lk, v in hashes:
            for index, ik in ((self._hash_nodes, (ns, lk, v)),
                              (self._hash_total, (ns, lk))):
                bucket = index.get(ik)
                if bucket is None:
                    continue
                c = bucket.get(node, 0) - 1
                if c > 0:
                    bucket[node] = c
                else:
                    bucket.pop(node, None)
                    if not bucket:
                        index.pop(ik, None)

    def _index_bound_locked(self, key: Key, pod: Pod) -> None:
        self._unindex_bound_locked(key)
        node = pod.spec.node_name
        if not node:
            return
        chips = pod.spec.effective_tpu_chips()
        if chips:
            self._chips_by_node[node] = self._chips_by_node.get(node, 0) + chips
        ns = key[1]
        hashes: list[tuple[str, str]] = []
        for lk in self._TRACKED_HASH_KEYS:
            v = pod.meta.labels.get(lk)
            if v is None:
                continue
            hashes.append((lk, v))
            for index, ik in ((self._hash_nodes, (ns, lk, v)),
                              (self._hash_total, (ns, lk))):
                bucket = index.setdefault(ik, {})
                bucket[node] = bucket.get(node, 0) + 1
        self._bound_state[key] = (node, chips, hashes)

    # ---- incremental pod indexes (fleet-scale event fan-out) ---------------
    def _observe(self, event) -> None:
        # Store-watch observer, running on the committing writer's thread: an
        # index-update bug must not propagate into whichever reconcile/serving
        # thread committed the write. rebuild_from_store() re-seeds a
        # desynced index from store truth.
        if not isinstance(event.obj, Pod):
            return
        try:
            if event.type == "DELETED":
                self._forget_pending(event.obj.key())
            else:
                self.note_pod(event.obj)
        except Exception:  # vet: ignore[hazard-exception-swallow]: a broken index update must not kill the committing writer (purity-observer-raise); rebuild_from_store recovers
            pass

    def rebuild_from_store(self) -> None:
        """Seed the indexes from current store state (cold start over a
        restored store — the watch never saw those objects)."""
        with self._pending_lock:
            self._pending.clear()
            self._bound.clear()
            self._by_gang.clear()
            self._gang_of.clear()
            self._chips_by_node.clear()
            self._bound_state.clear()
            self._hash_nodes.clear()
            self._hash_total.clear()
        for pod in self.store.list("Pod"):
            self.note_pod(pod)

    def note_pod(self, pod) -> None:
        """Track binding state + gang membership for one observed pod."""
        if not isinstance(pod, Pod):
            return
        key = pod.key()
        # `or None`: an empty-string annotation means solo everywhere else
        # (reconcile's truthiness check) — storing "" would fold all such
        # pods into one pseudo-gang with a single requeue representative.
        gang = pod.meta.annotations.get(contract.POD_GROUP_ANNOTATION_KEY) or None
        with self._pending_lock:
            prev_gang = self._gang_of.get(key)
            if prev_gang is not None and prev_gang != gang:
                # Annotation changed/removed: leave the old gang's bucket so
                # its joint assignment never binds an ex-member.
                self._drop_from_gang_locked(key, prev_gang)
            if gang:
                self._by_gang.setdefault((key[1], gang), {})[key] = pod
                self._gang_of[key] = gang
            if pod.spec.node_name:
                self._pending.pop(key, None)
                self._bound[key] = pod
                self._index_bound_locked(key, pod)
            else:
                self._bound.pop(key, None)
                self._unindex_bound_locked(key)
                if pod.status.phase == PodPhase.PENDING:
                    self._pending[key] = gang
                else:
                    self._pending.pop(key, None)

    def pending_representatives(self) -> list[Key]:
        """One key per waiting gang + every waiting solo pod: what a capacity
        event (Node added/uncordoned, PodGroup created) needs to requeue."""
        with self._pending_lock:
            reps: dict[tuple[str, str], Key] = {}
            solos: list[Key] = []
            for key, gang in self._pending.items():
                if gang is None:
                    solos.append(key)
                else:
                    prev = reps.get((key[1], gang))
                    if prev is None or key < prev:
                        reps[(key[1], gang)] = key
            return solos + sorted(reps.values())

    def _drop_from_gang_locked(self, key: Key, gang: str) -> None:
        members = self._by_gang.get((key[1], gang))
        if members is not None:
            members.pop(key, None)
            if not members:
                del self._by_gang[(key[1], gang)]
        self._gang_of.pop(key, None)

    def _forget_pending(self, *keys: Key) -> None:
        """Drop deleted pods from every index."""
        with self._pending_lock:
            for key in keys:
                self._pending.pop(key, None)
                self._bound.pop(key, None)
                self._unindex_bound_locked(key)
                gang = self._gang_of.get(key)
                if gang is not None:
                    self._drop_from_gang_locked(key, gang)

    # ---- reconcile ---------------------------------------------------------
    def reconcile(self, key: Key) -> Result | None:
        pod = self.store.try_get("Pod", key[1], key[2])
        if pod is None or not isinstance(pod, Pod):
            self._forget_pending(key)  # belt-and-braces; _observe purges live
            return None
        if pod.spec.node_name:
            return None  # already bound (note_pod keeps the indexes current)
        if pod.spec.scheduler_name and pod.spec.scheduler_name != self.name:
            # Stamped for an external scheduler (ExternalSchedulerProvider):
            # binding happens via the API; the native scheduler leaves the
            # pod strictly alone even when both are enabled.
            return None

        gang_name = pod.meta.annotations.get(contract.POD_GROUP_ANNOTATION_KEY)
        if gang_name:
            self._schedule_gang(pod.meta.namespace, gang_name)
        else:
            nodes = self._nodes()
            node = self._feasible_node(pod, nodes, extra_assigned={})
            if node is not None:
                self._bind(pod, node)
            else:
                self.recorder.event(pod, "Warning", "FailedScheduling", "no feasible node")
        return None

    # ---- gang --------------------------------------------------------------
    def _schedule_gang(self, namespace: str, gang_name: str) -> None:
        group = self.store.try_get("PodGroup", namespace, gang_name)
        if group is None:
            return  # wait for the PodGroup; its creation event retriggers us
        members = self._gang_members(namespace, gang_name)
        pending = [p for p in members if not p.spec.node_name]
        min_member = group.spec.min_member
        if not pending:
            return
        nodes = self._nodes()
        allowed: Optional[set[str]] = None
        members_chips = sum(p.spec.effective_tpu_chips() for p in members)
        need_chips = group.spec.min_resources.get(contract.TPU_RESOURCE_NAME, 0)
        if len(members) < min_member or members_chips < need_chips:
            # The gang's full demand is not yet represented by live pods (the
            # common LWS shape: the leader exists, workers follow only once it
            # is placed — worker groupsets gate on leader binding under
            # exclusive placement, ref pod_controller.go:162-172; LeaderReady
            # even sets min_member=1). Admit early members only if some
            # topology domain can RESERVE the whole group's min_resources;
            # otherwise a leader binding to a too-small slice deadlocks the
            # group (SURVEY §7 "gang admission on slices").
            allowed = self._reserve_for_group(group, pending[0], nodes)
            if allowed is None:
                self.recorder.event(
                    group, "Warning", "GangNotSchedulable",
                    f"no topology domain can hold min_resources {group.spec.min_resources}",
                )
                return
        # Joint assignment: greedily place every pending member treating
        # earlier in-pass assignments as bound; all-or-nothing on failure.
        assignment: dict[str, str] = {}  # pod name -> node name
        extra: dict[str, Pod] = {}
        usable = nodes if allowed is None else [n for n in nodes if n.meta.name in allowed]
        for p in sorted(pending, key=lambda p: p.meta.name):
            node = self._feasible_node(p, usable, extra_assigned=extra)
            if node is None:
                self.recorder.event(
                    group, "Warning", "GangNotSchedulable",
                    f"no joint assignment for {len(pending)} pending pods",
                )
                return
            assignment[p.meta.name] = node.meta.name
            placed = p.deepcopy()
            placed.spec.node_name = node.meta.name
            extra[p.meta.name] = placed
        for p in pending:
            self._bind(p, node_name=assignment[p.meta.name])
        if len(members) >= min_member and group.status.phase != "Running":
            group.status.phase = "Running"
            self.store.update_status(group)

    def _reserve_for_group(
        self, group, sample_pod: Pod, nodes: list[Node]
    ) -> Optional[set[str]]:
        """Find a topology domain whose free chips fit the whole gang's
        min_resources; returns the node names of that domain (None if no fit).

        The domain key is the sample pod's exclusive-affinity topology key when
        present (one slice per group), else the whole cluster is one domain.
        """
        candidates = [
            n
            for n in nodes
            if all(n.meta.labels.get(k) == v for k, v in sample_pod.spec.node_selector.items())
        ]
        need = group.spec.min_resources.get(contract.TPU_RESOURCE_NAME, 0)
        topology_key = None
        if sample_pod.spec.affinity is not None and sample_pod.spec.affinity.required_affinity:
            topology_key = sample_pod.spec.affinity.required_affinity[0].topology_key
        domains: dict[str, list[Node]] = {}
        for n in candidates:
            domain = n.meta.labels.get(topology_key, "") if topology_key else ""
            if topology_key and domain == "":
                continue
            domains.setdefault(domain, []).append(n)
        with self._pending_lock:
            used_by_node = dict(self._chips_by_node)
        for _, domain_nodes in sorted(domains.items()):
            free = sum(
                n.spec.capacity.get(contract.TPU_RESOURCE_NAME, 0)
                - used_by_node.get(n.meta.name, 0)
                for n in domain_nodes
            )
            if free >= need:
                return {n.meta.name for n in domain_nodes}
        return None

    # ---- feasibility -------------------------------------------------------
    def _nodes(self) -> list[Node]:
        # Nodes are cluster-scoped hardware (api.node.CLUSTER_NAMESPACE);
        # the fleet changes rarely next to pod churn, so the view is cached
        # on the store's Node mutation counter (scheduling is O(pods) calls
        # deep and re-listing per call dominated turnup profiles).
        version = self.store.kind_version("Node")
        cached = getattr(self, "_node_cache", None)
        if cached is not None and cached[0] == version:
            return cached[1]
        nodes = [
            n
            for n in self.store.list("Node")  # vet: ignore[purity-fleet-scan]: cached on the Node mutation counter above — one scan per node-set CHANGE, not per reconcile
            if isinstance(n, Node) and n.status.ready and not n.spec.unschedulable
        ]
        self._node_cache = (version, nodes)
        return nodes

    def _nodes_by_domain(self, topology_key: str) -> dict[str, list[Node]]:
        """domain value -> nodes carrying it, cached on the Node mutation
        counter (same invalidation as _nodes): lets _feasible_node score
        only the nodes inside a group's affinity domains instead of the
        whole fleet (512 slices x 4 hosts = 2048 scored nodes per placement
        before this; 4 after, for every follower pod)."""
        version = self.store.kind_version("Node")
        cache = getattr(self, "_domain_cache", None)
        if cache is None or cache[0] != version:
            cache = (version, {})
            self._domain_cache = cache
        by_key = cache[1].get(topology_key)
        if by_key is None:
            by_key = {}
            for n in self._nodes():
                d = n.meta.labels.get(topology_key)
                if d is not None:
                    by_key.setdefault(d, []).append(n)
            cache[1][topology_key] = by_key
        return by_key

    def _gang_members(self, namespace: str, gang_name: str) -> list[Pod]:
        with self._pending_lock:
            members = self._by_gang.get((namespace, gang_name), {})
            return sorted(members.values(), key=lambda p: p.meta.name)

    def _bound_pods(self, namespace: str) -> list[Pod]:
        with self._pending_lock:
            return [p for k, p in self._bound.items() if k[1] == namespace]

    @staticmethod
    def _term_fast_shape(term) -> Optional[tuple[str, str, str]]:
        """Recognize the exclusive-placement webhook's two affinity-term
        shapes (pod_webhook.set_exclusive_affinities) so they can be
        answered from the watch-fed hash indexes instead of a bound-pod
        scan: ("in", key, v) for [key IN [v]]; ("anti", key, v) for
        [key EXISTS, key NOT_IN [v]]. Anything else -> None (the generic
        fallback scan keeps full selector semantics)."""
        from lws_tpu.api.pod import AffinityOperator as Op

        exprs = term.match_expressions
        if (len(exprs) == 1 and exprs[0].operator == Op.IN
                and len(exprs[0].values) == 1
                and exprs[0].key in Scheduler._TRACKED_HASH_KEYS):
            return ("in", exprs[0].key, exprs[0].values[0])
        if len(exprs) == 2:
            by_op = {e.operator: e for e in exprs}
            if (set(by_op) == {Op.EXISTS, Op.NOT_IN}
                    and by_op[Op.EXISTS].key == by_op[Op.NOT_IN].key
                    and len(by_op[Op.NOT_IN].values) == 1
                    and by_op[Op.EXISTS].key in Scheduler._TRACKED_HASH_KEYS):
                return ("anti", by_op[Op.EXISTS].key, by_op[Op.NOT_IN].values[0])
        return None

    def _feasible_node(
        self,
        pod: Pod,
        nodes: list[Node],
        extra_assigned: dict[str, Pod],
    ) -> Optional[Node]:
        node_by_name = {n.meta.name: n for n in nodes}
        extras = [p for p in extra_assigned.values() if p.meta.name != pod.meta.name]

        def domain_of_node(name: Optional[str], topology_key: str) -> Optional[str]:
            n = node_by_name.get(name)
            return None if n is None else n.meta.labels.get(topology_key)

        def domain_of(p: Pod, topology_key: str) -> Optional[str]:
            return domain_of_node(p.spec.node_name, topology_key)

        # Fast path: chip usage and the exclusive-placement affinity terms
        # are answered from the watch-fed indexes (O(group) per placement);
        # only terms the webhook never emits fall back to scanning the bound
        # pods — built lazily so the common path never pays O(fleet)
        # (CONTROL_r04: the scan made turnup O(fleet^2) at 512+ pods).
        _lazy: list = []

        def all_pods() -> list:
            if not _lazy:
                # The namespace-filtered bound snapshot is itself O(fleet);
                # built ONLY here so webhook-shaped placements never pay it.
                _lazy.append(
                    [p for p in self._bound_pods(pod.meta.namespace)
                     if p.meta.name != pod.meta.name] + extras
                )
            return _lazy[0]

        ns = pod.meta.namespace
        chips_needed = pod.spec.effective_tpu_chips()
        with self._pending_lock:
            used_by_node = dict(self._chips_by_node)
        for p in extras:
            if p.spec.node_name:
                used_by_node[p.spec.node_name] = (
                    used_by_node.get(p.spec.node_name, 0)
                    + p.spec.effective_tpu_chips()
                )

        aff = pod.spec.affinity
        # (topology_key, domains): node must carry the key AND, when domains
        # is non-None, sit in one of them. domains=None = self-affinity
        # bootstrap (first pod of the group may open any labeled domain —
        # kube-scheduler's special case; an UNlabeled node stays ineligible,
        # else peers would inherit an unschedulable None-domain).
        aff_domains: list[tuple[str, Optional[set]]] = []
        anti_domains: list[tuple[str, set]] = []
        if aff is not None:
            for term in aff.required_affinity:
                fast = self._term_fast_shape(term)
                if fast is not None and fast[0] == "in":
                    _, lk, v = fast
                    with self._pending_lock:
                        nodeset = set(self._hash_nodes.get((ns, lk, v), ()))
                    for p in extras:
                        if term.selector_matches(p.meta.labels):
                            nodeset.add(p.spec.node_name)
                    if not nodeset:
                        if term.selector_matches(pod.meta.labels):
                            aff_domains.append((term.topology_key, None))
                            continue
                        return None  # nothing can satisfy this term
                    aff_domains.append((
                        term.topology_key,
                        {domain_of_node(n, term.topology_key) for n in nodeset},
                    ))
                    continue
                matching = [p for p in all_pods() if term.selector_matches(p.meta.labels)]
                if not matching:
                    if term.selector_matches(pod.meta.labels):
                        aff_domains.append((term.topology_key, None))
                        continue
                    return None  # nothing can satisfy this term
                aff_domains.append(
                    (term.topology_key,
                     {domain_of(p, term.topology_key) for p in matching})
                )
            for term in aff.required_anti_affinity:
                fast = self._term_fast_shape(term)
                if fast is not None and fast[0] == "anti":
                    _, lk, v = fast
                    with self._pending_lock:
                        total = self._hash_total.get((ns, lk), {})
                        mine = self._hash_nodes.get((ns, lk, v), {})
                        nodeset = {
                            n for n, c in total.items() if c - mine.get(n, 0) > 0
                        }
                    for p in extras:
                        if term.selector_matches(p.meta.labels):
                            nodeset.add(p.spec.node_name)
                    domains = {
                        domain_of_node(n, term.topology_key) for n in nodeset
                    }
                else:
                    domains = {
                        domain_of(p, term.topology_key)
                        for p in all_pods()
                        if term.selector_matches(p.meta.labels)
                    }
                domains.discard(None)
                if domains:
                    anti_domains.append((term.topology_key, domains))

        group_key = pod.meta.labels.get(contract.GROUP_UNIQUE_HASH_LABEL_KEY)
        peers_by_slice: dict[str, int] = {}
        if group_key:
            with self._pending_lock:
                gbucket = dict(self._hash_nodes.get(
                    (ns, contract.GROUP_UNIQUE_HASH_LABEL_KEY, group_key), ()
                ))
            for n, c in gbucket.items():
                slice_id = domain_of_node(n, contract.NODE_TPU_SLICE_LABEL)
                if slice_id is not None:
                    peers_by_slice[slice_id] = peers_by_slice.get(slice_id, 0) + c
            for p in extras:
                if p.meta.labels.get(contract.GROUP_UNIQUE_HASH_LABEL_KEY) == group_key:
                    slice_id = domain_of(p, contract.NODE_TPU_SLICE_LABEL)
                    if slice_id is not None:
                        peers_by_slice[slice_id] = peers_by_slice.get(slice_id, 0) + 1

        # Candidate restriction: when an affinity term pins the pod to
        # concrete domains, only the nodes INSIDE those domains can pass the
        # per-node domain check below — score just those (the domain index
        # is fleet-wide, so intersect with the caller's `nodes` via the
        # node_by_name map already built). Winner is identical: the score
        # tuple is a strict total order and excluded nodes would have
        # failed the aff_domains check anyway.
        candidates = nodes
        allowed = node_by_name
        for topology_key, domains in aff_domains:
            if domains is None:
                continue
            by_dom = self._nodes_by_domain(topology_key)
            subset = [
                n
                for d in sorted(d for d in domains if d is not None)
                for n in by_dom.get(d, ())
                if n.meta.name in allowed
            ]
            if len(subset) < len(candidates):
                candidates = subset
                # Later terms intersect with THIS narrowing, not the full
                # fleet — otherwise a second term's larger-but-smaller-than-
                # baseline subset would resurrect nodes term 1 excluded.
                allowed = {n.meta.name for n in candidates}

        best = None
        best_score = None
        for node in candidates:
            labels = node.meta.labels
            if any(labels.get(k) != v for k, v in pod.spec.node_selector.items()):
                continue
            if chips_needed > 0:
                free = node.spec.capacity.get(contract.TPU_RESOURCE_NAME, 0) - used_by_node.get(
                    node.meta.name, 0
                )
                if free < chips_needed:
                    continue
            ok = True
            for topology_key, domains in aff_domains:
                node_domain = labels.get(topology_key)
                if node_domain is None or (domains is not None and node_domain not in domains):
                    ok = False
                    break
            if ok:
                for topology_key, domains in anti_domains:
                    node_domain = labels.get(topology_key)
                    if node_domain is not None and node_domain in domains:
                        ok = False
                        break
            if not ok:
                continue
            # Deterministic bin-packing: prefer slices already hosting peers
            # of the same group key, then stable order.
            slice_id = labels.get(contract.NODE_TPU_SLICE_LABEL, "")
            score = (-peers_by_slice.get(slice_id, 0), slice_id, node.meta.name)
            if best_score is None or score < best_score:
                best, best_score = node, score
        return best

    # ---- binding -----------------------------------------------------------
    def _bind(self, pod: Pod, node: Optional[Node] = None, node_name: str = "") -> None:
        fresh = self.store.try_get("Pod", pod.meta.namespace, pod.meta.name)
        if fresh is None or fresh.spec.node_name:
            return
        fresh.spec.node_name = node.meta.name if node is not None else node_name
        self.store.update(fresh)
        self.recorder.event(fresh, "Normal", "Scheduled", f"bound to {fresh.spec.node_name}")
