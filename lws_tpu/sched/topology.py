"""TPU fleet topology model: build Node objects for multi-host slices.

A vXe/vXp slice of H hosts shows up as H Nodes sharing one
NODE_TPU_SLICE_LABEL value, each with chips_per_host chips
(e.g. v5e 4x4 = 4 hosts x 4 chips; v5p-16 = 2 hosts x 4 chips).
"""

from __future__ import annotations

from lws_tpu.api import contract
from lws_tpu.api.node import CLUSTER_NAMESPACE, Node, NodeSpec
from lws_tpu.core.store import new_meta


def slice_host_count(topology: str, chips_per_host: int = 4) -> int:
    """'4x4' -> 16 chips -> 4 hosts; '2x2x4' (v5p) -> 16 chips -> 4 hosts."""
    chips = 1
    for part in topology.lower().split("x"):
        chips *= int(part)
    return max(1, chips // chips_per_host)


def make_slice_nodes(
    slice_name: str,
    topology: str = "4x4",
    chips_per_host: int = 4,
    accelerator: str = "v5e",
    namespace: str = CLUSTER_NAMESPACE,
) -> list[Node]:
    hosts = slice_host_count(topology, chips_per_host)
    nodes = []
    for h in range(hosts):
        nodes.append(
            Node(
                meta=new_meta(
                    f"{slice_name}-host-{h}",
                    namespace,
                    labels={
                        contract.NODE_TPU_SLICE_LABEL: slice_name,
                        contract.NODE_TPU_TOPOLOGY_LABEL: topology,
                        contract.NODE_TPU_ACCELERATOR_LABEL: accelerator,
                    },
                ),
                spec=NodeSpec(capacity={contract.TPU_RESOURCE_NAME: chips_per_host, "pods": 8}),
            )
        )
    return nodes
