"""Serving: KV-cache inference engine with a prefill/decode split — the
workload shape DisaggregatedSet roles orchestrate (prefill slice produces the
KV cache; decode slice consumes it)."""

from lws_tpu.serving.batch_engine import BatchEngine  # noqa: F401
from lws_tpu.serving.paged_engine import PagedBatchEngine  # noqa: F401
from lws_tpu.serving.pipeline import DecodePipeline  # noqa: F401
from lws_tpu.serving.engine import Engine, GenerationResult  # noqa: F401
