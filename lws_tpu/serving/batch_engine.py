"""Continuous batching: sequences at different lengths decode together and
new requests join mid-stream (slot-based, static shapes for XLA).

The fixed-size slot batch keeps every decode step identically shaped (no
recompilation); admission prefills a request alone and scatters its KV rows
into a free slot; per-slot position vectors drive RoPE, masking, and cache
scatter (models.llama.forward_decode_slotted). Inactive slots compute but
their outputs are ignored and their cache rows are overwritten on admission —
the standard static-shape continuous-batching trade.

Positioning: PagedBatchEngine supersedes this engine for production serving
(a pool sized to slots x max_len is the dense-equivalent configuration, and
it adds tp meshes, per-request sampling, and prefix caching). BatchEngine
stays as the simplest dense implementation and the exactness oracle the
paged tests compare against; it is greedy-only by design.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from lws_tpu.core import metrics, slo, trace
from lws_tpu.obs import device as devicemod
from lws_tpu.serving.pipeline import DecodePipeline, remaining_steps

from lws_tpu.models.llama import (
    LlamaConfig,
    forward_decode_slotted,
    forward_prefill,
    init_cache,
)


@dataclass
class Request:
    request_id: int
    prompt: np.ndarray
    max_new_tokens: int
    tokens: list[int] = field(default_factory=list)
    slot: int = -1
    # Per-request SLO timeline (queue wait / TTFT / ITL; core/slo.py).
    slo: "slo.RequestTimeline | None" = None

    @property
    def done(self) -> bool:
        return len(self.tokens) >= self.max_new_tokens


class BatchEngine:
    """Slot-based continuously-batched greedy engine."""

    def __init__(self, cfg: LlamaConfig, params: dict, slots: int = 8,
                 max_len: int = 512, pipeline_depth: int = 2):
        self.cfg = cfg
        self.params = params
        self.slots = slots
        self.max_len = max_len
        self._ids = itertools.count()
        self._free = list(range(slots))
        self._active: dict[int, Request] = {}  # slot -> request
        self._completed: dict[int, Request] = {}
        # Same overlap primitive as the paged engine: up to `pipeline_depth`
        # dispatched steps stay in flight, their tokens consumed while the
        # device runs the next step (depth 0 = the old synchronous loop).
        # _step donates the cache, which CPU PJRT dispatches synchronously —
        # on the CPU test backend this engine stays effectively sequential
        # (it is the exactness oracle; the paged engine owns the perf path).
        self._pipeline = DecodePipeline(depth=pipeline_depth, engine="batch")

        self.cache = init_cache(cfg, slots, max_len)
        self.pos_b = jnp.zeros((slots,), jnp.int32)
        self.tokens = jnp.zeros((slots,), jnp.int32)

        cfg_static = cfg

        @jax.jit
        def _prefill_one(params, prompt, last_pos):
            cache = init_cache(cfg_static, 1, max_len)
            logits, cache = forward_prefill(
                params, prompt, cache, cfg_static, last_pos=last_pos
            )
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        @partial(jax.jit, donate_argnums=(1,))
        def _insert(slot_cache, cache, pos_b, tokens, slot, plen, first_token):
            import dataclasses as _dc

            cache = _dc.replace(
                cache,
                k=cache.k.at[:, slot].set(slot_cache.k[:, 0]),
                v=cache.v.at[:, slot].set(slot_cache.v[:, 0]),
            )
            if cache.k_scale is not None:  # int8 KV: scales ride with values
                cache = _dc.replace(
                    cache,
                    k_scale=cache.k_scale.at[:, slot].set(slot_cache.k_scale[:, 0]),
                    v_scale=cache.v_scale.at[:, slot].set(slot_cache.v_scale[:, 0]),
                )
            return cache, pos_b.at[slot].set(plen), tokens.at[slot].set(first_token)

        @partial(jax.jit, donate_argnums=(1,))
        def _step(params, cache, tokens, pos_b, active):
            logits, cache = forward_decode_slotted(params, tokens, cache, pos_b, cfg_static)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            tokens = jnp.where(active, nxt, tokens)
            pos_b = jnp.where(active, pos_b + 1, pos_b)
            return cache, tokens, pos_b

        self._prefill_one = _prefill_one
        self._insert = _insert
        self._step_fn = _step

    # ------------------------------------------------------------------
    def submit(self, prompt: np.ndarray, max_new_tokens: int,
               klass: str = "",
               arrival_t: Optional[float] = None) -> Optional[int]:
        """Admit a request into a free slot; returns request id (None =
        full). `klass` labels the request's SLO/goodput series by workload
        class; `arrival_t` (a time.perf_counter() stamp) backdates the SLO
        arrival clock — the loadgen harness passes the scheduled open-loop
        arrival so admission delay shows up as queue wait."""
        if not self._free and self._pipeline:
            # A completion may be sitting unconsumed in the in-flight ring.
            self._pipeline.flush()
        if not self._free:
            return None
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError("prompt + max_new_tokens exceeds max_len")
        slot = self._free.pop(0)
        req = Request(next(self._ids), np.asarray(prompt), max_new_tokens, slot=slot,
                      slo=slo.request("batch", arrival_t, klass=klass))

        plen = len(prompt)
        t0 = time.perf_counter()
        with trace.span("serve.admission", engine="batch", prompt_len=plen):
            # Bucket prompt lengths (next power of two) so admission compiles a
            # handful of executables instead of one per distinct length; the
            # padded tail is never attendable (mask is key_pos <= pos) and decode
            # overwrites it position by position.
            bucket = 8
            while bucket < plen:
                bucket *= 2
            bucket = min(bucket, self.max_len)
            padded = np.zeros((bucket,), np.int32)
            padded[:plen] = prompt
            with trace.span("serve.prefill", chunked=False, prompt_len=plen), \
                    devicemod.compile_site(
                        "batch.prefill", engine="batch", shape=f"b{bucket}",
                        request_id=req.slo.request_id if req.slo else ""):
                first, slot_cache = self._prefill_one(
                    self.params, jnp.asarray(padded)[None, :], jnp.asarray(plen - 1)
                )
            self.cache, self.pos_b, self.tokens = self._insert(
                slot_cache, self.cache, self.pos_b, self.tokens, slot, plen, first[0]
            )
        metrics.inc("serving_requests_total", {"engine": "batch"})
        metrics.observe(
            "serving_admission_duration_seconds",
            time.perf_counter() - t0, {"engine": "batch"},
        )
        req.tokens.append(int(first[0]))
        # Queue wait (arrival -> slot) and TTFT (arrival -> prefill token):
        # for this engine both end at submit() — with a backdated arrival
        # (open-loop loadgen), the wait is the real arrival -> submit gap.
        req.slo.queue_wait(
            0.0 if arrival_t is None else max(0.0, t0 - arrival_t)
        )
        req.slo.first_token()
        if req.done:
            # max_new_tokens == 1: the prefill token alone finishes it.
            req.slo.finish()
            self._completed[req.request_id] = req
            self._free.append(slot)
        else:
            self._active[slot] = req
        metrics.set("serving_active_slots", len(self._active), {"engine": "batch"})
        return req.request_id

    def step(self) -> None:  # hot-path
        """One decode step across every active slot, pipelined: the dispatch
        is pushed onto the in-flight ring and its tokens consumed on a later
        call (or flush). A step that would run the soonest-finishing slot
        past its budget flushes the ring first, so no request can be stepped
        beyond max_new_tokens by work already in flight."""
        if not self._active:
            self._pipeline.flush()
            return
        bound = min(
            remaining_steps(r, self.max_len) for r in self._active.values()
        ) - self._pipeline.inflight_steps()
        if bound < 1:
            self._pipeline.flush()
            if not self._active:
                return
        t0 = time.perf_counter()
        with trace.span(
            "serve.decode_dispatch", engine="batch", steps=1,
            active=len(self._active), inflight=len(self._pipeline),
        ):
            with self._pipeline.host_section():
                active = jnp.asarray(
                    [s in self._active for s in range(self.slots)]
                )
                with devicemod.compile_site("batch.step", engine="batch"):
                    self.cache, self.tokens, self.pos_b = self._step_fn(
                        self.params, self.cache, self.tokens, self.pos_b,
                        active,
                    )
            # Only requests active AT DISPATCH got a real token this step.
            snapshot = dict(self._active)

            def commit(host_tokens, snapshot=snapshot):
                for slot, req in snapshot.items():
                    req.tokens.append(int(host_tokens[slot]))
                    req.slo.tokens(1)  # ITL: gap since this request's last commit
                    # Position is host-derivable: prompt + generated tokens.
                    if req.done or len(req.prompt) + len(req.tokens) >= self.max_len:
                        req.slo.finish()
                        self._completed[req.request_id] = req
                        # Identity-guarded as a whole: retiring twice would
                        # put the slot on the free list twice.
                        if self._active.get(slot) is req:
                            del self._active[slot]
                            self._free.append(slot)
                            metrics.set(
                                "serving_active_slots", len(self._active),
                                {"engine": "batch"},
                            )

            self._pipeline.push(1, self.tokens, commit)
        metrics.observe(
            "serving_decode_dispatch_duration_seconds",
            time.perf_counter() - t0, {"engine": "batch"},
        )

    def run_until_drained(self, max_steps: int = 10000) -> None:
        for _ in range(max_steps):
            if not self._active:
                self._pipeline.flush()  # commits only retire, never admit
                return
            self.step()
        raise RuntimeError("engine did not drain")

    def result(self, request_id: int) -> Optional[list[int]]:
        req = self._completed.get(request_id)
        if req is None and self._pipeline:
            # Flush only when the request could have finished in-flight: a
            # poll-while-decoding driver must not drain the ring per call.
            live = next(
                (r for r in self._active.values() if r.request_id == request_id),
                None,
            )
            if live is None or (
                remaining_steps(live, self.max_len)
                <= self._pipeline.inflight_steps()
            ):
                self._pipeline.flush()
                req = self._completed.get(request_id)
        return list(req.tokens) if req is not None else None

    @property
    def active_count(self) -> int:
        return len(self._active)
