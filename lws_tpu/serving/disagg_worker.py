"""Disaggregated serving workers: the llm-d shape (BASELINE config #5) as
runnable processes under a DisaggregatedSet.

  python -m lws_tpu.serving.disagg_worker prefill
  python -m lws_tpu.serving.disagg_worker decode

TCP is the transport (the real data plane, VERDICT r3 #5; the round-2
directory stand-in is deleted — no deployment can silently take a
shared-filesystem path): the prefill worker serves prompts-in /
KV-bundles-out on its LWS_TPU_KV_PORT; the decode worker DISCOVERS
prefill's endpoint from the DS's revision-aware `-prv` service record via
the API server (LWS_TPU_API), pulls bundles over the socket, decodes, and
serves results on its own port (ref the reference's
service_manager.go:126-163 endpoint publication).

Sharded workers (VERDICT r3 next #3): LWS_TPU_TP=N builds each role's
engine on an N-device tp mesh (params + KV cache sharded over 'tp').
Bundles cross the wire pos-truncated (bytes ∝ prompt length) and
host-gathered from the prefill mesh — the gathered byte count is logged
per handoff — then re-sharded onto the DECODE side's own mesh. Prefill
and decode meshes are independent (different slice shapes in production).

Both roles build the SAME model from a shared seed (in production: the same
checkpoint), so prefill's cache is exactly what decode expects — verified by
tests/test_e2e_disagg.py against a single-engine oracle.
"""

from __future__ import annotations

import argparse
import os
from typing import Optional

import numpy as np


def build_engine(batch: int, max_len: int):
    """The FLAGSHIP model (models/flagship.py) from a shared seed — smoke
    scale by default (CPU tests, structural twin of the full shape);
    LWS_TPU_MODEL=flagship serves the real 8B-int8w configuration (VERDICT
    r4 #5: the llm-d path must exercise the representative scale, not a
    d=64 toy). int8 weights either way: the full shape's bf16 tree (16 GB)
    does not fit a v5e at all. LWS_TPU_TP>1 serves tensor-parallel on that
    many devices (params + cache over 'tp'; quantized scales split with
    their output channels — shard_params_for_serving)."""
    from lws_tpu.parallel.bootstrap import assert_platform_from_env

    assert_platform_from_env()  # the pod env's JAX_PLATFORMS must win

    import jax

    from lws_tpu.models.flagship import flagship_config, init_quantized_params
    from lws_tpu.serving import Engine

    scale = "full" if os.environ.get("LWS_TPU_MODEL") == "flagship" else "smoke"
    cfg = flagship_config(scale, max_seq_len=max_len)
    params = init_quantized_params(cfg, jax.random.key(1234))
    tp = int(os.environ.get("LWS_TPU_TP", "0") or 0)
    mesh = None
    if tp > 1:
        from lws_tpu.parallel import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=tp), jax.devices()[:tp])
    return Engine(cfg, params, batch_size=batch, max_len=max_len, mesh=mesh)


def _decode_bundle(
    engine, payload, steps: int, gamma: int = 0, ngram: int = 3,
    klass: str = "", request_id: str = "",
) -> tuple[np.ndarray, dict, list]:  # hot-path
    """Bundle (monolithic payload bytes, or a finished streamed
    `CacheAssembler`) -> ([B, steps+1] tokens, per-handoff stats, span
    records). The pos-truncated wire prefix is padded to DECODE's own
    max_len and, when the decode engine is mesh-sharded, placed onto its
    cache shardings. Each real cost of the handoff (VERDICT r4 #5) runs in
    its own span — deserialize, reshard onto this side's mesh, decode — and
    the legacy stats dict is DERIVED from the span durations (the spans
    subsume the old ad-hoc timers; same keys on the wire). For a STREAMED
    handoff the deserialize/upload work already happened chunk-by-chunk
    while the wire was still moving (kv.deserialize then times only the
    residual take()), and the first decode step dispatches as soon as END
    landed. With gamma > 0 the decode leg runs device-resident speculative
    decoding (Engine.decode_speculative): byte-identical greedy tokens in
    fewer dispatches on repetitive content — a streamed handoff ships the
    prompt token ids alongside the KV rows, so drafting seeds from the
    REAL prompt instead of warming up from generated tokens only."""
    from lws_tpu.obs import device as devicemod

    # Ambient compile provenance for the whole leg: the decode engine's
    # first-call jit (the compile the KV ack window silently eats —
    # kv_transport.pull_bundle) lands on the ledger attributed to THIS
    # request, so the fleet-joined journey can blame it for TTFT.
    with devicemod.compile_site(
        "disagg.decode", engine="disagg", shape=f"steps{steps}/g{gamma}",
        request_id=request_id,
    ):
        return _decode_bundle_inner(
            engine, payload, steps, gamma, ngram, klass, request_id,
        )


def _decode_bundle_inner(
    engine, payload, steps: int, gamma: int = 0, ngram: int = 3,
    klass: str = "", request_id: str = "",
) -> tuple[np.ndarray, dict, list]:  # hot-path
    import jax

    from lws_tpu.core import slo, trace
    from lws_tpu.serving.kv_transport import (
        CacheAssembler,
        PoisonPayload,
        bundle_to_cache,
    )
    from lws_tpu.serving.pipeline import DecodePipeline

    if isinstance(payload, PoisonPayload):
        # Streamed content this engine rejected (receiver error — e.g.
        # more KV rows than our max_len): surface it HERE so the worker's
        # poison-message guard consumes the request with a failed result,
        # exactly like a poison monolithic bundle.
        raise payload.error
    streamed = isinstance(payload, CacheAssembler)
    bundle_bytes = payload.payload_bytes if streamed else len(payload)
    context = None
    with trace.span(
        "kv.deserialize", bundle_bytes=bundle_bytes, streamed=streamed,
        chunks=payload.chunks if streamed else 0,
    ) as s_deser:
        if streamed:
            cache, token, pos, context = payload.take()
        else:
            cache, token = bundle_to_cache(payload, max_len=engine.max_len)
            pos = int(cache.pos)  # still host-built here: free, and the spec
            # path needs the cache length without a post-placement round trip
    with trace.span("kv.reshard", tp_sharded=engine.mesh is not None) as s_reshard:
        if engine.mesh is not None:
            cache = jax.device_put(cache, engine._cache_shardings)
            jax.block_until_ready(cache.k)  # vet: ignore[hotpath-host-sync]: reshard fence — s_reshard must time the placement, not the next dispatch
    # Same overlap primitive as the engines' decode loops: dispatch FIRST,
    # then pull the first token to host while the decode chunk runs on
    # device (the old order host-synced `token` with the device idle).
    out: dict = {}
    spec_stats: dict = {}
    # engine="disagg" on BOTH the span and the pipeline's metrics: the span's
    # host_blocked_s attribute and serving_host_blocked_seconds{engine} must
    # reconcile per engine label (docs/observability.md ledger contract).
    with trace.span("serve.decode_dispatch", engine="disagg", steps=steps) as s_decode:
        if gamma > 0:
            # Speculative leg: decode_speculative runs its own in-flight
            # ring (engine-labelled "disagg") and returns host tokens. A
            # streamed handoff seeds the drafting history from the REAL
            # prompt tokens it shipped — no extra flush, no warm-up-from-
            # generated-tokens penalty.
            _, _, toks_spec = engine.decode_speculative(
                token, cache, steps, gamma=gamma, ngram=ngram, pos=pos,
                context=context, engine_label="disagg",
            )
            out["toks"] = toks_spec
            spec_stats = {"spec_gamma": gamma}
            first = np.asarray(token)  # vet: ignore[hotpath-host-sync]: token was host-built by bundle_to_cache — packaging, not a fence
        else:
            pipe = DecodePipeline(depth=1, engine="disagg")
            with pipe.host_section():
                _, _, tokens = engine.decode_n(token, cache, steps)
            pipe.push(steps, tokens, lambda h: out.__setitem__("toks", h))
            first = np.asarray(token)  # vet: ignore[hotpath-host-sync]: overlaps the in-flight decode dispatch — the ring still owns the chunk
            pipe.flush()  # blocks: decode_s is the real dispatch time
    toks = out["toks"]
    # Journey wire leg: a streamed handoff's per-chunk arrival timeline
    # (collected by the stream receiver while the wire was still moving)
    # attaches to this request's journey before the verdict folds.
    if streamed and getattr(payload, "chunk_timeline", None) and request_id:
        from lws_tpu.obs import journey as journeymod

        journeymod.VAULT.annotate(request_id, chunks=payload.chunk_timeline)
    # SLO timeline, decode leg: the chunk's mean step gap is the ITL sample
    # (same per-dispatch discipline as the engines' commit paths). The
    # workload class rode the bundle meta from the submitting client.
    timeline = slo.request("disagg", klass=klass, request_id=request_id)
    timeline.tokens(steps, s_decode.duration_s)
    timeline.finish()
    stats = {
        "bundle_bytes": bundle_bytes,
        "deserialize_s": round(s_deser.duration_s, 4),
        "reshard_s": round(s_reshard.duration_s, 4),
        "decode_s": round(s_decode.duration_s, 4),
        **({"streamed": True, "chunks": payload.chunks} if streamed else {}),
        **spec_stats,
    }
    spans = [s.to_dict() for s in (s_deser, s_reshard, s_decode)]
    return np.concatenate([first[:, None], toks], axis=1), stats, spans


def _own_pod(client, namespace: str, pod_name: str) -> dict:
    return client.get("Pod", namespace, pod_name)


def _force_tracing() -> None:
    """Workers keep tracing on regardless of env sampling: the span subtree
    IS the handoff cost breakdown the protocol ships with each result."""
    from lws_tpu.core import trace

    trace.TRACER.enabled = True
    trace.TRACER.sample_rate = 1.0


def _start_telemetry():
    """Expose this worker's /metrics when the pod declares a telemetry port
    (LWS_TPU_METRICS_PORT) — the surface the control plane's fleet scraper
    merges into /metrics/fleet."""
    from lws_tpu.runtime.telemetry import start_from_env

    server = start_from_env()
    if server is not None:
        print(f"[{os.environ.get('POD_NAME', '?')}] telemetry on :{server.port}",
              flush=True)
    return server


def _serve_prefix_tier(server) -> None:
    """Join the cross-instance prefix tier: advertise this worker's KV wire
    port for sibling `fetch_prefix` calls (the control plane folds it into
    /debug/prefixes -> FleetCollector's digest index) and, when the host
    arena is enabled (LWS_TPU_KV_HOST_ARENA_MB), serve arena-resident
    spilled blocks over that wire. Serving costs no device traffic — the
    arena holds wire-format host bytes already."""
    from lws_tpu.serving import kv_host_arena

    kv_host_arena.register_fetch_port(server.port)
    server.serve_prefixes(kv_host_arena.get_spilled)


def kv_chunk_tokens() -> int:
    """The streamed-handoff chunk size knob (`LWS_TPU_KV_CHUNK`, position
    rows per stream chunk; default 256). 0 selects the monolithic
    single-shot path — the oracle the streamed path is budgeted against."""
    return int(os.environ.get("LWS_TPU_KV_CHUNK", "256") or 0)


def use_streaming(prompt_len: int, chunk_tokens: int,
                  max_len: Optional[int] = None) -> bool:
    """Stream only when the prompt spans MULTIPLE chunks: a single-chunk
    stream is the single-shot transfer with extra frames — short prompts
    keep today's monolithic path. With `max_len`, also require the
    chunk-PADDED prompt to fit the engine's budget: chunked prefill pads
    to a whole number of chunks, so a 270-token prompt under
    chunk=256/max_len=300 must fall back to single-shot (which serves it
    fine) instead of raising in the engine and crash-looping the worker
    on a prompt the monolithic path accepts."""
    if chunk_tokens <= 0 or prompt_len <= chunk_tokens:
        return False
    if max_len is not None:
        padded = prompt_len + ((-prompt_len) % chunk_tokens)
        if padded > max_len:
            return False
    return True


def _prefill_streamed(
    engine, server, kt, meta: dict, req_id: str, prompt, chunk_tokens: int,
    deadline,
) -> None:
    """One STREAMED handoff (ISSUE 10): offer the KVStream FIRST (so a
    decode puller attaches while chunks are still being produced), then run
    the chunked prefill whose emit callback lands each position range into
    the stream — gather/serialize/send of chunk N overlapping compute of
    chunk N+1 on the engine's bounded sender ring. The END frame carries
    the first token + pos tail, the handoff record, and the span subtree
    (exactly what the monolithic bundle meta carried). A producer-side
    failure fails the stream (the server tells the puller and DROPS it —
    the router's resubmit recovers, same as prefill death pre-offer)."""
    import json as _json

    from lws_tpu.core import faults, metrics, slo, trace

    # Death-mid-handoff chaos hook, streamed placement: BEFORE the offer,
    # so an armed exit still kills the request's only copy (the router's
    # resubmit is the recovery path either way).
    faults.fire("disagg.prefill.handoff")
    stream = kt.KVStream(chunk_tokens)
    s_req = trace.span(
        "serve.request", parent=meta.get("trace"),
        role="prefill", request_id=req_id,
    )
    klass = str(meta.get("klass") or "")
    bundle_meta = {"id": req_id, "trace": s_req.context}
    if klass:
        bundle_meta["klass"] = klass  # rides to the decode leg's timeline
    if deadline is not None:
        bundle_meta["deadline_s"] = deadline.to_wire()
    server.offer_stream(bundle_meta, stream)
    try:
        with s_req:
            timeline = slo.request("disagg", klass=klass, request_id=req_id)
            wait = float(meta.get("queue_wait_s", 0.0))
            timeline.queue_wait(wait)
            # kv.gather parents serve.prefill here: the two phases overlap
            # by construction (that IS the streamed win), so the gather
            # span covers the whole streaming window and carries the
            # accumulated per-chunk gather fence time as an attribute.
            with trace.span(
                "kv.gather", streamed=True,
                tp_gathered=engine.mesh is not None,
            ) as s_gather:
                with trace.span(
                    "serve.prefill", chunked=True,
                    prompt_len=int(prompt.size),
                ) as s_prefill:
                    token, cache, pstats = engine.prefill_chunked_stream(
                        prompt.reshape(1, -1), chunk_tokens,
                        emit=stream.put_chunk,
                    )
                s_gather.set(
                    pos=int(cache.pos), bundle_bytes=stream.payload_bytes,
                    chunks=pstats["chunks"],
                    gather_s=round(pstats["gather_s"], 4),
                )
            # Journey wire leg, produce side: when each chunk left prefill
            # compute (the arrival twin lands on the decode journey).
            from lws_tpu.obs import journey as journeymod

            journeymod.VAULT.annotate(
                req_id, chunks_produced=list(stream.chunk_timeline)
            )
            timeline.first_token(wait + s_prefill.duration_s)
            timeline.finish()
    except Exception:
        stream.fail()  # wake the puller with a terminal verdict
        raise
    handoff = {
        "pos": int(cache.pos),
        "bundle_bytes": stream.payload_bytes,
        "prefill_s": round(s_prefill.duration_s, 4),
        "gather_s": round(pstats["gather_s"], 4),
        "tp_gathered": engine.mesh is not None,
        "streamed": True,
        "chunks": pstats["chunks"],
    }
    metrics.inc("serving_kv_handoffs_total")
    metrics.inc("serving_kv_handoff_bytes_total", value=stream.payload_bytes)
    import numpy as _np

    stream.finish(
        {
            "handoff": handoff,
            "spans": [s.to_dict() for s in (s_req, s_prefill, s_gather)],
        },
        {"token": _np.asarray(token), "pos": _np.asarray(int(cache.pos), _np.int32)},
    )
    print(f"[prefill] HANDOFF {req_id} {_json.dumps(handoff)}", flush=True)


def run_prefill_tcp(once: bool, max_len: int) -> int:
    """Serve prompts-in / KV-bundles-out on LWS_TPU_KV_PORT. With `once`,
    exit after the first bundle has been pulled AND acked by a peer.
    SIGTERM (or POST /debug/drain on the telemetry port) drains: stop
    admitting prompts, finish the in-flight handoff, exit clean — queued
    prompts stay the router's responsibility (at-least-once: unanswered
    ids are resubmitted).

    Long prompts (past `LWS_TPU_KV_CHUNK` rows) hand off STREAMED: the
    KVStream is offered BEFORE prefill computes, and each chunk's KV is
    gathered and shipped while the next chunk is still computing
    (Engine.prefill_chunked_stream) — decode starts uploading rows while
    prefill is mid-prompt, so the handoff costs ~max(compute, wire)
    instead of their sum."""
    from lws_tpu.core import metrics, resilience, slo, trace
    from lws_tpu.serving import kv_transport as kt

    _force_tracing()
    _start_telemetry()
    engine = build_engine(batch=1, max_len=max_len)
    server = kt.KVServer(port=int(os.environ.get("LWS_TPU_KV_PORT", "0")))
    _serve_prefix_tier(server)
    chunk_tokens = kv_chunk_tokens()
    print(f"[prefill {os.environ.get('POD_NAME', '?')}] serving KV on :{server.port}"
          f" (kv_chunk={chunk_tokens})", flush=True)
    while True:
        if resilience.DRAIN.draining:
            print(f"[prefill] DRAINED ({resilience.DRAIN.reason}): "
                  f"{server.delivery_counts()[0]} bundles delivered; exiting clean",
                  flush=True)
            return 0
        if once and server.delivery_counts()[0] >= 1:
            return 0
        item = server.next_prompt(timeout=0.5)
        if item is None:
            continue
        meta, payload = item
        req_id = meta["id"]
        # Deadline rides the frame meta like trace ctx: an already-expired
        # prompt is DROPPED (recorded, not prefilled) — burning a prefill
        # dispatch on a request nobody is waiting for starves live ones.
        deadline = resilience.Deadline.from_wire(meta.get("deadline_s"))
        if deadline is not None and deadline.expired():
            resilience.expire("prefill.admit", request_id=req_id)
            # The drop IS the request's ending here: complete its journey
            # as deadline-expired so the vault retains the story.
            from lws_tpu.obs import journey as journeymod

            journeymod.VAULT.complete(
                req_id, trace=meta.get("trace"), engine="disagg",
                klass=str(meta.get("klass") or ""),
                outcome="deadline_expired",
            )
            print(f"[prefill] DROPPED {req_id}: deadline expired in queue",
                  flush=True)
            continue
        prompt = kt.bytes_to_arrays(payload)["prompt"]
        import json as _json

        if use_streaming(int(prompt.size), chunk_tokens, engine.max_len):
            _prefill_streamed(
                engine, server, kt, meta, req_id, prompt, chunk_tokens,
                deadline,
            )
            continue

        # The request's span subtree grafts onto the submitting client's
        # trace (meta["trace"]) and replaces the old ad-hoc timers: the
        # handoff record is DERIVED from the span durations, same keys.
        with trace.span(
            "serve.request", parent=meta.get("trace"),
            role="prefill", request_id=req_id,
        ) as s_req:
            # SLO timeline, prefill leg: the KVServer stamped the prompt at
            # enqueue, so queue wait is the REAL socket-to-worker wait; TTFT
            # covers queue + prefill (the token exists after this dispatch).
            timeline = slo.request("disagg", klass=str(meta.get("klass") or ""),
                                   request_id=req_id)
            wait = float(meta.get("queue_wait_s", 0.0))
            timeline.queue_wait(wait)
            with trace.span("serve.prefill", chunked=False,
                            prompt_len=int(prompt.size)) as s_prefill:
                token, cache = engine.prefill(prompt.reshape(1, -1))
                np.asarray(token)  # block: prefill_s is the real dispatch time
            timeline.first_token(wait + s_prefill.duration_s)
            with trace.span("kv.gather", tp_gathered=engine.mesh is not None) as s_gather:
                bundle = kt.cache_to_bundle(cache, token)  # pos-truncated (+gathered)
                s_gather.set(pos=int(cache.pos), bundle_bytes=len(bundle))
            # finish() completes the journey — it must run after kv.gather
            # closes (like the streamed path) or the gather leg never joins
            # the completed journey and orphans an open-trace bucket.
            timeline.finish()
        handoff = {
            "pos": int(cache.pos),
            "bundle_bytes": len(bundle),
            "prefill_s": round(s_prefill.duration_s, 4),
            "gather_s": round(s_gather.duration_s, 4),
            "tp_gathered": engine.mesh is not None,
        }
        metrics.inc("serving_kv_handoffs_total")
        metrics.inc("serving_kv_handoff_bytes_total", value=len(bundle))
        # The handoff record rides the bundle meta: decode merges its own
        # deserialize/reshard/decode timings and returns the WHOLE handoff
        # cost breakdown — and the full span subtree — to the client with
        # the result. The bundle's trace ctx parents decode's subtree under
        # THIS request span, keeping one connected tree across processes.
        # The fault point below is the "prefill dies mid-handoff" chaos
        # hook: exit mode kills the process after prefill compute but
        # before the bundle is offered (the request's only copy dies with
        # it — the router's resubmit is the recovery path).
        from lws_tpu.core import faults

        faults.fire("disagg.prefill.handoff")
        bundle_meta = {
            "id": req_id, "handoff": handoff, "trace": s_req.context,
            "spans": [s.to_dict() for s in (s_req, s_prefill, s_gather)],
        }
        if meta.get("klass"):
            bundle_meta["klass"] = str(meta["klass"])  # decode leg's series
        if deadline is not None:
            bundle_meta["deadline_s"] = deadline.to_wire()
        server.offer_bundle(bundle_meta, bundle)
        print(f"[prefill] HANDOFF {req_id} {_json.dumps(handoff)}", flush=True)


def run_decode_tcp(
    steps: int, once: bool, max_len: int, gamma: int = 0, ngram: int = 3,
) -> int:
    """Discover prefill's endpoint from the DS -prv service record (via the
    API server), pull KV bundles over TCP, decode, serve results. The pull
    is acked only AFTER the result is posted (end-to-end at-least-once: a
    crash mid-decode re-queues the bundle server-side). With `once`, exit
    after the first result has been delivered to a peer. SIGTERM / POST
    /debug/drain drains between pulls: the in-flight bundle finishes and
    acks, nothing new is admitted, unacked bundles stay queued on prefill
    for a successor, and the process exits clean."""
    import time as _time

    from lws_tpu.api import disagg
    from lws_tpu.client import RemoteClient
    from lws_tpu.core import faults, resilience, trace
    from lws_tpu.utils.common import env_float
    from lws_tpu.serving import kv_transport as kt

    _force_tracing()
    _start_telemetry()
    engine = build_engine(batch=1, max_len=max_len)
    server = kt.KVServer(port=int(os.environ.get("LWS_TPU_KV_PORT", "0")))
    _serve_prefix_tier(server)
    # Replays HAPPEN on this path (ack loss, redelivery after a pull died
    # mid-processing): the bounded seen-id guard enforces the "decode is
    # idempotent per id" contract instead of documenting it.
    seen = resilience.SeenIds(capacity=1024, site="decode")
    breakers: dict[str, resilience.CircuitBreaker] = {}
    me = os.environ.get("POD_NAME", str(os.getpid()))
    namespace = os.environ.get("POD_NAMESPACE", "default")
    client = RemoteClient(os.environ["LWS_TPU_API"])
    own = _own_pod(client, namespace, me)
    labels = own["metadata"]["labels"]
    ds_name = labels[disagg.DS_NAME_LABEL_KEY]
    # Pin the pairing to OUR revision and slice: during a rollout both
    # revisions' -prv services coexist, and pairing across them would decode
    # against different weights (silently wrong tokens).
    revision = labels.get(disagg.DS_REVISION_LABEL_KEY)
    slice_idx = labels.get(disagg.DS_SLICE_LABEL_KEY)
    print(f"[decode {me}] serving results on :{server.port}; discovering "
          f"prefill of DS {ds_name!r} rev={revision} slice={slice_idx}", flush=True)

    def process(meta, payload):
        import json as _json

        # Chaos hook: exit mode here is "decode crashes mid-processing" —
        # the connection drops unacked, the bundle re-queues on prefill,
        # and a successor (or restart) redelivers.
        faults.fire("disagg.decode.process")
        if seen.contains(meta["id"]):
            # A replayed delivery (the ack was lost): the result was
            # already posted — ack without decoding again, or the replay
            # would double-spend device time and could double-deliver.
            # (Ids are recorded only AFTER post_result succeeds — see
            # below — so a first attempt that died mid-post redelivers
            # into a real retry, never an ack-with-no-result.)
            print(f"[decode] REPLAY {meta['id']}: already decoded, "
                  "acking without re-decode", flush=True)
            return
        deadline = resilience.Deadline.from_wire(meta.get("deadline_s"))
        if deadline is not None and deadline.expired():
            resilience.expire("decode.admit", request_id=meta["id"])
            from lws_tpu.obs import journey as journeymod

            journeymod.VAULT.complete(
                meta["id"], trace=meta.get("trace"), engine="disagg",
                klass=str(meta.get("klass") or ""),
                outcome="deadline_expired",
            )
            server.post_result(
                meta["id"],
                {"id": meta["id"], "failed": "deadline exceeded before decode"},
                b"",
            )
            seen.record(meta["id"])
            return
        # Parent decode's subtree under the prefill-side request span (the
        # bundle meta's trace ctx): one connected tree, client -> prefill ->
        # decode, reassembled client-side from the "spans" records below.
        s_req = trace.span(
            "serve.request", parent=meta.get("trace"),
            role="decode", request_id=meta["id"],
        )
        try:
            with s_req:
                full, dstats, dspans = _decode_bundle(
                    engine, payload, steps, gamma=gamma, ngram=ngram,
                    klass=str(meta.get("klass") or ""),
                    request_id=meta["id"],
                )
        except Exception as e:  # noqa: BLE001
            # Poison-message guard: a bundle this engine can't process (e.g.
            # prompt longer than decode's max_len budget) must be CONSUMED
            # with a failed result, not crash the worker — an un-acked crash
            # would re-queue the same bundle forever and head-of-line block
            # every request behind it. The failure is also the request's
            # ending: its journey completes ERRORED (always retained).
            from lws_tpu.obs import journey as journeymod

            journeymod.VAULT.complete(
                meta["id"], trace=meta.get("trace"), engine="disagg",
                klass=str(meta.get("klass") or ""),
                outcome="errored", error=repr(e),
            )
            print(f"[decode] FAILED {meta['id']}: {e!r}", flush=True)
            server.post_result(meta["id"], {"id": meta["id"], "failed": repr(e)[:300]}, b"")
            seen.record(meta["id"])
            return
        handoff = {**meta.get("handoff", {}), **dstats}
        spans_out = list(meta.get("spans", [])) + dspans + [s_req.to_dict()]
        server.post_result(
            meta["id"], {"id": meta["id"], "handoff": handoff, "spans": spans_out},
            kt.arrays_to_bytes(tokens=full),
        )
        # Only NOW is the id complete: recording before the post could turn
        # a redelivery after a mid-post failure into a silent ack-no-result.
        seen.record(meta["id"])
        print(f"[decode] HANDOFF {meta['id']} {_json.dumps(handoff)}", flush=True)
        print(f"[decode] finished {meta['id']}: {full[0][:8]}...", flush=True)

    endpoint = None
    breaker = None
    while True:
        if resilience.DRAIN.draining:
            print(f"[decode] DRAINED ({resilience.DRAIN.reason}): "
                  f"{server.delivery_counts()[1]} results delivered; "
                  "unacked bundles stay queued on prefill; exiting clean",
                  flush=True)
            return 0
        if once and server.delivery_counts()[1] >= 1:
            return 0
        if endpoint is None:
            # The -prv service exists only once the revision is ready on ALL
            # roles — poll the record, not a filesystem.
            endpoint = kt.discover_role_endpoint(
                client, namespace, ds_name, "prefill",
                revision=revision, slice_idx=slice_idx,
            )
            if endpoint is None:
                _time.sleep(0.5)
                continue
            print(f"[decode] prefill endpoint via -prv service: {endpoint}", flush=True)
            # One breaker per endpoint, kept across rediscoveries: failure
            # counts must survive the endpoint=None round trips below or
            # the circuit could never accumulate enough to open. BOUNDED:
            # every prefill roll mints a fresh ip:port, and a long-lived
            # decode worker must not leak breakers (or their gauge series)
            # across weeks of rolls — oldest evicted, its gauge retired.
            name = f"prefill@{endpoint[0]}:{endpoint[1]}"
            if name not in breakers:
                while len(breakers) >= 8:
                    breakers.pop(next(iter(breakers))).retire()
                breakers[name] = resilience.CircuitBreaker(
                    name,
                    failure_threshold=int(env_float("LWS_TPU_BREAKER_THRESHOLD", 5)),
                    reset_timeout_s=env_float("LWS_TPU_BREAKER_RESET_S", 5.0),
                )
            breaker = breakers[name]
        if not breaker.allow():
            # Open circuit: fail fast instead of re-dialing a dead peer
            # every poll; the half-open probe re-tests after the reset
            # window (a rolled replica comes back through here).
            _time.sleep(0.1)
            continue
        try:
            # process() runs BEFORE the ack goes back (see pull_bundle); the
            # ack window covers decode + first-call compile. One bounded
            # in-line retry absorbs transient blips (accept-queue hiccups)
            # without waiting out a full poll interval. Streamed replies
            # assemble through a CacheAssembler: each chunk device-uploads
            # into its position slice ON ARRIVAL (host assembly under a
            # mesh — the reshard leg keeps the single sharded device_put),
            # so the first decode step dispatches the moment END lands.
            resilience.call(
                lambda: kt.pull_bundle(endpoint, timeout=1.0, process=process,
                                       ack_timeout=600.0,
                                       receiver_factory=lambda m: kt.CacheAssembler(
                                           max_len=engine.max_len,
                                           device=engine.mesh is None)),
                site="kv.pull_bundle",
                policy=resilience.RetryPolicy(max_attempts=2, base_s=0.05,
                                              cap_s=0.25),
            )
            breaker.record_success()
        except OSError:
            breaker.record_failure()
            endpoint = None  # peer rolled/moved: rediscover through the service
            continue


def main() -> int:
    from lws_tpu.core import faults, resilience

    # SIGTERM = the kubelet's stop signal: drain instead of dying mid-
    # request. Fault schedules arm from the pod env (LWS_TPU_FAULTS) for
    # chaos runs; POST /debug/faults on the telemetry port can re-arm live.
    resilience.DRAIN.install_signal_handler()
    faults.arm_from_env()
    parser = argparse.ArgumentParser()
    parser.add_argument("role", choices=["prefill", "decode"])
    # The directory transport was deleted (round 4); the flag survives so
    # round-3 manifests that pass --transport tcp still apply.
    parser.add_argument("--transport", choices=["tcp"], default="tcp")
    parser.add_argument("--steps", type=int, default=6)
    parser.add_argument("--max-len", type=int, default=32)
    parser.add_argument("--once", action="store_true")
    # Speculative decode leg (ISSUE 9): gamma > 0 turns on device-resident
    # speculation for the decode worker — byte-identical greedy tokens,
    # fewer dispatches on repetitive content. Defaults come from the pod
    # env so a DisaggregatedSet template can flip it fleet-wide.
    parser.add_argument(
        "--gamma", type=int,
        default=int(os.environ.get("LWS_TPU_SPEC_GAMMA", "0") or 0),
    )
    parser.add_argument(
        "--ngram", type=int,
        default=int(os.environ.get("LWS_TPU_SPEC_NGRAM", "3") or 3),
    )
    args = parser.parse_args()
    if args.role == "prefill":
        return run_prefill_tcp(args.once, args.max_len)
    return run_decode_tcp(
        args.steps, args.once, args.max_len, gamma=args.gamma, ngram=args.ngram
    )


if __name__ == "__main__":
    raise SystemExit(main())
