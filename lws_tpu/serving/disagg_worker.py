"""Disaggregated serving workers: the llm-d shape (BASELINE config #5) as
runnable processes under a DisaggregatedSet.

  python -m lws_tpu.serving.disagg_worker prefill --transport tcp
  python -m lws_tpu.serving.disagg_worker decode  --transport tcp

TCP transport (the real data plane, VERDICT r3 #5): the prefill worker
serves prompts-in / KV-bundles-out on its LWS_TPU_KV_PORT; the decode
worker DISCOVERS prefill's endpoint from the DS's revision-aware `-prv`
service record via the API server (LWS_TPU_API), pulls bundles over the
socket, decodes, and serves results on its own port. KV bytes move over
TCP only — zero shared-filesystem coupling (ref the reference's
service_manager.go:126-163 endpoint publication).

Directory transport (--transport dir, the round-2 stand-in): prompt files
(`<id>.prompt.npy`) -> bundle files (`<id>.kv.npz`) -> `<id>.tokens.npy`
in a shared --handoff dir; kept for single-host dev without an API server.

Both roles build the SAME model from a shared seed (in production: the same
checkpoint), so prefill's cache is exactly what decode expects — verified by
tests/test_e2e_disagg.py against a single-engine oracle.
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np


def _claim(path: str, worker_id: str):
    """Atomically claim a work file: replicas of a role share the handoff dir
    and race on the same files; os.rename decides the winner, losers skip."""
    claimed = f"{path}.claimed.{worker_id}"
    try:
        os.rename(path, claimed)
        return claimed
    except FileNotFoundError:
        return None


def build_engine(batch: int, max_len: int):
    from lws_tpu.parallel.bootstrap import assert_platform_from_env

    assert_platform_from_env()  # the pod env's JAX_PLATFORMS must win

    import jax
    import jax.numpy as jnp

    from lws_tpu.models.llama import LlamaConfig, init_params
    from lws_tpu.serving import Engine

    cfg = LlamaConfig(
        vocab_size=101, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=max_len, dtype=jnp.float32, remat=False,
    )
    params = init_params(cfg, jax.random.key(1234))
    return Engine(cfg, params, batch_size=batch, max_len=max_len)


def run_prefill(handoff: str, once: bool) -> int:
    engine = build_engine(batch=1, max_len=32)
    print(f"[prefill {os.environ.get('POD_NAME', '?')}] ready, watching {handoff}")
    me = os.environ.get("POD_NAME", str(os.getpid()))
    while True:
        work = [f for f in os.listdir(handoff) if f.endswith(".prompt.npy")]
        for fname in sorted(work):
            req_id = fname.split(".")[0]
            path = _claim(os.path.join(handoff, fname), me)
            if path is None:
                continue  # a replica beat us to it
            from lws_tpu.serving.kv_transport import cache_to_bundle

            prompt = np.load(path)
            token, cache = engine.prefill(prompt.reshape(1, -1))
            out = os.path.join(handoff, f"{req_id}.kv.npz")
            tmp = out + ".tmp.npz"
            with open(tmp, "wb") as f:
                f.write(cache_to_bundle(cache, token))
            os.replace(tmp, out)
            os.remove(path)
            print(f"[prefill] handed off {req_id} (pos={int(cache.pos)})", flush=True)
            if once:
                return 0
        time.sleep(0.2)


def _decode_bundle(engine, payload: bytes, steps: int) -> np.ndarray:
    """Bundle bytes -> [B, steps+1] tokens (first token + decode_n)."""
    from lws_tpu.serving.kv_transport import bundle_to_cache

    cache, token = bundle_to_cache(payload)
    first = np.asarray(token)
    _, _, tokens = engine.decode_n(token, cache, steps)
    return np.concatenate([first[:, None], np.asarray(tokens)], axis=1)


def run_decode(handoff: str, steps: int, once: bool) -> int:
    engine = build_engine(batch=1, max_len=32)
    print(f"[decode {os.environ.get('POD_NAME', '?')}] ready, watching {handoff}")
    me = os.environ.get("POD_NAME", str(os.getpid()))
    while True:
        work = [f for f in os.listdir(handoff) if f.endswith(".kv.npz")]
        for fname in sorted(work):
            req_id = fname.split(".")[0]
            path = _claim(os.path.join(handoff, fname), me)
            if path is None:
                continue
            with open(path, "rb") as f:
                full = _decode_bundle(engine, f.read(), steps)
            out = os.path.join(handoff, f"{req_id}.tokens.npy")
            np.save(out + ".tmp.npy", full)
            os.replace(out + ".tmp.npy", out)
            os.remove(path)
            print(f"[decode] finished {req_id}: {full[0][:8]}...")
            if once:
                return 0
        time.sleep(0.2)


def _own_pod(client, namespace: str, pod_name: str) -> dict:
    return client.get("Pod", namespace, pod_name)


def run_prefill_tcp(once: bool) -> int:
    """Serve prompts-in / KV-bundles-out on LWS_TPU_KV_PORT. With `once`,
    exit after the first bundle has been pulled AND acked by a peer."""
    from lws_tpu.serving import kv_transport as kt

    engine = build_engine(batch=1, max_len=32)
    server = kt.KVServer(port=int(os.environ.get("LWS_TPU_KV_PORT", "0")))
    print(f"[prefill {os.environ.get('POD_NAME', '?')}] serving KV on :{server.port}",
          flush=True)
    while True:
        if once and server.bundles_delivered >= 1:
            return 0
        item = server.next_prompt(timeout=0.5)
        if item is None:
            continue
        meta, payload = item
        req_id = meta["id"]
        prompt = kt.bytes_to_arrays(payload)["prompt"]
        token, cache = engine.prefill(prompt.reshape(1, -1))
        server.offer_bundle({"id": req_id}, kt.cache_to_bundle(cache, token))
        print(f"[prefill] handed off {req_id} (pos={int(cache.pos)})", flush=True)


def run_decode_tcp(steps: int, once: bool) -> int:
    """Discover prefill's endpoint from the DS -prv service record (via the
    API server), pull KV bundles over TCP, decode, serve results. With
    `once`, exit after the first result has been delivered to a peer."""
    import time as _time

    from lws_tpu.api import disagg
    from lws_tpu.client import RemoteClient
    from lws_tpu.serving import kv_transport as kt

    engine = build_engine(batch=1, max_len=32)
    server = kt.KVServer(port=int(os.environ.get("LWS_TPU_KV_PORT", "0")))
    me = os.environ.get("POD_NAME", str(os.getpid()))
    namespace = os.environ.get("POD_NAMESPACE", "default")
    client = RemoteClient(os.environ["LWS_TPU_API"])
    own = _own_pod(client, namespace, me)
    labels = own["metadata"]["labels"]
    ds_name = labels[disagg.DS_NAME_LABEL_KEY]
    # Pin the pairing to OUR revision and slice: during a rollout both
    # revisions' -prv services coexist, and pairing across them would decode
    # against different weights (silently wrong tokens).
    revision = labels.get(disagg.DS_REVISION_LABEL_KEY)
    slice_idx = labels.get(disagg.DS_SLICE_LABEL_KEY)
    print(f"[decode {me}] serving results on :{server.port}; discovering "
          f"prefill of DS {ds_name!r} rev={revision} slice={slice_idx}", flush=True)

    endpoint = None
    while True:
        if once and server.results_served >= 1:
            return 0
        if endpoint is None:
            # The -prv service exists only once the revision is ready on ALL
            # roles — poll the record, not a filesystem.
            endpoint = kt.discover_role_endpoint(
                client, namespace, ds_name, "prefill",
                revision=revision, slice_idx=slice_idx,
            )
            if endpoint is None:
                _time.sleep(0.5)
                continue
            print(f"[decode] prefill endpoint via -prv service: {endpoint}", flush=True)
        try:
            pulled = kt.pull_bundle(endpoint, timeout=1.0)
        except OSError:
            endpoint = None  # peer rolled/moved: rediscover through the service
            continue
        if pulled is None:
            continue
        meta, payload = pulled
        full = _decode_bundle(engine, payload, steps)
        server.post_result(meta["id"], {"id": meta["id"]}, kt.arrays_to_bytes(tokens=full))
        print(f"[decode] finished {meta['id']}: {full[0][:8]}...", flush=True)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("role", choices=["prefill", "decode"])
    parser.add_argument("--transport", choices=["dir", "tcp"], default="dir")
    parser.add_argument("--handoff", default=os.environ.get("LWS_TPU_HANDOFF_DIR", "/tmp/lws-handoff"))
    parser.add_argument("--steps", type=int, default=6)
    parser.add_argument("--once", action="store_true")
    args = parser.parse_args()
    if args.transport == "tcp":
        if args.role == "prefill":
            return run_prefill_tcp(args.once)
        return run_decode_tcp(args.steps, args.once)
    os.makedirs(args.handoff, exist_ok=True)
    if args.role == "prefill":
        return run_prefill(args.handoff, args.once)
    return run_decode(args.handoff, args.steps, args.once)


if __name__ == "__main__":
    raise SystemExit(main())
