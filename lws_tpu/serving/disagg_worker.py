"""Disaggregated serving workers: the llm-d shape (BASELINE config #5) as
runnable processes under a DisaggregatedSet.

  python -m lws_tpu.serving.disagg_worker prefill --handoff DIR
  python -m lws_tpu.serving.disagg_worker decode  --handoff DIR

The prefill role consumes prompt files (`<id>.prompt.npy`), runs
`Engine.prefill`, and writes the KV cache + first token as a handoff bundle
(`<id>.kv.npz`). The decode role consumes bundles, runs `Engine.decode_n`,
and writes `<id>.tokens.npy`. The handoff directory stands in for the
cross-slice DCN transfer; the endpoints real deployments would dial are the
DS's per-(slice, revision, role) `-prv` services.

Both roles build the SAME model from a shared seed (in production: the same
checkpoint), so prefill's cache is exactly what decode expects — verified by
tests/test_e2e_disagg.py against a single-engine oracle.
"""

from __future__ import annotations

import argparse
import os
import time

import numpy as np


def _claim(path: str, worker_id: str):
    """Atomically claim a work file: replicas of a role share the handoff dir
    and race on the same files; os.rename decides the winner, losers skip."""
    claimed = f"{path}.claimed.{worker_id}"
    try:
        os.rename(path, claimed)
        return claimed
    except FileNotFoundError:
        return None


def build_engine(batch: int, max_len: int):
    from lws_tpu.parallel.bootstrap import assert_platform_from_env

    assert_platform_from_env()  # the pod env's JAX_PLATFORMS must win

    import jax
    import jax.numpy as jnp

    from lws_tpu.models.llama import LlamaConfig, init_params
    from lws_tpu.serving import Engine

    cfg = LlamaConfig(
        vocab_size=101, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=max_len, dtype=jnp.float32, remat=False,
    )
    params = init_params(cfg, jax.random.key(1234))
    return Engine(cfg, params, batch_size=batch, max_len=max_len)


def run_prefill(handoff: str, once: bool) -> int:
    engine = build_engine(batch=1, max_len=32)
    print(f"[prefill {os.environ.get('POD_NAME', '?')}] ready, watching {handoff}")
    me = os.environ.get("POD_NAME", str(os.getpid()))
    while True:
        work = [f for f in os.listdir(handoff) if f.endswith(".prompt.npy")]
        for fname in sorted(work):
            req_id = fname.split(".")[0]
            path = _claim(os.path.join(handoff, fname), me)
            if path is None:
                continue  # a replica beat us to it
            prompt = np.load(path)
            token, cache = engine.prefill(prompt.reshape(1, -1))
            out = os.path.join(handoff, f"{req_id}.kv.npz")
            tmp = out + ".tmp.npz"  # keep the .npz suffix so np.savez doesn't append one
            extra = {}
            if cache.k_scale is not None:  # kv_quant caches carry scales
                extra = {"k_scale": np.asarray(cache.k_scale), "v_scale": np.asarray(cache.v_scale)}
            np.savez(
                tmp,
                k=np.asarray(cache.k), v=np.asarray(cache.v),
                pos=np.asarray(cache.pos), token=np.asarray(token), **extra,
            )
            os.replace(tmp, out)
            os.remove(path)
            print(f"[prefill] handed off {req_id} (pos={int(cache.pos)})", flush=True)
            if once:
                return 0
        time.sleep(0.2)


def run_decode(handoff: str, steps: int, once: bool) -> int:
    import jax.numpy as jnp

    from lws_tpu.models.llama import KVCache

    engine = build_engine(batch=1, max_len=32)
    print(f"[decode {os.environ.get('POD_NAME', '?')}] ready, watching {handoff}")
    me = os.environ.get("POD_NAME", str(os.getpid()))
    while True:
        work = [f for f in os.listdir(handoff) if f.endswith(".kv.npz")]
        for fname in sorted(work):
            req_id = fname.split(".")[0]
            path = _claim(os.path.join(handoff, fname), me)
            if path is None:
                continue
            bundle = np.load(path)
            cache = KVCache(
                k=jnp.asarray(bundle["k"]), v=jnp.asarray(bundle["v"]),
                pos=jnp.asarray(bundle["pos"]),
                k_scale=jnp.asarray(bundle["k_scale"]) if "k_scale" in bundle else None,
                v_scale=jnp.asarray(bundle["v_scale"]) if "v_scale" in bundle else None,
            )
            token = jnp.asarray(bundle["token"])
            _, _, tokens = engine.decode_n(token, cache, steps)
            full = np.concatenate([np.asarray(bundle["token"])[:, None], np.asarray(tokens)], axis=1)
            out = os.path.join(handoff, f"{req_id}.tokens.npy")
            np.save(out + ".tmp.npy", full)
            os.replace(out + ".tmp.npy", out)
            os.remove(path)
            print(f"[decode] finished {req_id}: {full[0][:8]}...")
            if once:
                return 0
        time.sleep(0.2)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("role", choices=["prefill", "decode"])
    parser.add_argument("--handoff", default=os.environ.get("LWS_TPU_HANDOFF_DIR", "/tmp/lws-handoff"))
    parser.add_argument("--steps", type=int, default=6)
    parser.add_argument("--once", action="store_true")
    args = parser.parse_args()
    os.makedirs(args.handoff, exist_ok=True)
    if args.role == "prefill":
        return run_prefill(args.handoff, args.once)
    return run_decode(args.handoff, args.steps, args.once)


if __name__ == "__main__":
    raise SystemExit(main())
