"""Inference engine: jitted prefill + decode over a static-shape KV cache.

Prefill is compute-bound (MXU, whole prompt in one pass); decode is
HBM-bandwidth-bound (every step streams params + cache). The two phases are
separable — `prefill()` returns the cache that `decode()` consumes, which is
exactly the KV handoff a DisaggregatedSet prefill/decode deployment performs
across slices (over DCN, endpoints published by the DS service manager).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from lws_tpu.models.llama import (
    KVCache,
    LlamaConfig,
    forward_prefill,
    forward_with_cache,
    init_cache,
)


def host_sync(x) -> None:
    """Force completion via a host transfer — `block_until_ready` is not a
    reliable fence on relay-backed remote TPU backends."""
    np.asarray(x)


@dataclass
class GenerationResult:
    tokens: jax.Array  # [B, steps]
    ttft_s: float
    decode_s: float
    decode_steps: int
    decode_tokens_per_s: float


class Engine:
    def __init__(self, cfg: LlamaConfig, params: dict, batch_size: int = 1, max_len: int = 2048):
        self.cfg = cfg
        self.params = params
        self.batch_size = batch_size
        self.max_len = max_len

        cfg_static = cfg

        @jax.jit
        def _prefill(params, tokens, cache):
            # Engine.prefill always starts on an empty cache, so the
            # flash-attention prefill path applies (causal over the prompt
            # only, not masked attention over the whole cache length).
            logits, cache = forward_prefill(params, tokens, cache, cfg_static)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        @partial(jax.jit, donate_argnums=(2,))
        def _decode(params, tokens, cache):
            logits, cache = forward_with_cache(params, tokens[:, None], cache, cfg_static)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        @partial(jax.jit, donate_argnums=(2,), static_argnums=(3,))
        def _decode_n(params, tokens, cache, n):
            # Whole decode loop on-device: one dispatch for n steps (no
            # per-step host round trips — critical on relay-backed links).
            def body(carry, _):
                token, cache = carry
                logits, cache = forward_with_cache(params, token[:, None], cache, cfg_static)
                token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (token, cache), token

            (token, cache), toks = jax.lax.scan(body, (tokens, cache), None, length=n)
            return token, cache, toks.swapaxes(0, 1)  # [B, n]

        self._prefill = _prefill
        self._decode = _decode
        self._decode_n = _decode_n

    def new_cache(self) -> KVCache:
        return init_cache(self.cfg, self.batch_size, self.max_len)

    def prefill(self, tokens: jax.Array) -> tuple[jax.Array, KVCache]:
        """tokens [B, S] -> (first generated token [B], cache)."""
        return self._prefill(self.params, tokens, self.new_cache())

    def decode(self, tokens: jax.Array, cache: KVCache) -> tuple[jax.Array, KVCache]:
        """tokens [B] -> (next token [B], cache)."""
        return self._decode(self.params, tokens, cache)

    def decode_n(self, tokens: jax.Array, cache: KVCache, n: int):
        """n chained greedy steps in ONE device call; returns
        (last token [B], cache, all tokens [B, n])."""
        return self._decode_n(self.params, tokens, cache, n)

    def generate(self, prompt: jax.Array, max_new_tokens: int) -> GenerationResult:
        """Greedy generation with timing split (TTFT vs steady decode).

        Decode steps are chained without intermediate syncs (the token feeds
        the next step), with one host-transfer fence at the end; the timing
        therefore includes one fixed sync overhead — callers benching on
        high-latency links should difference two runs (see bench.py)."""
        t0 = time.perf_counter()
        token, cache = self.prefill(prompt)
        host_sync(token)
        ttft = time.perf_counter() - t0

        out = [token]
        if max_new_tokens > 1:
            # Warm the decode path (compile) before timing.
            token, cache = self.decode(token, cache)
            out.append(token)
            host_sync(token)

        t1 = time.perf_counter()
        steps = max(0, max_new_tokens - len(out))
        for _ in range(steps):
            token, cache = self.decode(token, cache)
            out.append(token)
        host_sync(token)
        dt = time.perf_counter() - t1
        tok_per_s = (steps * self.batch_size) / dt if steps else 0.0
        return GenerationResult(
            tokens=jnp.stack(out, axis=1),
            ttft_s=ttft,
            decode_s=dt,
            decode_steps=steps,
            decode_tokens_per_s=tok_per_s,
        )
