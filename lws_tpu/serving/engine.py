"""Inference engine: jitted prefill + decode over a static-shape KV cache.

Prefill is compute-bound (MXU, whole prompt in one pass); decode is
HBM-bandwidth-bound (every step streams params + cache). The two phases are
separable — `prefill()` returns the cache that `decode()` consumes, which is
exactly the KV handoff a DisaggregatedSet prefill/decode deployment performs
across slices (over DCN, endpoints published by the DS service manager).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from lws_tpu.core import metrics, slo, trace
from lws_tpu.serving.pipeline import DecodePipeline
from lws_tpu.models.llama import (
    KVCache,
    LlamaConfig,
    cache_shardings,
    forward_prefill,
    forward_with_cache,
    init_cache,
    param_shardings,
)


def shard_params_for_serving(params: dict, cfg: LlamaConfig, mesh) -> dict:
    """Place params onto a serving mesh per the model's TP sharding rules
    (weights split over 'tp'; the layer-stack dim rides 'pp', size 1 on a
    pure-TP serving mesh). On a multi-host mesh every process calls this
    with the same host params and jax builds the global sharded arrays.

    int8 weights compose: a QuantizedArray's q takes the weight's spec
    verbatim; its per-output-channel scale takes the spec with the
    CONTRACTION dim removed (embed is scaled over its last dim, everything
    else over -2 — quantize_params' layout contract), so tp-split output
    channels carry their tp-split scales."""
    import jax.tree_util as jtu
    from jax.sharding import NamedSharding, PartitionSpec as P

    from lws_tpu.models.quant import QuantizedArray

    def place(path, p, spec):
        sh = NamedSharding(mesh, spec)
        if isinstance(p, QuantizedArray):
            name = next(
                (e.key for e in reversed(path) if hasattr(e, "key")), ""
            )
            contract = -1 if name == "embed" else -2
            parts = list(spec) + [None] * (p.q.ndim - len(spec))
            del parts[contract + p.q.ndim if contract < 0 else contract]
            scale_sh = NamedSharding(mesh, P(*parts))
            return QuantizedArray(
                q=jax.device_put(p.q, sh),
                scale=jax.device_put(p.scale, scale_sh),
            )
        return jax.device_put(p, sh)

    return jtu.tree_map_with_path(
        place, params, param_shardings(cfg),
        is_leaf=lambda x: isinstance(x, QuantizedArray),
    )


@dataclass(frozen=True)
class SamplingParams:
    """temperature == 0 is greedy; top_k/top_p restrict the candidate set."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0


def sample_logits(logits: jax.Array, key, params: SamplingParams) -> jax.Array:
    """logits [B, V] -> token ids [B]."""
    if params.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    V = logits.shape[-1]
    logits = logits / params.temperature
    if params.top_k > 0 and params.top_k < V:
        # lax.top_k: O(V) threshold instead of a full-vocab sort.
        kth = jax.lax.top_k(logits, params.top_k)[0][:, -1][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if params.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumulative = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with mass >= top_p (always >= 1 token).
        cutoff_idx = jnp.sum(cumulative < params.top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def sample_logits_per_slot(
    logits: jax.Array,       # [B, V]
    keys: jax.Array,         # [B] typed PRNG keys (one stream per slot)
    temperature: jax.Array,  # [B] f32; <= 0 means greedy for that slot
    top_k: jax.Array,        # [B] i32; 0 disables
    top_p: jax.Array,        # [B] f32; 1.0 disables
) -> jax.Array:
    """Per-slot sampling for continuous batching: every slot carries ITS OWN
    request's sampling params and PRNG stream (vLLM's per-request
    SamplingParams shape), vectorized so one [B, V] pass serves mixed
    greedy/sampled batches. Same semantics as sample_logits per slot:
    temperature scaling, then top-k mask, then top-p on the masked
    distribution, then categorical; temperature <= 0 short-circuits to
    argmax for that slot."""
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]

    # top-k: mask everything below each slot's kth value (k=0 / k>=V off).
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    k_idx = jnp.clip(top_k - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=1)
    use_k = (top_k > 0) & (top_k < V)
    scaled = jnp.where(use_k[:, None] & (scaled < kth), -jnp.inf, scaled)

    # top-p on the post-top-k distribution (mirrors sample_logits' order).
    sorted_masked = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_masked, axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.clip(jnp.sum(cumulative < top_p[:, None], axis=-1), 0, V - 1)
    cutoff = jnp.take_along_axis(sorted_masked, cutoff_idx[:, None], axis=1)
    use_p = top_p < 1.0
    scaled = jnp.where(use_p[:, None] & (scaled < cutoff), -jnp.inf, scaled)

    sampled = jax.vmap(jax.random.categorical)(keys, scaled).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


def host_sync(x) -> None:
    """Force completion via a host transfer — `block_until_ready` is not a
    reliable fence on relay-backed remote TPU backends."""
    np.asarray(x)  # vet: ignore[hotpath-host-sync]: host_sync IS the named fence — callers invoke it exactly where a sync is the point


from contextlib import contextmanager


@contextmanager
def _occupancy_gauge(engine: str):
    """serving_active_slots for the request-scoped dense engine: 1 while a
    generate holds the batch, back to 0 on ANY exit — an exception mid-
    request must not leave a phantom active slot on the fleet view."""
    metrics.set("serving_active_slots", 1.0, {"engine": engine})
    try:
        yield
    finally:
        metrics.set("serving_active_slots", 0.0, {"engine": engine})


@dataclass
class GenerationResult:
    # [B, steps]; host np.ndarray from the pipelined generate() (tokens were
    # already consumed to host chunk by chunk — re-uploading them only for
    # the caller to download again would be two wasted transfers on exactly
    # the relay-backed links this engine optimizes), jax.Array elsewhere.
    tokens: "np.ndarray | jax.Array"
    ttft_s: float
    decode_s: float
    decode_steps: int
    decode_tokens_per_s: float
    # generate_speculative only: {"dispatches", "drafted", "accepted"}.
    spec_stats: Optional[dict] = None


class Engine:
    def __init__(
        self,
        cfg: LlamaConfig,
        params: dict,
        batch_size: int = 1,
        max_len: int = 2048,
        sampling: SamplingParams = SamplingParams(),
        seed: int = 0,
        mesh=None,
        pipeline_depth: int = 2,
    ):
        """With `mesh` (axes incl. 'tp'/'dp'), the engine serves TENSOR-
        PARALLEL under GSPMD: params are placed per param_shardings (pass
        them pre-sharded or host-replicated — shard_params_for_serving is
        applied when they aren't already on the mesh), the KV cache is
        sharded over ('dp' batch, 'tp' kv-heads), and prefill/decode jits
        pin those shardings so XLA inserts the tp collectives (the o-proj /
        lm-head all-reduces) and the cache never reshards between steps.
        This is the single-model-too-big-for-one-chip path (BASELINE #3,
        70B-class serving; ref vLLM-TPU TP=16 shape,
        /root/reference/docs/examples/vllm/TPU/lws.yaml:22-34)."""
        self.cfg = cfg
        self.mesh = mesh
        if mesh is not None:
            tp = dict(zip(mesh.axis_names, mesh.devices.shape)).get("tp", 1)
            if cfg.n_kv_heads % max(tp, 1):
                raise ValueError(
                    f"n_kv_heads={cfg.n_kv_heads} not divisible by tp={tp}"
                )
            from jax.sharding import NamedSharding

            # Unconditional: device_put to the target shardings is an
            # identity when params already match, and merely being ON the
            # mesh (e.g. compiler-chosen replication) is not TP-sharded.
            params = shard_params_for_serving(params, cfg, mesh)
            self._cache_shardings = jax.tree.map(
                lambda s: NamedSharding(mesh, s), cache_shardings(cfg)
            )
        else:
            self._cache_shardings = None
        self.params = params
        self.batch_size = batch_size
        self.max_len = max_len
        self._sampling = sampling  # baked into the jitted paths below
        self._key = jax.random.key(seed)
        # Bounded in-flight decode dispatches for generate(): the host
        # consumes chunk N's tokens while chunk N+1 runs on device, instead
        # of queueing every chunk then fencing once at the end (unbounded
        # in-flight) — 0 restores a strictly synchronous per-chunk loop.
        # Caveat: the decode executables donate the cache, and CPU PJRT
        # blocks a dispatch whose donated input is still computing — real
        # overlap therefore needs a TPU backend (the paged engine, which
        # owns the benchmarked hot path, disables donation on CPU instead).
        self.pipeline_depth = pipeline_depth

        cfg_static = cfg
        sampling_static = sampling

        if mesh is not None:
            # Pin the phase outputs: tokens replicated, cache on its mesh
            # shardings — the cache must never reshard between steps.
            from jax.sharding import NamedSharding, PartitionSpec as _P

            _rep = NamedSharding(mesh, _P())
            _sh2 = {"out_shardings": (_rep, self._cache_shardings)}
            _sh3 = {"out_shardings": (_rep, self._cache_shardings, _rep)}
        else:
            _sh2 = {}
            _sh3 = {}

        @partial(jax.jit, **_sh2)
        def _prefill(params, tokens, cache, key):
            # Engine.prefill always starts on an empty cache, so the
            # flash-attention prefill path applies (causal over the prompt
            # only, not masked attention over the whole cache length).
            logits, cache = forward_prefill(params, tokens, cache, cfg_static)
            return sample_logits(logits, key, sampling_static), cache

        @partial(jax.jit, donate_argnums=(2,), **_sh2)
        def _decode(params, tokens, cache, key):
            logits, cache = forward_with_cache(params, tokens[:, None], cache, cfg_static)
            return sample_logits(logits, key, sampling_static), cache

        @partial(jax.jit, donate_argnums=(2,), static_argnums=(3,), **_sh3)
        def _decode_n(params, tokens, cache, n, key):
            # Whole decode loop on-device: one dispatch for n steps (no
            # per-step host round trips — critical on relay-backed links).
            def body(carry, step_key):
                token, cache = carry
                logits, cache = forward_with_cache(params, token[:, None], cache, cfg_static)
                token = sample_logits(logits, step_key, sampling_static)
                return (token, cache), token

            (token, cache), toks = jax.lax.scan(
                body, (tokens, cache), jax.random.split(key, n)
            )
            return token, cache, toks.swapaxes(0, 1)  # [B, n]

        @partial(jax.jit, donate_argnums=(2,), **_sh2)  # (hidden rep, cache pinned)
        def _prefill_chunk(params, tokens, cache):
            # Chunked prefill step: compiled ONCE for the chunk shape and
            # reused across chunks and requests.
            from lws_tpu.models.llama import forward_prefill_chunk

            return forward_prefill_chunk(params, tokens, cache, cfg_static)

        @partial(jax.jit, donate_argnums=(1,), static_argnums=(3,), **_sh2)
        def _finish_chunked(params, cache, hidden, last_off, key):
            import dataclasses as _dc

            h = hidden[:, last_off]
            from lws_tpu.models.quant import matmul as _qmm
            logits = _qmm(h, params["lm_head"]).astype(jnp.float32)
            return sample_logits(logits, key, sampling_static), cache

        self._prefill_chunk = _prefill_chunk
        self._finish_chunked = _finish_chunked
        self._prefill = _prefill
        self._decode = _decode
        self._decode_n = _decode_n
        # Jitted ONCE here: a per-call jit(lambda) would re-trace and
        # re-compile the cache init on every request.
        self._new_cache = jax.jit(
            lambda: init_cache(cfg_static, batch_size, max_len),
            **({"out_shardings": self._cache_shardings} if mesh is not None else {}),
        )

    @property
    def sampling(self) -> SamplingParams:
        """Read-only: sampling is compiled into the jitted decode paths at
        construction; build a new Engine to change it."""
        return self._sampling

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def new_cache(self) -> KVCache:
        return self._new_cache()

    def prefill(self, tokens: jax.Array) -> tuple[jax.Array, KVCache]:
        """tokens [B, S] -> (first generated token [B], cache)."""
        return self._prefill(self.params, tokens, self.new_cache(), self._next_key())

    def prefill_chunked(
        self, tokens: jax.Array, chunk_size: int = 256
    ) -> tuple[jax.Array, KVCache]:
        """Long-context prefill: process the prompt in fixed-size chunks so
        peak attention memory is O(chunk * cache) instead of O(S^2), with one
        compile for the chunk shape. Semantically identical to prefill():
        same first token (greedy), same cache contents up to the prompt
        length. The final (padded) chunk's KV beyond the true prompt length
        is masked out of the first decode step and overwritten by subsequent
        ones, so padding never leaks into attention."""
        import dataclasses as _dc

        B, S = tokens.shape
        if S <= chunk_size:
            return self.prefill(tokens)
        pad = (-S) % chunk_size
        padded = jnp.pad(tokens, ((0, 0), (0, pad)))
        if S + pad > self.max_len:
            raise ValueError(
                f"padded prompt {S + pad} exceeds max_len {self.max_len}; "
                f"use a chunk_size dividing max_len or a shorter prompt"
            )
        cache = self.new_cache()
        hidden = None
        with trace.span(
            "serve.prefill", chunked=True, prompt_len=S,
            chunks=(S + pad) // chunk_size,
        ):
            for i in range(0, S + pad, chunk_size):
                hidden, cache = self._prefill_chunk(
                    self.params, padded[:, i : i + chunk_size], cache
                )
            token, cache = self._finish_chunked(
                self.params, cache, hidden, (S - 1) % chunk_size, self._next_key()
            )
        # Rewind pos past the padding: decode appends at the true length,
        # masking out (then overwriting) the padded tail's K/V.
        return token, _dc.replace(cache, pos=jnp.asarray(S, cache.pos.dtype))

    def prefill_chunked_stream(
        self, tokens: jax.Array, chunk_size: int, emit,
        ring_depth: int = 1,
    ) -> tuple[jax.Array, KVCache, dict]:
        """Chunked prefill whose per-chunk KV leaves the device AS IT LANDS
        (ISSUE 10, the streamed-handoff producer): after dispatching chunk
        N+1's compute, chunk N's position range is sliced from the cache
        (a cheap on-device op, dispatched BEFORE the next chunk donates the
        cache buffers), host-gathered, and handed to
        `emit(lo, hi, arrays)` — so gather/serialize/send of chunk N
        overlaps compute of chunk N+1 instead of waiting for the whole
        prompt. A bounded sender ring (DecodePipeline's discipline) caps
        how far the gather may trail the compute frontier; depth 1 is the
        default because the drain runs synchronously in this thread — the
        gather IS the fence, so trailing by one chunk buys the full
        overlap and any deeper ring only delays the FIRST chunk onto the
        wire (first-chunk latency is exactly what streaming exists to
        cut). `ring_depth=0` degenerates to the serial gather-after-
        compute loop.

        `arrays` per emit: {"k", "v", (+"k_scale"/"v_scale" for kv_quant),
        "tokens"} — each truncated to the TRUE prompt rows (the padded tail
        never ships), "tokens" being the [B, width] prompt slice so the
        decode side can seed its speculative drafting history for free.

        Returns (first token [B], cache, stats) with stats =
        {"chunks", "gather_s"}. Semantically identical to
        prefill_chunked(): same first token, same cache contents."""
        import dataclasses as _dc
        from collections import deque

        B, S = tokens.shape
        if S <= 0:
            raise ValueError("empty prompt")
        pad = (-S) % chunk_size
        if S + pad > self.max_len:
            raise ValueError(
                f"padded prompt {S + pad} exceeds max_len {self.max_len}; "
                f"use a chunk_size dividing max_len or a shorter prompt"
            )
        padded = jnp.pad(tokens, ((0, 0), (0, pad))) if pad else tokens
        tokens_host = np.asarray(tokens)
        cache = self.new_cache()
        hidden = None
        stats = {"chunks": 0, "gather_s": 0.0}
        pending: "deque[tuple[int, int, dict]]" = deque()

        def drain_one() -> None:
            lo, hi, slices = pending.popleft()
            t0 = time.perf_counter()
            host = {name: np.asarray(x) for name, x in slices.items()}  # vet: ignore[hotpath-host-sync]: the per-chunk gather fence — scheduled while the NEXT chunk computes, which is the point
            stats["gather_s"] += time.perf_counter() - t0
            host["tokens"] = tokens_host[:, lo:hi]
            emit(lo, hi, host)
            stats["chunks"] += 1

        for i in range(0, S + pad, chunk_size):
            hidden, cache = self._prefill_chunk(
                self.params, padded[:, i: i + chunk_size], cache
            )
            # Slice THIS chunk's true rows now — the ops dispatch against
            # the current cache value before the next chunk donates it.
            lo, hi = i, min(i + chunk_size, S)
            slices = {
                "k": cache.k[:, :, lo:hi], "v": cache.v[:, :, lo:hi],
            }
            if cache.k_scale is not None:
                slices["k_scale"] = cache.k_scale[:, :, lo:hi]
                slices["v_scale"] = cache.v_scale[:, :, lo:hi]
            pending.append((lo, hi, slices))
            while len(pending) > max(0, ring_depth):
                drain_one()
        while pending:
            drain_one()
        token, cache = self._finish_chunked(
            self.params, cache, hidden, (S - 1) % chunk_size, self._next_key()
        )
        return (
            token,
            _dc.replace(cache, pos=jnp.asarray(S, cache.pos.dtype)),
            stats,
        )

    def decode(self, tokens: jax.Array, cache: KVCache) -> tuple[jax.Array, KVCache]:
        """tokens [B] -> (next token [B], cache)."""
        return self._decode(self.params, tokens, cache, self._next_key())

    def decode_n(self, tokens: jax.Array, cache: KVCache, n: int):
        """n chained sampling steps in ONE device call; returns
        (last token [B], cache, all tokens [B, n])."""
        return self._decode_n(self.params, tokens, cache, n, self._next_key())

    # decode_n compiles once per distinct n; generate() chunks its loop so any
    # max_new_tokens reuses at most this one extra executable (+ the
    # single-step _decode for the remainder).
    DECODE_CHUNK = 32

    def _warm_decode(self, chunked: bool, single: bool) -> None:
        """AOT-compile the decode executables OUTSIDE generate()'s timed
        window so decode_tokens_per_s measures steady state — no device
        allocation or wasted decode steps. Each executable is warmed at most
        once per Engine. Under a mesh the avals must carry the REAL
        shardings: sharding-less structs lower a different executable than
        the runtime call (wasting the warm compile) whose donation can't
        alias — the 'donated buffers were not usable' warning."""
        warmed = getattr(self, "_warmed", set())
        self._warmed = warmed
        token_s = jax.ShapeDtypeStruct((self.batch_size,), jnp.int32)
        cache_s = jax.eval_shape(self.new_cache)
        key_s = jax.eval_shape(lambda: jax.random.key(0))
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as _P

            rep = NamedSharding(self.mesh, _P())

            def with_sharding(struct, sh):
                return jax.ShapeDtypeStruct(struct.shape, struct.dtype, sharding=sh)

            token_s = with_sharding(token_s, rep)
            cache_s = jax.tree.map(with_sharding, cache_s, self._cache_shardings)
            key_s = with_sharding(key_s, rep)
        if chunked and "chunk" not in warmed:
            self._decode_n.lower(
                self.params, token_s, cache_s, self.DECODE_CHUNK, key_s
            ).compile()
            warmed.add("chunk")
        if single and "single" not in warmed:
            self._decode.lower(self.params, token_s, cache_s, key_s).compile()
            warmed.add("single")

    # ---- speculative decoding (n-gram / prompt-lookup drafts) -----------
    @staticmethod
    def _draft_ngram(context: list, ngram: int, gamma: int) -> list:
        """Draft gamma tokens by matching the context's trailing n-gram
        against its own history (prompt-lookup decoding: repetitive spans —
        code, quotes, RAG copies — predict themselves). ANY draft is safe:
        acceptance only keeps tokens that equal the model's own argmax, so
        a bad draft costs nothing but the slack in the verify pass."""
        tail = context[-ngram:]
        cand: list = []
        for i in range(len(context) - ngram - 1, -1, -1):
            if context[i:i + ngram] == tail:
                cand = context[i + ngram: i + ngram + gamma]
                break
        while len(cand) < gamma:
            cand.append(context[-1])
        return cand

    def _get_spec_step_dense(self, gamma: int, ngram: int):
        """Device-resident speculative step (ISSUE 9), B=1: draft from the
        on-device history ring, verify the draft run in one forward pass,
        compute the longest-accepted-prefix, and commit pos/history/budget
        in-kernel. Returns the packed [gamma+2] result (take, then produced
        tokens) — the only thing the host ever transfers. The pre-ISSUE-9
        loop drafted on host and blocked on the verify logits every dispatch
        (the vet baseline's five hotpath-host-sync findings); this kernel is
        what burned that baseline to zero."""
        cache_key = ("spec", gamma, ngram)
        store = getattr(self, "_spec_steps", None)
        if store is None:
            store = self._spec_steps = {}
        if cache_key not in store:
            import dataclasses as _dc

            cfg_static = self.cfg
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as _P

                _rep = NamedSharding(self.mesh, _P())
                sh = {"out_shardings": (
                    self._cache_shardings, _rep, _rep, _rep, _rep, _rep
                )}
            else:
                sh = {}

            @partial(jax.jit, donate_argnums=(1,), **sh)
            def _spec(params, cache, token, hist, hist_len, rem):
                from lws_tpu.models.llama import ngram_draft, speculative_accept

                drafts = ngram_draft(hist, hist_len, ngram=ngram, gamma=gamma)
                tokens_in = jnp.concatenate([token, drafts])[None, :]  # [1, S]
                pos0 = cache.pos
                all_logits, cache = forward_with_cache(
                    params, tokens_in, cache, cfg_static, all_logits=True
                )
                greedy = jnp.argmax(all_logits, axis=-1).astype(jnp.int32)
                take, out = speculative_accept(drafts[None, :], greedy, rem[None])
                take0, row = take[0], out[0]
                # pos IS the rewind: rejected draft rows sit past it, masked
                # out of attention until later appends overwrite them.
                cache = _dc.replace(
                    cache, pos=(pos0 + take0).astype(cache.pos.dtype)
                )
                rem = rem - take0
                token = row[jnp.maximum(take0 - 1, 0)][None]
                H = hist.shape[0]
                i = jnp.arange(gamma + 1)
                idx = (hist_len + i) % H
                hist = hist.at[idx].set(jnp.where(i < take0, row, hist[idx]))
                hist_len = hist_len + take0
                packed = jnp.concatenate([take, row])  # [S+1]
                return cache, token, hist, hist_len, rem, packed

            store[cache_key] = _spec
        return store[cache_key]

    def _seed_spec_history(self, context, token):
        """Device history ring for a fresh speculative run: `context`
        (optional [plen] int array — the prompt, normally) followed by the
        running token. Sized to max_len, so the drafting window always holds
        the full context — device drafts match Engine._draft_ngram exactly."""
        hist = jnp.zeros((self.max_len,), jnp.int32)
        n = 0
        if context is not None:
            context = jnp.asarray(context, jnp.int32).reshape(-1)
            n = context.shape[0]
            hist = jax.lax.dynamic_update_slice(hist, context, (0,))
        hist = hist.at[n].set(token[0].astype(jnp.int32))
        return hist, jnp.asarray(n + 1, jnp.int32)

    def _speculate_loop(  # hot-path
        self, cache, token, needed: int, gamma: int, ngram: int,
        pos_start: int, context, engine_label: str,
    ):
        """Pipelined device-resident speculative drain: produce exactly
        `needed` greedy tokens after `token`. Spec dispatches ride a bounded
        in-flight ring — the host consumes chunk N's packed tokens while
        chunk N+1 verifies — and acceptance/commit happen in-kernel, so the
        steady-state path has NO host drafting, NO logits transfer, and NO
        pos re-upload. The budget lives on device (the kernel clamps `take`
        by it), so overlapped dispatches can never overshoot; near max_len
        the loop flushes and finishes with pipelined single steps, exactly
        like the host loop it replaced. Returns (tokens list, cache, last
        token, stats dict)."""
        S = gamma + 1
        fn = self._get_spec_step_dense(gamma, ngram)
        hist, hist_len = self._seed_spec_history(context, token)
        rem = jnp.asarray(needed, jnp.int32)
        pipe = DecodePipeline(depth=self.pipeline_depth, engine=engine_label)
        out: list[int] = []
        acct = {"dispatches": 0, "drafted": 0, "accepted": 0}

        def commit(host_packed):
            with trace.span(
                "serve.spec_verify", engine=engine_label, gamma=gamma,
            ) as sp:
                t = int(host_packed[0])
                if t > 0:
                    out.extend(int(x) for x in host_packed[1:1 + t])
                    acct["dispatches"] += 1
                    acct["drafted"] += gamma
                    acct["accepted"] += t - 1
                sp.set(accepted=max(t - 1, 0))
            metrics.inc(
                "serving_spec_tokens_total",
                {"engine": engine_label, "kind": "drafted"},
                value=float(gamma if t > 0 else 0),
            )
            metrics.inc(
                "serving_spec_tokens_total",
                {"engine": engine_label, "kind": "accepted"},
                value=float(max(t - 1, 0)),
            )

        guard = 0
        while len(out) < needed:
            guard += 1
            if guard > 4 * needed + 16:
                raise RuntimeError("speculative loop did not converge")
            if pipe and len(out) + pipe.inflight_steps() >= needed:
                # Step-weighted gate (the paged engine's discipline): when
                # the in-flight chunks' POTENTIAL already covers the budget,
                # consume instead of dispatching — which also guarantees
                # every dispatched chunk still has device budget (take >= 1),
                # so acct's consume-side counters see every real dispatch.
                pipe.flush()
                continue
            if pos_start + len(out) + pipe.inflight_steps() + S > self.max_len:
                # Worst-case in-flight commits could push the verify writes
                # past max_len: sync to exact truth, then re-check.
                pipe.flush()
                if pos_start + len(out) + S > self.max_len:
                    break  # genuine tail — single steps below
                continue
            t0 = time.perf_counter()
            with trace.span(
                "serve.decode_dispatch", engine=engine_label, steps=S,
                speculative=True, inflight=len(pipe),
            ):
                with pipe.host_section():
                    cache, token, hist, hist_len, rem, packed = fn(
                        self.params, cache, token, hist, hist_len, rem
                    )
                pipe.push(S, packed, commit)
            metrics.observe(
                "serving_spec_verify_duration_seconds",
                time.perf_counter() - t0,
            )
        pipe.flush()
        # Tail: no room for a full verify run — pipelined single steps.
        # FIXED count, computed while host truth is exact (the ring just
        # flushed): each dispatch produces exactly one token, and counting
        # `len(out)` inside the loop would lag the in-flight pushes —
        # over-dispatching past `needed` (and appending K/V past max_len).
        tail = min(needed - len(out), self.max_len - pos_start - len(out))
        for _ in range(max(0, tail)):
            with trace.span(
                "serve.decode_dispatch", engine=engine_label, steps=1,
            ):
                with pipe.host_section():
                    token, cache = self._decode(
                        self.params, token, cache, self._next_key()
                    )
                pipe.push(1, token, lambda h: out.append(int(h[0])))
            acct["dispatches"] += 1
        pipe.flush()
        return out, cache, token, acct

    def decode_speculative(
        self, token, cache: KVCache, steps: int, gamma: int = 4,
        ngram: int = 3, pos: Optional[int] = None, context=None,
        engine_label: str = "dense",
    ):
        """Speculative counterpart of decode_n: produce exactly `steps`
        greedy tokens continuing `cache` — byte-identical to decode_n
        (acceptance only keeps tokens equal to the model's own argmax
        chain), in fewer dispatches on repetitive content. `pos` is the
        cache's current length as a host int (callers that deserialized the
        cache know it; passing it avoids a device round trip); `context`
        optionally seeds the drafting history (the prompt, when available —
        without it drafting warms up from generated tokens only). Returns
        (last token [1], cache, tokens [1, steps] host array). This is the
        disagg decode leg's speculation primitive (disagg_worker)."""
        if self._sampling.temperature > 0:
            raise NotImplementedError("speculative decoding is greedy-only")
        if self.batch_size != 1:
            raise ValueError("speculative decoding is single-sequence (B=1)")
        if pos is None:
            pos = int(cache.pos)
        out, cache, token, _ = self._speculate_loop(
            cache, token, steps, gamma, ngram, pos, context, engine_label
        )
        return token, cache, np.asarray(out, np.int32)[None, :]  # vet: ignore[hotpath-host-sync]: out is a host list — this is packaging, not a device fence

    def generate_speculative(  # hot-path
        self, prompt: jax.Array, max_new_tokens: int,
        gamma: int = 8, ngram: int = 3, klass: str = "",
    ) -> GenerationResult:
        """Greedy generation with n-gram speculative decoding: each dispatch
        verifies `gamma` drafted tokens plus the running token in ONE
        forward pass — on the HBM-bandwidth-bound decode path the params
        stream once either way, so every accepted draft token is nearly
        free. Accepted = the longest draft prefix matching the verify
        pass's argmax chain; the cache position rewinds past rejected rows
        (stale K/V masked, later overwritten — the prefill_chunked trick).
        B=1, greedy only (sampling would need rejection resampling).

        Device-resident since ISSUE 9: drafting, acceptance, and the cache
        rewind all run inside the jitted spec step, and dispatches ride a
        bounded in-flight ring — the host's only per-chunk work is unpacking
        the accepted tokens (no per-dispatch logits transfer or host
        drafting loop; the vet hotpath baseline this function carried is
        gone).

        Exactness: equal to generate() up to floating-point argmax ties —
        the verify pass computes logits at [1, gamma+1] and single-step
        decode at [1, 1], and XLA may tile/reduce the two shapes in
        different orders, so a near-tied top-2 can flip (the standard
        speculative-decoding caveat; bitwise-equal in this repo's f32
        test suite)."""
        if self.batch_size != 1 or prompt.shape[0] != 1:
            raise ValueError("speculative decoding is single-sequence (B=1)")
        if self._sampling.temperature > 0:
            raise NotImplementedError("speculative decoding is greedy-only")
        if prompt.shape[1] + max_new_tokens > self.max_len:
            # Same contract as the batch engines: the output shape is always
            # [1, max_new_tokens], never silently short.
            raise ValueError("prompt + max_new_tokens exceeds max_len")
        self._warm_spec(gamma, ngram)

        with trace.span(
            "serve.request", engine="dense", speculative=True,
            prompt_len=int(prompt.shape[1]), max_new_tokens=max_new_tokens,
        ) as request_span, _occupancy_gauge("dense"):
            timeline = slo.request("dense", klass=klass)
            t0 = time.perf_counter()
            with trace.span("serve.prefill", chunked=False,
                            prompt_len=int(prompt.shape[1])):
                token, cache = self.prefill(prompt)
                host_sync(token)
            ttft = time.perf_counter() - t0
            timeline.first_token(ttft)

            t1 = time.perf_counter()
            first = int(np.asarray(token)[0])  # vet: ignore[hotpath-host-sync]: first token already fenced for TTFT — this transfer is free
            new, cache, _, acct = self._speculate_loop(
                cache, token, max(0, max_new_tokens - 1), gamma, ngram,
                int(prompt.shape[1]), prompt[0], "dense",
            )
            out = ([first] + new)[: max(1, max_new_tokens)]
            dt = time.perf_counter() - t1
            steps = len(out) - 1
            if steps:
                timeline.tokens(steps, dt)
            timeline.finish()
            request_span.set(
                ttft_s=round(ttft, 6), decode_s=round(dt, 6),
                dispatches=acct["dispatches"], accepted=acct["accepted"],
            )
        metrics.inc("serving_requests_total", {"engine": "dense"})
        return GenerationResult(
            tokens=jnp.asarray([out], jnp.int32),
            ttft_s=ttft,
            decode_s=dt,
            decode_steps=acct["dispatches"],
            decode_tokens_per_s=steps / dt if steps else 0.0,
            spec_stats={
                "dispatches": acct["dispatches"],
                "drafted": acct["drafted"],    # draft slots verified
                "accepted": acct["accepted"],  # model-accepted draft tokens
                # Decode tokens only — the prefill-produced first token is
                # not a dispatch's output.
                "tokens_per_dispatch": round(
                    steps / max(acct["dispatches"], 1), 2
                ),
            },
        )

    def _warm_spec(self, gamma: int, ngram: int) -> None:
        """AOT-compile the speculative step (and the single-step tail)
        outside the timed window — same discipline as _warm_decode, so
        spec-vs-plain comparisons measure steady state on both sides."""
        warmed = getattr(self, "_warmed_spec", set())
        self._warmed_spec = warmed
        if (gamma, ngram) in warmed:
            return
        token_s = jax.ShapeDtypeStruct((1,), jnp.int32)
        hist_s = jax.ShapeDtypeStruct((self.max_len,), jnp.int32)
        scalar_s = jax.ShapeDtypeStruct((), jnp.int32)
        cache_s = jax.eval_shape(self.new_cache)
        if self.mesh is not None:
            # Same discipline as _warm_decode: the avals must carry the REAL
            # shardings (replicated small inputs, cache on its mesh
            # shardings) or this compiles a different executable than the
            # runtime call and the warm is wasted.
            from jax.sharding import NamedSharding, PartitionSpec as _P

            rep = NamedSharding(self.mesh, _P())
            token_s = jax.ShapeDtypeStruct(token_s.shape, token_s.dtype, sharding=rep)
            hist_s = jax.ShapeDtypeStruct(hist_s.shape, hist_s.dtype, sharding=rep)
            scalar_s = jax.ShapeDtypeStruct(scalar_s.shape, scalar_s.dtype, sharding=rep)
            cache_s = jax.tree.map(
                lambda st, sh: jax.ShapeDtypeStruct(st.shape, st.dtype, sharding=sh),
                cache_s, self._cache_shardings,
            )
        self._get_spec_step_dense(gamma, ngram).lower(
            self.params, cache_s, token_s, hist_s, scalar_s, scalar_s
        ).compile()
        self._warm_decode(chunked=False, single=True)
        warmed.add((gamma, ngram))

    def generate(self, prompt: jax.Array, max_new_tokens: int,
                 klass: str = "") -> GenerationResult:  # hot-path
        """Generation under the engine's SamplingParams (greedy by default),
        with timing split (TTFT vs steady decode).

        The decode loop runs ON DEVICE via decode_n in fixed-size chunks (one
        dispatch per DECODE_CHUNK steps — no per-token host round trips, which
        dominate on relay-backed links). Dispatches ride a bounded in-flight
        ring (`pipeline_depth`): chunk N's tokens land on the host while
        chunk N+1 computes, so results STREAM instead of arriving in one
        end-of-run fence — and in-flight device state stays bounded. Callers
        benching on high-latency links should still difference two runs
        (see bench.py)."""
        steps = max(0, max_new_tokens - 1)
        n_full, rem = divmod(steps, self.DECODE_CHUNK)
        self._warm_decode(n_full > 0, rem > 0)

        request_span = trace.span(
            "serve.request", engine="dense", prompt_len=int(prompt.shape[1]),
            max_new_tokens=max_new_tokens,
        )
        with request_span, _occupancy_gauge("dense"):
            timeline = slo.request("dense", klass=klass)
            t0 = time.perf_counter()
            with trace.span("serve.prefill", chunked=False,
                            prompt_len=int(prompt.shape[1])):
                token, cache = self.prefill(prompt)
                host_sync(token)
            ttft = time.perf_counter() - t0
            timeline.first_token(ttft)

            t1 = time.perf_counter()
            pipe = DecodePipeline(depth=self.pipeline_depth, engine="dense")
            host_chunks: list[np.ndarray] = [np.asarray(token)[:, None]]  # vet: ignore[hotpath-host-sync]: first token already fenced for TTFT — this transfer is free
            for _ in range(n_full):
                with trace.span("serve.decode_dispatch", engine="dense",
                                steps=self.DECODE_CHUNK):
                    with pipe.host_section():
                        token, cache, toks = self.decode_n(
                            token, cache, self.DECODE_CHUNK
                        )
                    pipe.push(self.DECODE_CHUNK, toks, host_chunks.append)
            for _ in range(rem):
                with trace.span("serve.decode_dispatch", engine="dense", steps=1):
                    with pipe.host_section():
                        token, cache = self.decode(token, cache)
                    pipe.push(1, token[:, None], host_chunks.append)
            pipe.flush()
            tokens = np.concatenate(host_chunks, axis=1)
            dt = time.perf_counter() - t1
            if steps:
                timeline.tokens(steps, dt)
            timeline.finish()
            request_span.set(ttft_s=round(ttft, 6), decode_s=round(dt, 6))
        metrics.inc("serving_requests_total", {"engine": "dense"})
        metrics.observe(
            "serving_admission_duration_seconds", ttft, {"engine": "dense"}
        )
        tok_per_s = (steps * self.batch_size) / dt if steps else 0.0
        return GenerationResult(
            tokens=tokens,
            ttft_s=ttft,
            decode_s=dt,
            decode_steps=steps,
            decode_tokens_per_s=tok_per_s,
        )
