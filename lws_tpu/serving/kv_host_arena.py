"""Host-RAM spill tier for the paged engine's prefix cache (ISSUE 18).

HBM is the scarce resource the paged pool rations; host RAM is two orders
of magnitude cheaper per byte. When `_alloc_blocks` evicts an LRU-parked
prefix block its contents used to be simply lost — the next prompt sharing
that prefix re-prefilled it from scratch. The arena keeps those bytes: the
engine spills the evicted block's K/V (one `pack_payload`-format blob per
content digest) into a bounded host arena, and a later prefix-map miss
that hits the arena restores the block with a donated device upload
instead of a recompute — a HOST-tier hit (`serving_prefix_cache_hits_total
{tier="host"}`), TTFT-cheap next to the suffix prefill it replaces.

Capacity is `LWS_TPU_KV_HOST_ARENA_MB` (0/unset disables the tier). The
arena is LRU within itself: a `get` refreshes the entry, inserts evict
from the cold end until the new entry fits, and an entry larger than the
whole arena is dropped (counted — a silent drop would read as a cache that
never hits). Entries are ONE contiguous bytes object in `pack_payload`'s
wire format, so `get` returns zero-copy `np.frombuffer` views and a spill
costs exactly one host join (counted in `serving_kv_spill_bytes_total
{direction="spill"}`).

This module also owns the process-level prefix REGISTRY the telemetry
server's `GET /debug/prefixes` reads: engines register a snapshot provider
(weakly — dead engines fall out), workers register their KV fetch port,
and `debug_prefixes()` merges both into the digest advertisement the
control plane's FleetCollector folds into its digest -> instance index
(the remote tier's discovery half)."""

from __future__ import annotations

import os
import threading
from typing import Callable, Optional

from lws_tpu.core import metrics
from lws_tpu.obs import device

ARENA_MB_ENV = "LWS_TPU_KV_HOST_ARENA_MB"


class KVHostArena:
    """Bounded digest-addressed host store of spilled prefix blocks."""

    def __init__(self, capacity_bytes: int) -> None:
        if capacity_bytes <= 0:
            raise ValueError("arena capacity must be > 0 bytes")
        self.capacity = int(capacity_bytes)
        self._lock = threading.Lock()
        # digest -> packed payload bytes; dict order IS the LRU order
        # (oldest first; get() re-inserts at the hot end).
        self._entries: dict[bytes, bytes] = {}  # guarded-by: _lock
        self._bytes = 0                         # guarded-by: _lock
        self.drops = 0                          # guarded-by: _lock
        self._publish_gauges(0, 0)
        # Weak process registry: get_spilled() (the KVServer fetch_prefix
        # provider) serves from whichever live arena holds the digest.
        import weakref

        with _REG_LOCK:
            _ARENAS.append(weakref.ref(self))

    @staticmethod
    def _publish_gauges(nbytes: int, entries: int) -> None:
        metrics.set("serving_kv_host_arena_bytes", float(nbytes))
        metrics.set("serving_kv_host_arena_entries", float(entries))

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def put(self, digest: bytes, arrays: dict) -> bool:
        """Spill one block's arrays under `digest`. Returns False when the
        entry alone exceeds the arena (dropped — the caller's eviction
        proceeds as if the tier were off). The join here is the spill's one
        host copy; the stored blob then serves every later restore
        zero-copy."""
        from lws_tpu.serving.kv_transport import pack_payload

        bufs, _ = pack_payload(arrays)
        payload = b"".join(
            bytes(v) if isinstance(v, memoryview) else v for v in bufs
        )
        size = len(payload)
        with self._lock:
            if size > self.capacity:
                self.drops += 1
                return False
            old = self._entries.pop(digest, None)
            if old is not None:
                self._bytes -= len(old)
            while self._bytes + size > self.capacity and self._entries:
                cold = next(iter(self._entries))
                self._bytes -= len(self._entries.pop(cold))
            self._entries[digest] = payload
            self._bytes += size
            nbytes, entries = self._bytes, len(self._entries)
        metrics.inc("serving_kv_spill_bytes_total", {"direction": "spill"},
                    value=float(size))
        self._publish_gauges(nbytes, entries)
        return True

    def get(self, digest: bytes) -> Optional[dict]:
        """Zero-copy array views of a spilled block (None on miss). The hit
        refreshes the entry's LRU position; the restore-direction byte
        accounting is the ENGINE's job (it knows whether the upload actually
        landed)."""
        from lws_tpu.serving.kv_transport import bytes_to_arrays

        with self._lock:
            payload = self._entries.pop(digest, None)
            if payload is None:
                return None
            self._entries[digest] = payload  # re-insert at the hot end
        return bytes_to_arrays(payload)

    def __contains__(self, digest: bytes) -> bool:
        with self._lock:
            return digest in self._entries

    def digests(self) -> list[bytes]:
        """Cold-to-hot digest list (a snapshot — advertisement, not truth)."""
        with self._lock:
            return list(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "capacity": self.capacity,
                "drops": self.drops,
            }


def from_env() -> Optional[KVHostArena]:
    """Arena sized by LWS_TPU_KV_HOST_ARENA_MB; None when unset/0 (the
    spill tier is opt-in — host copies are not free on every deployment)."""
    raw = os.environ.get(ARENA_MB_ENV, "").strip()
    if not raw:
        return None
    mb = float(raw)
    if mb <= 0:
        return None
    return KVHostArena(int(mb * 1e6))


# ---------------------------------------------------------------------------
# Process prefix registry: what GET /debug/prefixes advertises.

_REG_LOCK = threading.Lock()
# name -> snapshot provider; providers return {"block_size", "digests":
# [bytes...], "arena_digests": [bytes...]} or None when their engine died
# (weakref-backed providers prune themselves that way).
_PREFIX_SOURCES: dict[str, Callable[[], Optional[dict]]] = {}  # guarded-by: _REG_LOCK
_FETCH_PORT: Optional[int] = None  # guarded-by: _REG_LOCK
_ARENAS: list = []  # weakrefs to every live KVHostArena; guarded-by: _REG_LOCK


def get_spilled(digest: bytes) -> Optional[dict]:
    """`fetch_prefix` provider: zero-copy views of the first live arena's
    entry for `digest`, None when no arena holds it. Spilled blocks are
    already host-resident wire-format bytes, so serving a sibling costs no
    device traffic and no engine coordination — this is THE provider
    workers wire into `KVServer.serve_prefixes`."""
    with _REG_LOCK:
        live = [r() for r in _ARENAS]
        _ARENAS[:] = [r for r, a in zip(list(_ARENAS), live) if a is not None]
    for arena in live:
        if arena is None:
            continue
        got = arena.get(digest)
        if got is not None:
            return got
    return None


def arena_pool_bytes() -> float:
    """Total bytes across every live arena — the `arena_restore` pool feed
    for serving_hbm_pool_bytes (host-resident, so the device-memory refresh
    reports it without subtracting it from HBM in-use)."""
    with _REG_LOCK:
        live = [r() for r in _ARENAS]
        _ARENAS[:] = [r for r, a in zip(list(_ARENAS), live) if a is not None]
    return float(sum(a.nbytes for a in live if a is not None))


# Registered once at import: the pool reads 0 until an arena exists, which
# is itself the honest answer.
device.register_pool_provider("arena_restore", arena_pool_bytes)


def register_prefix_source(name: str,
                           provider: Callable[[], Optional[dict]]) -> None:
    with _REG_LOCK:
        _PREFIX_SOURCES[name] = provider


def unregister_prefix_source(name: str) -> None:
    with _REG_LOCK:
        _PREFIX_SOURCES.pop(name, None)


def register_fetch_port(port: Optional[int]) -> None:
    """Advertise the KV wire port siblings should `fetch_prefix` against
    (the worker's KVServer port). None clears it."""
    global _FETCH_PORT
    with _REG_LOCK:
        _FETCH_PORT = int(port) if port is not None else None


def debug_prefixes(limit: int = 256) -> dict:
    """The /debug/prefixes body: every live source's resident (HBM) and
    arena digests as hex, capped at `limit` each, plus the advertised KV
    fetch port. Dead sources (provider returned None) are pruned."""
    with _REG_LOCK:
        sources = list(_PREFIX_SOURCES.items())
        port = _FETCH_PORT
    digests: list[str] = []
    arena: list[str] = []
    dead: list[str] = []
    for name, provider in sources:
        snap = provider()
        if snap is None:
            dead.append(name)
            continue
        digests.extend(d.hex() for d in snap.get("digests", []))
        arena.extend(d.hex() for d in snap.get("arena_digests", []))
    for name in dead:
        unregister_prefix_source(name)
    if limit:
        digests, arena = digests[:limit], arena[:limit]
    return {
        "digests": digests,
        "arena_digests": arena,
        "count": len(digests) + len(arena),
        "kv_port": port,
    }
