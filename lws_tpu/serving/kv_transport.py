"""KV-cache handoff transport: length-prefixed frames over TCP.

The disaggregated data plane (llm-d shape, BASELINE #5): the prefill role
serves its finished KV bundles on a TCP port; the decode role DISCOVERS that
endpoint from the DS's revision-aware `-prv` service record in the API
server (ref service_manager.go:126-163 — the service selector names the
pods; the pod's address + declared KV port form the endpoint, exactly how a
k8s Service routes to containerPort) and pulls bundles over the socket.
No shared filesystem anywhere (VERDICT r3 #5).

Frame = !II (header_len, payload_len) + JSON header + raw payload bytes.
One request per connection: dial, send one op frame, read one reply frame,
close — the bundles are MB-scale, so connection setup is noise, and
stateless requests keep replica failover trivial (any endpoint of the
service can answer).
"""

from __future__ import annotations

import hmac
import io
import json
import queue
import socket
import struct
import threading
from typing import Optional

from lws_tpu.core import faults, resilience

_FRAME = struct.Struct("!II")


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def send_msg(sock: socket.socket, meta: dict, payload: bytes = b"") -> None:
    header = json.dumps(meta).encode()
    sock.sendall(_FRAME.pack(len(header), len(payload)) + header + payload)


def _send_partial(sock: socket.socket, meta: dict, payload: bytes,
                  nbytes: int) -> None:
    """Cooperative `partial_write` fault: ship only the first `nbytes` of
    the frame, leaving the peer with a truncated read — the mid-frame
    death the re-queue/re-insert paths must survive."""
    header = json.dumps(meta).encode()
    frame = _FRAME.pack(len(header), len(payload)) + header + payload
    sock.sendall(frame[: max(0, nbytes)])


def recv_msg(sock: socket.socket) -> tuple[Optional[dict], bytes]:
    raw = _recv_exact(sock, _FRAME.size)
    if raw is None:
        return None, b""
    hlen, plen = _FRAME.unpack(raw)
    header = _recv_exact(sock, hlen)
    if header is None:
        return None, b""
    payload = _recv_exact(sock, plen) if plen else b""
    return json.loads(header), payload or b""


def arrays_to_bytes(**arrays) -> bytes:
    """npz-serialize a dict of arrays (the KV bundle wire format)."""
    import numpy as np

    bio = io.BytesIO()
    np.savez(bio, **{k: np.asarray(v) for k, v in arrays.items()})
    return bio.getvalue()


def bytes_to_arrays(data: bytes) -> dict:
    import numpy as np

    return dict(np.load(io.BytesIO(data)))


def cache_to_bundle(cache, token) -> bytes:
    """KVCache + first token -> wire bundle. The ONE place the bundle schema
    lives (both roles go through here).

    Bundle bytes are ∝ PROMPT LENGTH, not the prefill engine's allocation:
    the sequence dim is truncated to `pos` (only rows [0, pos) hold prompt
    KV; everything past is zeros the decode mask never attends). A 1k-token
    prompt in a 2k-slot allocation ships half the bytes; production prompts
    in 70B-scale caches ship orders less than the reservation (VERDICT r3
    next #3). For a tp-sharded cache np.asarray performs an explicit host
    gather — the recorded len() of the result is the true wire cost; the
    decode side re-shards onto ITS mesh (see disagg_worker)."""
    import numpy as np

    p = int(np.asarray(cache.pos))
    arrays = {
        "k": np.asarray(cache.k)[:, :, :p],
        "v": np.asarray(cache.v)[:, :, :p],
        "pos": cache.pos,
        "token": token,
    }
    if cache.k_scale is not None:  # kv_quant caches carry scales
        arrays.update(
            k_scale=np.asarray(cache.k_scale)[:, :, :p],
            v_scale=np.asarray(cache.v_scale)[:, :, :p],
        )
    return arrays_to_bytes(**arrays)


def bundle_to_cache(data: bytes, max_len: Optional[int] = None):
    """Wire bundle -> (KVCache, first token [B]).

    `max_len` is the DECODE side's sequence budget: the pos-truncated prefix
    from the wire is pasted into a zeroed [*, max_len, *] allocation with
    room to append (decode's budget is its own, not prefill's). Omitted,
    the cache is exactly the wire length — full for decode purposes."""
    import numpy as np

    import jax.numpy as jnp

    from lws_tpu.models.llama import KVCache

    bundle = bytes_to_arrays(data)

    def fit(a):
        if max_len is None or a.shape[2] == max_len:
            return a
        if a.shape[2] > max_len:
            raise ValueError(
                f"bundle holds {a.shape[2]} KV rows but decode max_len={max_len}"
            )
        pad = [(0, 0)] * a.ndim
        pad[2] = (0, max_len - a.shape[2])
        return np.pad(a, pad)

    cache = KVCache(
        k=jnp.asarray(fit(bundle["k"])), v=jnp.asarray(fit(bundle["v"])),
        pos=jnp.asarray(bundle["pos"]),
        k_scale=jnp.asarray(fit(bundle["k_scale"])) if "k_scale" in bundle else None,
        v_scale=jnp.asarray(fit(bundle["v_scale"])) if "v_scale" in bundle else None,
    )
    return cache, jnp.asarray(bundle["token"])


class KVServer:
    """Per-worker handoff server. The owning worker enqueues/dequeues
    locally; remote peers drive the queues through one-shot TCP ops:

      submit_prompt  (router/client -> prefill)   meta {id}, payload bytes
      pull_prompt    (unused remotely; prefill drains its own queue)
      pull_bundle    (decode -> prefill)          reply meta {id}|{none};
                     the puller ACKS on the same connection — unacked
                     bundles are re-queued (at-least-once; decode is
                     idempotent per id, so replays are harmless)
      pull_result    (router/client -> decode)    meta {id}; the entry is
                     evicted on delivery (no unbounded growth)

    Trust model: the server binds the pod network (0.0.0.0) exactly like a
    containerPort behind a k8s Service — network reachability IS the k8s
    intra-cluster trust boundary. For anything stronger set LWS_TPU_KV_TOKEN
    in both roles' env (or pass `token=`): every op must then carry the
    matching "token" in its frame meta or is rejected unauthorized. The
    client helpers read the same env var.
    """

    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 token: Optional[str] = None) -> None:
        import os

        self._token = token if token is not None else os.environ.get("LWS_TPU_KV_TOKEN")
        self._prompts: "queue.Queue[tuple[dict, bytes]]" = queue.Queue()
        self._bundles: "queue.Queue[tuple[dict, bytes]]" = queue.Queue()
        self._results: dict[str, tuple[dict, bytes]] = {}  # guarded-by: _results_lock
        self._results_lock = threading.Lock()
        # Delivery counters are bumped from per-connection threads — every
        # touch IN THIS CLASS goes through _counts_lock (`+=` is a
        # read-modify-write; two racing acks used to be able to drop a
        # count). External pollers read through delivery_counts().
        self._counts_lock = threading.Lock()
        self.bundles_delivered = 0  # guarded-by: _counts_lock — acked pulls (drives prefill --once)
        self.results_served = 0     # guarded-by: _counts_lock — delivered results (drives decode --once)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((host, port))
            self._sock.listen(16)
        except OSError:
            # Error-path hygiene (vet: resource-ctor-leak): a failed bind —
            # port in use, bad host — must not leak the socket until GC.
            self._sock.close()
            raise
        self.port = self._sock.getsockname()[1]
        self._closed = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    # ---- worker-side (in-process) ----------------------------------------
    def next_prompt(self, timeout: float = 0.2) -> Optional[tuple[dict, bytes]]:
        try:
            item = self._prompts.get(timeout=timeout)
        except queue.Empty:
            return None
        meta, payload = item
        # Queue wait (enqueue stamp -> worker pickup) for the SLO recorder:
        # the one place in this repo a request actually queues.
        enq = meta.pop("_enq_t", None)
        if enq is not None:
            import time as _time

            meta["queue_wait_s"] = max(0.0, _time.time() - enq)
            # The deadline budget pays for queue time too: deduct the
            # measured wait so a 2s-budget prompt that queued 30s dequeues
            # EXPIRED, not with a fresh 2s (the wire carries remaining
            # seconds; the clock only ticks while someone holds it).
            if "deadline_s" in meta:
                meta["deadline_s"] = max(
                    0.0, float(meta["deadline_s"]) - meta["queue_wait_s"]
                )
        return meta, payload

    def offer_bundle(self, meta: dict, payload: bytes) -> None:
        if "deadline_s" in meta:
            # Anchor the bundle's remaining budget at ENQUEUE: time spent
            # waiting for a decode pull is charged against the deadline
            # when the bundle ships (see the pull_bundle leg).
            import time as _time

            meta["_offered_t"] = _time.monotonic()
        self._bundles.put((meta, payload))
        self._backlog_beat()

    def _backlog_beat(self) -> None:
        # KV-handoff backlog feed for the watchdog: progress = bundles the
        # decode side has pulled AND acked, depth = bundles still waiting.
        from lws_tpu.core import flightrecorder

        with self._counts_lock:
            delivered = self.bundles_delivered
        flightrecorder.beat(
            f"kv_backlog:{self.port}",
            progress=delivered,
            depth=self._bundles.qsize(),
        )

    def delivery_counts(self) -> tuple[int, int]:
        """(bundles_delivered, results_served) read under the counter lock
        — the accessor the worker --once exit loops poll."""
        with self._counts_lock:
            return self.bundles_delivered, self.results_served

    def post_result(self, req_id: str, meta: dict, payload: bytes) -> None:
        with self._results_lock:
            self._results[req_id] = (meta, payload)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    # ---- network side -----------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,), daemon=True).start()

    def _serve_one(self, conn: socket.socket) -> None:
        # Connection-level failures (peer died mid-frame, injected partial
        # writes, resets) must not kill the handler thread with a stack
        # trace: the protocol is one-shot, the peer's retry covers it, and
        # the bundle/result re-queue paths below already ran.
        try:
            with conn:
                self._handle_one(conn)
        except OSError:
            from lws_tpu.core import metrics

            metrics.inc("serving_kv_connection_errors_total")

    def _handle_one(self, conn: socket.socket) -> None:
        faults.fire("kv.server.recv")
        meta, payload = recv_msg(conn)
        if meta is None:
            return
        if self._token and not hmac.compare_digest(
            str(meta.get("token", "")).encode(), self._token.encode()
        ):
            send_msg(conn, {"error": "unauthorized"})
            return
        op = meta.get("op")
        if op == "submit_prompt":
            import time as _time

            meta["_enq_t"] = _time.time()  # queue-wait stamp (same host)
            self._prompts.put((meta, payload))
            send_msg(conn, {"ok": True})
        elif op == "pull_bundle":
            try:
                bmeta, bpayload = self._bundles.get(timeout=meta.get("timeout", 1.0))
            except queue.Empty:
                send_msg(conn, {"none": True})
                return
            import time as _time

            offered = bmeta.pop("_offered_t", None)
            pop_t = _time.monotonic()
            if offered is not None and "deadline_s" in bmeta:
                # Charge the bundle-queue wait against the deadline (the
                # internal anchor never crosses the wire).
                bmeta["deadline_s"] = max(
                    0.0, float(bmeta["deadline_s"]) - (pop_t - offered)
                )
            # At-least-once END TO END: the bundle is only discarded once
            # the puller acks on this connection, and the puller acks only
            # after it has PROCESSED the bundle (result posted) — a decode
            # crash mid-processing drops the connection, the bundle
            # re-queues, and another pull redelivers (decode is idempotent
            # per id, so replays are harmless). The ack window covers
            # decode + first-call compile.
            try:
                fault = faults.fire("kv.server.send_bundle")
                if fault is not None and fault.mode == "partial_write":
                    _send_partial(conn, bmeta, bpayload, int(fault.arg))
                    raise OSError("injected partial bundle write")
                send_msg(conn, bmeta, bpayload)
                conn.settimeout(float(meta.get("ack_timeout", 120.0)))
                ack, _ = recv_msg(conn)
                if not (ack or {}).get("ack"):
                    raise OSError("no ack")
                with self._counts_lock:
                    self.bundles_delivered += 1
                self._backlog_beat()  # progress advanced: backlog drains
            except OSError:
                if "deadline_s" in bmeta:
                    # The failed delivery window (pop -> here) burned real
                    # budget too; deduct it and re-anchor for redelivery.
                    now = _time.monotonic()
                    bmeta["deadline_s"] = max(
                        0.0, float(bmeta["deadline_s"]) - (now - pop_t)
                    )
                    bmeta["_offered_t"] = now
                self._bundles.put((bmeta, bpayload))
                self._backlog_beat()
        elif op == "pull_result":
            # Pop under the lock BEFORE sending: two concurrent pulls for
            # the same id must not both deliver (results_served drives
            # --once exit); re-insert on send failure so a retry works.
            with self._results_lock:
                entry = self._results.pop(meta.get("id", ""), None)
            if entry is None:
                send_msg(conn, {"none": True})
                return
            try:
                fault = faults.fire("kv.server.send_result")
                if fault is not None and fault.mode == "partial_write":
                    _send_partial(conn, entry[0], entry[1], int(fault.arg))
                    raise OSError("injected partial result write")
                send_msg(conn, entry[0], entry[1])
            except OSError:
                with self._results_lock:
                    self._results.setdefault(meta.get("id", ""), entry)
                return
            with self._counts_lock:
                self.results_served += 1
        else:
            send_msg(conn, {"error": f"unknown op {op!r}"})


def _auth(meta: dict) -> dict:
    import os

    token = os.environ.get("LWS_TPU_KV_TOKEN")
    if token:
        meta = dict(meta, token=token)
    return meta


def _one_shot(endpoint: tuple[str, int], meta: dict, payload: bytes = b"",
              timeout: float = 10.0) -> tuple[Optional[dict], bytes]:
    # Every blocking point checks the bound deadline BEFORE waiting and
    # clamps its socket timeout to the remaining budget: a dead peer costs
    # what the request had left, never the full transport timeout.
    resilience.check("kv.connect")
    faults.fire("kv.client.connect")
    with socket.create_connection(
        endpoint, timeout=resilience.clamp_timeout(timeout)
    ) as sock:
        send_msg(sock, _auth(meta), payload)
        faults.fire("kv.client.recv")
        return recv_msg(sock)


def _deadline_meta(meta: dict) -> dict:
    """Attach the caller's bound deadline to the frame meta — remaining
    seconds, re-anchored by the peer — exactly like the trace ctx rides."""
    deadline = resilience.current()
    if deadline is not None:
        meta["deadline_s"] = deadline.to_wire()
    return meta


def submit_prompt(endpoint, req_id: str, prompt_bytes: bytes,
                  trace_ctx: Optional[dict] = None) -> None:
    """`trace_ctx` (default: the caller's current span context) rides the
    frame meta so the prefill worker's span subtree grafts onto the
    caller's trace — the cross-process leg of the trace spine. The bound
    `resilience.Deadline` (if any) rides the same way: the prefill worker
    drops expired prompts instead of burning prefill on them."""
    if trace_ctx is None:
        from lws_tpu.core import trace

        trace_ctx = trace.current_context()
    meta = _deadline_meta({"op": "submit_prompt", "id": req_id})
    if trace_ctx:
        meta["trace"] = trace_ctx
    meta, _ = _one_shot(endpoint, meta, prompt_bytes)
    if not (meta or {}).get("ok"):
        raise RuntimeError(f"submit_prompt failed: {meta}")


def pull_bundle(endpoint, timeout: float = 1.0, process=None,
                ack_timeout: float = 120.0):
    """Returns (meta, payload) — or `process(meta, payload)`'s result when a
    callback is given — or None when the peer has nothing pending.

    Without `process`, receipt is acked immediately (wire-level
    at-least-once only: a crash after the ack loses the request — the
    router's retry covers that). WITH `process`, the ack is sent only after
    the callback returns: the server re-queues the bundle if the puller
    dies mid-processing, making delivery at-least-once END TO END (decode
    must be idempotent per id — replays happen). `ack_timeout` is forwarded
    to the server as its ack-wait window — size it for the callback's worst
    case (decode + first-call jit compile), or the server re-queues and
    redelivers while the puller is still working."""
    resilience.check("kv.pull_bundle")
    faults.fire("kv.client.connect")
    with socket.create_connection(
        endpoint, timeout=resilience.clamp_timeout(timeout + 9.0)
    ) as sock:
        send_msg(sock, _auth({
            "op": "pull_bundle", "timeout": timeout, "ack_timeout": ack_timeout,
        }))
        faults.fire("kv.client.recv")
        meta, payload = recv_msg(sock)
        if meta is None:
            raise OSError("truncated pull_bundle reply")
        if meta.get("error"):
            raise RuntimeError(f"pull_bundle rejected: {meta}")
        if meta.get("none"):
            return None
        if process is None:
            _send_ack(sock)
            return meta, payload
        result = process(meta, payload)  # raise => no ack => server re-queues
        _send_ack(sock)
        return result


def _send_ack(sock: socket.socket) -> None:
    fault = faults.fire("kv.ack")
    if fault is not None and fault.mode == "drop":
        # Injected ack loss: the connection closes unacked, the server
        # re-queues, and the next pull REPLAYS the bundle — the decode
        # worker's seen-id dedup guard must absorb it.
        return
    send_msg(sock, {"ack": True})


def pull_result(endpoint, req_id: str, timeout: float = 10.0):
    """None = not ready yet. Raises on protocol-level rejection (e.g. auth)
    instead of handing the error reply back as if it were a result. A
    delivered result whose meta carries "failed" is the DECODE's verdict on
    a poison request — returned to the caller, who must check it.
    `timeout` bounds the socket (further clamped to any bound deadline)."""
    meta, payload = _one_shot(
        endpoint, {"op": "pull_result", "id": req_id}, timeout=timeout
    )
    if meta is None or meta.get("none"):
        return None
    if meta.get("error"):
        raise RuntimeError(f"pull_result rejected: {meta}")
    return meta, payload


# ---------------------------------------------------------------------------
# Endpoint discovery from the DS `-prv` service record (API-server backed).


def discover_role_endpoint(
    client, namespace: str, ds_name: str, role: str,
    port_env: str = "LWS_TPU_KV_PORT",
    revision: Optional[str] = None,
    slice_idx: Optional[str] = None,
) -> Optional[tuple[str, int]]:
    """Resolve role's KV endpoint THROUGH the revision-aware service record:
    find the `-prv` Service labeled (ds, role), match its selector against
    Pods (k8s Endpoints semantics: selector + readiness), and read the
    pod's published address + its declared KV port (containerPort analog:
    the `port_env` env var in the pod spec). `client` is a RemoteClient —
    the worker talks to the API server exactly like any external consumer.

    Pass `revision`/`slice_idx` (a worker passes ITS OWN labels) to pin the
    pairing: during a rolling update old still-ready revisions keep their
    -prv services alongside the target's, and multi-slice DSes publish one
    service per slice — an unpinned pick could pair a new-revision decode
    with an old-revision prefill (different weights: silent garbage) or
    cross slices (the pairing is slice-scoped by design)."""
    from lws_tpu.api import disagg

    def svc_label(s, key):
        return s.get("metadata", {}).get("labels", {}).get(key)

    services = [
        s for s in client.list("Service")
        if s.get("metadata", {}).get("namespace") == namespace
        and svc_label(s, disagg.DS_NAME_LABEL_KEY) == ds_name
        and svc_label(s, disagg.DS_ROLE_LABEL_KEY) == role
        and s.get("metadata", {}).get("name", "").endswith("-prv")
        and (revision is None or svc_label(s, disagg.DS_REVISION_LABEL_KEY) == revision)
        and (slice_idx is None or svc_label(s, disagg.DS_SLICE_LABEL_KEY) == str(slice_idx))
    ]
    for svc in services:
        selector = svc.get("spec", {}).get("selector", {})
        for pod in client.list("Pod"):
            meta = pod.get("metadata", {})
            if meta.get("namespace") != namespace:
                continue
            labels = meta.get("labels", {})
            if any(labels.get(k) != v for k, v in selector.items()):
                continue
            status = pod.get("status", {})
            if not status.get("ready"):
                continue
            host = status.get("address") or "127.0.0.1"
            for container in pod.get("spec", {}).get("containers", []):
                for env in container.get("env", []):
                    if env.get("name") == port_env and env.get("value"):
                        return host, int(env["value"])
    return None
