"""KV-cache handoff transport: length-prefixed frames over TCP.

The disaggregated data plane (llm-d shape, BASELINE #5): the prefill role
serves its finished KV bundles on a TCP port; the decode role DISCOVERS that
endpoint from the DS's revision-aware `-prv` service record in the API
server (ref service_manager.go:126-163 — the service selector names the
pods; the pod's address + declared KV port form the endpoint, exactly how a
k8s Service routes to containerPort) and pulls bundles over the socket.
No shared filesystem anywhere (VERDICT r3 #5).

Frame = !II (header_len, payload_len) + JSON header + raw payload bytes.
One request per connection: dial, send one op frame, read one reply frame,
close — the bundles are MB-scale, so connection setup is noise, and
stateless requests keep replica failover trivial (any endpoint of the
service can answer).
"""

from __future__ import annotations

import io
import json
import queue
import socket
import struct
import threading
from typing import Optional

_FRAME = struct.Struct("!II")


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def send_msg(sock: socket.socket, meta: dict, payload: bytes = b"") -> None:
    header = json.dumps(meta).encode()
    sock.sendall(_FRAME.pack(len(header), len(payload)) + header + payload)


def recv_msg(sock: socket.socket) -> tuple[Optional[dict], bytes]:
    raw = _recv_exact(sock, _FRAME.size)
    if raw is None:
        return None, b""
    hlen, plen = _FRAME.unpack(raw)
    header = _recv_exact(sock, hlen)
    if header is None:
        return None, b""
    payload = _recv_exact(sock, plen) if plen else b""
    return json.loads(header), payload or b""


def arrays_to_bytes(**arrays) -> bytes:
    """npz-serialize a dict of arrays (the KV bundle wire format)."""
    import numpy as np

    bio = io.BytesIO()
    np.savez(bio, **{k: np.asarray(v) for k, v in arrays.items()})
    return bio.getvalue()


def bytes_to_arrays(data: bytes) -> dict:
    import numpy as np

    return dict(np.load(io.BytesIO(data)))


def cache_to_bundle(cache, token) -> bytes:
    """KVCache + first token -> wire bundle. The ONE place the bundle schema
    lives (both transports and both roles go through here)."""
    arrays = {"k": cache.k, "v": cache.v, "pos": cache.pos, "token": token}
    if cache.k_scale is not None:  # kv_quant caches carry scales
        arrays.update(k_scale=cache.k_scale, v_scale=cache.v_scale)
    return arrays_to_bytes(**arrays)


def bundle_to_cache(data: bytes):
    """Wire bundle -> (KVCache, first token [B])."""
    import jax.numpy as jnp

    from lws_tpu.models.llama import KVCache

    bundle = bytes_to_arrays(data)
    cache = KVCache(
        k=jnp.asarray(bundle["k"]), v=jnp.asarray(bundle["v"]),
        pos=jnp.asarray(bundle["pos"]),
        k_scale=jnp.asarray(bundle["k_scale"]) if "k_scale" in bundle else None,
        v_scale=jnp.asarray(bundle["v_scale"]) if "v_scale" in bundle else None,
    )
    return cache, jnp.asarray(bundle["token"])


class KVServer:
    """Per-worker handoff server. The owning worker enqueues/dequeues
    locally; remote peers drive the queues through one-shot TCP ops:

      submit_prompt  (router/client -> prefill)   meta {id}, payload bytes
      pull_prompt    (unused remotely; prefill drains its own queue)
      pull_bundle    (decode -> prefill)          reply meta {id}|{none};
                     the puller ACKS on the same connection — unacked
                     bundles are re-queued (at-least-once; decode is
                     idempotent per id, so replays are harmless)
      pull_result    (router/client -> decode)    meta {id}; the entry is
                     evicted on delivery (no unbounded growth)
    """

    def __init__(self, port: int = 0, host: str = "0.0.0.0") -> None:
        self._prompts: "queue.Queue[tuple[dict, bytes]]" = queue.Queue()
        self._bundles: "queue.Queue[tuple[dict, bytes]]" = queue.Queue()
        self._results: dict[str, tuple[dict, bytes]] = {}
        self._results_lock = threading.Lock()
        self.bundles_delivered = 0  # acked pulls (drives prefill --once)
        self.results_served = 0     # delivered results (drives decode --once)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._closed = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    # ---- worker-side (in-process) ----------------------------------------
    def next_prompt(self, timeout: float = 0.2) -> Optional[tuple[dict, bytes]]:
        try:
            return self._prompts.get(timeout=timeout)
        except queue.Empty:
            return None

    def offer_bundle(self, meta: dict, payload: bytes) -> None:
        self._bundles.put((meta, payload))

    def post_result(self, req_id: str, meta: dict, payload: bytes) -> None:
        with self._results_lock:
            self._results[req_id] = (meta, payload)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    # ---- network side -----------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,), daemon=True).start()

    def _serve_one(self, conn: socket.socket) -> None:
        with conn:
            meta, payload = recv_msg(conn)
            if meta is None:
                return
            op = meta.get("op")
            if op == "submit_prompt":
                self._prompts.put((meta, payload))
                send_msg(conn, {"ok": True})
            elif op == "pull_bundle":
                try:
                    bmeta, bpayload = self._bundles.get(timeout=meta.get("timeout", 1.0))
                except queue.Empty:
                    send_msg(conn, {"none": True})
                    return
                # At-least-once: the bundle is only discarded once the puller
                # acks on this connection; any failure re-queues it (a lost
                # MB-scale KV bundle would hang its request forever).
                try:
                    send_msg(conn, bmeta, bpayload)
                    conn.settimeout(10.0)
                    ack, _ = recv_msg(conn)
                    if not (ack or {}).get("ack"):
                        raise OSError("no ack")
                    self.bundles_delivered += 1
                except OSError:
                    self._bundles.put((bmeta, bpayload))
            elif op == "pull_result":
                with self._results_lock:
                    entry = self._results.get(meta.get("id", ""))
                if entry is None:
                    send_msg(conn, {"none": True})
                    return
                try:
                    send_msg(conn, entry[0], entry[1])
                except OSError:
                    return  # keep the entry for a retry
                with self._results_lock:
                    self._results.pop(meta.get("id", ""), None)
                self.results_served += 1
            else:
                send_msg(conn, {"error": f"unknown op {op!r}"})


def _one_shot(endpoint: tuple[str, int], meta: dict, payload: bytes = b"",
              timeout: float = 10.0) -> tuple[Optional[dict], bytes]:
    with socket.create_connection(endpoint, timeout=timeout) as sock:
        send_msg(sock, meta, payload)
        return recv_msg(sock)


def submit_prompt(endpoint, req_id: str, prompt_bytes: bytes) -> None:
    meta, _ = _one_shot(endpoint, {"op": "submit_prompt", "id": req_id}, prompt_bytes)
    if not (meta or {}).get("ok"):
        raise RuntimeError(f"submit_prompt failed: {meta}")


def pull_bundle(endpoint, timeout: float = 1.0):
    """Returns (meta, payload), or None when the peer has nothing pending.
    Acks receipt so the server can discard; a truncated reply raises (the
    server re-queues unacked bundles, the caller rediscovers/retries)."""
    with socket.create_connection(endpoint, timeout=timeout + 9.0) as sock:
        send_msg(sock, {"op": "pull_bundle", "timeout": timeout})
        meta, payload = recv_msg(sock)
        if meta is None:
            raise OSError("truncated pull_bundle reply")
        if meta.get("none"):
            return None
        send_msg(sock, {"ack": True})
        return meta, payload


def pull_result(endpoint, req_id: str):
    meta, payload = _one_shot(endpoint, {"op": "pull_result", "id": req_id})
    if meta is None or meta.get("none"):
        return None
    return meta, payload


# ---------------------------------------------------------------------------
# Endpoint discovery from the DS `-prv` service record (API-server backed).


def discover_role_endpoint(
    client, namespace: str, ds_name: str, role: str,
    port_env: str = "LWS_TPU_KV_PORT",
    revision: Optional[str] = None,
    slice_idx: Optional[str] = None,
) -> Optional[tuple[str, int]]:
    """Resolve role's KV endpoint THROUGH the revision-aware service record:
    find the `-prv` Service labeled (ds, role), match its selector against
    Pods (k8s Endpoints semantics: selector + readiness), and read the
    pod's published address + its declared KV port (containerPort analog:
    the `port_env` env var in the pod spec). `client` is a RemoteClient —
    the worker talks to the API server exactly like any external consumer.

    Pass `revision`/`slice_idx` (a worker passes ITS OWN labels) to pin the
    pairing: during a rolling update old still-ready revisions keep their
    -prv services alongside the target's, and multi-slice DSes publish one
    service per slice — an unpinned pick could pair a new-revision decode
    with an old-revision prefill (different weights: silent garbage) or
    cross slices (the pairing is slice-scoped by design)."""
    from lws_tpu.api import disagg

    def svc_label(s, key):
        return s.get("metadata", {}).get("labels", {}).get(key)

    services = [
        s for s in client.list("Service")
        if s.get("metadata", {}).get("namespace") == namespace
        and svc_label(s, disagg.DS_NAME_LABEL_KEY) == ds_name
        and svc_label(s, disagg.DS_ROLE_LABEL_KEY) == role
        and s.get("metadata", {}).get("name", "").endswith("-prv")
        and (revision is None or svc_label(s, disagg.DS_REVISION_LABEL_KEY) == revision)
        and (slice_idx is None or svc_label(s, disagg.DS_SLICE_LABEL_KEY) == str(slice_idx))
    ]
    for svc in services:
        selector = svc.get("spec", {}).get("selector", {})
        for pod in client.list("Pod"):
            meta = pod.get("metadata", {})
            if meta.get("namespace") != namespace:
                continue
            labels = meta.get("labels", {})
            if any(labels.get(k) != v for k, v in selector.items()):
                continue
            status = pod.get("status", {})
            if not status.get("ready"):
                continue
            host = status.get("address") or "127.0.0.1"
            for container in pod.get("spec", {}).get("containers", []):
                for env in container.get("env", []):
                    if env.get("name") == port_env and env.get("value"):
                        return host, int(env["value"])
    return None
