"""KV-cache handoff transport: length-prefixed frames over TCP.

The disaggregated data plane (llm-d shape, BASELINE #5): the prefill role
serves its finished KV bundles on a TCP port; the decode role DISCOVERS that
endpoint from the DS's revision-aware `-prv` service record in the API
server (ref service_manager.go:126-163 — the service selector names the
pods; the pod's address + declared KV port form the endpoint, exactly how a
k8s Service routes to containerPort) and pulls bundles over the socket.
No shared filesystem anywhere (VERDICT r3 #5).

Frame = !II (header_len, payload_len) + JSON header + raw payload bytes.
One request per connection: dial, send one op frame, read one reply frame,
close — the bundles are MB-scale, so connection setup is noise, and
stateless requests keep replica failover trivial (any endpoint of the
service can answer).

Payload format (ISSUE 10 — the npz path is gone): a payload is a
self-describing pack of raw array buffers,

    !I(spec_len) + spec_json + buf0 + buf1 + ...
    spec_json = {"arrays": [{"name", "dtype", "shape"}, ...]}

sent with scatter-gather (`socket.sendmsg` over memoryviews straight off
the source arrays — zero host copies on the send path; `np.savez` copied
every payload twice through a BytesIO) and decoded with `np.frombuffer`
views (zero copies on the receive path). Every KV-transport socket runs
`TCP_NODELAY` with an SO_SNDBUF/SO_RCVBUF floor so small ack frames never
ride Nagle under MB-scale payloads.

Streamed handoff (`kv_stream`): a bundle may be offered as a `KVStream`
instead of one monolithic payload. The server then answers `pull_bundle`
with a multi-frame reply on the same connection —

    BEGIN {.., "stream": true}
    CHUNK {"chunk": seq, "pos_range": [lo, hi)} + packed arrays   (per-chunk ack)
    END   {"end": true, "chunks": n, "checksum": crc32, ...} + packed tail

— chunks leaving the prefill worker WHILE later prefill chunks still
compute. Per-chunk acks ride the same deadline/retry/fault machinery as
everything else; any torn leg (partial write, dropped ack, checksum or
order mismatch) re-queues the WHOLE stream for redelivery from chunk 0, so
a mid-stream death can never deliver a torn cache. `LWS_TPU_KV_CHUNK=0`
keeps the monolithic single-shot path (the oracle).
"""

from __future__ import annotations

import hmac
import json
import queue
import socket
import struct
import threading
import zlib
from typing import Optional, Sequence, Union

from lws_tpu.core import faults, metrics, resilience

_FRAME = struct.Struct("!II")
_SPEC = struct.Struct("!I")

# Socket buffer floor: small ack frames must never sit behind Nagle, and
# MB-scale bundle frames should not drain through default-sized kernel
# buffers (the floor is a request — the kernel may clamp to its rmem/wmem
# ceilings, which is fine).
_SOCK_BUF_FLOOR = 1 << 20

Payload = Union[bytes, bytearray, memoryview, Sequence]


def tune_socket(sock: socket.socket) -> None:
    """TCP_NODELAY + SO_SNDBUF/SO_RCVBUF floor on every KV-transport socket
    (client dials AND the server's listen/accept path)."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass  # non-TCP test doubles
    for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
        try:
            if sock.getsockopt(socket.SOL_SOCKET, opt) < _SOCK_BUF_FLOOR:
                sock.setsockopt(socket.SOL_SOCKET, opt, _SOCK_BUF_FLOOR)
        except OSError:
            pass


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _as_views(payload: Payload) -> list:
    """Normalize a payload (bytes | buffer | sequence of buffers) to a flat
    list of byte views WITHOUT copying any of them."""
    if isinstance(payload, (bytes, bytearray, memoryview)):
        return [memoryview(payload).cast("B")] if len(payload) else []
    return [v if isinstance(v, memoryview) else memoryview(v) for v in payload]


def _sendall_vectored(sock: socket.socket, views: list) -> None:
    """sendall over a scatter-gather buffer list: the frame header and every
    array buffer go to the kernel straight from where they live — no
    intermediate join copy. Falls back to per-buffer sendall where sendmsg
    is unavailable."""
    bufs = [v for v in views if v.nbytes]
    if not hasattr(sock, "sendmsg"):
        for v in bufs:
            sock.sendall(v)
        return
    while bufs:
        sent = sock.sendmsg(bufs)
        while bufs and sent >= bufs[0].nbytes:
            sent -= bufs[0].nbytes
            bufs.pop(0)
        if sent and bufs:
            bufs[0] = bufs[0][sent:]


def send_msg(sock: socket.socket, meta: dict, payload: Payload = b"") -> None:
    views = _as_views(payload)
    header = json.dumps(meta).encode()
    plen = sum(v.nbytes for v in views)
    frame = _FRAME.pack(len(header), plen) + header
    _sendall_vectored(sock, [memoryview(frame)] + views)


def _send_partial(sock: socket.socket, meta: dict, payload: Payload,
                  nbytes: int) -> None:
    """Cooperative `partial_write` fault: ship only the first `nbytes` of
    the frame, leaving the peer with a truncated read — the mid-frame
    death the re-queue/re-insert paths must survive. (Test-only path: the
    join copy here is deliberate and irrelevant.)"""
    header = json.dumps(meta).encode()
    body = b"".join(bytes(v) for v in _as_views(payload))
    frame = _FRAME.pack(len(header), len(body)) + header + body
    sock.sendall(frame[: max(0, nbytes)])


def recv_msg(sock: socket.socket) -> tuple[Optional[dict], bytes]:
    raw = _recv_exact(sock, _FRAME.size)
    if raw is None:
        return None, b""
    return _recv_msg_body(sock, raw)


def _recv_msg_body(sock: socket.socket, raw: bytes) -> tuple[Optional[dict], bytes]:
    """Finish reading a frame whose !II prefix (`raw`) already arrived —
    split out so pull_bundle can open its transfer clock AT the first
    frame byte (the long-poll wait for the server's queue pop must not
    pollute `serving_kv_transfer_seconds`)."""
    hlen, plen = _FRAME.unpack(raw)
    header = _recv_exact(sock, hlen)
    if header is None:
        return None, b""
    payload = _recv_exact(sock, plen) if plen else b""
    return json.loads(header), payload or b""


# ---------------------------------------------------------------------------
# Raw-buffer array packing (the one wire serialization — npz is deleted).


def _resolve_dtype(name: str):
    """np.dtype by name, including the ml_dtypes extension types a bf16
    serving cache ships (registered by the jax import in any worker)."""
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def pack_payload(arrays: dict) -> tuple[list, int]:
    """dict of arrays -> ([spec_header_bytes, raw buffer views...], payload
    nbytes). ZERO-COPY: each C-contiguous array contributes its own buffer
    view (the views keep their arrays alive); `np.asarray` on a jax/sharded
    array is the host gather the caller intends."""
    import numpy as np

    spec = []
    views: list = []
    nbytes = 0
    for name, value in arrays.items():
        arr = np.asarray(value)
        if not arr.flags["C_CONTIGUOUS"]:
            # The one copy a non-contiguous source costs (sliced host
            # views); device gathers and packed chunks arrive contiguous.
            arr = np.ascontiguousarray(arr)
        spec.append({"name": name, "dtype": arr.dtype.name,
                     "shape": list(arr.shape)})
        if arr.nbytes:
            # uint8 reinterpret, not memoryview.cast: ml_dtypes extension
            # types (bfloat16) have no buffer-protocol format code.
            view = memoryview(arr.reshape(-1).view(np.uint8))
            views.append(view)
            nbytes += view.nbytes
    head = json.dumps({"arrays": spec}).encode()
    return [_SPEC.pack(len(head)) + head] + views, nbytes


def arrays_to_bytes(**arrays) -> bytes:
    """Pack arrays into ONE contiguous payload. This is the convenience
    path for small payloads (prompts, token results, tests) — the join is
    the single host copy it costs, accounted in
    `serving_kv_copy_bytes_total` so perf budgets can pin the hot KV path
    to zero copies (it streams via `pack_payload` views instead)."""
    bufs, nbytes = pack_payload(arrays)
    if nbytes:
        metrics.inc("serving_kv_copy_bytes_total",
                    {"site": "arrays_to_bytes"}, value=float(nbytes))
    return b"".join(bytes(v) if isinstance(v, memoryview) else v
                    for v in bufs)


def bytes_to_arrays(data) -> dict:
    """Payload bytes -> dict of arrays, ZERO-COPY: every array is an
    `np.frombuffer` view into `data` (read-only when `data` is bytes)."""
    import numpy as np

    view = memoryview(data)
    (hlen,) = _SPEC.unpack(view[: _SPEC.size])
    spec = json.loads(bytes(view[_SPEC.size: _SPEC.size + hlen]))
    off = _SPEC.size + hlen
    out = {}
    for entry in spec["arrays"]:
        dt = _resolve_dtype(entry["dtype"])
        shape = tuple(entry["shape"])
        count = 1
        for dim in shape:
            count *= int(dim)
        nbytes = count * dt.itemsize
        arr = np.frombuffer(view[off: off + nbytes], dtype=dt, count=count)
        out[entry["name"]] = arr.reshape(shape)
        off += nbytes
    return out


def cache_arrays(cache, token) -> dict:
    """KVCache + first token -> the wire array dict (pos-truncated). The
    ONE place the bundle schema lives (both roles and both transfer shapes
    go through here).

    Bundle bytes are ∝ PROMPT LENGTH, not the prefill engine's allocation:
    the sequence dim is truncated to `pos` (only rows [0, pos) hold prompt
    KV; everything past is zeros the decode mask never attends). A 1k-token
    prompt in a 2k-slot allocation ships half the bytes; production prompts
    in 70B-scale caches ship orders less than the reservation (VERDICT r3
    next #3). For a tp-sharded cache np.asarray performs an explicit host
    gather — the recorded byte count of the result is the true wire cost;
    the decode side re-shards onto ITS mesh (see disagg_worker)."""
    import numpy as np

    p = int(np.asarray(cache.pos))
    arrays = {
        "k": np.asarray(cache.k)[:, :, :p],
        "v": np.asarray(cache.v)[:, :, :p],
        "pos": cache.pos,
        "token": token,
    }
    if cache.k_scale is not None:  # kv_quant caches carry scales
        arrays.update(
            k_scale=np.asarray(cache.k_scale)[:, :, :p],
            v_scale=np.asarray(cache.v_scale)[:, :, :p],
        )
    return arrays


def cache_to_bundle(cache, token) -> bytes:
    """KVCache + first token -> one monolithic wire bundle (the single-shot
    path; the streamed path ships `cache_arrays` position ranges through a
    `KVStream` without this join copy)."""
    return arrays_to_bytes(**cache_arrays(cache, token))


def bundle_to_cache(data, max_len: Optional[int] = None):
    """Wire bundle (payload bytes, or an already-unpacked array dict from a
    stream's `HostAssembler`) -> (KVCache, first token [B]).

    `max_len` is the DECODE side's sequence budget: the pos-truncated prefix
    from the wire is pasted into a zeroed [*, max_len, *] allocation with
    room to append (decode's budget is its own, not prefill's). Omitted,
    the cache is exactly the wire length — full for decode purposes."""
    import numpy as np

    import jax.numpy as jnp

    from lws_tpu.models.llama import KVCache

    bundle = data if isinstance(data, dict) else bytes_to_arrays(data)

    def fit(a):
        if max_len is None or a.shape[2] == max_len:
            return a
        if a.shape[2] > max_len:
            raise ValueError(
                f"bundle holds {a.shape[2]} KV rows but decode max_len={max_len}"
            )
        pad = [(0, 0)] * a.ndim
        pad[2] = (0, max_len - a.shape[2])
        return np.pad(a, pad)

    from lws_tpu.obs import device as devicemod

    cache = KVCache(
        k=jnp.asarray(fit(bundle["k"])), v=jnp.asarray(fit(bundle["v"])),
        pos=jnp.asarray(bundle["pos"]),
        k_scale=jnp.asarray(fit(bundle["k_scale"])) if "k_scale" in bundle else None,
        v_scale=jnp.asarray(fit(bundle["v_scale"])) if "v_scale" in bundle else None,
    )
    devicemod.record_transfer(
        "kv.bundle_to_cache",
        sum(int(a.nbytes) for a in bundle.values()
            if hasattr(a, "nbytes")))
    return cache, jnp.asarray(bundle["token"])


# ---------------------------------------------------------------------------
# Streamed handoff: server-side stream record + client-side assemblers.

# Axis each per-position array chunks along ("tokens" is the [B, width]
# prompt slice the stream ships so decode can seed its speculative drafting
# history — 4 bytes/token, noise next to the KV rows).
_CHUNK_AXES = {"k": 2, "v": 2, "k_scale": 2, "v_scale": 2, "tokens": 1}

# serving_kv_stream_inflight_chunks: chunks produced by prefill compute but
# not yet acked by a decode puller, summed over this process's live streams.
_INFLIGHT_LOCK = threading.Lock()
_INFLIGHT_CHUNKS = 0  # guarded-by: _INFLIGHT_LOCK


def _inflight_delta(delta: int) -> None:
    global _INFLIGHT_CHUNKS
    with _INFLIGHT_LOCK:
        _INFLIGHT_CHUNKS = max(0, _INFLIGHT_CHUNKS + delta)
        value = _INFLIGHT_CHUNKS
    metrics.set("serving_kv_stream_inflight_chunks", float(value))


class _StreamFailed(Exception):
    """Producer-side failure: the stream is dead, do NOT requeue (the
    router's resubmit is the recovery path, exactly like prefill death)."""


class PoisonPayload:
    """A streamed delivery whose RECEIVER rejected the content (e.g. more
    KV rows than the decode budget) while the WIRE completed cleanly. The
    stream is drained and acked per protocol — re-queueing cannot heal a
    content mismatch, it would crash-loop every successor — and the error
    surfaces where the monolithic path's would: inside `process()`, whose
    poison-message guard consumes the request with a failed result."""

    __slots__ = ("error",)

    def __init__(self, error: BaseException) -> None:
        self.error = error


class KVStream:
    """Server-side record of ONE streamed KV handoff.

    The prefill loop `put_chunk`s position ranges as their KV lands (each
    chunk packed zero-copy at produce time) and `finish`es with the tail
    payload (first token, pos) plus the END metadata; connection threads
    `read` it — possibly MULTIPLE times, because chunks stay buffered until
    the final ack so a torn delivery replays from chunk 0 (the same
    at-least-once contract the monolithic bundle queue gives). Memory cost
    equals the monolithic path's queued bundle. The running crc32 computed
    at produce time is the END frame's torn-cache check."""

    def __init__(self, chunk_tokens: int = 0) -> None:
        import time as _time

        self._cond = threading.Condition()
        self.chunk_tokens = int(chunk_tokens)
        self._chunks: list[tuple[dict, list, int]] = []  # guarded-by: _cond
        self._end: Optional[tuple[dict, list]] = None    # guarded-by: _cond
        self._failed = False                             # guarded-by: _cond
        self.checksum = 0                                # guarded-by: _cond
        self.payload_bytes = 0                           # guarded-by: _cond
        self._acked_hw = 0                               # guarded-by: _cond
        # Produce-side chunk timeline (stream-relative seconds): when each
        # position range left prefill compute — the journey vault's
        # prefill-leg wire story (chunks_produced annotation).
        self._t0 = _time.monotonic()
        self.chunk_timeline: list[dict] = []             # guarded-by: _cond

    @property
    def failed(self) -> bool:
        with self._cond:
            return self._failed

    @property
    def chunks(self) -> int:
        with self._cond:
            return len(self._chunks)

    def put_chunk(self, lo: int, hi: int, arrays: dict) -> None:
        """Buffer one position range [lo, hi) for delivery. Called by the
        prefill loop while LATER chunks still compute — a blocked puller
        never blocks the producer."""
        import time as _time

        bufs, _ = pack_payload(arrays)
        wire_len = _payload_len(bufs)  # incl. the spec header, like len(payload)
        # Gauge BEFORE the chunk becomes visible: a connection thread can
        # deliver and ack the chunk the moment notify lands, and its -1
        # racing ahead of this +1 would be eaten by the gauge's zero clamp
        # (drifting the counter permanently high).
        _inflight_delta(+1)
        try:
            with self._cond:
                if self._end is not None or self._failed:
                    raise RuntimeError("put_chunk on a finished KVStream")
                for view in bufs:
                    self.checksum = zlib.crc32(view, self.checksum)
                produce_t = round(_time.monotonic() - self._t0, 6)
                # t_produce_s rides the chunk meta over the wire: the
                # receive-side timeline can then show produce-vs-arrival
                # per chunk (the overlap the streamed handoff exists for).
                meta = {"chunk": len(self._chunks),
                        "pos_range": [int(lo), int(hi)],
                        "t_produce_s": produce_t}
                self.chunk_timeline.append({
                    "chunk": meta["chunk"],
                    "t_s": produce_t,
                    "bytes": wire_len,
                })
                self._chunks.append((meta, bufs, wire_len))
                self.payload_bytes += wire_len
                self._cond.notify_all()
        except BaseException:
            _inflight_delta(-1)  # the chunk never became visible
            raise

    def finish(self, end_meta: dict, end_arrays: Optional[dict] = None) -> None:
        bufs, _ = pack_payload(end_arrays or {})
        with self._cond:
            self._end = (dict(end_meta), bufs)
            self._cond.notify_all()

    def fail(self) -> None:
        """Producer died/raised: wake pullers with a terminal verdict."""
        with self._cond:
            self._failed = True
            pending = len(self._chunks) - self._acked_hw
            # Advance the high-water mark so an ack already in flight on a
            # connection thread becomes a no-op in chunk_acked() — without
            # this, fail() and the late ack would BOTH decrement the
            # process-wide gauge for the same chunk, eating another live
            # stream's contribution.
            self._acked_hw = len(self._chunks)
            self._cond.notify_all()
        if pending > 0:
            _inflight_delta(-pending)

    def chunk_acked(self, idx: int) -> None:
        """First-time ack bookkeeping for the in-flight gauge (redeliveries
        re-send already-acked chunks without double-decrementing)."""
        delta = 0
        with self._cond:
            if idx + 1 > self._acked_hw:
                delta = idx + 1 - self._acked_hw
                self._acked_hw = idx + 1
        if delta:
            _inflight_delta(-delta)

    def read(self, idx: int, timeout: float):
        """Next item for a delivery at position `idx`: ("chunk", meta,
        bufs), ("end", meta, bufs), ("failed", None, None), or ("timeout",
        None, None) when the producer stalls past `timeout`."""
        import time as _time

        deadline_t = _time.monotonic() + timeout
        with self._cond:
            while True:
                if self._failed:
                    return "failed", None, None
                if idx < len(self._chunks):
                    meta, bufs, _ = self._chunks[idx]
                    return "chunk", meta, bufs
                if self._end is not None:
                    end_meta, bufs = self._end
                    meta = {
                        **end_meta, "end": True,
                        "chunks": len(self._chunks),
                        "checksum": self.checksum,
                        "payload_bytes": self.payload_bytes,
                    }
                    return "end", meta, bufs
                remaining = deadline_t - _time.monotonic()
                if remaining <= 0:
                    return "timeout", None, None
                self._cond.wait(remaining)


def _payload_len(bufs: list) -> int:
    # Spec header included: this is the wire payload length a receiver's
    # per-chunk `len(payload)` sees, so both ends account identical bytes.
    return sum(memoryview(v).nbytes for v in bufs)


class HostAssembler:
    """Default stream receiver: reassemble the chunked per-position arrays
    into the monolithic bundle dict `bytes_to_arrays` would have returned
    (plus the streamed-only "tokens" prompt array)."""

    def __init__(self, begin_meta: Optional[dict] = None) -> None:
        self._parts: dict[str, list] = {}
        self.chunks = 0

    def chunk(self, cmeta: dict, arrays: dict) -> None:
        for name, arr in arrays.items():
            self._parts.setdefault(name, []).append(arr)
        self.chunks += 1

    def finish(self, end_meta: dict, end_arrays: dict):
        import numpy as np

        out = {
            name: np.concatenate(parts, axis=_CHUNK_AXES.get(name, 0))
            for name, parts in self._parts.items()
        }
        out.update(end_arrays)
        return out


# One jitted donating insert shared by every CacheAssembler: compiled per
# (chunk shape, dtype) — two shapes per stream (the fixed chunk width and
# the ragged tail), reused across requests.
_DEVICE_INSERT = None
_DEVICE_INSERT_LOCK = threading.Lock()


def _device_insert(buf, chunk, lo: int):
    global _DEVICE_INSERT
    import jax
    import jax.numpy as jnp

    from lws_tpu.obs import device as devicemod

    with _DEVICE_INSERT_LOCK:
        if _DEVICE_INSERT is None:
            _DEVICE_INSERT = jax.jit(
                lambda b, c, i: jax.lax.dynamic_update_slice_in_dim(
                    b, c, i, axis=2
                ),
                donate_argnums=(0,),
            )
        fn = _DEVICE_INSERT
    devicemod.record_transfer("kv.assembler_insert",
                              int(getattr(chunk, "nbytes", 0) or 0))
    with devicemod.compile_site("kv.assembler_insert", engine="disagg",
                                shape=f"c{chunk.shape[2]}"):
        return fn(buf, jnp.asarray(chunk), jnp.asarray(lo, jnp.int32))


class CacheAssembler:
    """Decode-side incremental `bundle_to_cache`: every streamed chunk is
    uploaded into its position slice of a zeroed [*, max_len, *] device
    buffer ON ARRIVAL (a donated `dynamic_update_slice` dispatch — async,
    so the upload overlaps the next chunk's wire transfer), and the
    finished cache is ready the moment END lands — the first decode step
    dispatches immediately, no deserialize/upload tail.

    `device=False` (mesh-sharded decode) assembles on HOST instead: a
    per-position-slice sharded insert would reshard every chunk, so the
    mesh path keeps the single `device_put` onto the engine's cache
    shardings at the end, still overlapping host assembly with the wire."""

    def __init__(self, max_len: int, device: bool = True) -> None:
        self.max_len = int(max_len)
        self.device = device
        self._bufs: dict = {}
        self._token_parts: list = []
        self.chunks = 0
        self.payload_bytes = 0
        self.array_bytes: dict[str, int] = {}
        self._token = None
        self._pos: Optional[int] = None

    def chunk(self, cmeta: dict, arrays: dict) -> None:
        lo, hi = (int(x) for x in cmeta["pos_range"])
        for name in ("k", "v", "k_scale", "v_scale"):
            arr = arrays.get(name)
            if arr is None:
                continue
            if lo + arr.shape[2] > self.max_len:
                raise ValueError(
                    f"stream chunk ends at {lo + arr.shape[2]} KV rows but "
                    f"decode max_len={self.max_len}"
                )
            self._insert(name, arr, lo)
            self.array_bytes[name] = self.array_bytes.get(name, 0) + arr.nbytes
        if "tokens" in arrays:
            self._token_parts.append(arrays["tokens"])
        self.chunks += 1

    def _insert(self, name: str, arr, lo: int) -> None:
        import numpy as np

        buf = self._bufs.get(name)
        if buf is None:
            shape = list(arr.shape)
            shape[2] = self.max_len
            if self.device:
                import jax.numpy as jnp

                buf = jnp.zeros(tuple(shape), arr.dtype)
            else:
                buf = np.zeros(tuple(shape), arr.dtype)
        if self.device:
            buf = _device_insert(buf, arr, lo)
        else:
            buf[:, :, lo: lo + arr.shape[2]] = arr
        self._bufs[name] = buf

    def finish(self, end_meta: dict, end_arrays: dict):
        if "token" not in end_arrays or "pos" not in end_arrays:
            raise OSError("kv stream END frame missing token/pos tail")
        self._token = end_arrays["token"]
        self._pos = int(end_arrays["pos"])
        if self._pos > self.max_len:
            raise ValueError(
                f"stream holds {self._pos} KV rows but decode max_len={self.max_len}"
            )
        return self

    def take(self):
        """-> (KVCache, first token [B], pos, context tokens [B, pos]|None).
        Device path: the cache IS the assembled device buffers (decode can
        dispatch on it immediately); host path: np arrays the caller
        device_puts onto its own shardings (the monolithic reshard leg)."""
        import numpy as np

        import jax.numpy as jnp

        from lws_tpu.models.llama import KVCache

        if self._pos is None:
            raise RuntimeError("take() before the stream END landed")
        cache = KVCache(
            k=self._bufs["k"], v=self._bufs["v"],
            pos=jnp.asarray(self._pos, jnp.int32),
            k_scale=self._bufs.get("k_scale"),
            v_scale=self._bufs.get("v_scale"),
        )
        context = (
            np.concatenate(self._token_parts, axis=1)
            if self._token_parts else None
        )
        return cache, jnp.asarray(self._token), self._pos, context


class KVServer:
    """Per-worker handoff server. The owning worker enqueues/dequeues
    locally; remote peers drive the queues through one-shot TCP ops:

      submit_prompt  (router/client -> prefill)   meta {id}, payload bytes
      pull_prompt    (unused remotely; prefill drains its own queue)
      pull_bundle    (decode -> prefill)          reply meta {id}|{none};
                     monolithic payload or a BEGIN/CHUNK/END stream; the
                     puller ACKS on the same connection (per-chunk acks for
                     streams, plus the final process ack) — unacked
                     bundles/streams are re-queued (at-least-once; decode
                     is idempotent per id, so replays are harmless)
      pull_result    (router/client -> decode)    meta {id}; the entry is
                     evicted on delivery (no unbounded growth)

    Trust model: the server binds the pod network (0.0.0.0) exactly like a
    containerPort behind a k8s Service — network reachability IS the k8s
    intra-cluster trust boundary. For anything stronger set LWS_TPU_KV_TOKEN
    in both roles' env (or pass `token=`): every op must then carry the
    matching "token" in its frame meta or is rejected unauthorized. The
    client helpers read the same env var.
    """

    def __init__(self, port: int = 0, host: str = "0.0.0.0",
                 token: Optional[str] = None) -> None:
        import os

        self._token = token if token is not None else os.environ.get("LWS_TPU_KV_TOKEN")
        # Sibling prefix serving (ISSUE 18): provider(digest_bytes) ->
        # arrays|None; set via serve_prefixes(). None = op answers {none}.
        self._prefix_provider = None
        self._prompts: "queue.Queue[tuple[dict, bytes]]" = queue.Queue()
        self._bundles: "queue.Queue[tuple[dict, object]]" = queue.Queue()
        self._results: dict[str, tuple[dict, bytes]] = {}  # guarded-by: _results_lock
        self._results_lock = threading.Lock()
        # Delivery counters are bumped from per-connection threads — every
        # touch IN THIS CLASS goes through _counts_lock (`+=` is a
        # read-modify-write; two racing acks used to be able to drop a
        # count). External pollers read through delivery_counts().
        self._counts_lock = threading.Lock()
        self.bundles_delivered = 0  # guarded-by: _counts_lock — acked pulls (drives prefill --once)
        self.results_served = 0     # guarded-by: _counts_lock — delivered results (drives decode --once)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            tune_socket(self._sock)  # buf floors inherit into accepted conns
            self._sock.bind((host, port))
            self._sock.listen(16)
        except OSError:
            # Error-path hygiene (vet: resource-ctor-leak): a failed bind —
            # port in use, bad host — must not leak the socket until GC.
            self._sock.close()
            raise
        self.port = self._sock.getsockname()[1]
        self._closed = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    # ---- worker-side (in-process) ----------------------------------------
    def next_prompt(self, timeout: float = 0.2) -> Optional[tuple[dict, bytes]]:
        try:
            item = self._prompts.get(timeout=timeout)
        except queue.Empty:
            return None
        meta, payload = item
        # Queue wait (enqueue stamp -> worker pickup) for the SLO recorder:
        # the one place in this repo a request actually queues.
        enq = meta.pop("_enq_t", None)
        if enq is not None:
            import time as _time

            meta["queue_wait_s"] = max(0.0, _time.time() - enq)
            # The deadline budget pays for queue time too: deduct the
            # measured wait so a 2s-budget prompt that queued 30s dequeues
            # EXPIRED, not with a fresh 2s (the wire carries remaining
            # seconds; the clock only ticks while someone holds it).
            if "deadline_s" in meta:
                meta["deadline_s"] = max(
                    0.0, float(meta["deadline_s"]) - meta["queue_wait_s"]
                )
        return meta, payload

    def offer_bundle(self, meta: dict, payload: bytes) -> None:
        if "deadline_s" in meta:
            # Anchor the bundle's remaining budget at ENQUEUE: time spent
            # waiting for a decode pull is charged against the deadline
            # when the bundle ships (see the pull_bundle leg).
            import time as _time

            meta["_offered_t"] = _time.monotonic()
        self._bundles.put((meta, payload))
        self._backlog_beat()

    def offer_stream(self, meta: dict, stream: KVStream) -> None:
        """Offer a STREAMED handoff: called BEFORE prefill computes, so a
        puller attaches while chunks are still being produced — the wire
        leg overlaps prefill compute instead of waiting for it."""
        self.offer_bundle(meta, stream)

    def _backlog_beat(self) -> None:
        # KV-handoff backlog feed for the watchdog: progress = bundles the
        # decode side has pulled AND acked, depth = bundles still waiting.
        from lws_tpu.core import flightrecorder

        with self._counts_lock:
            delivered = self.bundles_delivered
        flightrecorder.beat(
            f"kv_backlog:{self.port}",
            progress=delivered,
            depth=self._bundles.qsize(),
        )

    def delivery_counts(self) -> tuple[int, int]:
        """(bundles_delivered, results_served) read under the counter lock
        — the accessor the worker --once exit loops poll."""
        with self._counts_lock:
            return self.bundles_delivered, self.results_served

    def post_result(self, req_id: str, meta: dict, payload: bytes) -> None:
        with self._results_lock:
            self._results[req_id] = (meta, payload)

    def serve_prefixes(self, provider) -> None:
        """Enable the `fetch_prefix` op: `provider(digest_bytes)` returns
        one cached prefix block's array dict, or None when this instance no
        longer holds that digest. Typical provider: the host arena's `get`
        (spilled blocks are already host-resident wire-format bytes —
        serving them costs no device traffic); serving HBM-resident blocks
        requires a device gather against a possibly-busy engine, so wire it
        only from the engine's own thread discipline."""
        self._prefix_provider = provider

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    # ---- network side -----------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,), daemon=True).start()

    def _serve_one(self, conn: socket.socket) -> None:
        # Connection-level failures (peer died mid-frame, injected partial
        # writes, resets) must not kill the handler thread with a stack
        # trace: the protocol is one-shot, the peer's retry covers it, and
        # the bundle/result re-queue paths below already ran.
        try:
            with conn:
                tune_socket(conn)
                self._handle_one(conn)
        except OSError:
            metrics.inc("serving_kv_connection_errors_total")

    def _handle_one(self, conn: socket.socket) -> None:
        faults.fire("kv.server.recv")
        meta, payload = recv_msg(conn)
        if meta is None:
            return
        if self._token and not hmac.compare_digest(
            str(meta.get("token", "")).encode(), self._token.encode()
        ):
            send_msg(conn, {"error": "unauthorized"})
            return
        op = meta.get("op")
        if op == "submit_prompt":
            import time as _time

            meta["_enq_t"] = _time.time()  # queue-wait stamp (same host)
            self._prompts.put((meta, payload))
            send_msg(conn, {"ok": True})
        elif op == "pull_bundle":
            try:
                bmeta, bpayload = self._bundles.get(timeout=meta.get("timeout", 1.0))
            except queue.Empty:
                send_msg(conn, {"none": True})
                return
            import time as _time

            offered = bmeta.pop("_offered_t", None)
            pop_t = _time.monotonic()
            if offered is not None and "deadline_s" in bmeta:
                # Charge the bundle-queue wait against the deadline (the
                # internal anchor never crosses the wire).
                bmeta["deadline_s"] = max(
                    0.0, float(bmeta["deadline_s"]) - (pop_t - offered)
                )
            # At-least-once END TO END: the bundle is only discarded once
            # the puller acks on this connection, and the puller acks only
            # after it has PROCESSED the bundle (result posted) — a decode
            # crash mid-processing drops the connection, the bundle
            # re-queues, and another pull redelivers (decode is idempotent
            # per id, so replays are harmless). The ack window covers
            # decode + first-call compile. Streams re-queue WHOLE: every
            # redelivery replays from chunk 0, never a torn suffix.
            ack_timeout = float(meta.get("ack_timeout", 120.0))
            try:
                if isinstance(bpayload, KVStream):
                    self._send_stream(conn, bmeta, bpayload, ack_timeout)
                else:
                    t0 = _time.perf_counter()
                    fault = faults.fire("kv.server.send_bundle")
                    if fault is not None and fault.mode == "partial_write":
                        _send_partial(conn, bmeta, bpayload, int(fault.arg))
                        raise OSError("injected partial bundle write")
                    if fault is not None and fault.mode == "pace":
                        _pace_sleep(fault, len(bpayload))
                    send_msg(conn, bmeta, bpayload)
                    metrics.inc("serving_kv_transfer_bytes_total",
                                {"role": "prefill"}, value=float(len(bpayload)))
                    metrics.observe("serving_kv_transfer_seconds",
                                    _time.perf_counter() - t0,
                                    {"role": "prefill"})
                conn.settimeout(ack_timeout)
                ack, _ = recv_msg(conn)
                if not (ack or {}).get("ack"):
                    raise OSError("no ack")
                with self._counts_lock:
                    self.bundles_delivered += 1
                self._backlog_beat()  # progress advanced: backlog drains
            except _StreamFailed:
                # Producer-side death: the stream can never complete, so a
                # re-queue would head-of-line block the queue forever. The
                # router's resubmit is the recovery path (same contract as
                # prefill dying pre-offer).
                return
            except OSError:
                if "deadline_s" in bmeta:
                    # The failed delivery window (pop -> here) burned real
                    # budget too; deduct it and re-anchor for redelivery.
                    now = _time.monotonic()
                    bmeta["deadline_s"] = max(
                        0.0, float(bmeta["deadline_s"]) - (now - pop_t)
                    )
                    bmeta["_offered_t"] = now
                # The re-queue is the server half of the retry story: the
                # journey vault joins it to the request by id (this side
                # has no live span ctx — the id is the only join key).
                from lws_tpu.core import flightrecorder

                flightrecorder.record(
                    "kv_requeue", request_id=str(bmeta.get("id") or ""),
                )
                self._bundles.put((bmeta, bpayload))
                self._backlog_beat()
        elif op == "pull_result":
            # Pop under the lock BEFORE sending: two concurrent pulls for
            # the same id must not both deliver (results_served drives
            # --once exit); re-insert on send failure so a retry works.
            with self._results_lock:
                entry = self._results.pop(meta.get("id", ""), None)
            if entry is None:
                send_msg(conn, {"none": True})
                return
            try:
                fault = faults.fire("kv.server.send_result")
                if fault is not None and fault.mode == "partial_write":
                    _send_partial(conn, entry[0], entry[1], int(fault.arg))
                    raise OSError("injected partial result write")
                send_msg(conn, entry[0], entry[1])
            except OSError:
                with self._results_lock:
                    self._results.setdefault(meta.get("id", ""), entry)
                return
            with self._counts_lock:
                self.results_served += 1
        elif op == "fetch_prefix":
            # Sibling warm-up leg (ISSUE 18): serve the CONTIGUOUS PREFIX of
            # the requested digest chain this instance still holds, one
            # block per chunk, over the standard kv_stream protocol
            # (per-chunk acks, crc32 at END). The chain stops at the first
            # digest the provider misses — a block whose predecessors are
            # absent is useless to the requester (digests chain positions).
            provider = self._prefix_provider
            digests = [bytes.fromhex(h) for h in meta.get("digests", [])]
            if provider is None or not digests:
                send_msg(conn, {"none": True})
                return
            ack_timeout = float(meta.get("ack_timeout", 30.0))
            stream = KVStream()
            served: list[str] = []
            try:
                for d in digests:
                    arrays = provider(d)
                    if arrays is None:
                        break
                    stream.put_chunk(len(served), len(served) + 1, arrays)
                    served.append(d.hex())
                if not served:
                    send_msg(conn, {"none": True})
                    return
                stream.finish({"digests": served})
                # Torn legs raise OSError to _serve_one (connection-error
                # counter); there is NO re-queue — the requester's retry
                # re-serves from scratch, so a torn fetch can never leave a
                # torn suffix on either side.
                self._send_stream(conn, {"op": "fetch_prefix"}, stream,
                                  ack_timeout, role="prefix")
            finally:
                # No-op after a fully-acked delivery; on a torn leg it
                # releases the un-acked chunks' inflight-gauge contribution
                # (this one-shot stream has no redelivery to hold them for).
                stream.fail()
        else:
            send_msg(conn, {"error": f"unknown op {op!r}"})

    def _send_stream(self, conn: socket.socket, bmeta: dict,
                     stream: KVStream, ack_timeout: float,
                     role: str = "prefill") -> None:
        """One streamed delivery attempt: BEGIN, then chunk/ack pairs as
        the producer lands them, then END. Raises OSError on any torn leg
        (caller re-queues the stream) or _StreamFailed when the producer
        died (caller drops it). `role` labels the transfer metrics:
        "prefill" for bundle handoffs, "prefix" for sibling prefix legs."""
        import time as _time

        t0 = _time.perf_counter()
        send_msg(conn, {**bmeta, "stream": True})
        idx = 0
        while True:
            kind, cmeta, bufs = stream.read(idx, timeout=ack_timeout)
            if kind == "timeout":
                raise OSError("kv stream producer stalled")
            if kind == "failed":
                try:
                    send_msg(conn, {"stream_failed": True})
                except OSError:
                    pass
                raise _StreamFailed(bmeta.get("id", "?"))
            if kind == "chunk":
                fault = faults.fire("kv.stream.send_chunk")
                if fault is not None and fault.mode == "partial_write":
                    _send_partial(conn, cmeta, bufs, int(fault.arg))
                    raise OSError("injected partial stream chunk write")
                if fault is not None and fault.mode == "pace":
                    _pace_sleep(fault, _payload_len(bufs))
                send_msg(conn, cmeta, bufs)
                conn.settimeout(ack_timeout)
                ack, _ = recv_msg(conn)
                if ack is None or ack.get("ack_chunk") != cmeta["chunk"]:
                    raise OSError("kv stream chunk unacked")
                stream.chunk_acked(idx)
                idx += 1
                continue
            # END
            send_msg(conn, cmeta, bufs)
            metrics.inc("serving_kv_transfer_bytes_total",
                        {"role": role},
                        value=float(stream.payload_bytes))
            metrics.observe("serving_kv_transfer_seconds",
                            _time.perf_counter() - t0, {"role": role})
            return


def _pace_sleep(fault, nbytes: int) -> None:
    """Cooperative `pace:MBPS` fault: emulate a bandwidth-limited link by
    sleeping this frame's byte count at the armed MB/s — per-byte-fair
    across monolithic and streamed deliveries (the kv_handoff bench's
    DCN-like link; see docs/robustness.md)."""
    import time as _time

    _time.sleep(nbytes / (max(float(fault.arg), 1e-6) * 1e6))


def _auth(meta: dict) -> dict:
    import os

    token = os.environ.get("LWS_TPU_KV_TOKEN")
    if token:
        meta = dict(meta, token=token)
    return meta


def _one_shot(endpoint: tuple[str, int], meta: dict, payload: bytes = b"",
              timeout: float = 10.0) -> tuple[Optional[dict], bytes]:
    # Every blocking point checks the bound deadline BEFORE waiting and
    # clamps its socket timeout to the remaining budget: a dead peer costs
    # what the request had left, never the full transport timeout.
    resilience.check("kv.connect")
    faults.fire("kv.client.connect")
    with socket.create_connection(
        endpoint, timeout=resilience.clamp_timeout(timeout)
    ) as sock:
        tune_socket(sock)
        send_msg(sock, _auth(meta), payload)
        faults.fire("kv.client.recv")
        return recv_msg(sock)


def _deadline_meta(meta: dict) -> dict:
    """Attach the caller's bound deadline to the frame meta — remaining
    seconds, re-anchored by the peer — exactly like the trace ctx rides."""
    deadline = resilience.current()
    if deadline is not None:
        meta["deadline_s"] = deadline.to_wire()
    return meta


def submit_prompt(endpoint, req_id: str, prompt_bytes: bytes,
                  trace_ctx: Optional[dict] = None,
                  klass: Optional[str] = None) -> None:
    """`trace_ctx` (default: the caller's current span context) rides the
    frame meta so the prefill worker's span subtree grafts onto the
    caller's trace — the cross-process leg of the trace spine. The bound
    `resilience.Deadline` (if any) rides the same way: the prefill worker
    drops expired prompts instead of burning prefill on them. `klass`
    labels the request's workload/QoS class; it rides the meta to the
    prefill leg and onward with the bundle to decode, so BOTH workers'
    SLO/goodput series carry the class label (core/slo.py)."""
    if trace_ctx is None:
        from lws_tpu.core import trace

        trace_ctx = trace.current_context()
    meta = _deadline_meta({"op": "submit_prompt", "id": req_id})
    if trace_ctx:
        meta["trace"] = trace_ctx
    if klass:
        meta["klass"] = klass
    meta, _ = _one_shot(endpoint, meta, prompt_bytes)
    if not (meta or {}).get("ok"):
        raise RuntimeError(f"submit_prompt failed: {meta}")


def _recv_stream(sock: socket.socket, begin_meta: dict, receiver,
                 ack_timeout: float) -> tuple[dict, object, int]:
    """Client half of the kv_stream protocol: consume CHUNK frames into
    `receiver` (per-chunk acked) until END, verify the checksum/count, and
    return (merged meta, receiver.finish(...) result, payload bytes). Any
    mismatch raises OSError — no final ack, the server re-queues, the
    redelivery replays from chunk 0: a torn cache is impossible."""
    import time as _time

    crc = 0
    n = 0
    nbytes = 0
    t0 = _time.monotonic()
    # Arrival-side chunk timeline (stream-relative seconds): when each
    # chunk landed off the wire — attached to the END meta and the
    # receiver so the journey vault can render the wire leg per chunk.
    chunk_timeline: list[dict] = []
    poison: Optional[BaseException] = None
    while True:
        resilience.check("kv.stream.recv")
        fault = faults.fire("kv.stream.recv_chunk")
        if fault is not None and fault.mode in ("drop", "partial_write"):
            # Cooperative receive-side loss: the connection is abandoned
            # mid-stream exactly as if the read tore.
            raise OSError(f"injected kv stream recv loss at chunk {n}")
        sock.settimeout(resilience.clamp_timeout(ack_timeout))
        cmeta, payload = recv_msg(sock)
        if cmeta is None:
            raise OSError("kv stream truncated mid-frame")
        if cmeta.get("stream_failed"):
            raise OSError("kv stream failed at the sender")
        if cmeta.get("end"):
            if int(cmeta.get("chunks", -1)) != n or \
                    int(cmeta.get("checksum", -1)) != crc:
                raise OSError("torn kv stream: checksum/chunk-count mismatch")
            end_arrays = bytes_to_arrays(payload) if payload else {}
            merged = {k: v for k, v in {**begin_meta, **cmeta}.items()
                      if k not in ("end", "checksum", "stream")}
            merged["streamed"] = True
            merged["payload_bytes"] = nbytes
            try:
                receiver.payload_bytes = nbytes  # wire accounting for stats
                receiver.chunk_timeline = chunk_timeline  # journey wire leg
            except AttributeError:
                pass
            if poison is None:
                try:
                    result = receiver.finish(cmeta, end_arrays)
                except Exception as e:  # noqa: BLE001 — content verdict, see below
                    poison = e
            if poison is not None:
                merged["receiver_error"] = repr(poison)[:200]
                return merged, PoisonPayload(poison), nbytes
            return merged, result, nbytes
        if int(cmeta.get("chunk", -1)) != n:
            raise OSError("out-of-order kv stream chunk")
        crc = zlib.crc32(payload, crc)
        chunk_timeline.append({
            "chunk": n,
            "t_s": round(_time.monotonic() - t0, 6),
            "bytes": len(payload),
            **({"t_produce_s": cmeta["t_produce_s"]}
               if "t_produce_s" in cmeta else {}),
        })
        # Ack on RECEIPT, then insert: the per-chunk ack is flow control
        # (it keeps the sender's window moving while this side uploads);
        # durability is the END checksum + the final process ack — a death
        # after a chunk ack still re-queues the WHOLE stream. Inserting
        # after the ack overlaps this chunk's device upload with the
        # sender's next transmission instead of serializing them.
        send_msg(sock, {"ack_chunk": n})
        if poison is None:
            try:
                receiver.chunk(cmeta, bytes_to_arrays(payload))
            except Exception as e:  # noqa: BLE001
                # A RECEIVER rejection is a CONTENT verdict, not a wire
                # failure: re-queueing cannot heal it (every successor
                # would re-pull and re-die — a head-of-line crash loop).
                # Keep draining/acking so the protocol completes, then
                # hand the error to process() as a PoisonPayload — the
                # same consume-with-failed-result path a poison
                # monolithic bundle takes. Wire errors (OSError from the
                # socket reads above) still propagate and re-queue.
                poison = e
        n += 1
        nbytes += len(payload)


def pull_bundle(endpoint, timeout: float = 1.0, process=None,
                ack_timeout: float = 120.0, receiver_factory=None):
    """Returns (meta, payload) — or `process(meta, payload)`'s result when a
    callback is given — or None when the peer has nothing pending.

    Without `process`, receipt is acked immediately (wire-level
    at-least-once only: a crash after the ack loses the request — the
    router's retry covers that). WITH `process`, the ack is sent only after
    the callback returns: the server re-queues the bundle if the puller
    dies mid-processing, making delivery at-least-once END TO END (decode
    must be idempotent per id — replays happen). `ack_timeout` is forwarded
    to the server as its ack-wait window — size it for the callback's worst
    case (decode + first-call jit compile), or the server re-queues and
    redelivers while the puller is still working.

    STREAMED replies (the server offered a `KVStream`): chunks are fed to
    `receiver_factory(begin_meta)` as they arrive — the decode worker
    passes a `CacheAssembler` so each chunk device-uploads while the next
    is still on the wire — and `payload` is the receiver's `finish()`
    result (without a factory, a `HostAssembler`'s monolithic array dict).
    The per-chunk acks and the END checksum ride inside this call; `meta`
    gains `streamed`/`chunks`/`payload_bytes`. A RECEIVER exception (the
    content doesn't fit this side — e.g. more KV rows than max_len) does
    NOT re-queue: the stream drains per protocol and `payload` arrives as
    a `PoisonPayload` for `process()`'s poison guard to consume with a
    failed result (without `process`, the error re-raises after the
    wire-level ack)."""
    resilience.check("kv.pull_bundle")
    faults.fire("kv.client.connect")
    import time as _time

    with socket.create_connection(
        endpoint, timeout=resilience.clamp_timeout(timeout + 9.0)
    ) as sock:
        tune_socket(sock)
        send_msg(sock, _auth({
            "op": "pull_bundle", "timeout": timeout, "ack_timeout": ack_timeout,
        }))
        faults.fire("kv.client.recv")
        # Transfer clock opens at the FIRST frame byte: the blocking wait
        # before it is the server's long-poll queue wait, not wire time.
        raw = _recv_exact(sock, _FRAME.size)
        t0 = _time.perf_counter()
        meta, payload = (None, b"") if raw is None else _recv_msg_body(sock, raw)
        if meta is None:
            raise OSError("truncated pull_bundle reply")
        if meta.get("error"):
            raise RuntimeError(f"pull_bundle rejected: {meta}")
        if meta.get("none"):
            return None
        if meta.get("stream"):
            receiver = (receiver_factory(meta) if receiver_factory
                        else HostAssembler(meta))
            try:
                meta, payload, rx_bytes = _recv_stream(
                    sock, meta, receiver, ack_timeout
                )
            except OSError as e:
                # A torn stream is a NOTABLE event (the server re-queues
                # the whole stream; redelivery replays from chunk 0) and
                # carries the request id so the journey vault can flag the
                # retried leg on the request it delayed.
                from lws_tpu.core import flightrecorder

                flightrecorder.record(
                    "kv_stream_torn",
                    request_id=str(meta.get("id") or ""),
                    error=repr(e)[:200],
                )
                raise
        else:
            rx_bytes = len(payload)
        metrics.inc("serving_kv_transfer_bytes_total", {"role": "decode"},
                    value=float(rx_bytes))
        metrics.observe("serving_kv_transfer_seconds",
                        _time.perf_counter() - t0, {"role": "decode"})
        if process is None:
            _send_ack(sock)
            if isinstance(payload, PoisonPayload):
                # Content the receiver rejected: consumed at wire level
                # (same as any acked no-process pull), error to the caller.
                raise payload.error
            return meta, payload
        result = process(meta, payload)  # raise => no ack => server re-queues
        _send_ack(sock)
        return result


def _send_ack(sock: socket.socket) -> None:
    fault = faults.fire("kv.ack")
    if fault is not None and fault.mode == "drop":
        # Injected ack loss: the connection closes unacked, the server
        # re-queues, and the next pull REPLAYS the bundle — the decode
        # worker's seen-id dedup guard must absorb it.
        return
    send_msg(sock, {"ack": True})


def pull_result(endpoint, req_id: str, timeout: float = 10.0):
    """None = not ready yet. Raises on protocol-level rejection (e.g. auth)
    instead of handing the error reply back as if it were a result. A
    delivered result whose meta carries "failed" is the DECODE's verdict on
    a poison request — returned to the caller, who must check it.
    `timeout` bounds the socket (further clamped to any bound deadline)."""
    meta, payload = _one_shot(
        endpoint, {"op": "pull_result", "id": req_id}, timeout=timeout
    )
    if meta is None or meta.get("none"):
        return None
    if meta.get("error"):
        raise RuntimeError(f"pull_result rejected: {meta}")
    return meta, payload


# ---------------------------------------------------------------------------
# Cross-instance prefix fetch (ISSUE 18): warm a replica's prefix cache from
# a sibling over the same streamed KV wire as the disagg handoff.


class _PrefixReceiver:
    """fetch_prefix's stream receiver: chunk i is block i of the served
    digest chain, kept as zero-copy array views; finish() returns the
    ordered block list (the END meta's digest list zips against it)."""

    def __init__(self) -> None:
        self.blocks: list[dict] = []

    def chunk(self, cmeta: dict, arrays: dict) -> None:
        self.blocks.append(arrays)

    def finish(self, end_meta: dict, end_arrays: dict):
        return self.blocks


def fetch_prefix(endpoint, digests: Sequence, timeout: float = 5.0,
                 ack_timeout: float = 30.0) -> dict:
    """Pull cached prefix blocks for `digests` (a hash-chain run, in order)
    from a sibling's KVServer -> {digest_bytes: array dict}, covering the
    contiguous chain prefix the peer still held; {} when it held nothing.
    Rides the kv_stream protocol end to end: per-chunk acks, crc32/count
    verification at END — any torn leg raises OSError WITHOUT a partial
    result, so the caller falls back to recompute, never a torn cache. The
    bound Deadline (if any) gates the dial and clamps every socket wait."""
    resilience.check("kv.prefix.fetch")
    faults.fire("kv.prefix.fetch")
    import time as _time

    with socket.create_connection(
        endpoint, timeout=resilience.clamp_timeout(timeout)
    ) as sock:
        tune_socket(sock)
        send_msg(sock, _auth({
            "op": "fetch_prefix",
            "digests": [d.hex() for d in digests],
            "ack_timeout": ack_timeout,
        }))
        t0 = _time.perf_counter()
        meta, _ = recv_msg(sock)
        if meta is None:
            raise OSError("truncated fetch_prefix reply")
        if meta.get("error"):
            raise RuntimeError(f"fetch_prefix rejected: {meta}")
        if meta.get("none"):
            return {}
        if not meta.get("stream"):
            raise OSError("fetch_prefix reply was not a stream")
        merged, blocks, rx_bytes = _recv_stream(
            sock, meta, _PrefixReceiver(), ack_timeout
        )
        if isinstance(blocks, PoisonPayload):
            raise OSError(f"fetch_prefix receiver failed: {blocks.error!r}")
        metrics.inc("serving_kv_transfer_bytes_total", {"role": "prefix"},
                    value=float(rx_bytes))
        metrics.observe("serving_kv_transfer_seconds",
                        _time.perf_counter() - t0, {"role": "prefix"})
        served = [bytes.fromhex(h) for h in merged.get("digests", [])]
        return dict(zip(served, blocks))


class RemotePrefixSource:
    """The engine's remote tier: candidate sibling endpoints come from a
    dynamic `lookup(digest_hex) -> (host, port)|None` (the FleetCollector's
    digest index) and/or a static `endpoints` list, each behind its own
    CircuitBreaker, each fetch retried once on transient OSError
    (RetryPolicy — a retry re-serves the whole stream from chunk 0).

    `fetch()` NEVER raises: every failure — open circuit, dead peer, torn
    stream, expired deadline — degrades to {} and the engine prefills the
    suffix itself. The remote tier is an optimization; it must not become
    a new way for admission to crash or hang."""

    def __init__(self, endpoints: Sequence = (), lookup=None,
                 timeout: float = 5.0, ack_timeout: float = 30.0,
                 failure_threshold: int = 3, reset_timeout_s: float = 10.0):
        self.endpoints = [tuple(e) for e in endpoints]
        self.lookup = lookup
        self.timeout = timeout
        self.ack_timeout = ack_timeout
        self._failure_threshold = failure_threshold
        self._reset_timeout_s = reset_timeout_s
        self._breakers: dict[str, resilience.CircuitBreaker] = {}

    def _breaker(self, endpoint) -> resilience.CircuitBreaker:
        key = f"{endpoint[0]}:{endpoint[1]}"
        br = self._breakers.get(key)
        if br is None:
            br = resilience.CircuitBreaker(
                key, failure_threshold=self._failure_threshold,
                reset_timeout_s=self._reset_timeout_s,
            )
            self._breakers[key] = br
        return br

    def _candidates(self, digests: list) -> list:
        out: list = []
        if self.lookup is not None:
            try:
                hit = self.lookup(digests[0].hex())
            except Exception:  # noqa: BLE001 — index staleness is not fatal
                hit = None
            if hit:
                out.append(tuple(hit))
        for ep in self.endpoints:
            if ep not in out:
                out.append(ep)
        return out

    def fetch(self, digests: Sequence) -> dict:
        digests = list(digests)
        if not digests:
            return {}
        for endpoint in self._candidates(digests):
            br = self._breaker(endpoint)
            if not br.allow():
                continue  # open circuit: fail fast to the next candidate
            try:
                found = resilience.call(
                    lambda ep=endpoint: fetch_prefix(
                        ep, digests, timeout=self.timeout,
                        ack_timeout=self.ack_timeout,
                    ),
                    site="kv.prefix.fetch",
                    policy=resilience.RetryPolicy(
                        max_attempts=2, base_s=0.05, cap_s=0.25
                    ),
                )
            except Exception:  # noqa: BLE001 — any failure = miss, next peer
                br.record_failure()
                continue
            br.record_success()
            if found:
                return found
        return {}


# ---------------------------------------------------------------------------
# Endpoint discovery from the DS `-prv` service record (API-server backed).


def discover_role_endpoint(
    client, namespace: str, ds_name: str, role: str,
    port_env: str = "LWS_TPU_KV_PORT",
    revision: Optional[str] = None,
    slice_idx: Optional[str] = None,
) -> Optional[tuple[str, int]]:
    """Resolve role's KV endpoint THROUGH the revision-aware service record:
    find the `-prv` Service labeled (ds, role), match its selector against
    Pods (k8s Endpoints semantics: selector + readiness), and read the
    pod's published address + its declared KV port (containerPort analog:
    the `port_env` env var in the pod spec). `client` is a RemoteClient —
    the worker talks to the API server exactly like any external consumer.

    Pass `revision`/`slice_idx` (a worker passes ITS OWN labels) to pin the
    pairing: during a rolling update old still-ready revisions keep their
    -prv services alongside the target's, and multi-slice DSes publish one
    service per slice — an unpinned pick could pair a new-revision decode
    with an old-revision prefill (different weights: silent garbage) or
    cross slices (the pairing is slice-scoped by design)."""
    from lws_tpu.api import disagg

    def svc_label(s, key):
        return s.get("metadata", {}).get("labels", {}).get(key)

    services = [
        s for s in client.list("Service")
        if s.get("metadata", {}).get("namespace") == namespace
        and svc_label(s, disagg.DS_NAME_LABEL_KEY) == ds_name
        and svc_label(s, disagg.DS_ROLE_LABEL_KEY) == role
        and s.get("metadata", {}).get("name", "").endswith("-prv")
        and (revision is None or svc_label(s, disagg.DS_REVISION_LABEL_KEY) == revision)
        and (slice_idx is None or svc_label(s, disagg.DS_SLICE_LABEL_KEY) == str(slice_idx))
    ]
    for svc in services:
        selector = svc.get("spec", {}).get("selector", {})
        for pod in client.list("Pod"):
            meta = pod.get("metadata", {})
            if meta.get("namespace") != namespace:
                continue
            labels = meta.get("labels", {})
            if any(labels.get(k) != v for k, v in selector.items()):
                continue
            status = pod.get("status", {})
            if not status.get("ready"):
                continue
            host = status.get("address") or "127.0.0.1"
            for container in pod.get("spec", {}).get("containers", []):
                for env in container.get("env", []):
                    if env.get("name") == port_env and env.get("value"):
                        return host, int(env["value"])
    return None
