"""Paged continuous batching: BatchEngine's slot model with K/V in a shared
block pool instead of a dense [slots, max_len] reservation.

Why: dense continuous batching reserves max_len KV rows per slot, so HBM
capacity caps slots at hbm / (max_len * kv_row_bytes) even when typical
sequences are much shorter. Paging sizes physical memory to the EXPECTED
live footprint: each request holds exactly ceil(footprint/block_size) blocks
for its lifetime and returns them on completion, so the same pool serves
~max_len/avg_len x more slots (VERDICT #4 "decode tok/s at 2x batch without
HBM overflow"). All device shapes stay static — the block table is data, not
shape — so XLA compiles one executable regardless of allocation state.

Allocation policy (host side, exclusive):
  * block 0 is the NULL block — never allocated; freed/unallocated table
    entries point at it, so inactive slots' dead writes and padding reads
    land there (position-masked, never attendable).
  * submit() takes ceil(max(bucket, plen+max_new)/bs) blocks up front and
    returns None when the pool (or slot set) is exhausted — callers retry
    after a drain, exactly like a full BatchEngine.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from lws_tpu.models.llama import (
    LlamaConfig,
    cache_shardings,
    forward_decode_paged,
    forward_prefill,
    init_cache,
    init_paged_cache,
    paged_cache_shardings,
    paged_insert,
)


@dataclass
class PagedRequest:
    request_id: int
    prompt: np.ndarray
    max_new_tokens: int
    tokens: list[int] = field(default_factory=list)
    slot: int = -1
    blocks: list[int] = field(default_factory=list)
    # Per-request sampling (vLLM SamplingParams shape): temperature <= 0 is
    # greedy; seed pins the slot's PRNG stream for reproducible sampling.
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None

    @property
    def done(self) -> bool:
        return len(self.tokens) >= self.max_new_tokens


class PagedBatchEngine:
    """Slot-based continuously-batched engine over a paged KV pool, with
    per-request sampling (greedy by default; temperature/top-k/top-p/seed
    per submit — mixed batches sample each slot from its own stream)."""

    def __init__(
        self,
        cfg: LlamaConfig,
        params: dict,
        slots: int = 8,
        max_len: int = 512,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        mesh=None,
    ):
        """With `mesh` (axes incl. 'tp'), the engine serves TENSOR-PARALLEL
        paged continuous batching under GSPMD: params per param_shardings,
        K/V pools (+ scale pools) sharded over 'tp' on the kv-heads dim,
        block tables / positions / tokens replicated (host-side allocation
        state is identical on every shard). This is the conjunction the
        70B-class llm-d shape needs — TP x paged x continuous batching in
        ONE engine (ref vLLM-TPU TP=16 shape,
        /root/reference/docs/examples/vllm/TPU/lws.yaml:22-34). dp inside
        one pool is deliberately unused: blocks are randomly indexed, so dp
        stays the replica-level axis (see paged_cache_shardings)."""
        if max_len % block_size:
            raise ValueError("max_len must be a multiple of block_size")
        self.cfg = cfg
        self.mesh = mesh
        self._tp = 1
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as _P

            self._tp = dict(zip(mesh.axis_names, mesh.devices.shape)).get("tp", 1)
            if cfg.n_kv_heads % max(self._tp, 1):
                raise ValueError(
                    f"n_kv_heads={cfg.n_kv_heads} not divisible by tp={self._tp}"
                )
            from lws_tpu.serving.engine import shard_params_for_serving

            params = shard_params_for_serving(params, cfg, mesh)
            self._pool_shardings = jax.tree.map(
                lambda s: NamedSharding(mesh, s), paged_cache_shardings(cfg)
            )
            self._rep = NamedSharding(mesh, _P())
            # Single-request prefill cache: B=1 can't shard over dp.
            self._prefill_cache_shardings = jax.tree.map(
                lambda s: NamedSharding(mesh, s), cache_shardings(cfg, dp=False)
            )
            _sh_prefill = {"out_shardings": (self._rep, self._prefill_cache_shardings)}
            _sh_insert = {"out_shardings": (self._pool_shardings, self._rep, self._rep)}
            _sh_step = {"out_shardings": (
                self._pool_shardings, self._rep, self._rep, self._rep, self._rep
            )}
        else:
            self._pool_shardings = None
            self._rep = None
            _sh_prefill = _sh_insert = _sh_step = {}
        self.params = params
        self.slots = slots
        self.max_len = max_len
        self.block_size = block_size
        self.max_blocks = max_len // block_size
        # Default pool = dense equivalent (+null); callers shrink it for
        # density (that is the whole point).
        self.num_blocks = num_blocks if num_blocks is not None else slots * self.max_blocks + 1
        self._ids = itertools.count()
        self._free_slots = list(range(slots))
        self._free_blocks = list(range(1, self.num_blocks))  # 0 = null
        self._active: dict[int, PagedRequest] = {}
        self._completed: dict[int, PagedRequest] = {}

        cfg_static = cfg
        self._cfg_static = cfg
        self._sh_step = _sh_step

        with self._mesh_ctx():
            self.cache = jax.jit(
                lambda: init_paged_cache(cfg_static, self.num_blocks, block_size),
                **({"out_shardings": self._pool_shardings} if mesh is not None else {}),
            )()
        self.table = np.zeros((slots, self.max_blocks), np.int32)  # host truth
        self.pos_b = jnp.zeros((slots,), jnp.int32)
        self.tokens = jnp.zeros((slots,), jnp.int32)
        # Per-slot sampling state (host truth, tiny; shipped per dispatch).
        self.temp = np.zeros((slots,), np.float32)
        self.top_k = np.zeros((slots,), np.int32)
        self.top_p = np.ones((slots,), np.float32)
        self._keys = jax.random.split(jax.random.key(0), slots)

        @partial(jax.jit, **_sh_prefill)
        def _prefill_one(params, prompt, last_pos):
            cache = init_cache(cfg_static, 1, prompt.shape[1])
            logits, cache = forward_prefill(
                params, prompt, cache, cfg_static, last_pos=last_pos
            )
            return logits, cache  # [1, V]: the caller samples per-request

        @jax.jit
        def _sample_first(logits, key, temp, top_k, top_p):
            from lws_tpu.serving.engine import sample_logits_per_slot

            return sample_logits_per_slot(
                logits, key[None], temp[None], top_k[None], top_p[None]
            )[0]

        self._sample_first = _sample_first

        @partial(jax.jit, donate_argnums=(0,), **_sh_insert)
        def _insert(cache, slot_k, slot_v, block_ids, pos_b, tokens, slot, plen,
                    first_token, slot_ks=None, slot_vs=None):
            cache = paged_insert(cache, slot_k, slot_v, block_ids, slot_ks, slot_vs)
            return cache, pos_b.at[slot].set(plen), tokens.at[slot].set(first_token)

        self._prefill_one = _prefill_one
        self._insert = _insert
        # Attention path: the kernel's first real-chip contact happens inside
        # a serving engine, so a compile failure must fall back, not crash
        # (VERDICT r3 next #4). stats records which path actually serves.
        from lws_tpu.models.llama import paged_kernel_default

        kernel_intent = paged_kernel_default()
        self.stats = {"attention_path": "kernel" if kernel_intent else "xla_fallback"}
        # The kernel's first step is the compile probe: run it WITHOUT cache
        # donation (a post-compile runtime failure would have consumed the
        # donated pool, leaving nothing for the fallback retry); switch to
        # the donating executable once the kernel has proven itself.
        self._kernel_probed = not kernel_intent
        self._use_kernel = kernel_intent
        # Step executables, cached per (use_kernel, donate, sample): the
        # all-greedy default must stay a single argmax — the full sampling
        # pipeline (two [slots, V] sorts + softmax + cumsum + categorical)
        # would tax every decode step of the benchmarked path for nothing.
        self._step_cache: dict = {}

    def _get_step_fn(self, sample: bool):
        key = (self._use_kernel, self._kernel_probed, sample)
        if key not in self._step_cache:
            self._step_cache[key] = self._make_step_n(
                use_kernel=self._use_kernel, donate=self._kernel_probed, sample=sample
            )
        return self._step_cache[key]

    def _make_step_n(self, use_kernel: bool, donate: bool = True, sample: bool = False):
        cfg_static = self._cfg_static
        tp_static = self._tp

        @partial(
            jax.jit,
            static_argnums=(6,),
            **({"donate_argnums": (1,)} if donate else {}),
            **self._sh_step,
        )
        def _step_n(params, cache, table, tokens, pos_b, active, n, keys, temp, top_k, top_p):
            # n chained steps in ONE dispatch (lax.scan): admission state is
            # frozen for the chunk, so callers bound n by the soonest
            # completion. Kills the per-step host round trip that dominates
            # relay-backed links (same trick as Engine.decode_n).
            from lws_tpu.serving.engine import sample_logits_per_slot

            def body(carry, _):
                cache, tokens, pos_b, keys = carry
                logits, cache = forward_decode_paged(
                    params, tokens, cache, table, pos_b, cfg_static,
                    tp_shard=tp_static, use_kernel=use_kernel,
                )
                if sample:
                    # Each slot advances ITS OWN stream; inactive slots
                    # advance too (harmless — a new occupant reseeds).
                    split = jax.vmap(jax.random.split)(keys)  # [slots, 2]
                    step_keys, keys = split[:, 0], split[:, 1]
                    nxt = sample_logits_per_slot(logits, step_keys, temp, top_k, top_p)
                else:  # all-greedy batch: plain argmax, keys pass through
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                tokens = jnp.where(active, nxt, tokens)
                pos_b = jnp.where(active, pos_b + 1, pos_b)
                return (cache, tokens, pos_b, keys), tokens

            (cache, tokens, pos_b, keys), toks = jax.lax.scan(
                body, (cache, tokens, pos_b, keys), None, length=n
            )
            return cache, tokens, pos_b, toks, keys  # toks [n, slots]

        return _step_n

    def _mesh_ctx(self):
        """Ambient-mesh context for tracing/executing the jitted phases: the
        shard_map inside the paged kernel path (and shardings resolution)
        needs jax.set_mesh when the engine is mesh-sharded."""
        import contextlib

        return jax.set_mesh(self.mesh) if self.mesh is not None else contextlib.nullcontext()

    # ------------------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free_blocks)

    def submit(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: Optional[int] = None,
    ) -> Optional[int]:
        """Admit a request; returns request id, or None when out of slots OR
        out of pool blocks (the density backpressure signal). Sampling is
        per-request (vLLM SamplingParams shape): temperature <= 0 is greedy;
        with temperature > 0, `seed` pins this request's PRNG stream
        (auto-assigned otherwise) — sampled and greedy requests mix freely
        in one batch without perturbing each other."""
        if not self._free_slots:
            return None
        plen = len(prompt)
        if plen + max_new_tokens > self.max_len:
            raise ValueError("prompt + max_new_tokens exceeds max_len")
        # Same power-of-two length bucketing as BatchEngine, floored at one
        # block so the prefill scatter is block-aligned.
        bucket = self.block_size
        while bucket < plen:
            bucket *= 2
        bucket = min(bucket, self.max_len)
        footprint = max(bucket, plen + max_new_tokens)
        n_blocks = -(-footprint // self.block_size)
        if n_blocks > len(self._free_blocks):
            return None
        slot = self._free_slots.pop(0)
        blocks = [self._free_blocks.pop(0) for _ in range(n_blocks)]
        req = PagedRequest(
            next(self._ids), np.asarray(prompt), max_new_tokens, slot=slot,
            blocks=blocks, temperature=temperature, top_k=top_k, top_p=top_p,
            seed=seed,
        )
        self.table[slot] = 0
        self.table[slot, :n_blocks] = blocks
        self.temp[slot] = temperature
        self.top_k[slot] = top_k
        self.top_p[slot] = top_p
        # Unseeded sampling must be nondeterministic (vLLM seed=None): draw
        # from process entropy, not a counter — a counter would collide with
        # small user seeds and make every dp replica replay identical
        # "random" samples. User seeds stay a pure function of the seed.
        if seed is None:
            import os as _os

            # 63 bits: jax.random.key seeds go through np.int64.
            seed = int.from_bytes(_os.urandom(8), "little") >> 1
        req_key = jax.random.key(seed)

        padded = np.zeros((bucket,), np.int32)
        padded[:plen] = prompt
        with self._mesh_ctx():
            logits, slot_cache = self._prefill_one(
                self.params, jnp.asarray(padded)[None, :], jnp.asarray(plen - 1)
            )
            first_key, slot_key = jax.random.split(req_key)
            first = self._sample_first(
                logits, first_key,
                jnp.float32(temperature), jnp.int32(top_k), jnp.float32(top_p),
            )
            self._keys = self._keys.at[slot].set(slot_key)
            prefill_ids = jnp.asarray(blocks[: bucket // self.block_size], jnp.int32)
            scales = (
                (slot_cache.k_scale[:, 0], slot_cache.v_scale[:, 0])
                if self.cfg.kv_quant
                else ()
            )
            self.cache, self.pos_b, self.tokens = self._insert(
                self.cache, slot_cache.k[:, 0], slot_cache.v[:, 0], prefill_ids,
                self.pos_b, self.tokens, slot, plen, first, *scales,
            )
        req.tokens.append(int(first))
        if req.done:
            self._completed[req.request_id] = req
            self._release(req)
        else:
            self._active[slot] = req
        return req.request_id

    def _release(self, req: PagedRequest) -> None:
        self.table[req.slot] = 0  # dead writes + stale reads -> null block
        self._free_blocks.extend(req.blocks)
        req.blocks = []
        self._free_slots.append(req.slot)

    def step(self) -> None:
        """One decode step across every active slot."""
        self.step_n(1)

    def _completion_bound(self) -> int:
        """Steps until the soonest completion/length-overflow among active
        slots — the longest chunk that cannot overrun any budget."""
        return min(
            min(r.max_new_tokens - len(r.tokens) for r in self._active.values()),
            min(self.max_len - len(r.prompt) - len(r.tokens)
                for r in self._active.values()),
        )

    def step_n(self, n: int) -> None:
        """Up to n decode steps in one device dispatch. Clamped to the
        soonest completion among active slots (admission state is frozen for
        the chunk, and a slot stepping past its block footprint would write
        into the shared null block while its mask starts attending it)."""
        if not self._active or n <= 0:
            return
        n = min(n, max(1, self._completion_bound()), 32)
        n = 1 << (n.bit_length() - 1)  # floor pow2: bounded compile set
        active = jnp.asarray(
            [s in self._active and not self._active[s].done for s in range(self.slots)]
        )
        table = jnp.asarray(self.table)
        sampling = (
            self._keys, jnp.asarray(self.temp), jnp.asarray(self.top_k),
            jnp.asarray(self.top_p),
        )
        # All-greedy batches (the default and the benchmarked configuration)
        # take the argmax-only executable.
        any_sampled = bool(
            any(self._active[s].temperature > 0.0 for s in self._active)
        )
        if self.mesh is not None:
            # Pin the host-built inputs replicated: left uncommitted, GSPMD
            # may shard them and the shard_map'd kernel expects them whole.
            active = jax.device_put(active, self._rep)
            table = jax.device_put(table, self._rep)
            sampling = tuple(jax.device_put(s, self._rep) for s in sampling)
        with self._mesh_ctx():
            try:
                step_fn = self._get_step_fn(any_sampled)
                self.cache, self.tokens, self.pos_b, toks, self._keys = step_fn(
                    self.params, self.cache, table, self.tokens,
                    self.pos_b, active, n, *sampling,
                )
            except Exception as e:  # noqa: BLE001 — kernel trace/compile/runtime failure
                if self.stats["attention_path"] != "kernel" or self._kernel_probed:
                    raise
                # One-time probe semantics: the pallas kernel failed its
                # first contact with this backend — log, rebuild the step on
                # the XLA gather path (slower, never wrong), and keep
                # serving. The probe step ran WITHOUT donation, so the cache
                # survives even a post-compile runtime failure.
                import sys

                print(
                    f"[paged-engine] pallas kernel failed on "
                    f"{jax.default_backend()!r}: {e!r:.300}; falling back to "
                    f"the XLA gather path",
                    file=sys.stderr, flush=True,
                )
                self.stats["attention_path"] = "xla_fallback"
                self.stats["kernel_error"] = repr(e)[:300]
                self._kernel_probed = True
                self._use_kernel = False
                self.cache, self.tokens, self.pos_b, toks, self._keys = (
                    self._get_step_fn(any_sampled)(
                        self.params, self.cache, table, self.tokens,
                        self.pos_b, active, n, *sampling,
                    )
                )
            else:
                if not self._kernel_probed:
                    # Kernel proved itself: subsequent steps use the
                    # donating executables (in-place pool updates).
                    self._kernel_probed = True
        host_toks = np.asarray(toks)  # [n, slots]
        for slot, req in list(self._active.items()):
            req.tokens.extend(int(t) for t in host_toks[:, slot])
            if req.done or len(req.prompt) + len(req.tokens) >= self.max_len:
                self._completed[req.request_id] = req
                del self._active[slot]
                self._release(req)

    def run_until_drained(self, max_steps: int = 10000) -> None:
        """Drain via chunked on-device stepping: each dispatch runs exactly
        up to the soonest completion, so no slot oversteps its budget."""
        for _ in range(max_steps):
            if not self._active:
                return
            self.step_n(32)  # step_n clamps to the completion bound itself
        raise RuntimeError("engine did not drain")

    def result(self, request_id: int) -> Optional[list[int]]:
        req = self._completed.get(request_id)
        return list(req.tokens) if req is not None else None

    @property
    def active_count(self) -> int:
        return len(self._active)
