"""Paged continuous batching: BatchEngine's slot model with K/V in a shared
block pool instead of a dense [slots, max_len] reservation.

Why: dense continuous batching reserves max_len KV rows per slot, so HBM
capacity caps slots at hbm / (max_len * kv_row_bytes) even when typical
sequences are much shorter. Paging sizes physical memory to the EXPECTED
live footprint: each request holds exactly ceil(footprint/block_size) blocks
for its lifetime and returns them on completion, so the same pool serves
~max_len/avg_len x more slots (VERDICT #4 "decode tok/s at 2x batch without
HBM overflow"). All device shapes stay static — the block table is data, not
shape — so XLA compiles one executable regardless of allocation state.

Allocation policy (host side, exclusive):
  * block 0 is the NULL block — never allocated; freed/unallocated table
    entries point at it, so inactive slots' dead writes and padding reads
    land there (position-masked, never attendable).
  * submit() takes ceil(max(bucket, plen+max_new)/bs) blocks up front and
    returns None when the pool (or slot set) is exhausted — callers retry
    after a drain, exactly like a full BatchEngine.

Pipelined dispatch (ISSUE 3): step_n never blocks on its own chunk's
tokens. Dispatched chunks ride a bounded in-flight ring
(serving/pipeline.py); the host commits chunk N's tokens — and retires the
requests they complete — while chunk N+1 computes. Correctness invariants:
  * the completion bound subtracts in-flight steps, so a chunk that would
    run the soonest-finishing slot past its budget is never dispatched —
    which also means no in-flight chunk can ever read blocks of a request
    that has already been released;
  * host-built dispatch inputs (active mask, block table, sampling params)
    are device-resident dirty-tracked buffers rebuilt only on
    admission/release — in-flight chunks keep their own handles;
  * the ring flushes before anything that must see host truth or roll back
    cleanly: the pallas-probe dispatch, LRU eviction, and admission
    backpressure checks (an in-flight completion may be about to free the
    slot/blocks being refused).

Speculative decoding (ISSUE 9) rides the same ring: drafting (n-gram over a
per-slot device history ring), verification, acceptance
(longest-accepted-prefix), and the commit (pos_b/tokens/history/budget) all
run inside the jitted _spec_step, so spec dispatches are chunks like
step_n's — the host unpacks each chunk's packed accepted tokens at consume
time. The per-slot budget lives on device (the kernel clamps `take`), so
in-flight spec chunks can never overshoot max_new_tokens or max_len; the
steady-state spec loop therefore never flushes. Flushes remain only at
spec-mode entry (rebuilding device history/budget from host truth after
plain step_n dispatches), completion/tail boundaries, and rollback (a
failed push discards the ring and pos_b/tokens are restored from host
truth — pos_b IS the cache rewind). The PR-8 host loop survives as
step_speculative_sync, the byte-identical oracle for tests and
benchmarks/spec_decode_bench.py.
"""

from __future__ import annotations

import collections
import itertools
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from lws_tpu.core import flightrecorder, metrics, slo, trace
from lws_tpu.obs import device as devicemod
from lws_tpu.serving.pipeline import DecodePipeline, remaining_steps

from lws_tpu.models.llama import (
    LlamaConfig,
    cache_shardings,
    forward_decode_paged,
    forward_prefill,
    init_cache,
    init_paged_cache,
    paged_cache_shardings,
    paged_insert,
)


def _tree_nbytes(tree) -> int:
    """Total buffer bytes across a pytree's array leaves (HBM pool
    attribution feed — leaves without nbytes contribute nothing)."""
    return sum(int(getattr(leaf, "nbytes", 0) or 0)
               for leaf in jax.tree.leaves(tree))


@dataclass
class PagedRequest:
    request_id: int
    prompt: np.ndarray
    max_new_tokens: int
    tokens: list[int] = field(default_factory=list)
    slot: int = -1
    blocks: list[int] = field(default_factory=list)
    # Prefix caching: blocks this request shares through the prefix map
    # (released by refcount) vs privately owned (released to the free list).
    shared_blocks: list[int] = field(default_factory=list)
    # Per-request sampling (vLLM SamplingParams shape): temperature <= 0 is
    # greedy; seed pins the slot's PRNG stream for reproducible sampling.
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None
    # Per-request SLO timeline (queue wait / TTFT / ITL; core/slo.py).
    slo: "slo.RequestTimeline | None" = None

    @property
    def done(self) -> bool:
        return len(self.tokens) >= self.max_new_tokens


class PagedBatchEngine:
    """Slot-based continuously-batched engine over a paged KV pool, with
    per-request sampling (greedy by default; temperature/top-k/top-p/seed
    per submit — mixed batches sample each slot from its own stream)."""

    def __init__(
        self,
        cfg: LlamaConfig,
        params: dict,
        slots: int = 8,
        max_len: int = 512,
        block_size: int = 16,
        num_blocks: Optional[int] = None,
        mesh=None,
        prefix_cache: bool = False,
        prefill_chunk: Optional[int] = None,
        interleave_steps: int = 8,
        pipeline_depth: Optional[int] = None,
        donate_steps: Optional[bool] = None,
        spec_history: Optional[int] = None,
        host_arena=None,
        remote_prefix=None,
    ):
        """With `mesh` (axes incl. 'tp'), the engine serves TENSOR-PARALLEL
        paged continuous batching under GSPMD: params per param_shardings,
        K/V pools (+ scale pools) sharded over 'tp' on the kv-heads dim,
        block tables / positions / tokens replicated (host-side allocation
        state is identical on every shard). This is the conjunction the
        70B-class llm-d shape needs — TP x paged x continuous batching in
        ONE engine (ref vLLM-TPU TP=16 shape,
        /root/reference/docs/examples/vllm/TPU/lws.yaml:22-34). dp inside
        one pool is deliberately unused: blocks are randomly indexed, so dp
        stays the replica-level axis (see paged_cache_shardings)."""
        if max_len % block_size:
            raise ValueError("max_len must be a multiple of block_size")
        # Chunked-prefill admission (vLLM-scheduler shape, VERDICT r4 #3):
        # with prefill_chunk set, a long prompt is prefilled in fixed-size
        # chunks with `interleave_steps` decode steps dispatched for the
        # ACTIVE slots between chunks — a long submit() can no longer stall
        # every active request for the whole prompt's prefill. Power of two
        # so every bucket (itself pow2) is a whole number of chunks and the
        # padded chunk tail can never overflow the bucket-sized dense cache.
        if prefill_chunk is not None and (
            prefill_chunk < block_size or prefill_chunk & (prefill_chunk - 1)
        ):
            raise ValueError("prefill_chunk must be a power of two >= block_size")
        self.prefill_chunk = prefill_chunk
        self.interleave_steps = interleave_steps
        self.cfg = cfg
        self.mesh = mesh
        self._tp = 1
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as _P

            self._tp = dict(zip(mesh.axis_names, mesh.devices.shape)).get("tp", 1)
            if cfg.n_kv_heads % max(self._tp, 1):
                raise ValueError(
                    f"n_kv_heads={cfg.n_kv_heads} not divisible by tp={self._tp}"
                )
            from lws_tpu.serving.engine import shard_params_for_serving

            params = shard_params_for_serving(params, cfg, mesh)
            self._pool_shardings = jax.tree.map(
                lambda s: NamedSharding(mesh, s), paged_cache_shardings(cfg)
            )
            self._rep = NamedSharding(mesh, _P())
            # Single-request prefill cache: B=1 can't shard over dp.
            self._prefill_cache_shardings = jax.tree.map(
                lambda s: NamedSharding(mesh, s), cache_shardings(cfg, dp=False)
            )
            _sh_prefill = {"out_shardings": (self._rep, self._prefill_cache_shardings)}
            _sh_insert = {"out_shardings": (self._pool_shardings, self._rep, self._rep)}
            _sh_step = {"out_shardings": (
                self._pool_shardings, self._rep, self._rep, self._rep, self._rep
            )}
        else:
            self._pool_shardings = None
            self._rep = None
            _sh_prefill = _sh_insert = _sh_step = {}
        self.params = params
        self.slots = slots
        self.max_len = max_len
        self.block_size = block_size
        self.max_blocks = max_len // block_size
        # Default pool = dense equivalent (+null); callers shrink it for
        # density (that is the whole point).
        self.num_blocks = num_blocks if num_blocks is not None else slots * self.max_blocks + 1
        self._ids = itertools.count()
        self._free_slots = list(range(slots))
        # Deque, FIFO: submit/alloc pop from the LEFT, release appends on the
        # right — the same recycling order the old list gave, without the
        # O(pool) shift every pop(0) paid (pinned by the pool-order test).
        self._free_blocks = collections.deque(range(1, self.num_blocks))  # 0 = null
        self._active: dict[int, PagedRequest] = {}
        self._completed: dict[int, PagedRequest] = {}
        # Automatic prefix caching (vLLM APC shape, opt-in): full prompt
        # blocks are content-addressed by a position-binding hash chain;
        # later prompts sharing a block-aligned prefix reuse the cached
        # blocks and prefill only their suffix. Shareable blocks carry
        # refcounts; at refcount 0 they park in an LRU (contents intact,
        # still mapped) and are evicted only when allocation needs them.
        self.prefix_cache = prefix_cache
        self._prefix_map: dict[bytes, int] = {}      # digest -> pool block
        self._block_digest: dict[int, bytes] = {}    # reverse map
        self._block_refs: dict[int, int] = {}        # shareable-block refs
        self._lru: "dict[int, None]" = {}            # refcount-0, evictable
        self.stats_prefix = {
            "hit_tokens": 0, "hit_blocks": 0, "evictions": 0,
            "spills": 0, "host_hits": 0, "remote_hits": 0,
        }
        # Hierarchical prefix tiers (ISSUE 18): `host_arena` catches evicted
        # parked blocks (device->host spill) so a later miss restores instead
        # of recomputing; `remote_prefix` (a RemotePrefixSource) consults
        # warm siblings over the KV wire when the arena misses too. Both are
        # opt-in; the arena defaults from LWS_TPU_KV_HOST_ARENA_MB.
        self._host_arena = host_arena
        self._remote_prefix = remote_prefix
        self._prefix_source_name: Optional[str] = None
        if prefix_cache:
            import weakref

            from lws_tpu.serving import kv_host_arena as _kha

            if host_arena is None:
                self._host_arena = _kha.from_env()
            # Advertise this engine's resident + spilled digests for
            # GET /debug/prefixes (weakly: a dead engine's provider returns
            # None and the registry prunes it).
            _self = weakref.ref(self)

            def _prefix_snapshot():
                eng = _self()
                if eng is None:
                    return None
                return {
                    "block_size": eng.block_size,
                    "digests": list(eng._prefix_map),
                    "arena_digests": (
                        eng._host_arena.digests()
                        if eng._host_arena is not None else []
                    ),
                }

            self._prefix_source_name = f"paged-engine-{id(self):x}"
            _kha.register_prefix_source(
                self._prefix_source_name, _prefix_snapshot
            )
        # Request mid-chunked-admission: holds allocated blocks but is not
        # in _active yet — pool_accounting counts its blocks as live so the
        # interleaved decode steps' gauge updates stay conserved.
        self._admitting: Optional[PagedRequest] = None

        cfg_static = cfg
        self._cfg_static = cfg
        self._sh_step = _sh_step

        with self._mesh_ctx():
            self.cache = jax.jit(
                lambda: init_paged_cache(cfg_static, self.num_blocks, block_size),
                **({"out_shardings": self._pool_shardings} if mesh is not None else {}),
            )()
        self.table = np.zeros((slots, self.max_blocks), np.int32)  # host truth
        self.pos_b = jnp.zeros((slots,), jnp.int32)
        self.tokens = jnp.zeros((slots,), jnp.int32)
        # Per-slot sampling state (host truth, tiny; shipped per dispatch).
        self.temp = np.zeros((slots,), np.float32)
        self.top_k = np.zeros((slots,), np.int32)
        self.top_p = np.ones((slots,), np.float32)
        # Pinned replicated up front: every later _keys value comes out of a
        # jitted fn with replicated out_shardings, so only this initial
        # array could reach a dispatch uncommitted (GSPMD may shard it and
        # the shard_map'd kernel expects it whole).
        self._keys = self._put_rep(jax.random.split(jax.random.key(0), slots))
        # Pipelined dispatch (ISSUE 3): a bounded in-flight ring of decode
        # chunks — the host consumes chunk N's tokens while chunk N+1 runs
        # on device. depth=0 restores the strictly synchronous loop. The
        # default is backend-dependent because overlap on CPU requires
        # giving up step donation (see donate_steps below) and the per-step
        # pool copy costs more than the ~ms-scale host windows overlap
        # saves — CPU defaults to the donating synchronous loop, real
        # accelerators to depth 2 (decode_overlap_bench pins both modes
        # explicitly, so its comparison is backend-independent).
        if pipeline_depth is None:
            pipeline_depth = 0 if jax.default_backend() == "cpu" else 2
        self._pipeline = DecodePipeline(depth=pipeline_depth, engine="paged")
        # Host-built dispatch inputs are device-resident, dirty-tracked
        # buffers: admission/release marks them dirty; step_n re-uploads
        # only what changed instead of jnp.asarray-ing every dispatch.
        self._active_mask = np.zeros((slots,), bool)
        self._active_dev = None
        self._table_dev = None
        self._sampling_dev = None
        self._dirty_active = self._dirty_table = self._dirty_sampling = True
        # Sampled-slot counter (maintained by _assign_sampling/_release):
        # replaces the per-dispatch any() scan over self._active.
        self._sampled_active = 0
        # Device-resident speculative state (ISSUE 9): a per-slot token
        # history ring (global token t at column t % H) for in-kernel n-gram
        # drafting, plus per-slot remaining-token budgets so acceptance can
        # clamp in-kernel. Both are maintained by _spec_step itself; they go
        # stale ONLY when plain step_n dispatches advance tokens without
        # them (_spec_fresh), and are rebuilt from host truth at the next
        # spec-mode entry. hist rows are tiny (slots x H i32) next to the
        # KV pool.
        self.spec_history = spec_history if spec_history is not None else max_len
        if self.spec_history < 2:
            raise ValueError("spec_history must be >= 2")
        self._hist = self._put_rep(jnp.zeros((slots, self.spec_history), jnp.int32))
        self._hist_len = self._put_rep(jnp.zeros((slots,), jnp.int32))
        self._rem = self._put_rep(jnp.zeros((slots,), jnp.int32))
        self._spec_fresh = False

        @partial(jax.jit, **(
            {"out_shardings": (self._rep,) * 3} if mesh is not None else {}
        ))
        def _seed_spec(hist, hist_len, rem, slot, window, total, rem_v):
            return (
                hist.at[slot].set(window),
                hist_len.at[slot].set(total),
                rem.at[slot].set(rem_v),
            )

        self._seed_spec = _seed_spec
        if donate_steps is None:
            # CPU PJRT blocks a dispatch whose donated input is still being
            # computed — donation there would serialize the pipeline back to
            # the synchronous loop. TPU runtimes donate in-flight buffers
            # without blocking, and there the in-place pool update is the
            # memory win donation exists for. With pipelining OFF the
            # donated input is always a fully-consumed chunk's output, so
            # donation keeps its in-place win on every backend (the
            # two-point-differencing benches run depth 0 and rely on it).
            donate_steps = pipeline_depth == 0 or jax.default_backend() != "cpu"
        self._donate_steps = donate_steps

        @partial(jax.jit, **_sh_prefill)
        def _prefill_one(params, prompt, last_pos):
            cache = init_cache(cfg_static, 1, prompt.shape[1])
            logits, cache = forward_prefill(
                params, prompt, cache, cfg_static, last_pos=last_pos
            )
            return logits, cache  # [1, V]: the caller samples per-request

        @jax.jit
        def _sample_first(logits, key, temp, top_k, top_p):
            from lws_tpu.serving.engine import sample_logits_per_slot

            return sample_logits_per_slot(
                logits, key[None], temp[None], top_k[None], top_p[None]
            )[0]

        self._sample_first = _sample_first

        # Jitted: self.tokens/self._keys may be GLOBAL (non-addressable)
        # arrays in a multi-process mesh, where eager .at[].set is not
        # allowed. One helper serves both (jit specializes per dtype).
        self._set_at = jax.jit(
            lambda arr, idx, val: arr.at[idx].set(val),
            **({"out_shardings": self._rep} if mesh is not None else {}),
        )

        @partial(jax.jit, donate_argnums=(0,), **_sh_insert)
        def _insert(cache, slot_k, slot_v, block_ids, pos_b, tokens, slot, plen,
                    first_token, slot_ks=None, slot_vs=None):
            cache = paged_insert(cache, slot_k, slot_v, block_ids, slot_ks, slot_vs)
            return cache, pos_b.at[slot].set(plen), tokens.at[slot].set(first_token)

        quant = cfg.kv_quant
        _sh_insert_prefix = (
            {"out_shardings": (self._pool_shardings, self._rep, self._rep)}
            if mesh is not None else {}
        )

        def _dense_view(cache, block_ids, pad, hit_len):
            """Pool blocks -> dense KVCache [L, 1, bucket+pad, ...] at
            pos=hit_len: hit blocks carry cached prefix K/V, new blocks
            carry garbage the suffix pass overwrites. Shared by the one-shot
            prefix insert and the chunked-admission gather."""
            from lws_tpu.models.llama import KVCache

            L = cache.k.shape[0]
            bucket = block_ids.shape[0] * cache.block_size

            def view(pool):  # [L, nb, bs, ...] -> [L, 1, bucket(+pad), ...]
                v = pool[:, block_ids].reshape(L, 1, bucket, *pool.shape[3:])
                padz = jnp.zeros((L, 1, pad, *pool.shape[3:]), pool.dtype)
                return jnp.concatenate([v, padz], axis=2)

            return KVCache(
                k=view(cache.k), v=view(cache.v),
                pos=hit_len.astype(jnp.int32),
                k_scale=view(cache.k_scale) if quant else None,
                v_scale=view(cache.v_scale) if quant else None,
            )

        @partial(jax.jit, donate_argnums=(1,), **_sh_insert_prefix)
        def _insert_with_prefix(params, cache, suffix, block_ids, hit_len,
                                last_off, pos_b, slot, plen):
            """Prefix-cache admission: gather the slot's table blocks into a
            dense view, run the SUFFIX only through forward_with_cache at
            pos=hit_len, scatter the view back. Returns (cache, pos_b',
            last-token logits [1, V]). The hit-block scatter rewrites
            identical bytes — harmless, and it keeps one code path for
            quantized and plain pools."""
            from lws_tpu.models.llama import forward_with_cache

            bucket = block_ids.shape[0] * cache.block_size
            dense = _dense_view(cache, block_ids, suffix.shape[1], hit_len)
            logits, dense = forward_with_cache(
                params, suffix, dense, cfg_static, last_offset=last_off
            )
            scales = (
                (dense.k_scale[:, 0, :bucket], dense.v_scale[:, 0, :bucket])
                if quant else ()
            )
            cache = paged_insert(
                cache, dense.k[:, 0, :bucket], dense.v[:, 0, :bucket],
                block_ids, *scales,
            )
            return cache, pos_b.at[slot].set(plen), logits

        self._insert_with_prefix = _insert_with_prefix

        # Spill-tier restore: scatter one host-resident block's K/V back
        # into the pool (donated — the pool updates in place, same contract
        # as _insert). paged_insert with a single block id IS the
        # dynamic_update_slice upload the CacheAssembler path uses, shapes
        # included: [L, bs, Hkv, hd] dense rows -> pool block `block_id`.
        @partial(jax.jit, donate_argnums=(0,), **(
            {"out_shardings": self._pool_shardings} if mesh is not None else {}
        ))
        def _restore_insert(cache, blk_k, blk_v, block_id, blk_ks=None, blk_vs=None):
            return paged_insert(cache, blk_k, blk_v, block_id, blk_ks, blk_vs)

        self._restore_insert = _restore_insert

        # ---- chunked-prefill admission helpers ---------------------------
        # One dense [1, width] cache is built per admission (width = bucket,
        # or bucket+chunk for the prefix path), filled chunk by chunk, then
        # scattered into the pool in one go. Compile set stays bounded:
        # _chunk_append specializes per (chunk, width); widths are pow2
        # buckets, the chunk size is fixed.
        _sh_chunk = (
            {"out_shardings": (self._rep, self._prefill_cache_shardings)}
            if mesh is not None else {}
        )

        @partial(jax.jit, donate_argnums=(2,), **_sh_chunk)
        def _chunk_append(params, chunk, cache):
            from lws_tpu.models.llama import forward_prefill_chunk

            return forward_prefill_chunk(params, chunk, cache, cfg_static)

        @partial(jax.jit, **({"out_shardings": self._rep} if mesh is not None else {}))
        def _chunk_logits(params, hidden, last_off):
            from lws_tpu.models.quant import matmul as _qmm

            h = jnp.take_along_axis(
                hidden,
                jnp.broadcast_to(
                    jnp.reshape(last_off, (1, 1, 1)), (1, 1, hidden.shape[-1])
                ),
                axis=1,
            )[:, 0]
            return _qmm(h, params["lm_head"]).astype(jnp.float32)

        _sh_scatter = (
            {"out_shardings": (self._pool_shardings, self._rep)}
            if mesh is not None else {}
        )

        # Only the pool is donated: the dense chunk cache's buffers cannot
        # alias the pool-shaped outputs (donating them just warns).
        @partial(jax.jit, donate_argnums=(0,), **_sh_scatter)
        def _scatter_dense(cache, dense, block_ids, pos_b, slot, plen):
            """Scatter a chunk-filled dense cache's first bucket rows into
            the pool blocks and commit the slot's position. Rows past the
            true prompt length carry padded-chunk garbage — position-masked
            out of attention and overwritten by decode appends, exactly like
            the one-shot path's padded tail."""
            bucket = block_ids.shape[0] * cache.block_size
            scales = (
                (dense.k_scale[:, 0, :bucket], dense.v_scale[:, 0, :bucket])
                if quant else ()
            )
            cache = paged_insert(
                cache, dense.k[:, 0, :bucket], dense.v[:, 0, :bucket],
                block_ids, *scales,
            )
            return cache, pos_b.at[slot].set(plen)

        _sh_view = (
            {"out_shardings": self._prefill_cache_shardings}
            if mesh is not None else {}
        )

        @partial(jax.jit, static_argnums=(2,), **_sh_view)
        def _gather_view(cache, block_ids, pad, hit_len):
            """Jitted _dense_view (the chunked-admission entry: chunks then
            append incrementally outside this dispatch)."""
            return _dense_view(cache, block_ids, pad, hit_len)

        self._chunk_append = _chunk_append
        self._chunk_logits = _chunk_logits
        self._scatter_dense = _scatter_dense
        self._gather_view = _gather_view
        self._chunk_cache_init: dict = {}  # width -> jitted dense-cache init

        self._prefill_one = _prefill_one
        self._insert = _insert
        # Attention path: the kernel's first real-chip contact happens inside
        # a serving engine, so a compile failure must fall back, not crash
        # (VERDICT r3 next #4). stats records which path actually serves.
        from lws_tpu.models.llama import paged_kernel_default

        kernel_intent = paged_kernel_default()
        self.stats = {
            "attention_path": "kernel" if kernel_intent else "xla_fallback",
            "chunked_admissions": 0,
            "interleaved_decode_steps": 0,
        }
        # The kernel's first step is the compile probe: run it WITHOUT cache
        # donation (a post-compile runtime failure would have consumed the
        # donated pool, leaving nothing for the fallback retry); switch to
        # the donating executable once the kernel has proven itself.
        self._kernel_probed = not kernel_intent
        self._use_kernel = kernel_intent
        # Step executables, cached per (use_kernel, donate, sample): the
        # all-greedy default must stay a single argmax — the full sampling
        # pipeline (two [slots, V] sorts + softmax + cumsum + categorical)
        # would tax every decode step of the benchmarked path for nothing.
        self._step_cache: dict = {}
        # HBM attribution (lws_tpu/obs/device.py): the two big pools this
        # engine owns, published as serving_hbm_pool_bytes{pool} on the
        # scrape-time refresh (workspace is the allocator residual).
        devicemod.set_pool_bytes("weights", _tree_nbytes(self.params))
        devicemod.set_pool_bytes("kv", _tree_nbytes(self.cache))
        self._update_pool_gauges()  # capacity gauges valid from first scrape

    def _get_step_fn(self, sample: bool):
        donate = self._kernel_probed and self._donate_steps
        key = (self._use_kernel, donate, sample)
        if key not in self._step_cache:
            self._step_cache[key] = self._make_step_n(
                use_kernel=self._use_kernel, donate=donate, sample=sample
            )
        return self._step_cache[key]

    def _dispatch_inputs(self):
        """Device-resident dispatch inputs, re-uploaded only when dirty.
        Old in-flight chunks keep references to the arrays they were
        dispatched with — a dirty rebuild replaces the cached handle, never
        mutates a buffer under a dispatched executable. Each upload COPIES
        the host array: jnp.asarray of an aligned numpy array can be
        ZERO-COPY on the CPU backend, and an aliased buffer would let the
        next host-side admission/release mutate an input an in-flight chunk
        is still reading (nondeterministic tokens — caught by the
        pipelined-vs-sync prefix-cache equivalence test)."""
        if self._dirty_active:
            self._active_dev = self._put_rep(jnp.asarray(np.array(self._active_mask)))
            self._dirty_active = False
            devicemod.record_transfer("paged.dispatch_inputs",
                                      self._active_mask.nbytes)
        if self._dirty_table:
            self._table_dev = self._put_rep(jnp.asarray(np.array(self.table)))
            self._dirty_table = False
            devicemod.record_transfer("paged.dispatch_inputs",
                                      self.table.nbytes)
        if self._dirty_sampling:
            self._sampling_dev = tuple(
                self._put_rep(jnp.asarray(np.array(a)))
                for a in (self.temp, self.top_k, self.top_p)
            )
            self._dirty_sampling = False
            devicemod.record_transfer(
                "paged.dispatch_inputs",
                self.temp.nbytes + self.top_k.nbytes + self.top_p.nbytes)
        return self._active_dev, self._table_dev, (self._keys, *self._sampling_dev)

    def _make_step_n(self, use_kernel: bool, donate: bool = True, sample: bool = False):
        cfg_static = self._cfg_static
        tp_static = self._tp

        @partial(
            jax.jit,
            static_argnums=(6,),
            **({"donate_argnums": (1,)} if donate else {}),
            **self._sh_step,
        )
        def _step_n(params, cache, table, tokens, pos_b, active, n, keys, temp, top_k, top_p):
            # n chained steps in ONE dispatch (lax.scan): admission state is
            # frozen for the chunk, so callers bound n by the soonest
            # completion. Kills the per-step host round trip that dominates
            # relay-backed links (same trick as Engine.decode_n).
            from lws_tpu.serving.engine import sample_logits_per_slot

            def body(carry, _):
                cache, tokens, pos_b, keys = carry
                logits, cache = forward_decode_paged(
                    params, tokens, cache, table, pos_b, cfg_static,
                    tp_shard=tp_static, use_kernel=use_kernel,
                )
                if sample:
                    # Each slot advances ITS OWN stream; inactive slots
                    # advance too (harmless — a new occupant reseeds).
                    split = jax.vmap(jax.random.split)(keys)  # [slots, 2]
                    step_keys, keys = split[:, 0], split[:, 1]
                    nxt = sample_logits_per_slot(logits, step_keys, temp, top_k, top_p)
                else:  # all-greedy batch: plain argmax, keys pass through
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                tokens = jnp.where(active, nxt, tokens)
                pos_b = jnp.where(active, pos_b + 1, pos_b)
                return (cache, tokens, pos_b, keys), tokens

            (cache, tokens, pos_b, keys), toks = jax.lax.scan(
                body, (cache, tokens, pos_b, keys), None, length=n
            )
            return cache, tokens, pos_b, toks, keys  # toks [n, slots]

        return _step_n

    def _mesh_ctx(self):
        """Ambient-mesh context for tracing/executing the jitted phases: the
        shard_map inside the paged kernel path (and shardings resolution)
        needs jax.set_mesh when the engine is mesh-sharded."""
        import contextlib

        return jax.set_mesh(self.mesh) if self.mesh is not None else contextlib.nullcontext()

    def _put_rep(self, x):
        """Pin a host-built input replicated on the mesh. In MULTI-PROCESS
        meshes device_put rejects non-addressable shardings — there the raw
        (identical-on-every-process) host array goes straight into the jit,
        which is the supported multi-controller pattern; the explicit pin
        only exists to stop single-process GSPMD from re-sharding host
        inputs under the shard_map'd kernel."""
        if self.mesh is None or not self._rep.is_fully_addressable:
            return x
        return jax.device_put(x, self._rep)

    # ------------------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        # LRU-parked blocks are allocatable (evict-on-demand) — they count
        # toward the backpressure signal.
        return len(self._free_blocks) + len(self._lru)

    def pool_accounting(self) -> dict[str, int]:
        """Block-pool state counts. `live` is computed from the blocks
        requests ACTUALLY hold (not derived as the residual), so the
        conservation invariant — free + live + parked == num_blocks - 1,
        block 0 being the never-allocated null block — genuinely detects a
        leaked or double-counted block instead of hiding it in the residual.
        Holds at every quiescent point (pinned by
        tests/test_profile_plane.py); a request mid-chunked-admission counts
        as live via `_admitting`."""
        live_blocks: set[int] = set()
        for req in self._active.values():
            live_blocks.update(req.blocks)
        if self._admitting is not None:
            live_blocks.update(self._admitting.blocks)
        return {
            "free": len(self._free_blocks),
            "parked": len(self._lru),
            "live": len(live_blocks),
            "total": self.num_blocks - 1,
        }

    def _update_pool_gauges(self) -> None:
        acct = self.pool_accounting()
        for state in ("free", "live", "parked"):
            metrics.set(
                "serving_kv_pool_blocks", acct[state],
                {"engine": "paged", "state": state},
            )

    # ---- prefix caching ------------------------------------------------
    def set_remote_prefix(self, source) -> None:
        """Wire (or clear) the remote tier after construction — workers
        learn the sibling digest index from the control plane long after
        the engine exists."""
        self._remote_prefix = source

    def _block_digests(self, prompt: np.ndarray, n: int) -> list[bytes]:
        """Position-binding hash chain over the first n full blocks: block
        i's digest commits to ALL tokens in [0, (i+1)*bs) — equal digests
        mean equal tokens at equal positions, which is exactly when K/V
        match (RoPE binds position)."""
        import hashlib

        bs = self.block_size
        d = b"\x00" * 16
        out = []
        for i in range(n):
            chunk = np.ascontiguousarray(prompt[i * bs:(i + 1) * bs], dtype=np.int32)
            d = hashlib.blake2b(d + chunk.tobytes(), digest_size=16).digest()
            out.append(d)
        return out

    def _alloc_blocks(self, n: int) -> Optional[list[int]]:
        """Allocate n pool blocks, evicting LRU-parked prefix blocks on
        demand (unmapping their digests). Returns None when the pool cannot
        supply n — checked UP FRONT so a refused oversized request cannot
        flush parked prefixes it would never have used."""
        if self._pipeline and n > len(self._free_blocks):
            # Eviction (or an allocation failure) ahead with chunks still in
            # flight: consume them first. Retiring requests both returns
            # their private blocks (the allocation may no longer need to
            # evict at all) and guarantees eviction can never reclaim a
            # block an in-flight dispatch could still read.
            self._pipeline.flush()
        if n > len(self._free_blocks) + len(self._lru):
            return None
        out: list[int] = []
        while len(out) < n:
            if self._free_blocks:
                out.append(self._free_blocks.popleft())
                continue
            if self._lru:
                blk = next(iter(self._lru))
                del self._lru[blk]
                digest = self._block_digest.pop(blk, None)
                # Guarded: only unmap the digest if it still points at THIS
                # block (a re-registration after a partial eviction may have
                # remapped it to a newer block that must stay discoverable).
                if digest is not None and self._prefix_map.get(digest) == blk:
                    self._prefix_map.pop(digest, None)
                    # Spill instead of drop: the flush above guarantees no
                    # in-flight chunk can still read this block, so the
                    # device->host gather here sees its final contents. Only
                    # mapped evictions spill — an unmapped block's bytes are
                    # unreachable by digest anyway.
                    if self._host_arena is not None:
                        self._spill_block(blk, digest)
                self._block_refs.pop(blk, None)
                self.stats_prefix["evictions"] += 1
                metrics.inc(
                    "serving_prefix_cache_evictions_total", {"engine": "paged"}
                )
                out.append(blk)
                continue
            self._free_blocks.extendleft(reversed(out))  # undo: restore order
            return None
        return out

    def _spill_block(self, blk: int, digest: bytes) -> None:
        """Evicted parked block -> host arena (tentpole (a) write side): one
        device->host gather of the block's pool rows, packed by the arena
        into pack_payload wire format. Eviction proceeds identically whether
        the arena accepted the entry or dropped it as oversized."""
        arrays = {
            "k": np.asarray(self.cache.k[:, blk]),
            "v": np.asarray(self.cache.v[:, blk]),
        }
        if self.cfg.kv_quant:
            arrays["k_scale"] = np.asarray(self.cache.k_scale[:, blk])
            arrays["v_scale"] = np.asarray(self.cache.v_scale[:, blk])
        if self._host_arena.put(digest, arrays):
            self.stats_prefix["spills"] += 1

    def _restore_block(self, digest: bytes, arrays: dict) -> Optional[int]:
        """Upload one spilled/fetched block into a freshly allocated pool
        block, map its digest, and take this admission's ref on it (its
        release path is the shared-block refcount, exactly like an HBM hit).
        Returns None when the pool cannot supply a block — the caller stops
        extending the hit chain and prefills the rest."""
        got = self._alloc_blocks(1)
        if got is None:
            return None
        blk = got[0]
        nbytes = sum(int(a.nbytes) for a in arrays.values())
        with self._mesh_ctx():
            scales = ()
            if self.cfg.kv_quant:
                scales = (
                    self._put_rep(jnp.asarray(arrays["k_scale"])),
                    self._put_rep(jnp.asarray(arrays["v_scale"])),
                )
            self.cache = self._restore_insert(
                self.cache,
                self._put_rep(jnp.asarray(arrays["k"])),
                self._put_rep(jnp.asarray(arrays["v"])),
                self._put_rep(jnp.asarray([blk], jnp.int32)),
                *scales,
            )
        self._prefix_map[digest] = blk
        self._block_digest[blk] = digest
        self._block_refs[blk] = self._block_refs.get(blk, 0) + 1
        metrics.inc(
            "serving_kv_spill_bytes_total", {"direction": "restore"},
            value=float(nbytes),
        )
        devicemod.record_transfer("paged.kv_restore", nbytes)
        return blk

    def _assign_sampling(self, slot: int, temperature, top_k, top_p, seed):
        """Write the slot's sampling params and derive its request key.
        Shared by both admission paths — drift here would diverge cached vs
        uncached sampling behavior."""
        self.temp[slot] = temperature
        self.top_k[slot] = top_k
        self.top_p[slot] = top_p
        self._dirty_sampling = True
        if temperature > 0.0:
            # Counter, not a per-dispatch scan: _release decrements when the
            # request retires, so `> 0` is exactly "any sampled slot live".
            self._sampled_active += 1
        # Unseeded sampling must be nondeterministic (vLLM seed=None): draw
        # from process entropy, not a counter — a counter would collide with
        # small user seeds and make every dp replica replay identical
        # "random" samples. User seeds stay a pure function of the seed.
        if seed is None:
            import os as _os

            # 63 bits: jax.random.key seeds go through np.int64.
            seed = int.from_bytes(_os.urandom(8), "little") >> 1
            if self.mesh is not None and not self._rep.is_fully_addressable:
                # Multi-process mesh: per-process urandom would diverge the
                # logically-replicated key state (each process sampling
                # different tokens for one logical slot). Broadcast process
                # 0's entropy so unseeded sampling stays nondeterministic
                # AND coherent. Safe ordering: admissions are deterministic
                # and identical on every process.
                from jax.experimental import multihost_utils

                halves = np.array([seed & 0xFFFFFFFF, seed >> 32], np.uint32)
                halves = np.asarray(multihost_utils.broadcast_one_to_all(halves))
                seed = int(halves[0]) | (int(halves[1]) << 32)
        return jax.random.key(seed)

    def _sample_first_token(self, logits, req_key, slot, temperature, top_k, top_p):
        """Sample the post-prefill token from this request's stream and park
        the stream key on the slot. Caller holds the mesh context."""
        first_key, slot_key = jax.random.split(req_key)
        first = self._sample_first(
            logits, first_key,
            jnp.float32(temperature), jnp.int32(top_k), jnp.float32(top_p),
        )
        self._keys = self._set_at(self._keys, slot, slot_key)
        return first

    def _finish_admission(self, req: PagedRequest, first) -> int:
        req.tokens.append(int(first))
        if req.slo is not None:
            # The prefill token in hand marks TTFT on the arrival clock
            # (queue wait was recorded at slot acquisition).
            req.slo.first_token()
        if req.done:
            if req.slo is not None:
                req.slo.finish()
            self._completed[req.request_id] = req
            self._release(req)
        else:
            self._active[req.slot] = req
            self._active_mask[req.slot] = True
            self._dirty_active = True
            if self._spec_fresh:
                # Mid-stream admission during steady-state speculation:
                # host truth for THIS request is exact right now, so its
                # device spec rows are written directly — no ring flush, no
                # full-state rebuild.
                self._seed_spec_slot(req)
        return req.request_id

    def _spec_slot_state(self, req: PagedRequest) -> tuple[np.ndarray, int, int]:
        """One slot's device spec rows from host truth: the history window
        laid out on the ring invariant (global token t at column t % H —
        the ONE place that invariant is encoded), the total token count,
        and the remaining budget. Shared by admission-time seeding and the
        spec-mode refresh so the two can never drift."""
        H = self.spec_history
        ctx = [int(t) for t in req.prompt] + req.tokens
        L = len(ctx)
        W = min(L, H)
        window = np.zeros((H,), np.int32)
        window[np.arange(L - W, L) % H] = ctx[-W:]
        return window, L, remaining_steps(req, self.max_len)

    def _seed_spec_slot(self, req: PagedRequest) -> None:
        """Write one slot's device speculative state (history window,
        remaining budget) from its admission-time host truth."""
        window, total, rem_v = self._spec_slot_state(req)
        with self._mesh_ctx():
            self._hist, self._hist_len, self._rem = self._seed_spec(
                self._hist, self._hist_len, self._rem, req.slot,
                self._put_rep(jnp.asarray(window)),
                jnp.int32(total), jnp.int32(rem_v),
            )

    def _refresh_spec_state(self) -> None:
        """Rebuild the device speculative state for EVERY slot from host
        truth. Requires (and performs) a ring flush so host truth is exact —
        this is the one flush the speculative path keeps: entering spec mode
        after plain step_n dispatches, or after a dispatch rollback. The
        steady-state spec loop never comes through here."""
        self._pipeline.flush()
        H = self.spec_history
        hist = np.zeros((self.slots, H), np.int32)
        hlen = np.zeros((self.slots,), np.int32)
        rem = np.zeros((self.slots,), np.int32)
        for s, r in self._active.items():
            hist[s], hlen[s], rem[s] = self._spec_slot_state(r)
        self._hist = self._put_rep(jnp.asarray(hist))
        self._hist_len = self._put_rep(jnp.asarray(hlen))
        self._rem = self._put_rep(jnp.asarray(rem))
        self._spec_fresh = True

    def _rollback_to_host_truth(self) -> None:
        """Restore device decode truth (pos_b/tokens) from host truth after
        in-flight chunks were discarded: un-consumed device commits are
        abandoned, and pos_b IS the paged cache's rewind (rows past it are
        masked out of attention and overwritten by later appends). The
        device spec state is marked stale so the next spec dispatch rebuilds
        it from the same host truth."""
        pos = np.zeros((self.slots,), np.int32)
        tok = np.zeros((self.slots,), np.int32)
        for s, r in self._active.items():
            pos[s] = len(r.prompt) + len(r.tokens) - 1
            tok[s] = r.tokens[-1]
        self.pos_b = self._put_rep(jnp.asarray(pos))
        self.tokens = self._put_rep(jnp.asarray(tok))
        self._spec_fresh = False

    def _retire(self, slot: int, req: PagedRequest) -> None:
        """Move a finished request out of the active set and return its
        resources. Called from the pipeline's commit path and the
        speculative loop — the ONLY places a slot leaves self._active. The
        identity check makes retire idempotent as a whole: a request already
        retired by an earlier chunk's commit must not release twice (a
        double _release would double-free its blocks and underflow the
        sampled-slot counter)."""
        if req.slo is not None:
            req.slo.finish()  # idempotent: later duplicate retires no-op
        self._completed[req.request_id] = req
        if self._active.get(slot) is not req:
            return
        del self._active[slot]
        self._active_mask[slot] = False
        self._dirty_active = True
        self._release(req)

    def submit(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: Optional[int] = None,
        klass: str = "",
        arrival_t: Optional[float] = None,
    ) -> Optional[int]:
        """Admit a request; returns request id, or None when out of slots OR
        out of pool blocks (the density backpressure signal). Sampling is
        per-request (vLLM SamplingParams shape): temperature <= 0 is greedy;
        with temperature > 0, `seed` pins this request's PRNG stream
        (auto-assigned otherwise) — sampled and greedy requests mix freely
        in one batch without perturbing each other. With prefix_cache=True,
        block-aligned prompt prefixes already resident in the pool are
        REUSED: only the suffix is prefilled (vLLM automatic-prefix-caching
        shape; exactness-tested against the uncached engine). `klass`
        labels the request's SLO/goodput series by workload class;
        `arrival_t` (a time.perf_counter() stamp) backdates the SLO arrival
        clock so open-loop admission delay shows up as queue wait."""
        t0 = time.perf_counter()
        # Arrival clock starts at submit() unless the caller backdates it.
        timeline = slo.request("paged", arrival_t, klass=klass)
        with trace.span(
            "serve.admission", engine="paged", prompt_len=len(prompt)
        ) as sp:
            rid = self._submit(
                prompt, max_new_tokens, temperature, top_k, top_p, seed,
                timeline=timeline,
            )
            sp.set(admitted=rid is not None)
        if rid is not None:
            metrics.inc("serving_requests_total", {"engine": "paged"})
            metrics.observe(
                "serving_admission_duration_seconds",
                time.perf_counter() - t0, {"engine": "paged"},
            )
            metrics.set(
                "serving_active_slots", len(self._active), {"engine": "paged"}
            )
        # Unconditional: a REFUSED admission may still have flushed the ring
        # (retiring requests) or evicted parked blocks — the pool gauges
        # must reflect whatever state the attempt left behind.
        self._update_pool_gauges()
        return rid

    def _submit(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: Optional[int] = None,
        timeline: "slo.RequestTimeline | None" = None,
    ) -> Optional[int]:
        if timeline is None:
            timeline = slo.request("paged")
        if not self._free_slots and self._pipeline:
            # Backpressure with chunks in flight: completions may be sitting
            # unconsumed in the ring — consume before refusing admission.
            self._pipeline.flush()
        if not self._free_slots:
            return None
        plen = len(prompt)
        if plen + max_new_tokens > self.max_len:
            raise ValueError("prompt + max_new_tokens exceeds max_len")
        # Same power-of-two length bucketing as BatchEngine, floored at one
        # block so the prefill scatter is block-aligned.
        bucket = self.block_size
        while bucket < plen:
            bucket *= 2
        bucket = min(bucket, self.max_len)
        footprint = max(bucket, plen + max_new_tokens)
        n_blocks = -(-footprint // self.block_size)
        if self.prefix_cache:
            return self._submit_prefix(
                prompt, max_new_tokens, temperature, top_k, top_p, seed,
                plen, bucket, n_blocks, timeline,
            )
        if n_blocks > len(self._free_blocks) and self._pipeline:
            self._pipeline.flush()  # in-flight completions may free blocks
        if n_blocks > len(self._free_blocks):
            return None
        slot = self._free_slots.pop(0)
        timeline.queue_wait()  # arrival -> slot (includes any ring flushes)
        blocks = [self._free_blocks.popleft() for _ in range(n_blocks)]
        req = PagedRequest(
            next(self._ids), np.asarray(prompt), max_new_tokens, slot=slot,
            blocks=blocks, temperature=temperature, top_k=top_k, top_p=top_p,
            seed=seed, slo=timeline,
        )
        req_key = self._assign_sampling(slot, temperature, top_k, top_p, seed)
        if self.prefill_chunk is not None and plen > self.prefill_chunk:
            self.table[slot] = 0  # null-mapped until _admit_chunked commits
            self._dirty_table = True
            first = self._admit_chunked(req, req_key, blocks, bucket, plen, 0, None)
            return self._finish_admission(req, first)
        self.table[slot] = 0
        self.table[slot, :n_blocks] = blocks
        self._dirty_table = True

        padded = np.zeros((bucket,), np.int32)
        padded[:plen] = prompt
        with trace.span("serve.prefill", chunked=False, prompt_len=plen), \
                devicemod.compile_site(
                    "paged.prefill", engine="paged", shape=f"b{bucket}",
                    request_id=timeline.request_id):
            with self._mesh_ctx():
                logits, slot_cache = self._prefill_one(
                    self.params, jnp.asarray(padded)[None, :], jnp.asarray(plen - 1)
                )
                first = self._sample_first_token(
                    logits, req_key, slot, temperature, top_k, top_p
                )
                prefill_ids = jnp.asarray(blocks[: bucket // self.block_size], jnp.int32)
                scales = (
                    (slot_cache.k_scale[:, 0], slot_cache.v_scale[:, 0])
                    if self.cfg.kv_quant
                    else ()
                )
                self.cache, self.pos_b, self.tokens = self._insert(
                    self.cache, slot_cache.k[:, 0], slot_cache.v[:, 0], prefill_ids,
                    self.pos_b, self.tokens, slot, plen, first, *scales,
                )
        return self._finish_admission(req, first)

    def _submit_prefix(
        self, prompt, max_new_tokens, temperature, top_k, top_p, seed,
        plen, bucket, n_blocks, timeline=None,
    ) -> Optional[int]:
        prompt = np.asarray(prompt)
        bs = self.block_size
        # Never cache the FULL prompt: at least one token must be computed
        # so the first-token logits exist (vLLM caps hits the same way).
        shareable_n = (plen - 1) // bs
        digests = self._block_digests(prompt, shareable_n)
        # Tiered hit walk (ISSUE 18): hbm (resident in _prefix_map) -> host
        # (arena restore) -> remote (sibling fetch over the KV wire), in
        # digest-chain order; the first tier-exhausted digest ends the chain.
        # Each hit is PINNED as the walk reaches it — a restore allocates a
        # block, which can LRU-evict, and an unpinned earlier hit could be
        # the victim (its id would alias the restored block: corruption).
        # On a later allocation failure every pin rolls back.
        hits: list[int] = []
        tiers: list[str] = []
        remote_found: Optional[dict] = None
        for i, d in enumerate(digests):
            blk = self._prefix_map.get(d)
            if blk is not None:
                tiers.append("hbm")
            else:
                arrays = (
                    self._host_arena.get(d)
                    if self._host_arena is not None else None
                )
                if arrays is not None:
                    tiers.append("host")
                else:
                    if self._remote_prefix is not None and remote_found is None:
                        # One fetch per admission, for the whole remaining
                        # chain — per-digest round trips would hand the TTFT
                        # win back to wire latency.
                        remote_found = self._remote_prefix.fetch(digests[i:]) or {}
                    arrays = (remote_found or {}).get(d)
                    if arrays is None:
                        break
                    tiers.append("remote")
                blk = self._restore_block(d, arrays)
                if blk is None:
                    tiers.pop()  # pool exhausted: chain ends here
                    break
                hits.append(blk)
                continue  # _restore_block already pinned
            if self._block_refs.get(blk, 0) == 0:
                self._lru.pop(blk, None)
            self._block_refs[blk] = self._block_refs.get(blk, 0) + 1
            hits.append(blk)
        hit_len = len(hits) * bs
        new_needed = n_blocks - len(hits)
        new_blocks = self._alloc_blocks(new_needed)
        if new_blocks is None:
            for blk in hits:  # backpressure: unpin and park again
                self._block_refs[blk] -= 1
                if self._block_refs[blk] <= 0:
                    self._block_refs[blk] = 0
                    self._lru[blk] = None
            return None
        slot = self._free_slots.pop(0)
        if timeline is not None:
            timeline.queue_wait()  # arrival -> slot
        blocks = hits + new_blocks
        req = PagedRequest(
            next(self._ids), prompt, max_new_tokens, slot=slot, blocks=blocks,
            shared_blocks=list(hits), temperature=temperature, top_k=top_k,
            top_p=top_p, seed=seed, slo=timeline,
        )
        req_key = self._assign_sampling(slot, temperature, top_k, top_p, seed)
        chunked = (
            self.prefill_chunk is not None
            and plen - hit_len > self.prefill_chunk
        )
        if not chunked:
            self.table[slot] = 0
            self.table[slot, :n_blocks] = blocks
            self._dirty_table = True

        if chunked:
            # Chunked admission composed with prefix caching: gather the hit
            # blocks into the dense view ONCE (a copy — stable across the
            # interleaved decodes: decode never writes below an active
            # request's prompt length, so pinned hit blocks cannot change
            # under it), append suffix chunks, commit. The view is padded by
            # one chunk so the final padded tail cannot overflow the bucket.
            self.table[slot] = 0  # null-mapped until _admit_chunked commits
            self._dirty_table = True
            dense = None
            if hits:
                with self._mesh_ctx():
                    dense = self._gather_view(
                        self.cache,
                        self._put_rep(jnp.asarray(
                            blocks[: bucket // bs], jnp.int32
                        )),
                        self.prefill_chunk,
                        self._put_rep(jnp.asarray(hit_len, jnp.int32)),
                    )
            first = self._admit_chunked(
                req, req_key, blocks, bucket, plen, hit_len if hits else 0, dense
            )
        elif not hits:
            # Cache miss: the plain prefill path is cheaper (no garbage
            # gather/concat round trip) and compiles per bucket, not per
            # (bucket, suffix) pair. Registration below still publishes the
            # computed blocks for future prompts.
            padded = np.zeros((bucket,), np.int32)
            padded[:plen] = prompt
            with trace.span("serve.prefill", chunked=False, prompt_len=plen), \
                    devicemod.compile_site(
                        "paged.prefill", engine="paged", shape=f"b{bucket}",
                        request_id=timeline.request_id if timeline else ""):
                with self._mesh_ctx():
                    logits, slot_cache = self._prefill_one(
                        self.params, jnp.asarray(padded)[None, :], jnp.asarray(plen - 1)
                    )
                    first = self._sample_first_token(
                        logits, req_key, slot, temperature, top_k, top_p
                    )
                    prefill_ids = jnp.asarray(blocks[: bucket // bs], jnp.int32)
                    scales = (
                        (slot_cache.k_scale[:, 0], slot_cache.v_scale[:, 0])
                        if self.cfg.kv_quant else ()
                    )
                    self.cache, self.pos_b, self.tokens = self._insert(
                        self.cache, slot_cache.k[:, 0], slot_cache.v[:, 0], prefill_ids,
                        self.pos_b, self.tokens, slot, plen, first, *scales,
                    )
        else:
            # Suffix: its own power-of-two bucket (bounded compile set); true
            # rows land in [hit_len, plen) of the dense view, padding spills
            # past `bucket` into the scratch tail the scatter drops.
            s_true = plen - hit_len
            s_suf = 8
            while s_suf < s_true:
                s_suf *= 2
            suffix = np.zeros((s_suf,), np.int32)
            suffix[:s_true] = prompt[hit_len:]
            block_ids = np.asarray(blocks[: bucket // bs], np.int32)
            args = (
                jnp.asarray(suffix)[None, :], jnp.asarray(block_ids),
                jnp.asarray(hit_len, jnp.int32), jnp.asarray(s_true - 1, jnp.int32),
            )
            with trace.span(
                "serve.prefill", chunked=False, prompt_len=plen,
                prefix_hit_tokens=hit_len,
            ), devicemod.compile_site(
                "paged.prefill_suffix", engine="paged",
                shape=f"b{bucket}/s{s_suf}",
                request_id=timeline.request_id if timeline else "",
            ):
                with self._mesh_ctx():
                    args = tuple(self._put_rep(a) for a in args)
                    self.cache, self.pos_b, logits = self._insert_with_prefix(
                        self.params, self.cache, *args, self.pos_b, slot, plen,
                    )
                    first = self._sample_first_token(
                        logits, req_key, slot, temperature, top_k, top_p
                    )
                    self.tokens = self._set_at(self.tokens, slot, first)

        # Register the newly computed shareable blocks for future prompts
        # (this request holds a ref on each until it completes). A digest
        # that is somehow already mapped (partial eviction of a chain's
        # head, then recompute) keeps its existing mapping — our copy stays
        # private so eviction bookkeeping never splits one digest across
        # two blocks.
        for i in range(len(hits), shareable_n):
            d, blk = digests[i], blocks[i]
            if d in self._prefix_map:
                continue
            self._prefix_map[d] = blk
            self._block_digest[blk] = d
            self._block_refs[blk] = self._block_refs.get(blk, 0) + 1
            req.shared_blocks.append(blk)
        self.stats_prefix["hit_tokens"] += hit_len
        self.stats_prefix["hit_blocks"] += len(hits)
        self.stats_prefix["host_hits"] += tiers.count("host")
        self.stats_prefix["remote_hits"] += tiers.count("remote")
        # Hit-rate counters (capacity accounting): hits = shareable blocks
        # served from SOME tier of the hierarchy (labelled hbm/host/remote),
        # misses = shareable blocks this admission had to prefill.
        # hits/(hits+misses) is the cache hit rate `lws-tpu top` renders
        # from the fleet scrape; the tier label splits it (--by-tier).
        if hits:
            for tier in ("hbm", "host", "remote"):
                n_tier = tiers.count(tier)
                if n_tier:
                    metrics.inc(
                        "serving_prefix_cache_hits_total",
                        {"engine": "paged", "tier": tier},
                        value=float(n_tier),
                    )
        if shareable_n > len(hits):
            metrics.inc(
                "serving_prefix_cache_misses_total", {"engine": "paged"},
                value=float(shareable_n - len(hits)),
            )
        return self._finish_admission(req, first)

    def _get_chunk_cache(self, width: int):
        """Fresh dense [1, width] cache for a chunked admission (jitted init
        cached per width; widths are the pow2 buckets)."""
        fn = self._chunk_cache_init.get(width)
        if fn is None:
            cfg_static = self._cfg_static
            kw = (
                {"out_shardings": self._prefill_cache_shardings}
                if self.mesh is not None else {}
            )
            from lws_tpu.models.llama import init_cache as _init_cache

            fn = jax.jit(lambda w=width: _init_cache(cfg_static, 1, w), **kw)
            self._chunk_cache_init[width] = fn
        with self._mesh_ctx():
            return fn()

    def _admit_chunked(
        self, req: PagedRequest, req_key, blocks: list[int], bucket: int,
        plen: int, hit_len: int, dense,
    ):
        """Chunked-prefill admission body (VERDICT r4 #3, the vLLM-scheduler
        shape): fill a dense cache chunk by chunk, dispatching
        `interleave_steps` decode steps for the ACTIVE slots between chunks,
        then commit — sample the first token, bring the table row live, and
        scatter the dense rows into the pool. Exact vs the one-shot path:
        chunked appends produce the same K/V (Engine.prefill_chunked
        property), interleaved decodes only touch OTHER slots, and this
        slot's table row stays null-mapped until commit so those decodes'
        dead writes for it land in the null block, not the fresh blocks."""
        C = self.prefill_chunk
        s_true = plen - hit_len
        n_chunks = -(-s_true // C)
        padded = np.zeros((n_chunks * C,), np.int32)
        padded[:s_true] = req.prompt[hit_len:]
        slot = req.slot
        # The request owns its blocks but is not in _active yet: register it
        # so interleaved decode steps' pool-gauge updates count them live.
        # Cleared in a finally: an exception escaping the prefill body would
        # otherwise pin a stale registration that double-counts the dead
        # request's blocks once they are reused — with it cleared, the
        # abandoned blocks read as a conservation deficit, which is exactly
        # the leak signal the accounting exists to surface.
        self._admitting = req
        try:
            if dense is None:
                # Width must fit every append: when max_len caps the bucket
                # to a non-power-of-two, n_chunks*C can exceed it — and a
                # too-small cache would silently CLAMP the final
                # dynamic_update_slice, overwriting earlier rows with
                # wrong-position K/V. The scatter still takes only the first
                # `bucket` rows.
                dense = self._get_chunk_cache(max(bucket, n_chunks * C))
            hidden = None
            with trace.span(
                "serve.prefill", chunked=True, chunks=n_chunks,
                prompt_len=plen, prefix_hit_tokens=hit_len,
            ), devicemod.compile_site(
                "paged.chunk_prefill", engine="paged",
                shape=f"b{bucket}/c{C}",
                request_id=req.slo.request_id if req.slo else "",
            ):
                for i in range(n_chunks):
                    chunk = jnp.asarray(padded[i * C:(i + 1) * C])[None, :]
                    with self._mesh_ctx():
                        hidden, dense = self._chunk_append(
                            self.params, self._put_rep(chunk), dense
                        )
                    if self._active and self.interleave_steps > 0 and i < n_chunks - 1:
                        executed = self.step_n(self.interleave_steps)
                        self.stats["interleaved_decode_steps"] = (
                            self.stats.get("interleaved_decode_steps", 0) + executed
                        )
                with self._mesh_ctx():
                    logits = self._chunk_logits(
                        self.params, hidden,
                        self._put_rep(jnp.asarray((s_true - 1) % C, jnp.int32)),
                    )
                    first = self._sample_first_token(
                        logits, req_key, slot, req.temperature, req.top_k, req.top_p
                    )
                    # Commit: table row live only now (see docstring).
                    self.table[slot] = 0
                    self.table[slot, : len(blocks)] = blocks
                    self._dirty_table = True
                    prefill_ids = self._put_rep(
                        jnp.asarray(blocks[: bucket // self.block_size], jnp.int32)
                    )
                    self.cache, self.pos_b = self._scatter_dense(
                        self.cache, dense, prefill_ids, self.pos_b, slot, plen
                    )
                    self.tokens = self._set_at(self.tokens, slot, first)
        finally:
            self._admitting = None
        self.stats["chunked_admissions"] = self.stats.get("chunked_admissions", 0) + 1
        return first

    def _release(self, req: PagedRequest) -> None:
        self.table[req.slot] = 0  # dead writes + stale reads -> null block
        self._dirty_table = True
        if req.temperature > 0.0:
            self._sampled_active -= 1
        shared = set(req.shared_blocks)
        for blk in req.blocks:
            if blk in shared:
                # Shared prefix block: drop our ref; at zero it PARKS in the
                # LRU (contents + digest mapping intact) for future hits.
                self._block_refs[blk] -= 1
                if self._block_refs[blk] <= 0:
                    self._block_refs[blk] = 0
                    self._lru[blk] = None
            else:
                self._free_blocks.append(blk)
        req.blocks = []
        req.shared_blocks = []
        self._free_slots.append(req.slot)
        metrics.set("serving_active_slots", len(self._active), {"engine": "paged"})
        self._update_pool_gauges()

    def step(self) -> None:
        """One decode step across every active slot."""
        self.step_n(1)

    def _completion_bound(self) -> int:
        """Steps until the soonest completion/length-overflow among active
        slots — the longest chunk that cannot overrun any budget. One pass:
        both budgets of a slot are folded before crossing slots."""
        return min(remaining_steps(r, self.max_len) for r in self._active.values())

    def step_n(self, n: int) -> int:  # hot-path
        """Up to n decode steps in one device dispatch, PIPELINED: the chunk
        is pushed onto the in-flight ring and its tokens are consumed on a
        later call (or flush) while the device keeps computing — the host
        never blocks on `np.asarray(toks)` inside the dispatch path. Clamped
        to the soonest completion among active slots MINUS the steps already
        in flight (admission state is frozen per chunk, and a slot stepping
        past its block footprint would write into the shared null block
        while its mask starts attending it); when every remaining step of
        the soonest-finishing slot is already in the ring, the ring is
        flushed first and the bound re-clamped over whatever survives.
        Returns the number of steps actually dispatched."""
        if n <= 0:
            return 0
        if not self._active:
            self._pipeline.flush()
            return 0
        bound = self._completion_bound() - self._pipeline.inflight_steps()
        if bound < 1:
            self._pipeline.flush()  # consume; retires re-clamp the bound
            if not self._active:
                return 0
            bound = self._completion_bound()
        probing = not self._kernel_probed and self.stats["attention_path"] == "kernel"
        if probing and self._pipeline:
            # Probe rollback contract: a failed kernel dispatch must leave
            # nothing half-committed — enter the probe with an empty ring.
            self._pipeline.flush()
            if not self._active:
                return 0
            bound = self._completion_bound()
        n = min(n, max(1, bound), 32)
        n = 1 << (n.bit_length() - 1)  # floor pow2: bounded compile set
        # Span + histogram per DISPATCH (not per token): the decode loop is
        # the hot path, and one ~µs span against a ms-scale device dispatch
        # is what keeps tracing always-on viable (trace_overhead_bench).
        t0 = time.perf_counter()
        dispatch_span = trace.span(
            "serve.decode_dispatch", engine="paged", steps=n,
            active=len(self._active), inflight=len(self._pipeline),
        )
        with dispatch_span:
            # Host-side scheduling window: with chunks in flight it overlaps
            # device compute; with an empty ring it counts as host-blocked.
            with self._pipeline.host_section():
                # Dirty-tracked device inputs (already pinned replicated —
                # see _put_rep; uncommitted, GSPMD may shard them and the
                # shard_map'd kernel expects them whole).
                active, table, sampling = self._dispatch_inputs()
                # All-greedy batches (the default and the benchmarked
                # configuration) take the argmax-only executable.
                any_sampled = self._sampled_active > 0
                with devicemod.compile_site(
                    "paged.step_n", engine="paged",
                    shape=f"n{n}/sample={any_sampled}",
                ), self._mesh_ctx():
                    try:
                        step_fn = self._get_step_fn(any_sampled)
                        out = step_fn(
                            self.params, self.cache, table, self.tokens,
                            self.pos_b, active, n, *sampling,
                        )
                        if probing:
                            # JAX dispatch is async: a post-compile pallas
                            # RUNTIME failure only surfaces at the first
                            # blocking consume, which would otherwise happen
                            # chunks later in the pipeline. Force the consume
                            # here, before committing state, so the
                            # no-donation probe can still fall back with the
                            # old cache intact.
                            out = jax.block_until_ready(out)  # vet: ignore[hotpath-host-sync]: one-time probe fence — a pallas runtime failure must surface before state commits
                    except Exception as e:  # noqa: BLE001 — kernel trace/compile/runtime failure
                        if self.stats["attention_path"] != "kernel" or self._kernel_probed:
                            raise
                        # One-time probe semantics: the pallas kernel failed
                        # its first contact with this backend — log, rebuild
                        # the step on the XLA gather path (slower, never
                        # wrong), and keep serving. The probe step ran
                        # WITHOUT donation, so the cache survives even a
                        # post-compile runtime failure. The log line carries
                        # the active trace id + the dispatch's request ids so
                        # a flight-recorder dump correlates the fallback with
                        # the requests that hit it.
                        import sys

                        ctx = trace.current_context() or {}
                        req_ids = sorted(
                            r.request_id for r in self._active.values()
                        )
                        print(
                            f"[paged-engine] pallas kernel failed on "
                            f"{jax.default_backend()!r}: {e!r:.300}; falling back to "
                            f"the XLA gather path "
                            f"(trace_id={ctx.get('trace_id', '-')} "
                            f"requests={req_ids})",
                            file=sys.stderr, flush=True,
                        )
                        flightrecorder.record(
                            "kernel_fallback", engine="paged",
                            error=repr(e)[:300], requests=req_ids,
                        )
                        self.stats["attention_path"] = "xla_fallback"
                        self.stats["kernel_error"] = repr(e)[:300]
                        self._kernel_probed = True
                        self._use_kernel = False
                        out = self._get_step_fn(any_sampled)(
                            self.params, self.cache, table, self.tokens,
                            self.pos_b, active, n, *sampling,
                        )
                    else:
                        if not self._kernel_probed:
                            # Kernel proved itself: subsequent steps may use
                            # the donating executables (in-place pool
                            # updates) where the backend supports async
                            # donation.
                            self._kernel_probed = True
                    self.cache, self.tokens, self.pos_b, toks, self._keys = out
            # Commit runs at consume time: only requests active AT DISPATCH
            # received real tokens from this chunk (later admissions into
            # freed slots computed masked-out garbage for it).
            snapshot = dict(self._active)

            def commit(host_toks, snapshot=snapshot):  # host_toks [n, slots]
                for slot, req in snapshot.items():
                    req.tokens.extend(int(t) for t in host_toks[:, slot])
                    if req.slo is not None:
                        # ITL: per-dispatch mean of this chunk's step gaps.
                        req.slo.tokens(host_toks.shape[0])
                    if req.done or len(req.prompt) + len(req.tokens) >= self.max_len:
                        self._retire(slot, req)

            # Plain decode advanced tokens without the spec history/budget
            # arrays: the next spec-mode entry rebuilds them from host truth.
            self._spec_fresh = False
            self._pipeline.push(n, toks, commit)
        metrics.observe(
            "serving_decode_dispatch_duration_seconds",
            time.perf_counter() - t0, {"engine": "paged"},
        )
        return n

    def run_until_drained(self, max_steps: int = 10000) -> None:
        """Drain via chunked on-device stepping: each dispatch runs exactly
        up to the soonest completion (in-flight steps included), so no slot
        oversteps its budget; the final in-flight chunks are flushed."""
        for _ in range(max_steps):
            if not self._active:
                self._pipeline.flush()  # commits only retire, never admit
                return
            self.step_n(32)  # step_n clamps to the completion bound itself
        raise RuntimeError("engine did not drain")

    # ---- speculative decoding (composed with paged continuous batching) --
    def _get_spec_step(self, sample: bool, gamma: int, ngram: int):
        """Device-resident speculative step (ISSUE 9): draft, verify, accept
        AND commit in one dispatch. The kernel n-gram-drafts from the
        per-slot history ring, scores the draft runs in one batched
        forward_verify_paged pass, computes the longest-accepted-prefix via
        cumprod-of-matches, clamps by the device budget, and commits
        pos_b/tokens/history/budget in-kernel — the host only receives the
        packed [slots, gamma+2] result (col 0 = per-slot take, cols 1.. =
        produced tokens) and never rewinds or re-uploads state."""
        donate = self._donate_steps
        key = ("spec", sample, gamma, ngram, donate)
        if key not in self._step_cache:
            cfg_static = self._cfg_static
            H = self.spec_history
            sh = (
                {"out_shardings": (self._pool_shardings,) + (self._rep,) * 7}
                if self.mesh is not None else {}
            )

            @partial(jax.jit, **({"donate_argnums": (1,)} if donate else {}), **sh)
            def _spec_step(params, cache, table, tokens, pos_b, active,
                           hist, hist_len, rem, keys, temp, top_k, top_p):
                from lws_tpu.models.llama import (
                    forward_verify_paged, ngram_draft, speculative_accept,
                )
                from lws_tpu.serving.engine import sample_logits_per_slot

                drafts = jax.vmap(
                    lambda h, l: ngram_draft(h, l, ngram=ngram, gamma=gamma)
                )(hist, hist_len)                            # [slots, gamma]
                is_greedy = temp <= 0.0
                # Sampled slots ride the verify at full width (static
                # shapes: a row cannot shrink the dispatch) but their draft
                # rows are filler — the running token, exactly like the host
                # loop shipped (docs/tasks/speculative-decoding.md covers
                # the cost model).
                tokens_in = jnp.concatenate(
                    [tokens[:, None],
                     jnp.where(is_greedy[:, None], drafts, tokens[:, None])],
                    axis=1,
                )                                             # [slots, S]
                all_logits, cache = forward_verify_paged(
                    params, tokens_in, cache, table, pos_b, cfg_static,
                )
                greedy = jnp.argmax(all_logits, axis=-1).astype(jnp.int32)
                if sample:
                    # Sampled slots advance ONE token per dispatch, from the
                    # same per-slot stream schedule as step_n: one split per
                    # produced token.
                    split = jax.vmap(jax.random.split)(keys)
                    step_keys, keys = split[:, 0], split[:, 1]
                    sampled = sample_logits_per_slot(
                        all_logits[:, 0, :], step_keys, temp, top_k, top_p
                    )
                else:
                    sampled = greedy[:, 0]
                # Filler rows must never extend acceptance: sampled slots
                # compare against an impossible draft.
                cmp = jnp.where(is_greedy[:, None], drafts, -1)
                take, out = speculative_accept(cmp, greedy, rem)
                out = jnp.where(
                    is_greedy[:, None], out,
                    jnp.broadcast_to(sampled[:, None], out.shape),
                )
                take = jnp.where(is_greedy, take, jnp.minimum(rem, 1))
                take = jnp.where(active, take, 0)
                # In-kernel commit: pos_b IS the paged cache's rewind
                # (rejected draft rows sit past it, masked until
                # overwritten); the history ring and budget advance with it.
                pos_b = pos_b + take
                rem = rem - take
                last = jnp.take_along_axis(
                    out, jnp.maximum(take - 1, 0)[:, None], axis=1
                )[:, 0]
                tokens = jnp.where(take > 0, last, tokens)
                i = jnp.arange(gamma + 1)[None, :]
                idx = (hist_len[:, None] + i) % H
                cur = jnp.take_along_axis(hist, idx, axis=1)
                rows = jnp.arange(hist.shape[0])[:, None]
                hist = hist.at[rows, idx].set(
                    jnp.where(i < take[:, None], out, cur)
                )
                hist_len = hist_len + take
                packed = jnp.concatenate([take[:, None], out], axis=1)
                return cache, tokens, pos_b, keys, hist, hist_len, rem, packed

            self._step_cache[key] = _spec_step
        return self._step_cache[key]

    def _spec_fits(self, S: int, inflight: int) -> bool:
        """Write-safety gate: a verify pass appends S K/V rows per active
        slot, and with worst-case in-flight commits no row may land at or
        past max_len (block-table indices past max_len would clip onto a
        live block — the one paged write that is NOT harmless)."""
        return all(
            len(r.prompt) + len(r.tokens) + inflight + S <= self.max_len
            for r in self._active.values()
        )

    def step_speculative(self, gamma: int = 4, ngram: int = 3) -> bool:  # hot-path
        """One speculative dispatch across every active slot (VERDICT r4 #4;
        device-resident since ISSUE 9): each greedy slot's n-gram draft run
        is drafted ON DEVICE from the slot's history ring, verified in one
        batched forward, and committed in-kernel — the accepted prefix plus
        the model's own next token per slot, with no host drafting, no
        host-side acceptance, and no pos/tokens re-upload. Dispatches ride
        the SAME in-flight ring as step_n: the host consumes chunk N's
        packed tokens while chunk N+1 verifies, and the steady-state loop
        never flushes (flushes remain only at spec-mode entry, budget/tail
        boundaries, and rollback). Sampled slots ride the same dispatch but
        advance exactly one token (own PRNG stream, same key schedule as
        step_n) — mixed batches stay exact vs the non-speculative engine.
        Returns False (no dispatch) when inapplicable: nothing active, no
        greedy slot, or a slot too close to max_len for a full draft run —
        callers fall back to step_n(1), exactly like the plain Engine's
        tail handling."""
        if not self._active:
            self._pipeline.flush()
            return False
        if len(self._active) <= self._sampled_active:
            # No greedy slot to draft for: a gamma-wide verify pass would
            # cost (gamma+1)x the FLOPs to advance every slot by one token —
            # strictly worse than plain decode. Let the caller batch-step.
            return False
        S = gamma + 1
        if S > self.spec_history:
            raise ValueError(
                f"gamma+1={S} exceeds spec_history={self.spec_history}"
            )
        if not self._spec_fresh:
            # Spec-mode entry after plain decode (or first use): rebuild the
            # device history/budget from host truth. The ONE flush on this
            # path — steady-state spec dispatches skip it.
            self._refresh_spec_state()
            if not self._active:
                return False
            if len(self._active) <= self._sampled_active:
                # The refresh's flush retired the last greedy slot.
                return False
        inflight = self._pipeline.inflight_steps()
        if (self._completion_bound() - inflight < 1
                or not self._spec_fits(S, inflight)):
            # The soonest completion is already covered by in-flight chunks
            # (or a slot's verify writes might cross max_len under the
            # worst case): consume, then re-check against exact truth.
            self._pipeline.flush()
            if not self._active:
                return False
            if len(self._active) <= self._sampled_active:
                # The flush's commits retired the last greedy slot: the
                # wide verify would be pure waste now (see the early gate).
                return False
            if not self._spec_fits(S, 0):
                return False  # genuine tail — caller single-steps
        t0 = time.perf_counter()
        with trace.span(
            "serve.decode_dispatch", engine="paged", steps=S, speculative=True,
            active=len(self._active), inflight=len(self._pipeline),
        ):
            with self._pipeline.host_section():
                active, table, sampling = self._dispatch_inputs()
                any_sampled = self._sampled_active > 0
                with devicemod.compile_site(
                    "paged.spec_step", engine="paged",
                    shape=f"g{gamma}/n{ngram}/sample={any_sampled}",
                ), self._mesh_ctx():
                    fn = self._get_spec_step(any_sampled, gamma, ngram)
                    (self.cache, self.tokens, self.pos_b, self._keys,
                     self._hist, self._hist_len, self._rem, packed) = fn(
                        self.params, self.cache, table, self.tokens,
                        self.pos_b, active, self._hist, self._hist_len,
                        self._rem, *sampling,
                    )
                snapshot = dict(self._active)
                greedy_slots = {
                    s for s, r in snapshot.items() if r.temperature <= 0
                }

                def commit(host_packed, snapshot=snapshot,
                           greedy_slots=greedy_slots):
                    with trace.span(
                        "serve.spec_verify", engine="paged", gamma=gamma,
                    ) as sp:
                        accepted = drafted = 0
                        for slot, req in snapshot.items():
                            t = int(host_packed[slot, 0])
                            if t <= 0:
                                continue  # budget already spent on device
                            req.tokens.extend(
                                int(x) for x in host_packed[slot, 1:1 + t]
                            )
                            if req.slo is not None:
                                req.slo.tokens(t)
                            if slot in greedy_slots:
                                drafted += gamma
                                accepted += t - 1
                            if req.done or (
                                len(req.prompt) + len(req.tokens)
                                >= self.max_len
                            ):
                                self._retire(slot, req)
                        sp.set(accepted=accepted, drafted=drafted)
                    self.stats["spec_drafted"] = (
                        self.stats.get("spec_drafted", 0) + drafted
                    )
                    self.stats["spec_accepted"] = (
                        self.stats.get("spec_accepted", 0) + accepted
                    )
                    metrics.inc(
                        "serving_spec_tokens_total",
                        {"engine": "paged", "kind": "drafted"},
                        value=float(drafted),
                    )
                    metrics.inc(
                        "serving_spec_tokens_total",
                        {"engine": "paged", "kind": "accepted"},
                        value=float(accepted),
                    )

                try:
                    self._pipeline.push(S, packed, commit)
                except Exception:
                    # The chunk computed on device but never made the ring
                    # (injected dispatch fault): its commit can never run,
                    # so device truth has outrun host truth. Drop EVERY
                    # in-flight chunk and restore device truth from host
                    # truth — pos_b is the cache rewind, so the abandoned
                    # verify rows are masked and later overwritten.
                    self._pipeline.discard()
                    self._rollback_to_host_truth()
                    raise
        metrics.observe(
            "serving_spec_verify_duration_seconds", time.perf_counter() - t0
        )
        self.stats["spec_dispatches"] = self.stats.get("spec_dispatches", 0) + 1
        return True

    def step_speculative_sync(self, gamma: int = 4, ngram: int = 3) -> bool:
        """The PR-8 host-loop speculative step, kept VERBATIM in behavior as
        the correctness oracle and benchmark baseline for the device-resident
        path (benchmarks/spec_decode_bench.py): drafts from host token
        history, blocks on the verify logits, computes acceptance on host,
        and re-uploads pos/tokens. Token streams from this loop and
        step_speculative must stay byte-identical — pinned by
        tests/test_paged_speculative.py."""
        from lws_tpu.serving.engine import Engine

        # Host drafting reads host token history and the commit below
        # rewrites device state from host truth — both require the in-flight
        # ring drained first.
        self._pipeline.flush()
        self._spec_fresh = False  # host commit below bypasses hist/rem
        if not self._active:
            return False
        if all(r.temperature > 0 for r in self._active.values()):
            return False
        S = gamma + 1
        for r in self._active.values():
            if len(r.prompt) + len(r.tokens) + S > self.max_len:
                return False
        tokens_in = np.zeros((self.slots, S), np.int32)
        drafts: dict[int, list[int]] = {}
        pos_h = np.zeros((self.slots,), np.int32)
        with self._pipeline.host_section():  # host drafting: device idle
            for s, r in self._active.items():
                if r.temperature <= 0:
                    d = Engine._draft_ngram(list(r.prompt) + r.tokens, ngram, gamma)
                else:
                    d = [r.tokens[-1]] * gamma  # never accepted; slot samples
                drafts[s] = d
                tokens_in[s, 0] = r.tokens[-1]
                tokens_in[s, 1:] = d
                pos_h[s] = len(r.prompt) + len(r.tokens) - 1
            any_sampled = self._sampled_active > 0
            _, table, sampling = self._dispatch_inputs()
            tokens_dev = self._put_rep(jnp.asarray(tokens_in))
            pos_dev = self._put_rep(jnp.asarray(pos_h))
        t0 = time.perf_counter()
        with trace.span(
            "serve.spec_verify", engine="paged", gamma=gamma,
            active=len(self._active),
        ):
            with self._pipeline.host_section():
                with self._mesh_ctx():
                    fn = self._get_spec_verify_sync(any_sampled)
                    self.cache, greedy, sampled, self._keys = fn(
                        self.params, self.cache, table, tokens_dev, pos_dev,
                        *sampling,
                    )
            greedy_h = np.asarray(greedy)   # [slots, S]
            sampled_h = np.asarray(sampled)  # [slots]
        metrics.observe(
            "serving_spec_verify_duration_seconds", time.perf_counter() - t0
        )
        self.stats["spec_dispatches"] = self.stats.get("spec_dispatches", 0) + 1
        with self._pipeline.host_section():  # host acceptance + commit
            for s, r in list(self._active.items()):
                if r.temperature > 0:
                    new = [int(sampled_h[s])]
                else:
                    d = drafts[s]
                    a = 0
                    while a < gamma and d[a] == int(greedy_h[s, a]):
                        a += 1
                    remaining = r.max_new_tokens - len(r.tokens)
                    new = ([*map(int, d[:a]), int(greedy_h[s, a])])[:remaining]
                    self.stats["spec_drafted"] = (
                        self.stats.get("spec_drafted", 0) + gamma
                    )
                    self.stats["spec_accepted"] = (
                        self.stats.get("spec_accepted", 0) + len(new) - 1
                    )
                r.tokens.extend(new)
                if r.slo is not None:
                    r.slo.tokens(len(new))
                if r.done or len(r.prompt) + len(r.tokens) >= self.max_len:
                    self._retire(s, r)
            # Commit host truth back to the device state the regular step
            # path reads — the same rebuild the rollback path uses (pos_b
            # IS the paged cache's rewind: rejected draft rows sit past
            # pos_b, masked out of attention until overwritten).
            self._rollback_to_host_truth()
        return True

    def _get_spec_verify_sync(self, sample: bool):
        """Verify-only jitted step for the sync oracle (the pre-ISSUE-9
        kernel: acceptance stays on host)."""
        donate = self._donate_steps
        key = ("spec_sync", sample, donate)
        if key not in self._step_cache:
            cfg_static = self._cfg_static
            sh = (
                {"out_shardings": (
                    self._pool_shardings, self._rep, self._rep, self._rep
                )}
                if self.mesh is not None else {}
            )

            @partial(jax.jit, **({"donate_argnums": (1,)} if donate else {}), **sh)
            def _spec_verify(params, cache, table, tokens_in, pos_b,
                             keys, temp, top_k, top_p):
                from lws_tpu.models.llama import forward_verify_paged
                from lws_tpu.serving.engine import sample_logits_per_slot

                all_logits, cache = forward_verify_paged(
                    params, tokens_in, cache, table, pos_b, cfg_static,
                )
                greedy = jnp.argmax(all_logits, axis=-1).astype(jnp.int32)
                if sample:
                    split = jax.vmap(jax.random.split)(keys)
                    step_keys, keys = split[:, 0], split[:, 1]
                    sampled = sample_logits_per_slot(
                        all_logits[:, 0, :], step_keys, temp, top_k, top_p
                    )
                else:
                    sampled = greedy[:, 0]
                return cache, greedy, sampled, keys

            self._step_cache[key] = _spec_verify
        return self._step_cache[key]

    def run_until_drained_speculative(
        self, gamma: int = 4, ngram: int = 3, max_dispatches: int = 10000,
        sync: bool = False,
    ) -> None:
        """Drain with speculative dispatches (`sync=True` runs the PR-8
        host-loop oracle instead — tests and spec_decode_bench compare the
        two). Fallback when a dispatch is refused: single steps while a
        greedy slot could re-enter speculation (near-max_len tail), full
        32-step scans when none can (all-sampled batch — speculation would
        never apply again)."""
        step = self.step_speculative_sync if sync else self.step_speculative
        for _ in range(max_dispatches):
            if not self._active:
                self._pipeline.flush()  # commits only retire, never admit
                return
            if not step(gamma, ngram):
                greedy_alive = any(
                    r.temperature <= 0 for r in self._active.values()
                )
                if self.step_n(1 if greedy_alive else 32):
                    # Counted so tokens/dispatch accounting can't silently
                    # exclude the non-speculative tail dispatches.
                    self.stats["spec_fallback_dispatches"] = (
                        self.stats.get("spec_fallback_dispatches", 0) + 1
                    )
        raise RuntimeError("engine did not drain")

    def result(self, request_id: int) -> Optional[list[int]]:
        req = self._completed.get(request_id)
        if req is None and self._pipeline:
            # The request may have finished inside an unconsumed chunk —
            # but only flush when it actually could have: a poll-style
            # driver calling result() for still-running requests after
            # every step must not degrade the ring back to the synchronous
            # loop.
            live = next(
                (r for r in self._active.values() if r.request_id == request_id),
                None,
            )
            if live is None or (
                remaining_steps(live, self.max_len)
                <= self._pipeline.inflight_steps()
            ):
                self._pipeline.flush()
                req = self._completed.get(request_id)
        return list(req.tokens) if req is not None else None

    @property
    def active_count(self) -> int:
        return len(self._active)
