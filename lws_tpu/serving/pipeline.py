"""Bounded in-flight dispatch ring: the serving overlap primitive.

JAX dispatch is asynchronous — a jitted decode chunk returns device futures
long before the compute finishes — but every engine loop in this repo used
to force a host sync (`np.asarray(toks)`) immediately after each dispatch,
so the device idled through every host-side admission/bookkeeping window
and the host idled through every device window. `DecodePipeline` keeps up
to `depth` dispatched-but-unconsumed chunks in flight: the host consumes
chunk N's tokens while chunk N+1 runs on device.

One instance per engine loop; three operations:

  * `push(steps, payload, commit)` — enqueue a dispatched chunk; `payload`
    is the device array carrying its tokens, `commit(host)` applies the
    host-side bookkeeping once the transfer lands. Pushing past `depth`
    consumes the oldest chunk (FIFO — commit order is dispatch order, which
    the engines' host truth depends on). `depth=0` is the synchronous loop:
    every push consumes immediately.
  * `flush()` — consume everything in flight. Engines call it before any
    operation that must see host truth up to date (speculative dispatch,
    the pallas-probe step, block eviction) or that re-reads device state
    the ring still owns.
  * `discard()` — drop in-flight chunks WITHOUT committing. The pallas
    probe itself never needs it (the paged engine flushes BEFORE the probe
    dispatch, so a failed probe leaves an empty ring); discard is the
    escape hatch for callers that must abandon in-flight work whose
    results are known-invalid rather than commit garbage.

Attribution (the host-blocked vs device-busy split):

  * `host_section()` wraps an engine's host-side scheduling window (input
    build + dispatch). Time spent there while the ring is EMPTY is time the
    device sat idle waiting on the host — counted into
    `serving_host_blocked_seconds{engine}` and added as `host_blocked_s` on
    the enclosing span. With chunks in flight the same window overlaps
    device compute and costs nothing.
  * each consume runs in a `serve.decode_consume` span whose
    `device_wait_s` attribute is the blocking part of the transfer — the
    device-busy side of the ledger.
  * `serving_inflight_dispatches{engine}` gauges the ring depth live.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable

import numpy as np

from lws_tpu.core import faults, flightrecorder, metrics, trace


def remaining_steps(req, max_len: int) -> int:
    """Decode steps a request can still take before completing: its token
    budget or the engine's length ceiling, whichever is nearer. THE
    completion predicate — the engines' bound clamps, flush gates, and
    result() fast paths all share it so their semantics cannot drift."""
    return min(
        req.max_new_tokens - len(req.tokens),
        max_len - len(req.prompt) - len(req.tokens),
    )


class _HostSection:
    """Times a host-side scheduling window; counts it as host-blocked only
    when no dispatched chunk was in flight at entry (device idle, host is
    the bottleneck). Re-entrant nesting is the caller's job to avoid —
    engines open one section per dispatch and one per commit."""

    __slots__ = ("_pipe", "_blocked", "_t0")

    def __init__(self, pipe: "DecodePipeline") -> None:
        self._pipe = pipe

    def __enter__(self) -> "_HostSection":
        self._blocked = not self._pipe  # ring emptiness, read under the pipe's lock
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        if self._blocked:
            dt = time.perf_counter() - self._t0
            with self._pipe._lock:
                self._pipe.stats["host_blocked_s"] += dt
            metrics.inc(
                "serving_host_blocked_seconds",
                {"engine": self._pipe.engine_label}, value=dt,
            )
            trace.current_span().add(host_blocked_s=dt)
        return False


class DecodePipeline:
    def __init__(self, depth: int = 2, engine: str = "paged") -> None:
        """`depth` caps dispatched-but-unconsumed chunks (0 = synchronous);
        `engine` labels the metrics this ring reports."""
        self.depth = max(0, int(depth))
        self.engine_label = engine
        # One engine loop owns the ring, but other threads reach it (disagg
        # drivers flush from their pull loops, tests/tools poll depth), so
        # ring + stats are RLock-guarded: re-entrant because flush()
        # consumes, and a consume's commit may call back into flush()/len()
        # on the same thread. The lock is DELIBERATELY held across the
        # consume's device fence + commit: FIFO commit order is the ring's
        # contract, so concurrent consumers must serialize for exactly that
        # long anyway — a reader arriving mid-consume waits one chunk, it
        # does not deadlock (and the owning engine loop never contends).
        self._lock = threading.RLock()
        self._ring: "deque[tuple[int, object, Callable]]" = deque()  # guarded-by: _lock
        self.stats = {  # guarded-by: _lock
            "dispatched": 0, "consumed": 0, "flushes": 0, "discarded": 0,
            "host_blocked_s": 0.0, "device_wait_s": 0.0, "max_inflight": 0,
        }

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def __bool__(self) -> bool:
        with self._lock:
            return bool(self._ring)

    def inflight_steps(self) -> int:
        """Total decode steps dispatched but not yet committed to host truth
        — the engines subtract this from their completion bound so no slot's
        budget can be overrun by work already in the ring."""
        with self._lock:
            return sum(steps for steps, _, _ in self._ring)

    def host_section(self) -> _HostSection:
        return _HostSection(self)

    def push(self, steps: int, payload, commit: Callable) -> None:  # hot-path
        # Disarmed this is one flag read (faults.py's no-op fast path); the
        # decode-overlap budget in make check holds the line. Armed `delay`
        # schedules inject dispatch-side slowness — the deterministic way
        # to rehearse a wedged ring against the stall watchdog.
        faults.fire("pipeline.dispatch")  # vet: ignore[hotpath-blocking-call]: delay-mode faults sleep BY DESIGN — armed only in chaos runs, disarmed cost is one flag read
        with self._lock:
            self._ring.append((steps, payload, commit))
            self.stats["dispatched"] += 1
            while len(self._ring) > self.depth:
                self._consume_oldest()
            # Gauge/max AFTER settling to depth: the documented contract is
            # "0 in a synchronous loop, up to the configured depth" — the
            # transient depth+1 during eviction is not an observable state.
            if len(self._ring) > self.stats["max_inflight"]:
                self.stats["max_inflight"] = len(self._ring)
            self._gauge()
            self._heartbeat()

    def flush(self) -> None:  # hot-path
        with self._lock:
            if self._ring:
                self.stats["flushes"] += 1
            while self._ring:
                self._consume_oldest()

    def discard(self) -> None:
        # The rollback escape hatch: in-flight results abandoned as known-
        # invalid. Ring event + trace id so a flight-recorder dump
        # correlates the rollback with the request that triggered it.
        with self._lock:
            if self._ring:
                flightrecorder.record(
                    "pipeline_discard", engine=self.engine_label,
                    chunks=len(self._ring), steps=self.inflight_steps(),
                )
            self.stats["discarded"] += len(self._ring)
            self._ring.clear()
            self._gauge()
            self._heartbeat()

    def _consume_oldest(self) -> None:  # hot-path — holds-lock: _lock
        steps, payload, commit = self._ring.popleft()
        with trace.span(
            "serve.decode_consume", engine=self.engine_label, steps=steps,
            inflight=len(self._ring),
        ) as sp:
            t0 = time.perf_counter()
            # np.asarray is the completion fence (block_until_ready is not
            # reliable on relay-backed remote backends — see engine.host_sync).
            host = np.asarray(payload)  # vet: ignore[hotpath-host-sync, lock-held-blocking]: this IS the consume fence — the one deliberate device wait the ring exists to schedule, under the ring lock by contract
            wait = time.perf_counter() - t0
            self.stats["device_wait_s"] += wait
            sp.set(device_wait_s=round(wait, 6))
            with self.host_section():
                commit(host)
        self.stats["consumed"] += 1
        self._gauge()
        self._heartbeat()

    def _gauge(self) -> None:  # holds-lock: _lock
        metrics.set(
            "serving_inflight_dispatches", len(self._ring),
            {"engine": self.engine_label},
        )

    def _heartbeat(self) -> None:  # holds-lock: _lock
        # Stall-watchdog feed: progress = chunks that LEFT the ring
        # (consumed or discarded), depth = chunks still in flight. A wedged
        # device dispatch shows as depth > 0 with frozen progress; a slow
        # but draining ring keeps advancing and never trips the watchdog.
        flightrecorder.beat(
            f"decode_ring:{self.engine_label}",
            progress=self.stats["consumed"] + self.stats["discarded"],
            depth=len(self._ring),
        )
