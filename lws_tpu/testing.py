"""Test utilities: fluent LWS builders, status manipulation, validators
(≈ test/wrappers/wrappers.go + test/testutils/{util,validators}.go).

Status setters simulate node-agent behavior the same way the reference's
envtest utilities do (SURVEY §4.2) — but here the GroupSet controller and
scheduler are real, so tests only flip *pod* status, never groupset status.
"""

from __future__ import annotations

from typing import Optional

from lws_tpu.api import contract
from lws_tpu.api.groupset import GroupSet
from lws_tpu.api.pod import Container, Pod, PodPhase, PodSpec, PodTemplateSpec, TemplateMeta
from lws_tpu.api.types import (
    LeaderWorkerSet,
    LeaderWorkerSetSpec,
    LeaderWorkerTemplate,
    NetworkConfig,
    RestartPolicy,
    RollingUpdateConfiguration,
    RolloutStrategy,
    StartupPolicy,
    SubdomainPolicy,
    SubGroupPolicy,
    SubGroupPolicyType,
)
from lws_tpu.core.store import Store, new_meta


def make_worker_template(image: str = "worker:v1", tpu_chips: int = 0) -> PodTemplateSpec:
    resources = {contract.TPU_RESOURCE_NAME: tpu_chips} if tpu_chips else {}
    return PodTemplateSpec(
        metadata=TemplateMeta(),
        spec=PodSpec(containers=[Container(name="worker", image=image, resources=dict(resources))]),
    )


class LWSBuilder:
    """Fluent builder (≈ wrappers.go LeaderWorkerSetWrapper)."""

    def __init__(self, name: str = "sample", namespace: str = "default") -> None:
        self._lws = LeaderWorkerSet(
            meta=new_meta(name, namespace),
            spec=LeaderWorkerSetSpec(
                replicas=2,
                leader_worker_template=LeaderWorkerTemplate(
                    worker_template=make_worker_template(), size=3
                ),
            ),
        )

    def replicas(self, n: int) -> "LWSBuilder":
        self._lws.spec.replicas = n
        return self

    def size(self, n: int) -> "LWSBuilder":
        self._lws.spec.leader_worker_template.size = n
        return self

    def image(self, image: str) -> "LWSBuilder":
        for c in self._lws.spec.leader_worker_template.worker_template.spec.containers:
            c.image = image
        if self._lws.spec.leader_worker_template.leader_template is not None:
            for c in self._lws.spec.leader_worker_template.leader_template.spec.containers:
                c.image = image
        return self

    def leader_template(self, template: Optional[PodTemplateSpec] = None, tpu_chips: int = 0) -> "LWSBuilder":
        self._lws.spec.leader_worker_template.leader_template = template or make_worker_template(
            "leader:v1", tpu_chips
        )
        return self

    def tpu_chips(self, chips: int) -> "LWSBuilder":
        for c in self._lws.spec.leader_worker_template.worker_template.spec.containers:
            c.resources[contract.TPU_RESOURCE_NAME] = chips
        return self

    def restart_policy(self, policy: RestartPolicy) -> "LWSBuilder":
        self._lws.spec.leader_worker_template.restart_policy = policy
        return self

    def startup_policy(self, policy: StartupPolicy) -> "LWSBuilder":
        self._lws.spec.startup_policy = policy
        return self

    def subdomain_policy(self, policy: SubdomainPolicy) -> "LWSBuilder":
        self._lws.spec.network_config = NetworkConfig(subdomain_policy=policy)
        return self

    def subgroup(self, size: int, type_: SubGroupPolicyType = SubGroupPolicyType.LEADER_WORKER) -> "LWSBuilder":
        self._lws.spec.leader_worker_template.sub_group_policy = SubGroupPolicy(
            type=type_, sub_group_size=size
        )
        return self

    def rollout(self, max_unavailable=1, max_surge=0, partition=0) -> "LWSBuilder":
        self._lws.spec.rollout_strategy = RolloutStrategy(
            rolling_update_configuration=RollingUpdateConfiguration(
                partition=partition, max_unavailable=max_unavailable, max_surge=max_surge
            )
        )
        return self

    def annotation(self, key: str, value: str) -> "LWSBuilder":
        self._lws.meta.annotations[key] = value
        return self

    def exclusive_topology(self, key: str = contract.NODE_TPU_SLICE_LABEL) -> "LWSBuilder":
        return self.annotation(contract.EXCLUSIVE_KEY_ANNOTATION_KEY, key)

    def build(self) -> LeaderWorkerSet:
        return self._lws


# ---- status manipulation (the "play kubelet" helpers) ----------------------


def set_pod_ready(store: Store, namespace: str, name: str) -> None:
    pod = store.get("Pod", namespace, name)
    pod.status.phase = PodPhase.RUNNING
    pod.status.ready = True
    pod.status.address = f"{name}.{pod.spec.subdomain}.{namespace}"
    store.update_status(pod)


def set_pod_not_ready(store: Store, namespace: str, name: str) -> None:
    pod = store.get("Pod", namespace, name)
    pod.status.ready = False
    store.update_status(pod)


def restart_pod_container(store: Store, namespace: str, name: str) -> None:
    pod = store.get("Pod", namespace, name)
    pod.status.container_restarts += 1
    store.update_status(pod)


def group_pod_names(lws_name: str, group: int, size: int) -> list[str]:
    names = [f"{lws_name}-{group}"]
    names += [f"{lws_name}-{group}-{i}" for i in range(1, size)]
    return names


def make_group_ready(store: Store, lws_name: str, group: int, namespace: str = "default") -> None:
    lws = store.get("LeaderWorkerSet", namespace, lws_name)
    for name in group_pod_names(lws_name, group, lws.spec.leader_worker_template.size):
        if store.try_get("Pod", namespace, name) is not None:
            set_pod_ready(store, namespace, name)


def make_all_groups_ready(cp, lws_name: str, namespace: str = "default", max_rounds: int = 10) -> None:
    """Flip every existing pod of the LWS ready, settling between passes —
    drives multi-step flows (LeaderReady gates, rolling updates) to completion
    with the test playing kubelet."""
    for _ in range(max_rounds):
        cp.run_until_stable()
        pods = cp.store.list("Pod", namespace, labels={contract.SET_NAME_LABEL_KEY: lws_name})
        flipped = False
        for pod in pods:
            if not pod.status.ready:
                set_pod_ready(cp.store, namespace, pod.meta.name)
                flipped = True
        if not flipped:
            return
    raise AssertionError(f"{lws_name} never settled after {max_rounds} rounds")


# ---- validators (≈ test/testutils/validators.go) ---------------------------


def expect_valid_leader_groupset(store: Store, lws: LeaderWorkerSet, replicas: Optional[int] = None) -> GroupSet:
    gs = store.get("GroupSet", lws.meta.namespace, lws.meta.name)
    assert gs.spec.selector == {
        contract.SET_NAME_LABEL_KEY: lws.meta.name,
        contract.WORKER_INDEX_LABEL_KEY: "0",
    }
    tmpl = gs.spec.template.metadata
    assert tmpl.labels[contract.WORKER_INDEX_LABEL_KEY] == "0"
    assert tmpl.labels[contract.SET_NAME_LABEL_KEY] == lws.meta.name
    assert tmpl.labels[contract.REVISION_LABEL_KEY]
    assert tmpl.annotations[contract.SIZE_ANNOTATION_KEY] == str(lws.spec.leader_worker_template.size)
    assert gs.meta.annotations[contract.REPLICAS_ANNOTATION_KEY] == str(lws.spec.replicas)
    assert gs.spec.service_name == lws.meta.name
    if replicas is not None:
        assert gs.spec.replicas == replicas, f"leader groupset replicas {gs.spec.replicas} != {replicas}"
    return gs


def expect_valid_worker_groupsets(store: Store, lws: LeaderWorkerSet, count: Optional[int] = None) -> list[GroupSet]:
    size = lws.spec.leader_worker_template.size
    out = []
    groupsets = [
        g
        for g in store.list("GroupSet", lws.meta.namespace, labels={contract.SET_NAME_LABEL_KEY: lws.meta.name})
        if g.meta.name != lws.meta.name
    ]
    for gs in groupsets:
        assert gs.spec.replicas == size - 1
        assert gs.spec.start_ordinal == 1
        assert gs.meta.labels[contract.GROUP_INDEX_LABEL_KEY] == gs.spec.template.metadata.labels[contract.GROUP_INDEX_LABEL_KEY]
        assert gs.spec.template.metadata.annotations[contract.SIZE_ANNOTATION_KEY] == str(size)
        assert gs.spec.template.metadata.annotations[contract.LEADER_POD_NAME_ANNOTATION_KEY] == gs.meta.name
        out.append(gs)
    if count is not None:
        assert len(out) == count, f"worker groupsets {len(out)} != {count}"
    return out


def lws_pods(store: Store, lws_name: str, namespace: str = "default") -> list[Pod]:
    return store.list("Pod", namespace, labels={contract.SET_NAME_LABEL_KEY: lws_name})


def condition_status(lws: LeaderWorkerSet, ctype: str) -> Optional[bool]:
    for c in lws.status.conditions:
        if c.type == ctype:
            return c.status
    return None


def assert_valid_group(store: Store, lws: LeaderWorkerSet, group: int) -> None:
    """Validate EVERY field the controllers promise for one group — labels,
    annotations, env contract, affinities, subdomain, revision links, worker
    groupset wiring (≈ validators.go ExpectValidLeaderStatefulSet +
    ExpectValidWorkerStatefulSets + pod-webhook postconditions rolled into
    one call, /root/reference/test/testutils/validators.go:45-367). Checks
    only pods that exist — callers assert counts separately (groups mid-
    recreate legitimately have missing pods)."""
    ns = lws.meta.namespace
    size = lws.spec.leader_worker_template.size
    tmpl = lws.spec.leader_worker_template
    leader_name = f"{lws.meta.name}-{group}"
    leader = store.try_get("Pod", ns, leader_name)
    assert leader is not None, f"leader pod {leader_name} missing"

    # ---- leader labels -----------------------------------------------------
    labels = leader.meta.labels
    assert labels[contract.SET_NAME_LABEL_KEY] == lws.meta.name
    assert labels[contract.GROUP_INDEX_LABEL_KEY] == str(group)
    assert labels[contract.WORKER_INDEX_LABEL_KEY] == "0"
    group_key = labels.get(contract.GROUP_UNIQUE_HASH_LABEL_KEY)
    assert group_key, "leader missing group unique key"
    revision = labels.get(contract.REVISION_LABEL_KEY)
    assert revision, "leader missing template revision label"

    # ---- revision link: the label resolves to a stored ControllerRevision --
    revs = [
        r for r in store.list("ControllerRevision", ns)
        if r.meta.labels.get(contract.SET_NAME_LABEL_KEY) == lws.meta.name
        and revision in r.meta.name
    ]
    assert revs, f"no ControllerRevision for hash {revision}"

    # ---- leader annotations ------------------------------------------------
    assert leader.meta.annotations[contract.SIZE_ANNOTATION_KEY] == str(size)
    exclusive = lws.meta.annotations.get(contract.EXCLUSIVE_KEY_ANNOTATION_KEY)
    if exclusive:
        aff = leader.spec.affinity
        assert aff is not None, "exclusive topology promised but no affinity"
        assert any(
            t.topology_key == exclusive
            and t.selector_matches({contract.GROUP_UNIQUE_HASH_LABEL_KEY: group_key})
            for t in aff.required_affinity
        ), "missing same-topology affinity on the group key"
        assert any(
            t.topology_key == exclusive
            and not t.selector_matches({contract.GROUP_UNIQUE_HASH_LABEL_KEY: group_key})
            for t in aff.required_anti_affinity
        ), "missing anti-affinity against other groups' keys"

    # ---- subdomain / DNS identity -----------------------------------------
    unique = (
        lws.spec.network_config is not None
        and lws.spec.network_config.subdomain_policy == SubdomainPolicy.UNIQUE_PER_REPLICA
    )
    want_subdomain = leader_name if unique else lws.meta.name
    assert leader.spec.subdomain == want_subdomain, (
        f"leader subdomain {leader.spec.subdomain!r} != {want_subdomain!r}"
    )

    # ---- env contract (every container, leader first) ----------------------
    leader_addr = f"{leader_name}.{want_subdomain}.{ns}"

    def check_env(pod, worker_index: int) -> None:
        for container in pod.spec.containers + pod.spec.init_containers:
            env = {e.name: e.value for e in container.env}
            assert container.env and container.env[0].name == contract.LWS_LEADER_ADDRESS, (
                f"{pod.meta.name}: LWS_LEADER_ADDRESS must be the FIRST env var"
            )
            assert env[contract.LWS_LEADER_ADDRESS] == leader_addr
            assert env[contract.LWS_GROUP_SIZE] == str(size)
            assert env[contract.LWS_WORKER_INDEX] == str(worker_index)
            assert env[contract.JAX_COORDINATOR_ADDRESS] == (
                f"{leader_addr}:{contract.JAX_COORDINATOR_PORT_DEFAULT}"
            )
            assert env[contract.JAX_PROCESS_ID] == str(worker_index)
        # TPU bootstrap rides any container that requests chips.
        for container in pod.spec.containers:
            if int(container.resources.get(contract.TPU_RESOURCE_NAME, 0) or 0) > 0:
                env = {e.name: e.value for e in container.env}
                assert contract.TPU_WORKER_HOSTNAMES in env, (
                    f"{pod.meta.name}: requests TPUs but no TPU_WORKER_HOSTNAMES"
                )
                assert contract.TPU_WORKER_ID in env
                n_hosts = len(env[contract.TPU_WORKER_HOSTNAMES].split(","))
                assert 0 <= int(env[contract.TPU_WORKER_ID]) < n_hosts

    check_env(leader, 0)

    # ---- workers -----------------------------------------------------------
    for i in range(1, size):
        wname = f"{leader_name}-{i}"
        worker = store.try_get("Pod", ns, wname)
        if worker is None:
            continue  # group mid-materialization; counts asserted by callers
        wl = worker.meta.labels
        assert wl[contract.SET_NAME_LABEL_KEY] == lws.meta.name
        assert wl[contract.GROUP_INDEX_LABEL_KEY] == str(group)
        assert wl[contract.WORKER_INDEX_LABEL_KEY] == str(i)
        assert wl[contract.GROUP_UNIQUE_HASH_LABEL_KEY] == group_key, (
            "worker group key differs from leader's"
        )
        assert wl[contract.REVISION_LABEL_KEY] == revision, (
            f"{wname}: revision {wl[contract.REVISION_LABEL_KEY]} != leader's {revision}"
        )
        assert worker.meta.annotations[contract.SIZE_ANNOTATION_KEY] == str(size)
        assert worker.meta.annotations[contract.LEADER_POD_NAME_ANNOTATION_KEY] == leader_name
        if tmpl.sub_group_policy is not None and tmpl.sub_group_policy.sub_group_size:
            from lws_tpu.utils.tpu import get_subgroup_index

            # get_subgroup_index owns the leader-fold rule ((size-1) % sgs
            # == 0 folds the leader into subgroup 0 and shifts workers) for
            # BOTH policies — recomputing it here diverged once already.
            want_sub = get_subgroup_index(size, tmpl.sub_group_policy.sub_group_size, i)
            assert wl[contract.SUBGROUP_INDEX_LABEL_KEY] == str(want_sub), (
                f"{wname}: subgroup index {wl.get(contract.SUBGROUP_INDEX_LABEL_KEY)} != {want_sub}"
            )
        check_env(worker, i)

    # ---- worker groupset wiring -------------------------------------------
    if size > 1:
        gs = store.try_get("GroupSet", ns, leader_name)
        if gs is not None:
            assert gs.spec.replicas == size - 1
            assert gs.spec.start_ordinal == 1
            assert gs.spec.template.metadata.labels[contract.REVISION_LABEL_KEY] == revision
            assert gs.spec.template.metadata.annotations[contract.LEADER_POD_NAME_ANNOTATION_KEY] == leader_name
            assert gs.spec.service_name == (leader_name if unique else lws.meta.name)

    # ---- services: the rendezvous plane ------------------------------------
    svc_name = leader_name if unique else lws.meta.name
    svc = store.try_get("Service", ns, svc_name)
    assert svc is not None, f"headless service {svc_name} missing"
    assert svc.spec.headless and svc.spec.publish_not_ready_addresses, (
        "rendezvous service must be headless and publish not-ready addresses"
    )
    assert svc.spec.selector.get(contract.SET_NAME_LABEL_KEY) == lws.meta.name
    if unique:
        assert svc.spec.selector.get(contract.GROUP_INDEX_LABEL_KEY) == str(group)


def assert_valid_lws(store: Store, lws_name: str, namespace: str = "default") -> None:
    """assert_valid_group over every group of the CURRENT stored LWS, plus
    the leader groupset checks — one call validating the whole promised
    surface (adopt in any test that reaches a stable state)."""
    lws = store.get("LeaderWorkerSet", namespace, lws_name)
    expect_valid_leader_groupset(store, lws)
    for g in range(lws.spec.replicas):
        if store.try_get("Pod", namespace, f"{lws_name}-{g}") is not None:
            assert_valid_group(store, lws, g)


# ---------------------------------------------------------------------------
# Instrumented-lock race harness: the runtime counterpart of `make vet`'s
# lock-discipline pass (≈ the reference repo's `go test -race`).
#
# The vet pass proves LEXICAL discipline (guarded attrs touched under their
# lock); this harness proves the discipline holds at RUNTIME, including
# paths the static pass cannot see (cross-object access, callbacks,
# socket-spawned threads). It implements the Eraser lockset algorithm:
#
#   * `InstrumentedLock` wraps a real Lock/RLock and maintains a
#     thread-local set of locks currently held;
#   * `RaceDetector.watch(obj, fields)` swaps the object's class for a
#     subclass whose `__getattribute__`/`__setattr__` record every access
#     to the named fields along with the caller's held-lock set;
#   * per (object, field) a candidate lockset is intersected across
#     accesses once a SECOND thread shows up (first-thread accesses are
#     the init phase, exempt — Eraser's shared-exclusive transition). An
#     empty intersection means no common lock protects the field: a race,
#     reported deterministically WITHOUT needing the racy interleaving to
#     actually strike.
#
# Register only genuinely-mutated shared state: the harness treats every
# access to a watched field as part of the conflict set (a deque mutated
# in place never shows an attribute WRITE, so reads count too).
#
# `NullLock` is the seeded-mutation aid: swapping an instance's lock for
# it simulates deleting the `with self._lock:` discipline from the source
# (tests/test_race_harness.py seeds exactly that mutation against
# serving/pipeline.py and asserts the detector catches it).


import ast as _ast
import inspect as _inspect
import re as _re
import textwrap as _textwrap
import threading as _threading

# The ONE annotation grammar shared with the static pass: this regex must
# stay byte-identical to tools/vet/core.py GUARDED_BY_RE (tests/
# test_race_harness.py pins them equal). `lws_tpu` must not import
# `tools.vet` — the shipped package cannot depend on dev tooling — so the
# pattern is restated here and the equality is enforced by test instead.
GUARDED_BY_RE = _re.compile(r"#.*?\bguarded-by:\s*([A-Za-z_]\w*)")


def guarded_fields(obj_or_cls) -> dict[str, str]:
    """attr -> lock-attr name for a class, read from the `# guarded-by:`
    comments on its attribute initializers — the SAME source annotations
    `make vet`'s lock pass enforces lexically. The static pass proves the
    discipline where it can see it; this reader hands the identical field
    set to the runtime detector (`RaceDetector.watch_guarded`) so the two
    checkers can never watch different state.

    Walks the MRO (subclass annotations win); classes without retrievable
    source (dynamically created, e.g. the detector's own Watched*
    wrappers) are skipped."""
    cls = obj_or_cls if isinstance(obj_or_cls, type) else type(obj_or_cls)
    out: dict[str, str] = {}
    for klass in reversed(cls.__mro__):
        if klass is object:
            continue
        try:
            src = _textwrap.dedent(_inspect.getsource(klass))
        except (OSError, TypeError):
            continue
        try:
            tree = _ast.parse(src)
        except SyntaxError:
            continue
        lines = src.splitlines()
        node = tree.body[0]
        if not isinstance(node, _ast.ClassDef):
            continue
        for fn in node.body:
            if not isinstance(fn, (_ast.FunctionDef, _ast.AsyncFunctionDef)):
                continue
            for stmt in _ast.walk(fn):
                if not isinstance(stmt, (_ast.Assign, _ast.AnnAssign)):
                    continue
                targets = (
                    stmt.targets if isinstance(stmt, _ast.Assign)
                    else [stmt.target]
                )
                for tgt in targets:
                    if (
                        isinstance(tgt, _ast.Attribute)
                        and isinstance(tgt.value, _ast.Name)
                        and tgt.value.id == "self"
                    ):
                        m = GUARDED_BY_RE.search(lines[stmt.lineno - 1])
                        if m:
                            out[tgt.attr] = m.group(1)
    return out


_HELD = _threading.local()


def _held_locks() -> list:
    locks = getattr(_HELD, "locks", None)
    if locks is None:
        locks = _HELD.locks = []
    return locks


class InstrumentedLock:
    """Drop-in Lock/RLock replacement feeding the detector's locksets."""

    def __init__(self, name: str = "lock", lock=None) -> None:
        self.name = name
        self._lock = lock if lock is not None else _threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _held_locks().append(self)
        return ok

    def release(self) -> None:
        held = _held_locks()
        if self in held:
            # Remove ONE entry: an RLock held re-entrantly stays held.
            held.reverse()
            held.remove(self)
            held.reverse()
        self._lock.release()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


class NullLock:
    """A lock that locks nothing: the seeded `lock-removal` mutation.
    Swapping it in for an instance's real lock simulates deleting the
    `with self._lock:` discipline from the source under test."""

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return True

    def release(self) -> None:
        pass

    def __enter__(self) -> "NullLock":
        return self

    def __exit__(self, *exc) -> bool:
        return False


class RaceDetector:
    """Happens-before-via-locksets checker for registered shared objects."""

    def __init__(self) -> None:
        self._mutex = _threading.Lock()
        # (name, field) -> {"threads": set, "lockset": None|frozenset}
        self._state: dict[tuple[str, str], dict] = {}
        self._races: list[dict] = []

    # ---- instrumentation --------------------------------------------------
    def watch(self, obj, fields, name: Optional[str] = None):
        """Instrument `obj` so every access to `fields` is recorded. The
        object's class is swapped for a recording subclass (objects using
        __slots__ are not supported); returns `obj` for chaining."""
        label = name or type(obj).__name__
        watched = frozenset(fields)
        detector = self
        cls = type(obj)

        class _Watched(cls):  # type: ignore[misc, valid-type]
            def __getattribute__(self, attr):
                if attr in watched:
                    detector._note(label, attr, is_write=False)
                return super().__getattribute__(attr)

            def __setattr__(self, attr, value):
                if attr in watched:
                    detector._note(label, attr, is_write=True)
                object.__setattr__(self, attr, value)

        _Watched.__name__ = f"Watched{cls.__name__}"
        obj.__class__ = _Watched
        return obj

    def watch_guarded(self, obj, name: Optional[str] = None) -> dict[str, str]:
        """The static↔dynamic bridge: watch() exactly the fields the
        object's class annotates `# guarded-by:` in source — no hand-kept
        field list to drift from the vet pass — and swap each named lock
        attribute for an InstrumentedLock wrapping the original so the
        lockset feed needs no further wiring. Returns the attr -> lock map
        (callers assert it is non-empty: watching nothing is a test bug).

        Caveat: the lock swap rebinds the ATTRIBUTE; anything that
        captured the raw lock object at init (e.g. a Condition built on
        it) keeps the uninstrumented original."""
        guarded = guarded_fields(obj)
        for lock_attr in sorted(set(guarded.values())):
            lk = getattr(obj, lock_attr, None)
            if lk is not None and not isinstance(lk, (InstrumentedLock, NullLock)):
                setattr(obj, lock_attr, InstrumentedLock(lock_attr, lk))
        self.watch(obj, sorted(guarded), name=name)
        return guarded

    def _note(self, name: str, field: str, is_write: bool) -> None:
        tid = _threading.get_ident()
        held = frozenset(id(lk) for lk in _held_locks())
        names = {id(lk): getattr(lk, "name", "?") for lk in _held_locks()}
        with self._mutex:
            st = self._state.setdefault(
                (name, field),
                {"threads": set(), "lockset": None, "locknames": {}, "raced": False},
            )
            st["threads"].add(tid)
            st["locknames"].update(names)
            if len(st["threads"]) < 2:
                return  # init phase: a single owner thread never races
            st["lockset"] = held if st["lockset"] is None else (st["lockset"] & held)
            if not st["lockset"] and not st["raced"]:
                st["raced"] = True
                self._races.append({
                    "object": name,
                    "field": field,
                    "threads": len(st["threads"]),
                    "write": is_write,
                    "detail": (
                        f"{name}.{field} accessed by {len(st['threads'])} "
                        "threads with no common lock held"
                    ),
                })

    # ---- verdicts ---------------------------------------------------------
    def races(self) -> list[dict]:
        with self._mutex:
            return list(self._races)

    def assert_clean(self) -> None:
        races = self.races()
        assert not races, "lock-free conflicting accesses detected:\n" + "\n".join(
            r["detail"] for r in races
        )
