"""L1 utilities: pure functions — index math, readiness predicates, revision
hashing/snapshots, TPU env synthesis (≈ pkg/utils/*)."""
