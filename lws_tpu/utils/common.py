"""Core helpers (≈ pkg/utils/utils.go)."""

from __future__ import annotations

import hashlib
import os
from typing import Callable, Optional, TypeVar

T = TypeVar("T")


def env_float(name: str, default: float) -> float:
    """Float env knob with a safe fallback (shared by the SLO targets and
    the watchdog windows — one parse rule for every telemetry tunable)."""
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def sha1_hash(s: str) -> str:
    """≈ utils.go:39 Sha1Hash."""
    return hashlib.sha1(s.encode()).hexdigest()


def nonzero(v: int) -> int:
    """Clamp negatives to 0 (≈ utils.go:45 NonZeroValue)."""
    return max(0, v)


def sort_by_index(
    index_fn: Callable[[T], int], items: list[T], length: int
) -> list[Optional[T]]:
    """Place items at their index in a fixed-length list; missing slots are
    None (≈ utils.go:53-71 SortByIndex). Indices outside [0, length) dropped."""
    out: list[Optional[T]] = [None] * length
    for item in items:
        try:
            idx = index_fn(item)
        except (ValueError, KeyError, TypeError):
            continue
        if 0 <= idx < length:
            out[idx] = item
    return out


def group_resource_total(leader_resources: dict[str, int], worker_resources: dict[str, int], size: int) -> dict[str, int]:
    """Whole-group resource sum: leader + (size-1) x worker — used as gang
    minResources (≈ utils.go:84-103 CalculatePGMinResources)."""
    total = dict(leader_resources)
    for k, v in worker_resources.items():
        total[k] = total.get(k, 0) + v * (size - 1)
    return total


def stable_hash(obj) -> str:
    """Canonical short hash of any plain-able object (shared by revision
    hashing and groupset template hashing so the two can never diverge)."""
    import json

    from lws_tpu.api.meta import to_plain

    canonical = json.dumps(to_plain(obj), sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canonical.encode()).hexdigest()[:10]
