"""Pod helpers + generic bootstrap env injection (≈ pkg/utils/pod/pod_utils.go).

`add_lws_variables` writes the generic group contract into every container:
LWS_LEADER_ADDRESS (always first — later vars may interpolate it), LWS_GROUP_SIZE,
LWS_WORKER_INDEX — plus the JAX-native coordinator triple so workloads can call
`jax.distributed.initialize()` with zero glue (this framework's addition; the
reference leaves that to the workload, ref docs/examples/vllm/TPU/lws.yaml:30-34).
"""

from __future__ import annotations

from lws_tpu.api import contract
from lws_tpu.api.pod import Container, EnvVar, Pod


def is_leader_pod(pod: Pod) -> bool:
    """≈ pod_utils.go:53 LeaderPod (worker-index label == "0")."""
    return pod.meta.labels.get(contract.WORKER_INDEX_LABEL_KEY) == "0"


def container_restarted(pod: Pod) -> bool:
    """≈ pod_utils.go:29-45 ContainerRestarted."""
    return pod.status.container_restarts > 0


def pod_running_and_ready(pod: Pod) -> bool:
    """≈ pod_utils.go:58 PodRunningAndReady."""
    from lws_tpu.api.pod import PodPhase

    return pod.status.phase == PodPhase.RUNNING and pod.status.ready


def add_env_vars_if_not_exists(c: Container, first: EnvVar, *rest: EnvVar) -> None:
    """Prepend [first, *rest] to the container env; existing vars with the
    same names are dropped so the injected value wins and sits first
    (≈ pod_utils.go:108-129 addEnvVarsIfNotExists)."""
    injected = [first, *rest]
    names = {e.name for e in injected}
    c.env = injected + [e for e in c.env if e.name not in names]


def leader_pod_name(lws_name: str, group_index: int | str) -> str:
    return f"{lws_name}-{group_index}"


def worker_pod_name(lws_name: str, group_index: int | str, worker_index: int | str) -> str:
    return f"{lws_name}-{group_index}-{worker_index}"


def add_lws_variables(pod: Pod) -> None:
    """≈ pod_utils.go:131-179 AddLWSVariables + JAX coordinator extension."""
    labels, annotations = pod.meta.labels, pod.meta.annotations
    lws_name = labels.get(contract.SET_NAME_LABEL_KEY)
    group_index = labels.get(contract.GROUP_INDEX_LABEL_KEY)
    worker_index = labels.get(contract.WORKER_INDEX_LABEL_KEY)
    size = annotations.get(contract.SIZE_ANNOTATION_KEY)
    if lws_name is None:
        raise ValueError(f"pod {pod.meta.name}: no set-name label")
    if group_index is None:
        raise ValueError(f"pod {pod.meta.name}: no group-index label")
    if worker_index is None:
        raise ValueError(f"pod {pod.meta.name}: no worker-index label")
    if size is None:
        raise ValueError(f"pod {pod.meta.name}: no size annotation")

    leader_address = (
        f"{lws_name}-{group_index}.{pod.spec.subdomain}.{pod.meta.namespace}"
    )
    leader_env = EnvVar(contract.LWS_LEADER_ADDRESS, leader_address)
    rest = [
        EnvVar(contract.LWS_GROUP_SIZE, size),
        EnvVar(contract.LWS_WORKER_INDEX, worker_index),
        # JAX-native bootstrap: coordinator on the leader, well-known port.
        EnvVar(
            contract.JAX_COORDINATOR_ADDRESS,
            f"{leader_address}:{contract.JAX_COORDINATOR_PORT_DEFAULT}",
        ),
        EnvVar(contract.JAX_NUM_PROCESSES, size),
        EnvVar(contract.JAX_PROCESS_ID, worker_index),
    ]
    sub_size = annotations.get(contract.SUBGROUP_SIZE_ANNOTATION_KEY)
    sub_index = labels.get(contract.SUBGROUP_INDEX_LABEL_KEY)
    if sub_size is not None and sub_index is not None:
        rest.append(EnvVar(contract.LWS_SUBGROUP_SIZE, sub_size))
        rest.append(EnvVar(contract.LWS_SUBGROUP_INDEX, sub_index))

    # Serving revision for worker-side telemetry: DS revision first, then the
    # template-revision hash — the same precedence the fleet scraper applies
    # to pod labels (runtime/fleet.py), so worker-local and fleet-injected
    # `revision` label values always agree.
    from lws_tpu.api import disagg

    revision = (labels.get(disagg.DS_REVISION_LABEL_KEY)
                or labels.get(contract.REVISION_LABEL_KEY))
    if revision:
        rest.append(EnvVar(contract.LWS_TPU_REVISION, revision))

    for c in pod.spec.containers:
        add_env_vars_if_not_exists(c, leader_env, *rest)
    for c in pod.spec.init_containers:
        add_env_vars_if_not_exists(c, leader_env, *rest)
