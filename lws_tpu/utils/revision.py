"""ControllerRevision-based template history
(≈ pkg/utils/revision/revision_utils.go).

A revision snapshots the revisable fields of an LWS — {network_config,
leader_worker_template} — so (a) template updates are detected semantically,
and (b) worker groups are built from the *revision their leader runs*, not the
live spec (no mixed groups mid-rollout, ref revision_utils.go:168-184).
"""

from __future__ import annotations

from typing import Optional

from lws_tpu.api import contract
from lws_tpu.api.meta import to_plain
from lws_tpu.api.revision import ControllerRevision
from lws_tpu.api.types import LeaderWorkerSet
from lws_tpu.core.store import clone_object, Store, new_meta
from lws_tpu.utils.common import stable_hash


def revision_data(lws: LeaderWorkerSet) -> dict:
    """The revisable subset (≈ getPatch, revision_utils.go:265-297)."""
    return {
        "leader_worker_template": clone_object(lws.spec.leader_worker_template),
        "network_config": clone_object(lws.spec.network_config),
    }


def hash_revision_data(data: dict) -> str:
    return stable_hash(data)


def get_revision_key(obj) -> str:
    return obj.meta.labels.get(contract.REVISION_LABEL_KEY, "")


def new_revision(lws: LeaderWorkerSet, revision_num: int = 1) -> ControllerRevision:
    data = revision_data(lws)
    key = hash_revision_data(data)
    rev = ControllerRevision(
        meta=new_meta(
            name=f"{lws.meta.name}-{key}",
            namespace=lws.meta.namespace,
            labels={
                contract.SET_NAME_LABEL_KEY: lws.meta.name,
                contract.REVISION_LABEL_KEY: key,
            },
            owners=[lws],
        ),
        data=data,
        revision=revision_num,
    )
    return rev


def list_revisions(store: Store, lws: LeaderWorkerSet) -> list[ControllerRevision]:
    revs = store.list(
        "ControllerRevision",
        lws.meta.namespace,
        labels={contract.SET_NAME_LABEL_KEY: lws.meta.name},
    )
    return sorted(revs, key=lambda r: r.revision)  # type: ignore[attr-defined]


def get_revision(store: Store, lws: LeaderWorkerSet, key: str) -> Optional[ControllerRevision]:
    for rev in list_revisions(store, lws):
        if get_revision_key(rev) == key:
            return rev
    return None


def equal_revision(lws: LeaderWorkerSet, rev: ControllerRevision) -> bool:
    """Semantic template equality (≈ revision_utils.go:188-235 EqualRevision;
    canonical plain-form comparison subsumes the serialization-drift LRU)."""
    return to_plain(revision_data(lws)) == to_plain(rev.data)


def get_or_create_current_revision(store: Store, lws: LeaderWorkerSet) -> ControllerRevision:
    """≈ leaderworkerset_controller.go:722-745 getOrCreateRevisionIfNonExist."""
    data = revision_data(lws)
    key = hash_revision_data(data)
    existing = get_revision(store, lws, key)
    if existing is not None:
        return existing
    revs = list_revisions(store, lws)
    next_num = (revs[-1].revision + 1) if revs else 1
    rev = new_revision(lws, next_num)
    return store.create(rev)  # type: ignore[return-value]


def apply_revision(lws: LeaderWorkerSet, rev: ControllerRevision) -> LeaderWorkerSet:
    """Restore the revisable fields from a snapshot (≈ ApplyRevision,
    revision_utils.go:168-184)."""
    restored = clone_object(lws)
    restored.spec.leader_worker_template = clone_object(rev.data["leader_worker_template"])
    restored.spec.network_config = clone_object(rev.data["network_config"])
    return restored


def truncate_revisions(store: Store, lws: LeaderWorkerSet, current_key: str) -> None:
    """GC all revisions but the current one, only safe once an update is done
    (≈ revision_utils.go:239-259 TruncateRevisions)."""
    for rev in list_revisions(store, lws):
        if get_revision_key(rev) != current_key:
            store.delete("ControllerRevision", rev.meta.namespace, rev.meta.name)
