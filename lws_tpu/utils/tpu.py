"""TPU bootstrap env synthesis (behavioral parity with
pkg/utils/accelerators/tpu.go).

Writes the libtpu multi-host contract into TPU-requesting containers:
  TPU_WORKER_HOSTNAMES   all hosts of the (sub)group, rank order == ICI order
  TPU_WORKER_ID          this host's rank within the (sub)group
  TPU_NAME               the group's leader pod name (slice identity)
  TPU_PROCESS_ADDRESSES  host:port list, TPU_PROCESS_PORT default 8476

Rank ordering rules (the hard part, ref tpu.go:99-299):
  * whole-group: leader gets id 0 iff it requests TPUs; otherwise workers are
    shifted down by one (leader is not a TPU worker).
  * multiple TPU containers per pod interleave ids: pod j's container i gets
    id j*numContainers+i, ports default+i.
  * subgroup: each subgroup [sgs*idx+1, sgs*(idx+1)] gets its own hostname
    window; windows shift left by one when the leader (which then joins
    subgroup 0) itself holds TPUs.
"""

from __future__ import annotations

from lws_tpu.api import contract
from lws_tpu.api.groupset import parent_name_and_ordinal
from lws_tpu.api.pod import Container, EnvVar, Pod


def pod_requests_tpus(pod: Pod) -> bool:
    return pod.spec.requests_tpus()


def _tpu_containers(pod: Pod) -> list[Container]:
    return [c for c in pod.spec.containers if c.tpu_chips() > 0] + [
        c for c in pod.spec.init_containers if c.tpu_chips() > 0
    ]


def add_tpu_annotations(leader_pod: Pod, annotations: dict[str, str]) -> None:
    """≈ tpu.go:302-306 — propagate leader-requests-tpus to worker metadata."""
    if pod_requests_tpus(leader_pod):
        annotations[contract.LEADER_REQUESTS_TPUS_ANNOTATION_KEY] = "true"


def add_tpu_variables(pod: Pod, size: int) -> None:
    """Entry point (≈ tpu.go:201 AddTPUVariables)."""
    if contract.SUBGROUP_SIZE_ANNOTATION_KEY in pod.meta.annotations:
        _add_tpu_variables_subgroup(pod)
        return

    containers = _tpu_containers(pod)
    if not containers:
        return
    for name in (contract.TPU_WORKER_HOSTNAMES, contract.TPU_WORKER_ID):
        if containers[0].env_value(name)[0]:
            return  # already injected

    is_leader = pod.meta.labels.get(contract.WORKER_INDEX_LABEL_KEY) == "0"
    leader_requests = (
        pod.meta.annotations.get(contract.LEADER_REQUESTS_TPUS_ANNOTATION_KEY) == "true"
    )
    if is_leader:
        leader_pod_name = pod.meta.name
        pod_worker_index = 0
    else:
        leader_pod_name, ordinal = parent_name_and_ordinal(pod.meta.name)
        if leader_pod_name is None:
            raise ValueError(f"parsing parent name from pod {pod.meta.name}")
        # Leader without TPUs is not a TPU worker: shift worker ids down.
        pod_worker_index = ordinal if leader_requests else ordinal - 1

    n = len(containers)
    ports: list[str] = []
    for i, c in enumerate(containers):
        found, val = c.env_value(contract.TPU_PROCESS_PORT)
        ports.append(val if found else str(contract.TPU_PROCESS_DEFAULT_PORT + i))

    subdomain = pod.spec.subdomain
    hostnames: list[str] = []
    addresses: list[str] = []
    if leader_requests or is_leader:
        leader_host = f"{leader_pod_name}.{subdomain}"
        for i in range(n):
            hostnames.append(leader_host)
            addresses.append(f"{leader_host}:{ports[i]}")
    for i in range(1, size):
        host = f"{leader_pod_name}-{i}.{subdomain}"
        for j in range(n):
            hostnames.append(host)
            addresses.append(f"{host}:{ports[j]}")

    for i, c in enumerate(containers):
        worker_id = pod_worker_index * n + i
        had_port = c.env_value(contract.TPU_PROCESS_PORT)[0]
        c.env.extend(
            [
                EnvVar(contract.TPU_WORKER_HOSTNAMES, ",".join(hostnames)),
                EnvVar(contract.TPU_WORKER_ID, str(worker_id)),
                EnvVar(contract.TPU_NAME, leader_pod_name),
                EnvVar(contract.TPU_PROCESS_ADDRESSES, ",".join(addresses)),
            ]
        )
        if not had_port:
            c.env.append(EnvVar(contract.TPU_PROCESS_PORT, ports[i]))


def _add_tpu_variables_subgroup(pod: Pod) -> None:
    """≈ tpu.go:99-198 addTPUVariablesSubGroup.

    Deviation from the reference (deliberate): a leader pod that itself
    requests TPUs gets TPU_WORKER_ID=0 even when the leader-requests-tpus
    annotation wasn't propagated onto it — the reference computes
    (0-1)%sgs = -1 there (tpu.go:126-129), which misassembles the ICI ring.
    """
    containers = _tpu_containers(pod)
    if not containers:
        return
    container = containers[0]
    for name in (contract.TPU_WORKER_HOSTNAMES, contract.TPU_WORKER_ID):
        if container.env_value(name)[0]:
            return

    annotations, labels = pod.meta.annotations, pod.meta.labels
    if contract.SUBGROUP_INDEX_LABEL_KEY not in labels:
        # A TPU-holding pod outside any subgroup (e.g. a LeaderExcluded leader,
        # which admission normally rejects) gets no subgroup TPU env.
        return
    sgs = int(annotations[contract.SUBGROUP_SIZE_ANNOTATION_KEY])
    sub_index = int(labels[contract.SUBGROUP_INDEX_LABEL_KEY])
    worker_index = int(labels[contract.WORKER_INDEX_LABEL_KEY])
    is_leader = labels.get(contract.WORKER_INDEX_LABEL_KEY) == "0"
    leader_requests = (
        annotations.get(contract.LEADER_REQUESTS_TPUS_ANNOTATION_KEY) == "true"
        or is_leader  # the leader reaching this path holds TPUs itself
    )

    tpu_worker_id = worker_index % sgs if leader_requests else (worker_index - 1) % sgs

    found_port, port = container.env_value(contract.TPU_PROCESS_PORT)
    if not found_port:
        port = str(contract.TPU_PROCESS_DEFAULT_PORT)

    start = sgs * sub_index + 1
    end = sgs * (sub_index + 1)
    subdomain = pod.spec.subdomain
    hostnames: list[str] = []
    addresses: list[str] = []

    if is_leader:
        leader_name = pod.meta.name
        hostnames.append(f"{leader_name}.{subdomain}")
        addresses.append(f"{leader_name}.{subdomain}:{port}")
        end -= 1
    else:
        leader_name, _ = parent_name_and_ordinal(pod.meta.name)
        if leader_name is None:
            raise ValueError(f"parsing parent name from pod {pod.meta.name}")
        if annotations.get(contract.LEADER_REQUESTS_TPUS_ANNOTATION_KEY) == "true" and sub_index == 0:
            # Leader holds TPUs and lives in subgroup 0: include it and shift
            # the window left by one.
            end -= 1
            hostnames.append(f"{leader_name}.{subdomain}")
            addresses.append(f"{leader_name}.{subdomain}:{port}")
        elif annotations.get(contract.LEADER_REQUESTS_TPUS_ANNOTATION_KEY) == "true":
            # Subsequent subgroups shift too.
            start -= 1
            end -= 1

    for i in range(start, end + 1):
        hostnames.append(f"{leader_name}-{i}.{subdomain}")
        addresses.append(f"{leader_name}-{i}.{subdomain}:{port}")

    container.env.extend(
        [
            EnvVar(contract.TPU_WORKER_HOSTNAMES, ",".join(hostnames)),
            EnvVar(contract.TPU_WORKER_ID, str(tpu_worker_id)),
            EnvVar(contract.TPU_NAME, leader_name),
            EnvVar(contract.TPU_PROCESS_ADDRESSES, ",".join(addresses)),
        ]
    )
    if not found_port:
        container.env.append(EnvVar(contract.TPU_PROCESS_PORT, port))


def get_subgroup_index(pod_count: int, subgroup_size: int, worker_index: int) -> int:
    """Worker's subgroup (≈ pod_webhook.go:249-255 getSubGroupIndex): when
    (size-1) divides evenly the leader is the 'extra pod' folded into subgroup
    0, so workers shift down by one."""
    if (pod_count - 1) % subgroup_size == 0:
        return (worker_index - 1) // subgroup_size
    return worker_index // subgroup_size
