"""Version + user-agent (≈ pkg/utils/useragent + pkg/version): identifies
this control plane in logs/API calls."""

from __future__ import annotations

import platform

VERSION = "0.1.0"
GIT_COMMIT = "unknown"  # stamped by packaging


def user_agent() -> str:
    """`lws-tpu/<version> (<os>/<arch>) <commit>` (≈ useragent.go:36)."""
    return f"lws-tpu/{VERSION} ({platform.system().lower()}/{platform.machine()}) {GIT_COMMIT}"
