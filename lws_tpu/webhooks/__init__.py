"""L2 admission: defaulting + validation for LWS/DS, pod mutation
(≈ pkg/webhooks/). Registered as store admission hooks — synchronous, inside
the write path, exactly like webhooks sit inside the apiserver request path.
"""

from lws_tpu.webhooks.lws_webhook import register_lws_webhooks  # noqa: F401
from lws_tpu.webhooks.pod_webhook import register_pod_webhooks  # noqa: F401
