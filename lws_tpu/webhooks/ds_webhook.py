"""DisaggregatedSet validation
(≈ pkg/webhooks/disaggregatedset/disaggregatedset_webhook.go + CRD CEL rules).
"""

from __future__ import annotations

from typing import Optional

from lws_tpu.api.disagg import MAX_ROLES, MAX_SLICES, MIN_ROLES, DisaggregatedSet
from lws_tpu.api.types import RolloutStrategyType
from lws_tpu.core.store import AdmissionError, Store
from lws_tpu.webhooks.lws_webhook import DNS1035


def validate_ds(ds: DisaggregatedSet, old: Optional[DisaggregatedSet]) -> None:
    if not DNS1035.match(ds.meta.name):
        raise AdmissionError(f"invalid name {ds.meta.name!r}: must be a valid DNS-1035 label")
    roles = ds.spec.roles
    if not (1 <= ds.spec.slices <= MAX_SLICES):
        raise AdmissionError(f"slices must be between 1 and {MAX_SLICES}")
    # Derived names must stay valid DNS labels: the longest is the private
    # service `<ds>-<slice>-<rev8>-<role>-prv` — reject at DS admission rather
    # than crash-looping reconcile when the child LWS is refused.
    slice_digits = len(str(max(1, ds.spec.slices) - 1))
    for r in roles:
        derived = len(ds.meta.name) + 1 + slice_digits + 1 + 8 + 1 + len(r.name) + 4
        if derived > 63:
            raise AdmissionError(
                f"name {ds.meta.name!r} + role {r.name!r} too long: derived service name "
                f"would be {derived} chars (max 63)"
            )
    if not (MIN_ROLES <= len(roles) <= MAX_ROLES):
        raise AdmissionError(f"roles must have between {MIN_ROLES} and {MAX_ROLES} entries")
    names = [r.name for r in roles]
    if len(set(names)) != len(names):
        raise AdmissionError("role names must be unique")
    for r in roles:
        if not DNS1035.match(r.name):
            raise AdmissionError(f"invalid role name {r.name!r}")
        if r.replicas < 0:
            raise AdmissionError(f"role {r.name}: replicas must be >= 0")
        strategy = r.template.spec.rollout_strategy
        # DS owns the cross-role rollout: per-role partitions are forbidden
        # (ref disaggregatedset_webhook.go:78-102).
        if strategy.type not in (None, RolloutStrategyType.ROLLING_UPDATE):
            raise AdmissionError(f"role {r.name}: rolloutStrategy.type must be RollingUpdate")
        rc = strategy.rolling_update_configuration
        if rc is not None and rc.partition not in (0, None):
            raise AdmissionError(
                f"role {r.name}: partition is not allowed (DisaggregatedSet owns cross-role rollout)"
            )
    # CEL rule: replicas all-zero or all-nonzero (disaggregatedset_types.go:62-73).
    zero = [r.name for r in roles if r.replicas == 0]
    if zero and len(zero) != len(roles):
        raise AdmissionError(
            f"role replicas must be all-zero or all-nonzero (zero roles: {zero})"
        )


def register_ds_webhooks(store: Store) -> None:
    store.register_validator("DisaggregatedSet", validate_ds)
