"""LWS defaulting + validation (≈ pkg/webhooks/leaderworkerset_webhook.go)."""

from __future__ import annotations

import re
from typing import Optional

from lws_tpu.api.intstr import scaled_value, validate as validate_intstr
from lws_tpu.api.meta import to_plain
from lws_tpu.api.types import (
    LeaderWorkerSet,
    NetworkConfig,
    RestartPolicy,
    RollingUpdateConfiguration,
    RolloutStrategy,
    RolloutStrategyType,
    StartupPolicy,
    SubdomainPolicy,
    SubGroupPolicyType,
)
from lws_tpu.core.store import AdmissionError, Store

MAX_INT32 = 2**31 - 1
# DNS-1035: the LWS name becomes a service name and a pod-name prefix.
DNS1035 = re.compile(r"^[a-z]([-a-z0-9]*[a-z0-9])?$")


def default_lws(lws: LeaderWorkerSet, old: Optional[LeaderWorkerSet]) -> None:
    """≈ :52-85 Default."""
    spec = lws.spec
    if spec.replicas is None:  # type: ignore[comparison-overlap]
        spec.replicas = 1
    if spec.leader_worker_template.size is None:  # type: ignore[comparison-overlap]
        spec.leader_worker_template.size = 1
    if spec.leader_worker_template.restart_policy == RestartPolicy.DEPRECATED_DEFAULT:
        spec.leader_worker_template.restart_policy = RestartPolicy.NONE
    if spec.rollout_strategy is None:  # type: ignore[comparison-overlap]
        spec.rollout_strategy = RolloutStrategy()
    if spec.rollout_strategy.type is None:  # type: ignore[comparison-overlap]
        spec.rollout_strategy.type = RolloutStrategyType.ROLLING_UPDATE
    if spec.rollout_strategy.rolling_update_configuration is None:
        spec.rollout_strategy.rolling_update_configuration = RollingUpdateConfiguration(
            partition=0, max_unavailable=1, max_surge=0
        )
    if spec.startup_policy is None:  # type: ignore[comparison-overlap]
        spec.startup_policy = StartupPolicy.LEADER_CREATED
    if spec.network_config is None:
        spec.network_config = NetworkConfig(subdomain_policy=SubdomainPolicy.SHARED)
    elif spec.network_config.subdomain_policy is None:
        spec.network_config.subdomain_policy = SubdomainPolicy.SHARED
    sgp = spec.leader_worker_template.sub_group_policy
    if sgp is not None and sgp.type is None:
        sgp.type = SubGroupPolicyType.LEADER_WORKER


def validate_lws(lws: LeaderWorkerSet, old: Optional[LeaderWorkerSet]) -> None:
    """≈ :92-256 ValidateCreate/ValidateUpdate."""
    if not DNS1035.match(lws.meta.name) or len(lws.meta.name) > 63:
        raise AdmissionError(
            f"invalid name {lws.meta.name!r}: must be a valid DNS-1035 label (it becomes the service name)"
        )
    spec = lws.spec
    if spec.replicas < 0:
        raise AdmissionError("replicas must be >= 0")
    size = spec.leader_worker_template.size
    if size < 1:
        raise AdmissionError("size must be >= 1")
    if spec.replicas * size > MAX_INT32:
        raise AdmissionError("replicas x size must not exceed MaxInt32")

    cfg = spec.rollout_strategy.rolling_update_configuration
    if cfg is not None:
        try:
            validate_intstr(cfg.max_unavailable, "maxUnavailable")
            validate_intstr(cfg.max_surge, "maxSurge")
        except ValueError as e:
            raise AdmissionError(str(e)) from e
        if cfg.partition < 0:
            raise AdmissionError("partition must be >= 0")
        mu = scaled_value(cfg.max_unavailable, spec.replicas, False)
        ms = scaled_value(cfg.max_surge, spec.replicas, True)
        if isinstance(cfg.max_unavailable, int) and isinstance(cfg.max_surge, int):
            if cfg.max_unavailable == 0 and cfg.max_surge == 0:
                raise AdmissionError("maxUnavailable and maxSurge must not both be 0")
        elif mu == 0 and ms == 0 and spec.replicas > 0:
            raise AdmissionError("maxUnavailable and maxSurge must not both resolve to 0")

    sgp = spec.leader_worker_template.sub_group_policy
    if sgp is not None:
        sgs = sgp.sub_group_size
        if sgs is None or sgs < 1:
            raise AdmissionError("subGroupSize must be >= 1")
        if sgs > size:
            raise AdmissionError("subGroupSize must not be greater than size")
        if (sgp.type or SubGroupPolicyType.LEADER_WORKER) == SubGroupPolicyType.LEADER_EXCLUDED:
            if (size - 1) % sgs != 0:
                raise AdmissionError("LeaderExcluded requires size-1 divisible by subGroupSize")
            leader_template = (
                spec.leader_worker_template.leader_template
                or spec.leader_worker_template.worker_template
            )
            if leader_template.spec.requests_tpus():
                raise AdmissionError(
                    "LeaderExcluded subgroups require a leader that does not request TPUs "
                    "(the leader is outside every subgroup's TPU hostname window)"
                )
        elif size % sgs != 0 and (size - 1) % sgs != 0:
            raise AdmissionError("size or size-1 must be divisible by subGroupSize")

    if spec.network_config is not None and spec.network_config.subdomain_policy is None:
        raise AdmissionError("subdomainPolicy must not be null")

    if old is not None:
        if to_plain(old.spec.leader_worker_template.sub_group_policy) != to_plain(sgp):
            raise AdmissionError("subGroupPolicy is immutable")


def register_lws_webhooks(store: Store) -> None:
    store.register_mutator("LeaderWorkerSet", default_lws)
    store.register_validator("LeaderWorkerSet", validate_lws)
