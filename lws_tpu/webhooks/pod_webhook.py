"""Pod mutation (≈ pkg/webhooks/pod_webhook.go): THE single place the whole
distributed-bootstrap contract is written into pods (SURVEY §3.3).

Leader branch: group index from ordinal, subdomain override (UniquePerReplica),
sha1 group key, exclusive affinity/anti-affinity, subgroup-0 labels.
Worker branch: worker index from ordinal, subgroup index math.
Then: gang metadata, TPU env (if chips requested), LWS + JAX env for all.
"""

from __future__ import annotations

from typing import Optional

from lws_tpu.api import contract
from lws_tpu.api.groupset import parent_name_and_ordinal
from lws_tpu.api.pod import (
    AffinityOperator,
    AffinityTerm,
    LabelSelectorRequirement,
    Pod,
    PodAffinity,
)
from lws_tpu.api.types import SubdomainPolicy, SubGroupPolicyType
from lws_tpu.core.store import Store
from lws_tpu.sched.provider import SchedulerProvider
from lws_tpu.utils.common import sha1_hash
from lws_tpu.utils.podutils import add_lws_variables, is_leader_pod
from lws_tpu.utils.tpu import add_tpu_variables, get_subgroup_index, pod_requests_tpus


def gen_group_unique_key(a: str, b: str) -> str:
    """≈ pod_webhook.go:180-183 genGroupUniqueKey (sha1 of "a/b")."""
    return sha1_hash(f"{a}/{b}")


def set_exclusive_affinities(pod: Pod, unique_key: str, topology_key: str, label_key: str) -> None:
    """1:1 exclusive placement (≈ pod_webhook.go:185-227): require landing in
    a topology domain with this group's pods; forbid domains hosting others."""
    if pod.spec.affinity is None:
        pod.spec.affinity = PodAffinity()
    aff = pod.spec.affinity
    # Skip if already applied for this key.
    for term in aff.required_affinity:
        if term.topology_key == topology_key and any(
            r.key == label_key for r in term.match_expressions
        ):
            return
    aff.required_affinity.append(
        AffinityTerm(
            topology_key=topology_key,
            match_expressions=[
                LabelSelectorRequirement(label_key, AffinityOperator.IN, [unique_key])
            ],
        )
    )
    aff.required_anti_affinity.append(
        AffinityTerm(
            topology_key=topology_key,
            match_expressions=[
                LabelSelectorRequirement(label_key, AffinityOperator.EXISTS),
                LabelSelectorRequirement(label_key, AffinityOperator.NOT_IN, [unique_key]),
            ],
        )
    )


class PodWebhook:
    def __init__(self, scheduler_provider: Optional[SchedulerProvider] = None) -> None:
        self.scheduler_provider = scheduler_provider

    def default(self, pod: Pod, old: Optional[Pod]) -> None:
        if old is not None:
            return  # mutate on create only
        if contract.SET_NAME_LABEL_KEY not in pod.meta.labels:
            return
        size_str = pod.meta.annotations.get(contract.SIZE_ANNOTATION_KEY)
        if size_str is None:
            raise ValueError(f"pod {pod.meta.name}: missing size annotation")
        pod_count = int(size_str)
        labels, annotations = pod.meta.labels, pod.meta.annotations

        if is_leader_pod(pod):
            if contract.GROUP_INDEX_LABEL_KEY not in labels:
                _, group_index = parent_name_and_ordinal(pod.meta.name)
                if group_index == -1:
                    raise ValueError(f"parsing pod ordinal for pod {pod.meta.name}")
                labels[contract.GROUP_INDEX_LABEL_KEY] = str(group_index)
            if annotations.get(contract.SUBDOMAIN_POLICY_ANNOTATION_KEY) == SubdomainPolicy.UNIQUE_PER_REPLICA.value:
                pod.spec.subdomain = pod.meta.name
            group_key = labels.get(contract.GROUP_UNIQUE_HASH_LABEL_KEY)
            if group_key is None:
                group_key = gen_group_unique_key(pod.meta.namespace, pod.meta.name)
                labels[contract.GROUP_UNIQUE_HASH_LABEL_KEY] = group_key
            ep_key = annotations.get(contract.EXCLUSIVE_KEY_ANNOTATION_KEY)
            if ep_key:
                set_exclusive_affinities(pod, group_key, ep_key, contract.GROUP_UNIQUE_HASH_LABEL_KEY)
            sub_policy = annotations.get(contract.SUBGROUP_POLICY_TYPE_ANNOTATION_KEY)
            if (
                contract.SUBGROUP_SIZE_ANNOTATION_KEY in annotations
                and not labels.get(contract.SUBGROUP_INDEX_LABEL_KEY)
                and sub_policy != SubGroupPolicyType.LEADER_EXCLUDED.value
            ):
                # The leader always lands in subgroup 0.
                labels[contract.SUBGROUP_INDEX_LABEL_KEY] = "0"
                sub_key = gen_group_unique_key(pod.meta.name, "0")
                labels[contract.SUBGROUP_UNIQUE_HASH_LABEL_KEY] = sub_key
                sub_ep_key = annotations.get(contract.SUBGROUP_EXCLUSIVE_KEY_ANNOTATION_KEY)
                if sub_ep_key:
                    set_exclusive_affinities(
                        pod, sub_key, sub_ep_key, contract.SUBGROUP_UNIQUE_HASH_LABEL_KEY
                    )
        else:
            _, worker_index = parent_name_and_ordinal(pod.meta.name)
            if worker_index == -1:
                raise ValueError(f"parsing pod ordinal for pod {pod.meta.name}")
            labels[contract.WORKER_INDEX_LABEL_KEY] = str(worker_index)
            if (
                contract.SUBGROUP_SIZE_ANNOTATION_KEY in annotations
                and not labels.get(contract.SUBGROUP_INDEX_LABEL_KEY)
            ):
                sgs = int(annotations[contract.SUBGROUP_SIZE_ANNOTATION_KEY])
                leader_name = annotations.get(contract.LEADER_POD_NAME_ANNOTATION_KEY, "")
                sub_index = get_subgroup_index(pod_count, sgs, worker_index)
                labels[contract.SUBGROUP_INDEX_LABEL_KEY] = str(sub_index)
                sub_key = gen_group_unique_key(leader_name, str(sub_index))
                labels[contract.SUBGROUP_UNIQUE_HASH_LABEL_KEY] = sub_key
                sub_ep_key = annotations.get(contract.SUBGROUP_EXCLUSIVE_KEY_ANNOTATION_KEY)
                if sub_ep_key:
                    set_exclusive_affinities(
                        pod, sub_key, sub_ep_key, contract.SUBGROUP_UNIQUE_HASH_LABEL_KEY
                    )

        if self.scheduler_provider is not None:
            self.scheduler_provider.inject_pod_group_metadata(pod)

        if pod_requests_tpus(pod):
            add_tpu_variables(pod, pod_count)

        add_lws_variables(pod)


def register_pod_webhooks(store: Store, scheduler_provider: Optional[SchedulerProvider] = None) -> None:
    store.register_mutator("Pod", PodWebhook(scheduler_provider).default)
