"""Build the native extensions into lws_tpu/core/ (run: `make native` or
`python native/build.py`). Uses the CPython C API directly — no pybind11."""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import sysconfig

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
TARGET_DIR = os.path.join(REPO, "lws_tpu", "core")


def build() -> str:
    include = sysconfig.get_path("include")
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = os.path.join(TARGET_DIR, f"_fastclone{suffix}")
    src = os.path.join(HERE, "fastclone.c")
    cc = os.environ.get("CC", "gcc")
    cmd = [
        cc, "-O2", "-fPIC", "-shared", "-o", out, src, f"-I{include}",
        "-Wall", "-Wextra",
    ]
    subprocess.run(cmd, check=True)
    return out


if __name__ == "__main__":
    if shutil.which(os.environ.get("CC", "gcc")) is None:
        print("no C compiler; skipping native build", file=sys.stderr)
        raise SystemExit(0)
    print(build())
