/* fastclone: C deep-clone for the Store's API-object trees.
 *
 * The control plane's correctness model (reference: the k8s apiserver always
 * hands out decoded copies) requires a deep copy at every read/write/notify
 * boundary. Profiling showed generic copy.deepcopy at ~95% of control-plane
 * convergence time, and even a specialized Python clone stays the top cost.
 * API objects are trees of dataclasses / dicts / lists / scalars / enums
 * with no cycles or shared refs, so a C walk is safe and ~10x faster.
 *
 * Fallback contract: anything unrecognized is delegated to the Python
 * fallback callable supplied at init (copy.deepcopy), so semantics match the
 * pure-Python `_clone` exactly.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

static PyObject *enum_type = NULL;     /* enum.Enum */
static PyObject *fallback = NULL;      /* copy.deepcopy */
static PyObject *str_dcfields = NULL;  /* "__dataclass_fields__" */
static PyObject *str_dunder_dict = NULL; /* "__dict__" */

/* Depth bound: API objects are shallow trees (<20 levels). A cyclic object
 * would otherwise exhaust the C stack and crash the interpreter; past the
 * bound we delegate to copy.deepcopy, whose memo handles cycles correctly. */
#define CLONE_MAX_DEPTH 200

static PyObject *clone_obj(PyObject *x, int depth);

static PyObject *
clone_dict(PyObject *x, int depth)
{
    PyObject *out = PyDict_New();
    if (out == NULL)
        return NULL;
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(x, &pos, &key, &value)) {
        PyObject *cv = clone_obj(value, depth);
        if (cv == NULL || PyDict_SetItem(out, key, cv) < 0) {
            Py_XDECREF(cv);
            Py_DECREF(out);
            return NULL;
        }
        Py_DECREF(cv);
    }
    return out;
}

static PyObject *
clone_list(PyObject *x, int depth)
{
    Py_ssize_t n = PyList_GET_SIZE(x);
    PyObject *out = PyList_New(n);
    if (out == NULL)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *cv = clone_obj(PyList_GET_ITEM(x, i), depth);
        if (cv == NULL) {
            Py_DECREF(out);
            return NULL;
        }
        PyList_SET_ITEM(out, i, cv); /* steals ref */
    }
    return out;
}

static PyObject *
clone_tuple(PyObject *x, int depth)
{
    Py_ssize_t n = PyTuple_GET_SIZE(x);
    PyObject *out = PyTuple_New(n);
    if (out == NULL)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *cv = clone_obj(PyTuple_GET_ITEM(x, i), depth);
        if (cv == NULL) {
            Py_DECREF(out);
            return NULL;
        }
        PyTuple_SET_ITEM(out, i, cv); /* steals ref */
    }
    return out;
}

static PyObject *
clone_dataclass(PyObject *x, PyTypeObject *tp, int depth)
{
    /* new = cls.__new__(cls); new.__dict__ = clone(x.__dict__) */
    PyObject *new = tp->tp_alloc(tp, 0);
    if (new == NULL)
        return NULL;
    PyObject **dictptr = _PyObject_GetDictPtr(x);
    PyObject **newdictptr = _PyObject_GetDictPtr(new);
    if (dictptr == NULL || *dictptr == NULL || newdictptr == NULL) {
        /* __slots__ or exotic layout: fall back for full generality */
        Py_DECREF(new);
        return PyObject_CallFunctionObjArgs(fallback, x, NULL);
    }
    PyObject *cloned = clone_dict(*dictptr, depth);
    if (cloned == NULL) {
        Py_DECREF(new);
        return NULL;
    }
#if PY_VERSION_HEX >= 0x030D0000
    /* 3.13+: objects use inline-values/managed-dict layouts where
     * _PyObject_GetDictPtr materializes a dict a raw slot write would leak,
     * and raw writes bypass the managed-dict bookkeeping. The generic
     * setter handles both layouts correctly. */
    if (PyObject_SetAttr(new, str_dunder_dict, cloned) < 0) {
        /* Frozen dataclasses override __setattr__ to reject all writes,
         * including __dict__; match the pure-Python fallback instead of
         * raising where _py_clone would succeed. */
        PyErr_Clear();
        Py_DECREF(cloned);
        Py_DECREF(new);
        return PyObject_CallFunctionObjArgs(fallback, x, NULL);
    }
    Py_DECREF(cloned);
#else
    /* tp_alloc'd instances normally start with a NULL dict slot, but be
     * defensive: never overwrite a live dict without releasing it. */
    Py_XDECREF(*newdictptr);
    *newdictptr = cloned; /* owns the new dict */
#endif
    return new;
}

static PyObject *
clone_obj(PyObject *x, int depth)
{
    PyTypeObject *tp = Py_TYPE(x);
    if (++depth > CLONE_MAX_DEPTH)
        return PyObject_CallFunctionObjArgs(fallback, x, NULL);
    /* scalars: immutable, shared */
    if (x == Py_None || x == Py_True || x == Py_False ||
        tp == &PyUnicode_Type || tp == &PyLong_Type || tp == &PyFloat_Type) {
        Py_INCREF(x);
        return x;
    }
    if (tp == &PyDict_Type)
        return clone_dict(x, depth);
    if (tp == &PyList_Type)
        return clone_list(x, depth);
    /* dataclass instance: type carries __dataclass_fields__ */
    PyObject *fields = PyObject_GetAttr((PyObject *)tp, str_dcfields);
    if (fields != NULL) {
        Py_DECREF(fields);
        return clone_dataclass(x, tp, depth);
    }
    PyErr_Clear();
    /* enum members are immutable singletons */
    int is_enum = PyObject_IsInstance(x, enum_type);
    if (is_enum < 0)
        return NULL;
    if (is_enum) {
        Py_INCREF(x);
        return x;
    }
    if (tp == &PyTuple_Type)
        return clone_tuple(x, depth);
    return PyObject_CallFunctionObjArgs(fallback, x, NULL);
}

static PyObject *
py_clone(PyObject *self, PyObject *arg)
{
    (void)self;
    if (enum_type == NULL || fallback == NULL) {
        PyErr_SetString(PyExc_RuntimeError, "call _fastclone.init() first");
        return NULL;
    }
    return clone_obj(arg, 0);
}

static PyObject *
py_init(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *et, *fb;
    if (!PyArg_ParseTuple(args, "OO", &et, &fb))
        return NULL;
    Py_XDECREF(enum_type);
    Py_XDECREF(fallback);
    Py_INCREF(et);
    Py_INCREF(fb);
    enum_type = et;
    fallback = fb;
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"clone", py_clone, METH_O, "Deep-clone an API object tree."},
    {"init", py_init, METH_VARARGS, "Set (enum.Enum, fallback_deepcopy)."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastclone", NULL, -1, methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC
PyInit__fastclone(void)
{
    str_dcfields = PyUnicode_InternFromString("__dataclass_fields__");
    str_dunder_dict = PyUnicode_InternFromString("__dict__");
    if (str_dcfields == NULL || str_dunder_dict == NULL)
        return NULL;
    return PyModule_Create(&moduledef);
}
