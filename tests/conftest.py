"""Test harness: force a virtual 8-device CPU platform.

Compute-plane tests exercise real dp/pp/ep/tp/sp shardings on this virtual
mesh (the reference proves multi-node logic without real nodes the same way —
SURVEY §4.2); bench.py (not run under pytest) uses the real TPU chip.

Note: the axon TPU plugin (when present) overrides `jax_platforms` via
jax.config at registration, so the env var alone is not enough — we must
update the config after importing jax, before any backend use.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# Loud failure on list_shared no-mutation contract violations (store.py):
# must be set before lws_tpu.core.store is imported by any test.
os.environ["LWS_TPU_STORE_DEBUG"] = "1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
