"""Test harness: force a virtual 8-device CPU platform BEFORE jax initializes.

Compute-plane tests exercise real dp/pp/ep/tp/sp shardings on this virtual
mesh (the reference proves multi-node logic without real nodes the same way —
SURVEY §4.2); bench.py (not run under pytest) uses the real TPU chip.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
