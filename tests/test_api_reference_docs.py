"""The generated API reference must stay in lockstep with the code: the
committed docs/reference/ pages are exactly what tools/gen_api_reference.py
produces from the current dataclasses + contract (≈ the reference's genref
CI check, /root/reference/hack/genref)."""

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_generated_api_reference_in_sync():
    p = subprocess.run(
        [sys.executable, os.path.join("tools", "gen_api_reference.py"), "--check"],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
    )
    assert p.returncode == 0, f"stale docs/reference — regenerate:\n{p.stderr}"


def test_no_builtin_docstring_noise_or_empty_enum_rows():
    """The r4 generator leaked inherited str.__doc__ into every str-enum
    section and emitted empty value-description cells; pin the fix."""
    import glob
    import re

    for path in glob.glob(os.path.join(ROOT, "docs", "reference", "*.md")):
        text = open(path).read()
        assert "str(object=" not in text, f"builtin docstring noise in {path}"
        in_enum = False
        for lineno, line in enumerate(text.splitlines(), start=1):
            if line.startswith("| value | description |"):
                in_enum = True
                continue
            if in_enum:
                if not line.startswith("|"):
                    in_enum = False
                elif re.match(r"^\|\s*`[^`]*`\s*\|\s*\|$", line):
                    raise AssertionError(
                        f"empty enum value description {path}:{lineno}: {line}"
                    )


def test_reference_covers_the_contract():
    """Every public contract constant appears in the generated page."""
    from lws_tpu.api import contract

    page = open(
        os.path.join(ROOT, "docs", "reference",
                     "labels-annotations-and-environment-variables.md")
    ).read()
    names = [n for n, v in vars(contract).items()
             if not n.startswith("_") and isinstance(v, (str, int))]
    assert len(names) > 30
    missing = [n for n in names if f"`{n}`" not in page]
    assert not missing, missing
