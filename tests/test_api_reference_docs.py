"""The generated API reference must stay in lockstep with the code: the
committed docs/reference/ pages are exactly what tools/gen_api_reference.py
produces from the current dataclasses + contract (≈ the reference's genref
CI check, /root/reference/hack/genref)."""

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_generated_api_reference_in_sync():
    p = subprocess.run(
        [sys.executable, os.path.join("tools", "gen_api_reference.py"), "--check"],
        cwd=ROOT, capture_output=True, text=True, timeout=120,
    )
    assert p.returncode == 0, f"stale docs/reference — regenerate:\n{p.stderr}"


def test_reference_covers_the_contract():
    """Every public contract constant appears in the generated page."""
    from lws_tpu.api import contract

    page = open(
        os.path.join(ROOT, "docs", "reference",
                     "labels-annotations-and-environment-variables.md")
    ).read()
    names = [n for n, v in vars(contract).items()
             if not n.startswith("_") and isinstance(v, (str, int))]
    assert len(names) > 30
    missing = [n for n in names if f"`{n}`" not in page]
    assert not missing, missing
