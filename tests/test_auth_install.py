"""API authn/authz + install bundle (VERDICT #6; ref anchors: metrics
authn/authz filters cmd/main.go:336-348, RBAC config/rbac/, charts/lws/)."""

import json
import urllib.error
import urllib.request

import pytest

from lws_tpu.core.auth import TokenAuth, write_bootstrap_tokens
from lws_tpu.runtime import ControlPlane
from lws_tpu.runtime.server import ApiServer

LWS_YAML = b"""
apiVersion: leaderworkerset.x-k8s.io/v1
kind: LeaderWorkerSet
metadata: {name: authy}
spec:
  replicas: 1
  leaderWorkerTemplate: {size: 2}
"""


@pytest.fixture
def authed_server(tmp_path):
    tokens = write_bootstrap_tokens(str(tmp_path / "tokens.csv"))
    auth = TokenAuth.load(str(tmp_path / "tokens.csv"))
    cp = ControlPlane(auto_ready=True)
    server = ApiServer(cp, port=0, auth=auth)
    server.start()
    yield server.port, tokens
    server.stop()


def _req(port, method, path, token=None, body=None):
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method=method, headers=headers
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, json.loads(resp.read().decode() or "{}")


def test_health_probes_stay_open(authed_server):
    port, _ = authed_server
    req = urllib.request.Request(f"http://127.0.0.1:{port}/healthz")
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.status == 200


def test_no_token_is_401(authed_server):
    port, _ = authed_server
    with pytest.raises(urllib.error.HTTPError) as e:
        _req(port, "GET", "/apis/lws")
    assert e.value.code == 401
    # Metrics are behind auth too (the reference filters them the same way).
    with pytest.raises(urllib.error.HTTPError) as e:
        _req(port, "GET", "/metrics")
    assert e.value.code == 401


def test_wrong_token_is_401(authed_server):
    port, _ = authed_server
    with pytest.raises(urllib.error.HTTPError) as e:
        _req(port, "GET", "/apis/lws", token="not-a-real-token")
    assert e.value.code == 401


def test_admin_can_write_view_cannot(authed_server):
    port, tokens = authed_server
    status, out = _req(port, "POST", "/apply", token=tokens["admin"], body=LWS_YAML)
    assert status == 200 and out["applied"] == ["LeaderWorkerSet/authy"]
    # view: reads ok, writes 403.
    status, objs = _req(port, "GET", "/apis/lws", token=tokens["view"])
    assert status == 200 and [o["metadata"]["name"] for o in objs] == ["authy"]
    with pytest.raises(urllib.error.HTTPError) as e:
        _req(port, "POST", "/apply", token=tokens["view"], body=LWS_YAML)
    assert e.value.code == 403
    with pytest.raises(urllib.error.HTTPError) as e:
        _req(port, "DELETE", "/apis/lws/default/authy", token=tokens["view"])
    assert e.value.code == 403


def test_remote_client_sends_token(authed_server):
    from lws_tpu.client import ApiError, RemoteClient

    port, tokens = authed_server
    ok = RemoteClient(f"http://127.0.0.1:{port}", token=tokens["admin"])
    ok.apply(LWS_YAML.decode())
    assert [o["metadata"]["name"] for o in ok.list("LeaderWorkerSet")] == ["authy"]
    anon = RemoteClient(f"http://127.0.0.1:{port}")
    with pytest.raises(ApiError) as e:
        anon.list("LeaderWorkerSet")
    assert e.value.code == 401


def test_token_file_parsing(tmp_path):
    p = tmp_path / "tokens.csv"
    p.write_text(
        "# comment\n\nsecret-a,alice,admin\nsecret-v,bob,view\nbare-token\n"
    )
    auth = TokenAuth.load(str(p))
    assert auth.authenticate("Bearer secret-a").role == "admin"
    assert auth.authenticate("Bearer bare-token").role == "admin"  # default
    assert auth.authenticate("Bearer nope") is None
    assert auth.authenticate(None) is None
    assert not TokenAuth.authorize(auth.authenticate("Bearer secret-v"), "POST")

    bad = tmp_path / "bad.csv"
    bad.write_text("tok,joe,superuser\n")
    with pytest.raises(ValueError):
        TokenAuth.load(str(bad))


def test_install_renders_bundle(tmp_path):
    from lws_tpu.cli import main

    root = tmp_path / "bundle"
    assert main(["install", str(root)]) == 0
    for name in ("config.yaml", "tokens.csv", "start.sh", "lws-tpu.service",
                 "README.md", "kubernetes/deployment.yaml", "state", "tls"):
        assert (root / name).exists(), name
    # Token file is private; tokens parse; config loads strictly.
    assert (root / "tokens.csv").stat().st_mode & 0o777 == 0o600
    auth = TokenAuth.load(str(root / "tokens.csv"))
    assert {e.role for e in auth.entries} == {"admin", "view"}
    from lws_tpu.config import load_configuration

    cfg = load_configuration(str(root / "config.yaml"))
    assert cfg.enable_scheduler and cfg.backend == "local"
    # The systemd unit and start.sh reference the rendered paths.
    unit = (root / "lws-tpu.service").read_text()
    assert f"--state-dir {root}/state" in unit and "--token-file" in unit


def test_non_ascii_token_is_rejected_not_crash(authed_server):
    port, _ = authed_server
    with pytest.raises(urllib.error.HTTPError) as e:
        _req(port, "GET", "/apis/lws", token="caf\xe9-token")
    assert e.value.code == 401


def test_install_rerun_preserves_tokens(tmp_path):
    from lws_tpu.cli import main

    root = tmp_path / "bundle"
    assert main(["install", str(root)]) == 0
    before = (root / "tokens.csv").read_text()
    assert main(["install", str(root)]) == 0
    assert (root / "tokens.csv").read_text() == before


def test_install_values_parameterized(tmp_path):
    """VERDICT r3 #10 (chart analog, ref charts/lws/values.yaml): --set /
    --values override the bundle's knobs; unknown keys are rejected; the
    resolved values are recorded for reproducible re-renders."""
    import argparse

    from lws_tpu.cli import cmd_install, resolve_install_values

    (tmp_path / "vals.yaml").write_text("port: 7443\nreplicaCount: 5\n")
    args = argparse.Namespace(
        dir=str(tmp_path / "bundle"), port=None, backend=None,
        python="python3", set=["namespace=prod", "enablePrometheus=true"],
        values=str(tmp_path / "vals.yaml"),
    )
    assert cmd_install(args) == 0
    dep = (tmp_path / "bundle" / "kubernetes" / "deployment.yaml").read_text()
    assert "namespace: prod" in dep
    assert "replicas: 5" in dep
    assert "containerPort: 7443" in dep
    assert "prometheus.io/scrape" in dep
    cfg = (tmp_path / "bundle" / "config.yaml").read_text()
    assert "port: 7443" in cfg
    vals = (tmp_path / "bundle" / "values.yaml").read_text()
    assert "replicaCount: 5" in vals and "namespace: prod" in vals
    readme = (tmp_path / "bundle" / "README.md").read_text()
    assert "https://127.0.0.1:7443" in readme and "None" not in readme

    # --set beats --values (helm precedence); flags beat both.
    v = resolve_install_values(str(tmp_path / "vals.yaml"), ["port=1234"], port=999)
    assert v["port"] == 999
    v = resolve_install_values(str(tmp_path / "vals.yaml"), ["port=1234"])
    assert v["port"] == 1234

    # Strictness: unknown keys and bad types are rejected.
    import pytest as _pytest

    with _pytest.raises(ValueError, match="unknown install value"):
        resolve_install_values(None, ["bogus=1"])
    with _pytest.raises(ValueError, match="boolean"):
        resolve_install_values(None, ["enablePrometheus=maybe"])


def test_install_values_error_paths(tmp_path):
    """Every malformed input comes back as a clean ValueError, not a raw
    traceback: null ints, invalid YAML, out-of-range enums."""
    import pytest as _pytest

    from lws_tpu.cli import resolve_install_values

    (tmp_path / "null.yaml").write_text("port:\n")
    with _pytest.raises(ValueError, match="integer"):
        resolve_install_values(str(tmp_path / "null.yaml"), None)
    (tmp_path / "bad.yaml").write_text("port: [1,2\n")
    with _pytest.raises(ValueError, match="invalid YAML"):
        resolve_install_values(str(tmp_path / "bad.yaml"), None)
    with _pytest.raises(ValueError, match="backend"):
        resolve_install_values(None, ["backend=locall"])
    with _pytest.raises(ValueError, match="serviceType"):
        resolve_install_values(None, ["serviceType=External"])
