"""Hardening features: native autoscaler (HPA equivalent), PVC lifecycle from
volume claim templates, orbax checkpoint save/restore into mesh shardings."""

import jax
import jax.numpy as jnp

from lws_tpu.api.autoscaler import METRIC_ANNOTATION_PREFIX, Autoscaler, AutoscalerSpec
from lws_tpu.api.pod import VolumeClaimTemplate
from lws_tpu.core.store import new_meta
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import LWSBuilder, lws_pods


def set_metric(cp, pod_name, metric, value):
    pod = cp.store.get("Pod", "default", pod_name)
    pod.meta.annotations[METRIC_ANNOTATION_PREFIX + metric] = str(value)
    cp.store.update(pod)


def test_autoscaler_scales_up_and_down_with_stabilization():
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(2).build())
    cp.create(
        Autoscaler(
            meta=new_meta("asc"),
            spec=AutoscalerSpec(
                target="sample", min_replicas=1, max_replicas=4,
                metric="inflight", target_value=2.0, scale_down_stabilization=2,
            ),
        )
    )
    cp.run_until_stable()

    # Load of 6 against target 2 -> scale 1 -> 3 immediately.
    set_metric(cp, "sample-0", "inflight", 6.0)
    cp.run_until_stable()
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.spec.replicas == 3
    assert len(lws_pods(cp.store, "sample")) == 6

    # Load redistributes to target: stable (new leaders without metrics count
    # as at-target, so no compounding either).
    for i in range(3):
        set_metric(cp, f"sample-{i}", "inflight", 2.0)
    cp.run_until_stable()
    assert cp.store.get("LeaderWorkerSet", "default", "sample").spec.replicas == 3

    # Low load: first distinct observation does NOT scale down (stabilization)
    for i in range(3):
        set_metric(cp, f"sample-{i}", "inflight", 0.1)
    cp.run_until_stable()
    assert cp.store.get("LeaderWorkerSet", "default", "sample").spec.replicas == 3
    # ...the second distinct below-target observation crosses the window.
    set_metric(cp, "sample-0", "inflight", 0.05)
    cp.run_until_stable()
    assert cp.store.get("LeaderWorkerSet", "default", "sample").spec.replicas == 1
    assert "Scaled" in {e.reason for e in cp.recorder.events}


def test_autoscaler_respects_bounds():
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(2).size(1).build())
    cp.create(
        Autoscaler(
            meta=new_meta("asc"),
            spec=AutoscalerSpec(target="sample", min_replicas=1, max_replicas=3, target_value=1.0),
        )
    )
    cp.run_until_stable()
    set_metric(cp, "sample-0", "inflight", 100.0)
    cp.run_until_stable()
    assert cp.store.get("LeaderWorkerSet", "default", "sample").spec.replicas == 3  # capped


def test_pvc_lifecycle_retention():
    cp = ControlPlane(auto_ready=True)
    lws = LWSBuilder().replicas(1).size(2).build()
    lws.spec.leader_worker_template.volume_claim_templates = [
        VolumeClaimTemplate(name="ckpt", storage="10Gi")
    ]
    lws.spec.leader_worker_template.pvc_retention_policy_when_deleted = "Delete"
    cp.create(lws)
    cp.run_until_stable()
    pvcs = sorted(p.meta.name for p in cp.store.list("PersistentVolumeClaim"))
    assert pvcs == ["ckpt-sample-0", "ckpt-sample-0-1"]

    # Group recreation keeps the PVCs (stable identity storage)...
    from lws_tpu.testing import restart_pod_container

    restart_pod_container(cp.store, "default", "sample-0-1")
    cp.run_until_stable()
    assert len(cp.store.list("PersistentVolumeClaim")) == 2
    # ...but whenDeleted=Delete cascades them away with the LWS.
    cp.store.delete("LeaderWorkerSet", "default", "sample")
    cp.run_until_stable()
    assert cp.store.list("PersistentVolumeClaim") == []


def test_checkpoint_roundtrip_sharded(tmp_path):
    from lws_tpu.models import LlamaConfig
    from lws_tpu.models.checkpoint import restore_checkpoint, save_checkpoint
    from lws_tpu.models.train import init_train_state, make_optimizer, make_train_step
    from lws_tpu.parallel import MeshSpec, build_mesh

    cfg = LlamaConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq_len=32, remat=False,
    )
    mesh = build_mesh(MeshSpec(dp=2, pp=2, tp=2))
    opt = make_optimizer()
    state = init_train_state(cfg, mesh, opt)
    step = make_train_step(cfg, mesh, opt)
    batch = {"tokens": jnp.ones((2, 9), jnp.int32)}
    params, opt_state, loss1, _ = step(state.params, state.opt_state, batch)
    state.params, state.opt_state = params, opt_state

    path = str(tmp_path / "ckpt")
    save_checkpoint(path, state)
    restored = restore_checkpoint(path, cfg, mesh, opt)

    # Restored params land in the SAME sharding layout.
    wq = restored.params["layers"]["wq"]
    assert wq.sharding.spec[0] == "pp" and wq.sharding.spec[2] == "tp"
    # And continue training deterministically vs the original.
    p1, o1, loss_a, _ = step(restored.params, restored.opt_state, batch)
    import numpy as np

    p2, o2, loss_b, _ = step(
        jax.tree.map(lambda x: x, params), jax.tree.map(lambda x: x, opt_state), batch
    )
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)


def test_autoscaler_steady_load_keeps_scaling():
    """Regression: re-reports of the SAME value are fresh observations — the
    loop must not stall on steady load (dedup is by observation, not value)."""
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(1).build())
    cp.create(
        Autoscaler(
            meta=new_meta("asc"),
            spec=AutoscalerSpec(target="sample", min_replicas=1, max_replicas=9, target_value=2.0),
        )
    )
    cp.run_until_stable()
    set_metric(cp, "sample-0", "inflight", 6.0)
    cp.run_until_stable()
    assert cp.store.get("LeaderWorkerSet", "default", "sample").spec.replicas == 3
    # Load stays hot: ALL leaders re-report the same 6.0.
    for i in range(3):
        set_metric(cp, f"sample-{i}", "inflight", 6.0)
    cp.run_until_stable()
    assert cp.store.get("LeaderWorkerSet", "default", "sample").spec.replicas == 9
