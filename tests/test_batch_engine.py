"""Continuous batching correctness: staggered admissions decode together yet
produce exactly the sequences an isolated engine produces."""

import jax
import jax.numpy as jnp
import numpy as np

from lws_tpu.models import init_params
from lws_tpu.models.llama import LlamaConfig
from lws_tpu.serving import Engine
from lws_tpu.serving.batch_engine import BatchEngine


def tiny_cfg():
    return LlamaConfig(
        vocab_size=101, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=64, dtype=jnp.float32, remat=False,
    )


def oracle(cfg, params, prompt, n):
    engine = Engine(cfg, params, batch_size=1, max_len=32)
    result = engine.generate(np.asarray(prompt).reshape(1, -1), max_new_tokens=n)
    return list(np.asarray(result.tokens)[0])


def test_staggered_requests_match_isolated_decoding():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = BatchEngine(cfg, params, slots=3, max_len=32)

    a = engine.submit(np.array([5, 9, 2], np.int32), max_new_tokens=8)
    for _ in range(3):
        engine.step()
    # B joins while A is mid-decode; C joins later still.
    b = engine.submit(np.array([7, 7, 1, 4], np.int32), max_new_tokens=6)
    engine.step()
    c = engine.submit(np.array([3], np.int32), max_new_tokens=5)
    engine.run_until_drained()

    assert engine.result(a) == oracle(cfg, params, [5, 9, 2], 8)
    assert engine.result(b) == oracle(cfg, params, [7, 7, 1, 4], 6)
    assert engine.result(c) == oracle(cfg, params, [3], 5)
    assert engine.active_count == 0


def test_slot_reuse_after_completion():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = BatchEngine(cfg, params, slots=1, max_len=32)

    a = engine.submit(np.array([5, 9, 2], np.int32), max_new_tokens=4)
    assert engine.submit(np.array([1], np.int32), max_new_tokens=2) is None  # full
    engine.run_until_drained()
    # The freed slot admits a new request whose output is uncontaminated by
    # the previous occupant's cache rows.
    b = engine.submit(np.array([7, 7, 1, 4], np.int32), max_new_tokens=6)
    engine.run_until_drained()
    assert engine.result(a) == oracle(cfg, params, [5, 9, 2], 4)
    assert engine.result(b) == oracle(cfg, params, [7, 7, 1, 4], 6)
