"""Cert management (≈ reference pkg/cert/cert.go webhook cert rotation):
self-signed CA + serving cert generation, rotation lookahead, and an HTTPS
API-server round trip trusting only the published CA bundle."""

import json
import ssl
import urllib.error
import urllib.request

import pytest

from lws_tpu.core.certs import CertManager, client_context
from lws_tpu.runtime import ControlPlane
from lws_tpu.runtime.server import ApiServer
from lws_tpu.testing import LWSBuilder


def test_ensure_generates_and_is_idempotent(tmp_path):
    mgr = CertManager(str(tmp_path / "pki"))
    paths = mgr.ensure()
    assert paths.ca_cert.exists() and paths.server_cert.exists()
    assert paths.server_key.stat().st_mode & 0o777 == 0o600
    before = paths.server_cert.read_bytes()
    mgr.ensure()
    assert paths.server_cert.read_bytes() == before  # no spurious rotation
    assert not mgr.needs_rotation()


def test_rotation_past_two_thirds_lifetime(tmp_path):
    # 1-second validity: generation instantly lands past the 2/3 lookahead.
    mgr = CertManager(str(tmp_path / "pki"), validity_s=1)
    first = mgr.ensure().server_cert.read_bytes()
    import time

    time.sleep(1.1)
    assert mgr.needs_rotation()
    assert mgr.ensure().server_cert.read_bytes() != first


def test_https_api_round_trip(tmp_path):
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(2).build())
    cp.run_until_stable()
    mgr = CertManager(str(tmp_path / "pki"))
    server = ApiServer(cp, port=0, tls=mgr)
    server.start()
    base = f"https://127.0.0.1:{server.port}"
    try:
        # Trusting the published CA works...
        ctx = client_context(str(mgr.paths.ca_cert))
        with urllib.request.urlopen(base + "/apis/lws", context=ctx) as r:
            assert json.loads(r.read())[0]["metadata"]["name"] == "sample"
        # ...the default trust store does not (self-signed CA).
        with pytest.raises(urllib.error.URLError) as e:
            urllib.request.urlopen(
                base + "/healthz", context=ssl.create_default_context()
            )
        assert isinstance(e.value.reason, ssl.SSLError)
        # --insecure equivalent: no verification.
        with urllib.request.urlopen(base + "/healthz", context=client_context(None)) as r:
            assert r.read() == b"ok"
    finally:
        server.stop()


def test_running_server_rotates_certs(tmp_path):
    """Rotation must reach clients of a RUNNING server: the listener wraps
    per-connection, so a regenerated cert/CA applies without a restart."""
    import time

    cp = ControlPlane()
    # 3s validity: rotation due after ~2s, and the regenerated cert then has
    # a fresh 2s window in which the re-published CA verifies it.
    mgr = CertManager(str(tmp_path / "pki"), validity_s=3)
    server = ApiServer(cp, port=0, tls=mgr)
    server.start()
    base = f"https://127.0.0.1:{server.port}"
    try:
        old_ctx = client_context(str(mgr.paths.ca_cert))
        with urllib.request.urlopen(base + "/healthz", context=old_ctx) as r:
            assert r.read() == b"ok"
        time.sleep(2.1)  # past 2/3 of the 3s lifetime -> rotation due
        # Old CA no longer vouches for the new chain...
        with pytest.raises(urllib.error.URLError):
            urllib.request.urlopen(base + "/healthz", context=old_ctx)
        # ...the re-published bundle does (cert-controller's CA re-patch).
        new_ctx = client_context(str(mgr.paths.ca_cert))
        with urllib.request.urlopen(base + "/healthz", context=new_ctx) as r:
            assert r.read() == b"ok"
    finally:
        server.stop()
