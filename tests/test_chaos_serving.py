"""Chaos suite: the serving data plane under injected faults (ISSUE 8).

Every scenario drives REAL transport (KVServer sockets over localhost)
through deterministic fault schedules — no monkeypatching the code under
test, no lucky interleavings. Clocks are injected where windows matter
(breaker reset, backoff); the only waits are injected `delay` faults and
bounded sub-second socket timeouts.

Mutation proof: each resilience mechanism (deadline, retry, breaker,
drain, dedup) has a paired test that env-disables it
(LWS_TPU_RESILIENCE_DISABLE) and asserts the failure it exists to close
RE-OPENS — a mechanism whose removal changes nothing is decoration, not
resilience.

The multi-process e2e (prefill killed mid-handoff + ack loss, byte-
identical replay) is `slow`-marked: `make chaos` runs it, the tier-1
sweep skips it like the other subprocess e2es."""

import socket
import threading
import time

import pytest

from lws_tpu.core import faults, flightrecorder, metrics, resilience
from lws_tpu.core.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    Deadline,
    DeadlineExceeded,
    DrainGate,
    RetryBudget,
    RetryPolicy,
    SeenIds,
)
from lws_tpu.serving import kv_transport as kt


@pytest.fixture
def armed():
    """Arm schedules on the process injector (what the wired fault points
    read); ALWAYS disarmed after — a leaked schedule poisons later tests."""

    def arm(point: str, spec: str) -> None:
        faults.INJECTOR.arm(point, spec)

    yield arm
    faults.INJECTOR.disarm()


@pytest.fixture
def server():
    s = kt.KVServer(port=0, host="127.0.0.1")
    yield s
    s.close()


def ep(server):
    return ("127.0.0.1", server.port)


def no_sleep(_s: float) -> None:
    """Injected retry sleeper: chaos runs never wait wall-clock backoff."""


# ---------------------------------------------------------------------------
# Retry


def test_retry_recovers_from_transient_connect_failures(armed, server):
    server.post_result("r1", {"id": "r1"}, b"out")
    armed("kv.client.connect", "fail_n_times:2:ConnectionError")
    before = metrics.REGISTRY.counter_value(
        "serving_retries_total", {"site": "chaos.pull", "outcome": "retry"})
    got = resilience.call(
        lambda: kt.pull_result(ep(server), "r1"),
        site="chaos.pull",
        policy=RetryPolicy(max_attempts=4, base_s=0.0, cap_s=0.0),
        sleeper=no_sleep,
    )
    assert got is not None and got[1] == b"out"
    after = metrics.REGISTRY.counter_value(
        "serving_retries_total", {"site": "chaos.pull", "outcome": "retry"})
    assert after == before + 2  # exactly the two injected failures


def test_retry_disabled_fails_on_first_transient(armed, server, monkeypatch):
    """Mutation proof: with retry off, the same two-blip schedule that the
    test above absorbs kills the call on blip one."""
    monkeypatch.setenv(resilience.DISABLE_ENV, "retry")
    server.post_result("r2", {"id": "r2"}, b"out")
    armed("kv.client.connect", "fail_n_times:2:ConnectionError")
    with pytest.raises(ConnectionError):
        resilience.call(
            lambda: kt.pull_result(ep(server), "r2"),
            site="chaos.pull",
            policy=RetryPolicy(max_attempts=4, base_s=0.0, cap_s=0.0),
            sleeper=no_sleep,
        )


def test_retry_exhaustion_and_budget():
    calls = {"n": 0}

    def always_fails():
        calls["n"] += 1
        raise OSError("down")

    with pytest.raises(OSError):
        resilience.call(always_fails, site="chaos.exhaust",
                        policy=RetryPolicy(max_attempts=3, base_s=0.0),
                        sleeper=no_sleep)
    assert calls["n"] == 3
    assert metrics.REGISTRY.counter_value(
        "serving_retries_total",
        {"site": "chaos.exhaust", "outcome": "exhausted"}) >= 1.0
    # A dry budget stops the storm after the FIRST failure.
    budget = RetryBudget(capacity=0.0)
    calls["n"] = 0
    with pytest.raises(OSError):
        resilience.call(always_fails, site="chaos.budget",
                        policy=RetryPolicy(max_attempts=5, base_s=0.0),
                        budget=budget, sleeper=no_sleep)
    assert calls["n"] == 1
    assert metrics.REGISTRY.counter_value(
        "serving_retries_total",
        {"site": "chaos.budget", "outcome": "budget_exhausted"}) >= 1.0


def test_retry_backoff_is_decorrelated_jitter_and_seedable():
    import random

    sleeps: list[float] = []

    def failing():
        raise OSError("down")

    with pytest.raises(OSError):
        resilience.call(failing, site="chaos.jitter",
                        policy=RetryPolicy(max_attempts=4, base_s=0.05,
                                           cap_s=1.0),
                        sleeper=sleeps.append, rng=random.Random(7))
    sleeps2: list[float] = []
    with pytest.raises(OSError):
        resilience.call(failing, site="chaos.jitter",
                        policy=RetryPolicy(max_attempts=4, base_s=0.05,
                                           cap_s=1.0),
                        sleeper=sleeps2.append, rng=random.Random(7))
    assert sleeps == sleeps2 and len(sleeps) == 3  # seeded = reproducible
    assert all(0.05 <= s <= 1.0 for s in sleeps)


# ---------------------------------------------------------------------------
# Deadlines


@pytest.fixture
def black_hole():
    """A peer that accepts and then says nothing — the hang shape."""
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    sock.listen(4)
    yield ("127.0.0.1", sock.getsockname()[1])
    sock.close()


def test_deadline_trips_instead_of_hanging(black_hole):
    """A dead-silent peer costs the request its REMAINING BUDGET, not the
    transport's 10s default: the clamped socket timeout fails the attempt
    fast and the next blocking point raises DeadlineExceeded."""
    before = metrics.REGISTRY.counter_value(
        "serving_deadline_expirations_total", {"site": "chaos.deadline"})
    t0 = time.perf_counter()
    with resilience.bind(Deadline(0.08)):
        with pytest.raises(DeadlineExceeded):
            resilience.call(
                lambda: kt.pull_result(black_hole, "nope"),
                site="chaos.deadline",
                policy=RetryPolicy(max_attempts=3, base_s=0.0),
                sleeper=no_sleep,
            )
    assert time.perf_counter() - t0 < 1.0  # budget-bounded, not 10s-bounded
    after = metrics.REGISTRY.counter_value(
        "serving_deadline_expirations_total", {"site": "chaos.deadline"})
    assert after >= before + 1


def test_deadline_disabled_waits_full_socket_timeout(black_hole, monkeypatch):
    """Mutation proof: deadline off = the call blocks for the transport
    timeout (bounded to 0.3s here only because the test passes one) and
    surfaces a socket error, never DeadlineExceeded."""
    monkeypatch.setenv(resilience.DISABLE_ENV, "deadline")
    t0 = time.perf_counter()
    with resilience.bind(Deadline(0.05)):
        with pytest.raises(OSError) as err:
            kt.pull_result(black_hole, "nope", timeout=0.3)
    assert not isinstance(err.value, DeadlineExceeded)
    assert time.perf_counter() - t0 >= 0.25  # waited PAST the dead budget


def test_deadline_rides_frame_meta_to_the_worker(server):
    """The wire leg: submit with a bound deadline, and the meta the worker
    dequeues carries the remaining budget (re-anchored on its own clock)."""
    with resilience.bind(Deadline(5.0)):
        kt.submit_prompt(ep(server), "dl1", b"prompt")
    meta, _ = server.next_prompt(timeout=2.0)
    assert 0.0 < float(meta["deadline_s"]) <= 5.0
    restored = Deadline.from_wire(meta["deadline_s"])
    assert restored is not None and not restored.expired()


def test_injected_delay_makes_slow_network_trip_deadline(armed, server):
    """The 'slow network' chaos shape from the issue: a delay fault on the
    server's recv leg makes the peer slow, the deadline-clamped socket
    timeout fails the attempt, and the retry loop's deadline check turns
    the would-be hang into a typed, recorded failure."""
    server.post_result("slow1", {"id": "slow1"}, b"out")
    armed("kv.server.recv", "delay:0.1")
    with resilience.bind(Deadline(0.05)):
        with pytest.raises(DeadlineExceeded):
            resilience.call(
                lambda: kt.pull_result(ep(server), "slow1"),
                site="chaos.slow", policy=RetryPolicy(max_attempts=2,
                                                      base_s=0.0),
                sleeper=no_sleep,
            )
    faults.INJECTOR.disarm()
    # The result was never consumed (the slow server found a dead client
    # socket and never popped the entry): a fresh pull still serves it.
    assert kt.pull_result(ep(server), "slow1") is not None


# ---------------------------------------------------------------------------
# Circuit breaker


def test_breaker_opens_half_opens_and_recovers():
    fake = {"t": 0.0}
    breaker = CircuitBreaker("chaos@peer", failure_threshold=2,
                             reset_timeout_s=5.0, clock=lambda: fake["t"])
    flightrecorder.RECORDER.clear()
    assert breaker.allow() and breaker.state == "closed"
    breaker.record_failure()
    assert breaker.state == "closed"  # below threshold
    breaker.record_failure()
    assert breaker.state == "open"
    assert not breaker.allow()  # fail fast: no dial at the dead peer
    assert metrics.REGISTRY.gauge_value(
        "serving_circuit_state", {"endpoint": "chaos@peer"}) == 2.0
    fake["t"] = 6.0
    assert breaker.allow()  # half-open: ONE probe
    assert breaker.state == "half_open"
    assert not breaker.allow()  # second caller blocked while probing
    breaker.record_failure()  # probe failed: straight back to open
    assert breaker.state == "open" and not breaker.allow()
    fake["t"] = 12.0
    assert breaker.allow()
    breaker.record_success()
    assert breaker.state == "closed" and breaker.allow()
    kinds = [
        (e["from_state"], e["to_state"])
        for e in flightrecorder.RECORDER.events()
        if e["kind"] == "circuit_breaker" and e["endpoint"] == "chaos@peer"
    ]
    assert kinds == [
        ("closed", "open"), ("open", "half_open"), ("half_open", "open"),
        ("open", "half_open"), ("half_open", "closed"),
    ]


def test_breaker_fails_fast_on_dead_endpoint():
    """Wire-level: after the circuit opens against a connection-refused
    endpoint, calls fail in microseconds WITHOUT dialing (the refused
    connect itself costs a syscall; CircuitOpenError costs nothing)."""
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        dead = ("127.0.0.1", probe.getsockname()[1])
    # Port now closed: connects are refused instantly.
    breaker = CircuitBreaker("chaos@dead", failure_threshold=1,
                             reset_timeout_s=60.0)
    with pytest.raises(OSError):
        breaker.call(lambda: kt.pull_result(dead, "x"))
    assert breaker.state == "open"
    t0 = time.perf_counter()
    with pytest.raises(CircuitOpenError):
        breaker.call(lambda: kt.pull_result(dead, "x"))
    assert time.perf_counter() - t0 < 0.05  # failed fast, no dial
    breaker.record_success()  # close: no open-breaker heartbeat outlives us


def test_breaker_disabled_keeps_dialing(monkeypatch):
    """Mutation proof: breaker off = every call hits the dead peer."""
    monkeypatch.setenv(resilience.DISABLE_ENV, "breaker")
    breaker = CircuitBreaker("chaos@disabled", failure_threshold=1,
                             reset_timeout_s=60.0)
    calls = {"n": 0}

    def dial():
        calls["n"] += 1
        raise OSError("refused")

    for _ in range(3):
        with pytest.raises(OSError):
            breaker.call(dial)
    assert calls["n"] == 3  # never failed fast
    assert breaker.state == "closed"


# ---------------------------------------------------------------------------
# At-least-once replay + dedup (satellite: idempotency ENFORCED)


def test_ack_loss_replays_bundle_and_dedup_decodes_once(armed, server):
    """The issue's headline replay scenario, in-process: the first ack is
    dropped (injected), the server re-queues, the second pull REPLAYS the
    same bundle — and the seen-id guard decodes it exactly once."""
    server.offer_bundle({"id": "req1"}, b"kvbytes")
    armed("kv.ack", "drop:1")
    seen = SeenIds(site="chaos")
    decodes = {"n": 0}

    def process(meta, payload):
        if seen.seen(meta["id"]):
            return
        decodes["n"] += 1
        assert payload == b"kvbytes"

    # First delivery: processed, ack DROPPED -> server re-queues.
    kt.pull_bundle(ep(server), timeout=1.0, process=process)
    assert server.delivery_counts()[0] == 0  # unacked
    # Redelivery: replay detected, acked WITHOUT re-decoding.
    kt.pull_bundle(ep(server), timeout=1.0, process=process)
    assert decodes["n"] == 1

    def wait_for(predicate, timeout=5.0):
        deadline = time.time() + timeout
        while time.time() < deadline and not predicate():
            time.sleep(0.02)
        return predicate()

    assert wait_for(lambda: server.delivery_counts()[0] == 1)
    assert kt.pull_bundle(ep(server), timeout=0.2) is None  # consumed
    assert metrics.REGISTRY.counter_value(
        "serving_replays_deduped_total", {"site": "chaos"}) >= 1.0


def test_dedup_disabled_decodes_replay_twice(armed, server, monkeypatch):
    """Mutation proof: dedup off = the replayed bundle burns a second
    decode (the double-work/double-deliver hazard the guard closes)."""
    monkeypatch.setenv(resilience.DISABLE_ENV, "dedup")
    server.offer_bundle({"id": "req2"}, b"kv")
    armed("kv.ack", "drop:1")
    seen = SeenIds(site="chaos")
    decodes = {"n": 0}

    def process(meta, payload):
        if seen.seen(meta["id"]):
            return
        decodes["n"] += 1

    kt.pull_bundle(ep(server), timeout=1.0, process=process)
    kt.pull_bundle(ep(server), timeout=1.0, process=process)
    assert decodes["n"] == 2


def test_deadline_budget_pays_for_queue_time(server):
    """Queue wait is charged against the wire deadline on BOTH legs: a
    prompt (or bundle) that waited out its whole budget dequeues expired,
    never with a fresh re-anchored budget."""
    with resilience.bind(Deadline(0.05)):
        kt.submit_prompt(ep(server), "qw1", b"p")
    time.sleep(0.1)  # the prompt queues past its entire budget
    meta, _ = server.next_prompt(timeout=2.0)
    assert float(meta["deadline_s"]) == 0.0, meta
    server.offer_bundle({"id": "qw2", "deadline_s": 0.05}, b"b")
    time.sleep(0.1)  # the bundle parks past its budget too
    bmeta, _ = kt.pull_bundle(ep(server), timeout=1.0)
    assert float(bmeta["deadline_s"]) == 0.0, bmeta
    assert "_offered_t" not in bmeta  # internal anchor never hits the wire


def test_two_phase_dedup_failed_first_attempt_retries_for_real(server):
    """The record-after-post contract: a first attempt that dies BEFORE
    posting its result must not poison the id — the redelivery is a real
    retry, not an ack-with-no-result."""
    server.offer_bundle({"id": "tp1"}, b"x")
    seen = SeenIds(site="chaos")
    attempts = []

    def process(meta, payload):
        if seen.contains(meta["id"]):
            return
        attempts.append(meta["id"])
        if len(attempts) == 1:
            raise OSError("died before post_result")
        seen.record(meta["id"])  # the worker records only after posting

    with pytest.raises(OSError):
        kt.pull_bundle(ep(server), timeout=1.0, process=process)
    kt.pull_bundle(ep(server), timeout=2.0, process=process)
    assert attempts == ["tp1", "tp1"]  # the redelivery really re-ran
    assert seen.contains("tp1")  # only NOW is a further replay deduped


def test_seen_ids_bound_evicts_oldest():
    seen = SeenIds(capacity=3, site="chaos")
    for rid in ("a", "b", "c"):
        assert not seen.seen(rid)
    assert not seen.seen("d")  # evicts "a"
    assert len(seen) == 3
    assert not seen.seen("a")  # "a" fell out of the window: not a replay
    assert seen.seen("c")


def test_decode_crash_mid_process_requeues_bundle(armed, server):
    """Injected decode death (exit mode) mid-processing: the connection
    drops unacked and the bundle survives server-side for a successor."""
    server.offer_bundle({"id": "crash1"}, b"payload")
    armed("disagg.decode.process", "exit:1")

    def process(meta, payload):
        faults.fire("disagg.decode.process")  # the worker's chaos hook

    with pytest.raises(SystemExit):
        kt.pull_bundle(ep(server), timeout=1.0, process=process)
    got = kt.pull_bundle(ep(server), timeout=2.0)  # successor pulls
    assert got is not None and got[0]["id"] == "crash1" and got[1] == b"payload"


# ---------------------------------------------------------------------------
# partial_write: the mid-frame death paths (satellite: KVServer re-insert)


def test_partial_write_requeues_bundle_intact(armed, server):
    """A bundle send that dies mid-frame (injected partial write) must
    re-queue the bundle server-side, and the next pull receives it INTACT
    — not truncated, not lost."""
    payload = bytes(range(256)) * 4
    server.offer_bundle({"id": "pw1"}, payload)
    armed("kv.server.send_bundle", "partial_write:6:1")
    with pytest.raises(OSError):  # truncated reply surfaces to the puller
        kt.pull_bundle(ep(server), timeout=1.0)
    got = kt.pull_bundle(ep(server), timeout=2.0)
    assert got is not None and got[0]["id"] == "pw1" and got[1] == payload


def test_partial_write_reinserts_result_for_retry(armed, server):
    """kv_transport's re-insert-on-send-failure path (pull_result): a send
    that dies mid-frame re-inserts the entry and a retry delivers it."""
    server.post_result("pw2", {"id": "pw2"}, b"result-bytes")
    armed("kv.server.send_result", "partial_write:4:1")
    assert kt.pull_result(ep(server), "pw2") is None  # truncated = not ready
    got = kt.pull_result(ep(server), "pw2")  # re-inserted: retry succeeds
    assert got is not None and got[1] == b"result-bytes"
    assert server.results_served == 1  # the failed send never counted


# ---------------------------------------------------------------------------
# Graceful drain


def _drain_worker(gate, server, hold, done, processed):
    """A decode-worker-shaped loop: pull/process until drained."""

    def process(meta, payload):
        processed.append(meta["id"])
        hold.wait(timeout=10)  # in-flight work the drain must NOT cut short

    while not gate.draining:
        try:
            if kt.pull_bundle(ep(server), timeout=0.2, process=process) is None:
                continue
        except OSError:
            break
    done.set()


def test_drain_finishes_in_flight_and_parks_the_rest(server):
    """Drain mid-bundle: the in-flight item finishes AND acks, nothing new
    is admitted, the parked items stay queued for a successor."""
    for i in range(3):
        server.offer_bundle({"id": f"d{i}"}, b"x")
    gate = DrainGate()
    hold, done = threading.Event(), threading.Event()
    processed: list = []
    worker = threading.Thread(
        target=_drain_worker, args=(gate, server, hold, done, processed),
        daemon=True,
    )
    worker.start()
    deadline = time.time() + 5
    while not processed and time.time() < deadline:
        time.sleep(0.01)
    assert processed == ["d0"]  # one bundle in flight
    assert gate.request("test")  # drain arrives MID-processing
    hold.set()  # in-flight work completes...
    assert done.wait(timeout=5)  # ...and the loop exits clean
    deadline = time.time() + 5
    while server.delivery_counts()[0] < 1 and time.time() < deadline:
        time.sleep(0.01)
    assert server.delivery_counts()[0] == 1  # the in-flight item WAS acked
    assert processed == ["d0"]  # nothing new admitted after the drain
    # Parked work survives for a successor: both remaining bundles pull.
    survivors = {kt.pull_bundle(ep(server), timeout=1.0)[0]["id"]
                 for _ in range(2)}
    assert survivors == {"d1", "d2"}


def test_drain_disabled_keeps_admitting(server, monkeypatch):
    """Mutation proof: drain off = the request is refused (False) and the
    loop keeps pulling new work past it."""
    monkeypatch.setenv(resilience.DISABLE_ENV, "drain")
    for i in range(3):
        server.offer_bundle({"id": f"nd{i}"}, b"x")
    gate = DrainGate()
    hold, done = threading.Event(), threading.Event()
    hold.set()  # processing never blocks
    processed: list = []
    worker = threading.Thread(
        target=_drain_worker, args=(gate, server, hold, done, processed),
        daemon=True,
    )
    worker.start()
    assert gate.request("test") is False  # refused: mechanism disabled
    deadline = time.time() + 5
    while len(processed) < 3 and time.time() < deadline:
        time.sleep(0.01)
    assert len(processed) == 3  # kept admitting straight past the drain
    monkeypatch.delenv(resilience.DISABLE_ENV)
    gate.request("cleanup")  # now it latches: the loop exits
    assert done.wait(timeout=5)
    gate.reset()


def test_drain_endpoint_flips_the_process_gate():
    """POST /debug/drain on the worker telemetry server drives the module
    DRAIN gate (what the disagg workers poll) and sets the gauge."""
    import json
    import urllib.request

    from lws_tpu.runtime.telemetry import TelemetryServer

    server = TelemetryServer(port=0)
    server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/debug/drain",
            data=b"{}", method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read().decode())["draining"] is True
        assert resilience.DRAIN.draining
        assert resilience.DRAIN.reason == "debug-endpoint"
        assert metrics.REGISTRY.gauge_value("serving_draining") == 1.0
    finally:
        resilience.DRAIN.reset()
        server.stop()


# ---------------------------------------------------------------------------
# Fleet scrape under injected faults


def test_fleet_scrape_fault_point_degrades_and_backs_off(armed):
    from lws_tpu.api.pod import PodPhase
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.telemetry import TelemetryServer
    from tests.test_telemetry_plane import _make_worker_pod

    worker = TelemetryServer(port=0)
    worker.start()
    cp = ControlPlane()
    try:
        pod = cp.store.create(_make_worker_pod("chaos-w0", worker.port))
        pod.status.phase = PodPhase.RUNNING
        pod.status.ready = True
        pod.status.address = "127.0.0.1"
        cp.store.update_status(pod)
        armed("fleet.scrape", "fail_n_times:1:ConnectionError")
        assert cp.fleet.collect(now=100.0) is not None
        assert cp.metrics.counter_value(
            "lws_fleet_scrape_errors_total", {"instance": "chaos-w0"}) == 1.0
        # Inside the backoff window the worker is not even dialed...
        cp.fleet.collect(now=100.5)
        assert cp.metrics.counter_value(
            "lws_fleet_scrape_errors_total", {"instance": "chaos-w0"}) == 1.0
        # ...and after it expires the (now fault-free) scrape recovers.
        sources = cp.fleet.collect(now=1000.0)
        assert any(labels.get("instance") == "chaos-w0"
                   for labels, _ in sources)
        assert [e for e in flightrecorder.RECORDER.events()
                if e["kind"] == "fleet_scrape_recovered"
                and e.get("instance") == "chaos-w0"]
    finally:
        worker.stop()


# ---------------------------------------------------------------------------
# Streamed KV handoff under injected faults (ISSUE 10): armed faults at the
# new stream points (`kv.stream.send_chunk`, `kv.stream.recv_chunk`) must
# NEVER deliver a torn cache — every scenario resumes or requeues and ends
# with token streams byte-identical to the fault-free oracle.


@pytest.fixture(scope="module")
def stream_rig():
    """Tiny real engines + the fault-free oracle tokens, shared across the
    stream-chaos scenarios (prefill produces once per test; decode engines
    are minted per pull because decode_n donates its cache)."""
    from types import SimpleNamespace

    import numpy as np

    import jax
    import jax.numpy as jnp

    from lws_tpu.models.llama import LlamaConfig, init_params
    from lws_tpu.serving.disagg_worker import _decode_bundle
    from lws_tpu.serving.engine import Engine

    cfg = LlamaConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq_len=64, dtype=jnp.float32, remat=False,
    )
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()

    def engine():
        return Engine(cfg, params, batch_size=1, max_len=32)

    prompt = np.asarray(
        jax.random.randint(jax.random.key(5), (13,), 0, 64), np.int32)
    pre = engine()
    token, cache = pre.prefill(jnp.asarray(prompt).reshape(1, -1))
    want, _, _ = _decode_bundle(
        engine(), kt.cache_to_bundle(cache, token), steps=5)
    return SimpleNamespace(
        engine=engine, prefill_engine=pre, prompt=prompt, want=want,
        decode=_decode_bundle,
    )


def _produce_stream(rig, server, req_id: str) -> None:
    from lws_tpu.serving.disagg_worker import _prefill_streamed

    _prefill_streamed(rig.prefill_engine, server, kt, {"id": req_id},
                      req_id, rig.prompt, 4, None)


def _pull_assembled(rig, server, **kw):
    return kt.pull_bundle(
        ep(server), timeout=2.0, ack_timeout=30.0,
        receiver_factory=lambda m: kt.CacheAssembler(max_len=32, device=True),
        **kw,
    )


def test_stream_partial_write_requeues_and_replays_byte_identical(
        armed, server, stream_rig):
    """A chunk send that dies mid-frame (injected partial write): the first
    delivery tears, the WHOLE stream re-queues, the redelivery replays from
    chunk 0, and the decoded tokens equal the fault-free oracle."""
    import numpy as np

    _produce_stream(stream_rig, server, "pw-stream")
    armed("kv.stream.send_chunk", "partial_write:6:1")
    with pytest.raises(OSError):
        _pull_assembled(stream_rig, server)
    assert server.delivery_counts()[0] == 0
    meta, payload = _pull_assembled(stream_rig, server)
    assert meta["chunks"] == 4  # 13 rows / 4-row chunks, replayed whole
    got, stats, _ = stream_rig.decode(stream_rig.engine(), payload, steps=5)
    np.testing.assert_array_equal(got, stream_rig.want)
    assert stats["streamed"]


def test_stream_recv_drop_requeues_and_replays_byte_identical(
        armed, server, stream_rig):
    """Receive-side loss (injected drop at kv.stream.recv_chunk): the
    puller abandons mid-stream, the server re-queues on the missing chunk
    ack, and the replay is byte-identical."""
    import numpy as np

    _produce_stream(stream_rig, server, "drop-stream")
    armed("kv.stream.recv_chunk", "drop:1")
    with pytest.raises(OSError, match="injected kv stream recv loss"):
        _pull_assembled(stream_rig, server)
    faults.INJECTOR.disarm()
    meta, payload = _pull_assembled(stream_rig, server)
    got, _, _ = stream_rig.decode(stream_rig.engine(), payload, steps=5)
    np.testing.assert_array_equal(got, stream_rig.want)


def test_stream_decode_death_mid_stream_requeue_then_replay_dedup(
        armed, server, stream_rig):
    """The full ISSUE-10 chaos chain: decode DIES mid-stream (exit fault on
    the recv leg) -> stream re-queues; the successor decodes and posts, but
    its ack is DROPPED -> redelivery replays into the seen-id guard, which
    acks WITHOUT a second decode. One decode total, tokens byte-identical."""
    import numpy as np

    _produce_stream(stream_rig, server, "death-stream")
    armed("kv.stream.recv_chunk", "exit:1")
    armed("kv.ack", "drop:1")
    seen = SeenIds(site="chaos-stream")
    decodes = []

    def process(meta, payload):
        if seen.contains(meta["id"]):
            return  # replay: ack without re-decoding
        got, _, _ = stream_rig.decode(stream_rig.engine(), payload, steps=5)
        decodes.append(got)
        seen.record(meta["id"])

    with pytest.raises(SystemExit):  # decode death mid-stream
        _pull_assembled(stream_rig, server, process=process)
    assert server.delivery_counts()[0] == 0 and not decodes
    # Successor: decodes for real, ack dropped -> server re-queues.
    _pull_assembled(stream_rig, server, process=process)
    assert len(decodes) == 1
    # Replay: deduped, acked, consumed.
    _pull_assembled(stream_rig, server, process=process)
    assert len(decodes) == 1  # never decoded twice
    np.testing.assert_array_equal(decodes[0], stream_rig.want)

    def wait_for(predicate, timeout=5.0):
        deadline = time.time() + timeout
        while time.time() < deadline and not predicate():
            time.sleep(0.02)
        return predicate()

    assert wait_for(lambda: server.delivery_counts()[0] == 1)
    assert kt.pull_bundle(ep(server), timeout=0.2) is None  # consumed
    assert metrics.REGISTRY.counter_value(
        "serving_replays_deduped_total", {"site": "chaos-stream"}) >= 1.0


def test_pace_fault_emulates_bandwidth_on_both_paths(armed, server):
    """`pace:MBPS` (the kv_handoff bench's DCN-like link): cooperative at
    both send points, per-byte-fair — a paced monolithic send sleeps the
    same total as the equivalent paced stream."""
    payload = bytes(200_000)
    server.offer_bundle({"id": "paced"}, payload)
    armed("kv.server.send_bundle", "pace:10")  # 10 MB/s -> ~20ms for 200kB
    t0 = time.perf_counter()
    got = kt.pull_bundle(ep(server), timeout=2.0)
    assert got is not None and got[1] == payload
    assert time.perf_counter() - t0 >= 0.015  # the link really throttled


# ---------------------------------------------------------------------------
# The multi-process e2e: prefill killed mid-handoff + ack loss -> replay,
# byte-identical. `slow` like the other subprocess e2es; `make chaos` runs it.


@pytest.mark.slow
def test_e2e_disagg_prefill_death_and_ack_loss_replay(tmp_path):
    """ISSUE 8 acceptance: a fault schedule kills the prefill worker mid-
    handoff (armed via POST /debug/faults on ITS telemetry server — the
    restarted replacement comes up clean) and drops the decode worker's
    first ack. The request still completes via replay — the restart policy
    recreates prefill, the router resubmits (its retry), the re-queued
    bundle replays into the dedup guard — and the token stream is byte-
    identical to the fault-free oracle. Retry/breaker/fault metrics are
    visible on the merged fleet exposition."""
    import json
    import urllib.request

    import numpy as np

    from lws_tpu.client import RemoteClient
    from lws_tpu.core import trace
    from lws_tpu.core.store import new_meta
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.server import ApiServer
    from lws_tpu.api.disagg import DisaggregatedSet, DisaggregatedSetSpec
    from tests.test_dns_metrics import parse_exposition
    from tests.test_e2e_disagg import DECODE_STEPS, free_port, role_spec
    from tests.test_e2e_local import make_backend

    trace.TRACER.enabled = True
    cp = ControlPlane()
    api = ApiServer(cp, port=0)
    api.start()
    api_url = f"http://127.0.0.1:{api.port}"
    prefill_port, decode_port = free_port(), free_port()
    prefill_metrics, decode_metrics = free_port(), free_port()
    from lws_tpu.api.pod import EnvVar

    ds = DisaggregatedSet(
        meta=new_meta("llmd-chaos"),
        spec=DisaggregatedSetSpec(roles=[
            role_spec("prefill", prefill_port, api_url,
                      metrics_port=prefill_metrics),
            role_spec("decode", decode_port, api_url,
                      # Fast breaker recovery: prefill WILL die and return.
                      extra_env=[EnvVar("LWS_TPU_BREAKER_RESET_S", "1.0")],
                      metrics_port=decode_metrics),
        ]),
    )
    backend = make_backend(cp, tmp_path)
    cp.manager.register(backend, {"Pod": lambda o: [o.key()]})
    client = RemoteClient(api_url)

    def post_faults(port: int, payload: dict) -> None:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/debug/faults",
            data=json.dumps(payload).encode(), method="POST",
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 200

    try:
        cp.create(ds)
        cp.run_until_stable()
        deadline = time.time() + 240

        # Arm the chaos BEFORE the request flows, via the live /debug/faults
        # control surface: prefill dies mid-handoff ONCE (its restarted
        # replacement is unarmed — fault state is per-process); decode
        # drops its first ack.
        for port, payload in (
            (prefill_metrics, {"arm": {"disagg.prefill.handoff": "exit:1"}}),
            (decode_metrics, {"arm": {"kv.ack": "drop:1"}}),
        ):
            while time.time() < deadline:
                try:
                    post_faults(port, payload)
                    break
                except OSError:
                    backend.poll_all()
                    cp.run_until_stable()
                    time.sleep(0.5)
            else:
                pytest.fail(f"telemetry port {port} never came up")

        prompt = np.array([5, 9, 2, 11, 7], dtype=np.int32)
        prompt_bytes = kt.arrays_to_bytes(prompt=prompt)

        def submit():
            endpoint = kt.discover_role_endpoint(
                client, "default", "llmd-chaos", "prefill")
            if endpoint is None:
                raise OSError("prefill endpoint not published yet")
            kt.submit_prompt(endpoint, "chaos-req", prompt_bytes)

        # First submission: retried until the (first) prefill accepts.
        while time.time() < deadline:
            try:
                submit()
                break
            except (OSError, RuntimeError):
                backend.poll_all()
                cp.run_until_stable()
                time.sleep(0.5)
        else:
            pytest.fail("prefill never accepted the prompt")

        # The armed prefill DIES mid-handoff: the prompt's only copy dies
        # with it. The router-shaped recovery is resubmission (decode is
        # idempotent per id, so over-submitting is safe) — through the
        # resilience retry helper so the attempts land in
        # serving_retries_total on the control-plane instance.
        result = None
        last_resubmit = time.time()
        while time.time() < deadline and result is None:
            backend.poll_all()
            cp.run_until_stable()
            decode_ep = kt.discover_role_endpoint(
                client, "default", "llmd-chaos", "decode")
            if decode_ep is not None:
                try:
                    got = kt.pull_result(decode_ep, "chaos-req")
                except (OSError, RuntimeError):
                    got = None
                if got is not None:
                    assert "failed" not in got[0], got[0]
                    result = kt.bytes_to_arrays(got[1])["tokens"]
                    break
            if time.time() - last_resubmit > 10.0:
                last_resubmit = time.time()
                try:
                    resilience.call(
                        submit, site="router.submit",
                        policy=RetryPolicy(max_attempts=3, base_s=0.1,
                                           cap_s=0.5,
                                           retry_on=(OSError, RuntimeError)),
                    )
                except (OSError, RuntimeError):
                    pass  # prefill still restarting: next lap resubmits
            time.sleep(0.5)
        assert result is not None, "request never completed via replay"

        # Byte-identical to the fault-free oracle: replay + dedup changed
        # NOTHING about the tokens.
        from lws_tpu.serving.disagg_worker import build_engine

        engine = build_engine(batch=1, max_len=32)
        oracle = engine.generate(
            np.asarray(prompt).reshape(1, -1), max_new_tokens=DECODE_STEPS + 1
        )
        np.testing.assert_array_equal(result[0], np.asarray(oracle.tokens)[0])

        # The resilience plane is VISIBLE on the merged fleet surface:
        # retry counters (control-plane resubmit + decode pull retries),
        # breaker state from the decode worker, and the injected-fault
        # trip counters from both workers.
        fams = None
        needed = {"serving_retries_total", "serving_circuit_state",
                  "lws_fault_trips_total"}
        while time.time() < deadline:
            with urllib.request.urlopen(f"{api_url}/metrics/fleet",
                                        timeout=10) as resp:
                fams = parse_exposition(resp.read().decode())
            if needed <= set(fams):
                break
            time.sleep(1.1)  # collector cache TTL
        assert needed <= set(fams), sorted(needed - set(fams))
        # The decode worker retried its pulls against the dead prefill:
        # those attempts are visible, instance-labelled, on the fleet view.
        assert any(
            labels.get("site") == "kv.pull_bundle"
            and labels.get("role") == "decode"
            for _, labels, _ in fams["serving_retries_total"]["samples"]
        ), fams["serving_retries_total"]["samples"]
        assert any(
            labels.get("role") == "decode"
            and labels.get("endpoint", "").startswith("prefill@")
            for _, labels, _ in fams["serving_circuit_state"]["samples"]
        ), fams["serving_circuit_state"]["samples"]
        # Only the SURVIVING worker's trip counter can ride the fleet: the
        # prefill's `disagg.prefill.handoff` trip died with the process it
        # killed. Its evidence is the group-atomic restart the control
        # plane recorded — the two halves of the chaos story, each on the
        # surface that survived it.
        trips = {
            labels.get("point"): value
            for _, labels, value in fams["lws_fault_trips_total"]["samples"]
        }
        assert trips.get("kv.ack") == 1.0, trips
        restarts = [e for e in list(cp.recorder.events)
                    if e.reason == "RecreateGroup"
                    and "prefill" in e.message]
        assert restarts, "prefill death never tripped a group recreation"
    finally:
        backend.shutdown()
        api.stop()
