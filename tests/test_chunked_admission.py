"""Chunked-prefill admission for the paged engine (VERDICT r4 #3).

The r4 engine ran a submitted prompt's whole prefill in one dispatch, so a
long-prompt admission stalled every active slot for its duration (the exact
failure the vLLM scheduler's chunked prefill exists to prevent —
serving/paged_engine.py:494-513 in the r4 tree). With prefill_chunk set,
admission fills a dense cache chunk by chunk and dispatches
`interleave_steps` decode steps for the active slots between chunks.

Pinned here:
  * decode stall per admission is bounded: active slots PROGRESS during a
    long submit (and by exactly interleave_steps per chunk gap);
  * token-exactness vs the unchunked engine — plain, prefix-cache (both
    hit and miss admissions, suffix longer than a chunk), int8 KV, and a
    tp=2 mesh;
  * the null-block commit discipline: interleaved decodes' dead writes for
    the being-admitted slot must not corrupt its freshly filled blocks
    (this is what token-exactness of the ADMITTED request proves).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lws_tpu.models.llama import LlamaConfig, init_params
from lws_tpu.serving.paged_engine import PagedBatchEngine


def tiny_cfg(**kw):
    return LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=256, dtype=jnp.float32, param_dtype=jnp.float32,
        remat=False, **kw,
    )


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.key(0))


def drain_results(engine, rids):
    engine.run_until_drained()
    return [engine.result(r) for r in rids]


def test_active_slots_progress_during_long_admission(setup):
    cfg, params = setup
    rng = np.random.RandomState(0)
    short = rng.randint(1, 200, size=10).astype(np.int32)
    long_prompt = rng.randint(1, 200, size=70).astype(np.int32)

    eng = PagedBatchEngine(cfg, params, slots=4, max_len=256, block_size=16,
                           prefill_chunk=16, interleave_steps=2)
    ra = eng.submit(short, max_new_tokens=60)
    eng.step_n(4)
    slot_a = next(s for s, r in eng._active.items() if r.request_id == ra)
    before = len(eng._active[slot_a].tokens)
    eng.submit(long_prompt, max_new_tokens=8)
    after = len(eng._active[slot_a].tokens)
    # 70 tokens / chunk 16 -> 5 chunks -> 4 interleave gaps x 2 steps.
    assert after - before == 8, (before, after)
    assert eng.stats["chunked_admissions"] == 1
    assert eng.stats["interleaved_decode_steps"] == 8


def test_unchunked_admission_stalls_actives(setup):
    """The contrast row: without prefill_chunk the long submit gives active
    slots zero progress — the stall the feature removes."""
    cfg, params = setup
    rng = np.random.RandomState(0)
    eng = PagedBatchEngine(cfg, params, slots=4, max_len=256, block_size=16)
    ra = eng.submit(rng.randint(1, 200, size=10).astype(np.int32), max_new_tokens=60)
    eng.step_n(4)
    slot_a = next(s for s, r in eng._active.items() if r.request_id == ra)
    before = len(eng._active[slot_a].tokens)
    eng.submit(rng.randint(1, 200, size=70).astype(np.int32), max_new_tokens=8)
    assert len(eng._active[slot_a].tokens) == before


def test_token_exact_vs_unchunked(setup):
    cfg, params = setup
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 200, size=n).astype(np.int32) for n in (10, 70, 33, 64)]

    def run(**kw):
        eng = PagedBatchEngine(cfg, params, slots=4, max_len=256, block_size=16, **kw)
        rids = []
        for p in prompts:
            rids.append(eng.submit(p, max_new_tokens=16))
            eng.step_n(3)
        return drain_results(eng, rids)

    assert run() == run(prefill_chunk=16, interleave_steps=2)


def test_token_exact_with_prefix_cache_long_suffix(setup):
    """Chunked admission composed with prefix hits: shared 64-token prefix,
    suffixes LONGER than a chunk (so the hit path itself chunks), plus a
    miss admission. Must match the unchunked prefix-cache engine exactly."""
    cfg, params = setup
    rng = np.random.RandomState(1)
    base = rng.randint(1, 200, size=64).astype(np.int32)
    prompts = [
        np.concatenate([base, rng.randint(1, 200, size=40).astype(np.int32)])
        for _ in range(3)
    ]

    def run(**kw):
        eng = PagedBatchEngine(cfg, params, slots=4, max_len=256, block_size=16,
                               prefix_cache=True, **kw)
        rids = []
        for p in prompts:
            rids.append(eng.submit(p, max_new_tokens=12))
            eng.step_n(2)
        return drain_results(eng, rids), dict(eng.stats), dict(eng.stats_prefix)

    r0, _, p0 = run()
    r1, s1, p1 = run(prefill_chunk=16, interleave_steps=2)
    assert r0 == r1
    assert p1["hit_tokens"] == p0["hit_tokens"] > 0
    # Both the miss admission (prompt 1) and the hit admissions (2, 3 with
    # 40-token suffixes > chunk) went through the chunked path.
    assert s1["chunked_admissions"] == 3


def test_token_exact_int8_kv(setup):
    cfg, params = setup
    qcfg = dataclasses.replace(cfg, kv_quant=True)
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, 200, size=n).astype(np.int32) for n in (50, 70)]

    def run(**kw):
        eng = PagedBatchEngine(qcfg, params, slots=2, max_len=256, block_size=16, **kw)
        rids = [eng.submit(p, max_new_tokens=10) for p in prompts]
        return drain_results(eng, rids)

    assert run() == run(prefill_chunk=16, interleave_steps=2)


def test_token_exact_tp_mesh(setup):
    cfg, params = setup
    from lws_tpu.parallel import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(dp=1, pp=1, cp=1, tp=2), jax.devices()[:2])
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 200, size=n).astype(np.int32) for n in (12, 70)]

    def run(**kw):
        eng = PagedBatchEngine(cfg, params, slots=2, max_len=256, block_size=16,
                               mesh=kw.pop("mesh", None), **kw)
        rids = []
        for p in prompts:
            rids.append(eng.submit(p, max_new_tokens=10))
            eng.step_n(2)
        return drain_results(eng, rids)

    plain = run()
    sharded_chunked = run(mesh=mesh, prefill_chunk=16, interleave_steps=2)
    assert plain == sharded_chunked


def test_non_pow2_max_len_bucket_cap(setup):
    """max_len caps the bucket to a non-power-of-two (384): n_chunks*chunk
    can exceed the bucket, and an exactly-bucket-sized dense cache would let
    dynamic_update_slice CLAMP the final append, silently overwriting
    earlier rows with wrong-position K/V. Token-exactness over a prompt in
    that regime pins the fix (width = max(bucket, n_chunks*chunk))."""
    cfg, params = setup
    rng = np.random.RandomState(5)
    prompt = rng.randint(1, 200, size=300).astype(np.int32)

    def run(**kw):
        eng = PagedBatchEngine(cfg, params, slots=2, max_len=384,
                               block_size=16, **kw)
        rid = eng.submit(prompt, max_new_tokens=10)
        eng.run_until_drained()
        return eng.result(rid)

    assert run() == run(prefill_chunk=256, interleave_steps=2)


def test_prefill_chunk_validation(setup):
    cfg, params = setup
    with pytest.raises(ValueError):
        PagedBatchEngine(cfg, params, block_size=16, prefill_chunk=24)
    with pytest.raises(ValueError):
        PagedBatchEngine(cfg, params, block_size=16, prefill_chunk=8)
