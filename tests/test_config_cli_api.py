"""L6/L7: component config loading, YAML manifests, HTTP API server, typed
client, plan-steps CLI."""

import json
import urllib.request

import pytest

from lws_tpu.client import Client
from lws_tpu.config import load_configuration
from lws_tpu.manifest import from_manifest, load_manifests, to_manifest
from lws_tpu.runtime import ControlPlane
from lws_tpu.runtime.server import ApiServer
from lws_tpu.testing import LWSBuilder, make_all_groups_ready


LWS_YAML = """
apiVersion: lws.tpu/v1
kind: LeaderWorkerSet
metadata:
  name: vllm
spec:
  replicas: 2
  startupPolicy: LeaderCreated
  networkConfig:
    subdomainPolicy: Shared
  rolloutStrategy:
    type: RollingUpdate
    rollingUpdateConfiguration:
      maxUnavailable: 1
      maxSurge: 1
  leaderWorkerTemplate:
    size: 4
    restartPolicy: RecreateGroupOnPodRestart
    subGroupPolicy:
      subGroupSize: 2
    workerTemplate:
      spec:
        containers:
        - name: jax
          image: vllm-tpu:latest
          resources:
            google.com/tpu: 4
"""


def test_config_load(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text(
        "apiVersion: config.lws.tpu/v1alpha1\nkind: Configuration\n"
        "backend: fake\nenableScheduler: false\n"
        "gangSchedulingManagement:\n  schedulerProvider: gang\n"
    )
    cfg = load_configuration(str(p))
    assert cfg.backend == "fake"
    assert cfg.enable_scheduler is False
    assert cfg.gang_scheduling_management.scheduler_provider == "gang"
    assert cfg.client_qps == 500  # defaulted (≈ defaults.go:35-36)


def test_config_rejects_unknown_fields(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("backnd: fake\n")  # typo must not pass silently
    with pytest.raises(ValueError, match="unknown configuration fields"):
        load_configuration(str(p))


def test_config_rejects_unknown_provider(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("gangSchedulingManagement:\n  schedulerProvider: volcano2\n")
    with pytest.raises(ValueError, match="unknown schedulerProvider"):
        load_configuration(str(p))


def test_manifest_roundtrip_and_apply(tmp_path):
    import yaml

    obj = from_manifest(yaml.safe_load(LWS_YAML))
    assert obj.spec.replicas == 2
    assert obj.spec.leader_worker_template.size == 4
    assert obj.spec.leader_worker_template.sub_group_policy.sub_group_size == 2
    assert obj.spec.rollout_strategy.rolling_update_configuration.max_surge == 1
    assert obj.spec.leader_worker_template.worker_template.spec.containers[0].tpu_chips() == 4

    cp = ControlPlane(auto_ready=True)
    cp.create(obj)
    cp.run_until_stable()
    pods = cp.store.list("Pod")
    assert len(pods) == 8

    manifest = to_manifest(cp.store.get("LeaderWorkerSet", "default", "vllm"))
    assert manifest["kind"] == "LeaderWorkerSet"
    assert manifest["status"]["replicas"] == 2


def test_load_manifests_multidoc(tmp_path):
    p = tmp_path / "m.yaml"
    p.write_text(LWS_YAML + "\n---\n" + LWS_YAML.replace("name: vllm", "name: vllm2"))
    objs = load_manifests(str(p))
    assert [o.meta.name for o in objs] == ["vllm", "vllm2"]


def test_http_api_server_lifecycle():
    cp = ControlPlane(auto_ready=True)
    server = ApiServer(cp, port=0)  # ephemeral port
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        def get(path):
            with urllib.request.urlopen(base + path) as r:
                return r.read().decode()

        def post(path, body: bytes):
            req = urllib.request.Request(base + path, data=body, method="POST")
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read().decode())

        assert get("/healthz") == "ok"

        out = post("/apply", LWS_YAML.encode())
        assert out["applied"] == ["LeaderWorkerSet/vllm"]
        cp.run_until_stable()

        listed = json.loads(get("/apis/LeaderWorkerSet"))
        assert listed[0]["metadata"]["name"] == "vllm"
        fetched = json.loads(get("/apis/Pod/default/vllm-0"))
        assert fetched["metadata"]["labels"]["leaderworkerset.lws.tpu/worker-index"] == "0"

        post("/scale/default/vllm", json.dumps({"replicas": 1}).encode())
        cp.run_until_stable()
        assert len(cp.store.list("Pod")) == 4

        metrics = get("/metrics")
        assert 'lws_reconcile_total{controller="lws"}' in metrics
        assert "lws_reconcile_duration_seconds_count" in metrics

        req = urllib.request.Request(f"{base}/apis/LeaderWorkerSet/default/vllm", method="DELETE")
        with urllib.request.urlopen(req):
            pass
        cp.run_until_stable()
        assert cp.store.list("Pod") == []
    finally:
        server.stop()


def test_http_apply_validation_422():
    cp = ControlPlane()
    server = ApiServer(cp, port=0)
    server.start()
    try:
        bad = LWS_YAML.replace("name: vllm", "name: Bad_Name")
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/apply", data=bad.encode(), method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 422
    finally:
        server.stop()


def test_typed_client_scale():
    cp = ControlPlane(auto_ready=True)
    client = Client(cp.store)
    client.create_lws(LWSBuilder().replicas(1).size(2).build())
    cp.run_until_stable()
    make_all_groups_ready(cp, "sample")
    assert client.get_lws("sample").status.ready_replicas == 1
    client.scale_lws("sample", 3)
    cp.run_until_stable()
    assert len(client.pods_of("sample")) == 6
    assert len(client.leader_pods_of("sample")) == 3


def test_plan_steps_cli(capsys):
    from lws_tpu.cli import main

    assert main(["plan-steps", "--initial", "2,2", "--target", "2,2"]) == 0
    out = capsys.readouterr().out
    assert "[2, 2]" in out and "[0, 0]" in out
    lines = [l for l in out.strip().splitlines()[1:]]
    assert lines[0].split()[0] == "0"
    assert "[0, 0]  [2, 2]" in lines[-1]


def test_config_rejects_nested_unknown_fields(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("metrics:\n  prot: 1234\n")  # typo inside a section
    with pytest.raises(ValueError, match="unknown configuration fields in metrics"):
        load_configuration(str(p))


def test_http_reapply_preserves_status():
    cp = ControlPlane(auto_ready=True)
    server = ApiServer(cp, port=0)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        def post(path, body: bytes):
            req = urllib.request.Request(base + path, data=body, method="POST")
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read().decode())

        post("/apply", LWS_YAML.encode())
        cp.run_until_stable()
        before = cp.store.get("LeaderWorkerSet", "default", "vllm").status.ready_replicas
        assert before == 2
        post("/apply", LWS_YAML.encode())  # unchanged re-apply
        after = cp.store.get("LeaderWorkerSet", "default", "vllm").status.ready_replicas
        assert after == before, "apply must never wipe live status"
    finally:
        server.stop()
