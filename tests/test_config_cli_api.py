"""L6/L7: component config loading, YAML manifests, HTTP API server, typed
client, plan-steps CLI."""

import json
import urllib.request

import pytest

from lws_tpu.client import Client
from lws_tpu.config import load_configuration
from lws_tpu.manifest import from_manifest, load_manifests, to_manifest
from lws_tpu.runtime import ControlPlane
from lws_tpu.runtime.server import ApiServer
from lws_tpu.testing import LWSBuilder, make_all_groups_ready


LWS_YAML = """
apiVersion: lws.tpu/v1
kind: LeaderWorkerSet
metadata:
  name: vllm
spec:
  replicas: 2
  startupPolicy: LeaderCreated
  networkConfig:
    subdomainPolicy: Shared
  rolloutStrategy:
    type: RollingUpdate
    rollingUpdateConfiguration:
      maxUnavailable: 1
      maxSurge: 1
  leaderWorkerTemplate:
    size: 4
    restartPolicy: RecreateGroupOnPodRestart
    subGroupPolicy:
      subGroupSize: 2
    workerTemplate:
      spec:
        containers:
        - name: jax
          image: vllm-tpu:latest
          resources:
            google.com/tpu: 4
"""


def test_config_load(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text(
        "apiVersion: config.lws.tpu/v1alpha1\nkind: Configuration\n"
        "backend: fake\nenableScheduler: false\n"
        "gangSchedulingManagement:\n  schedulerProvider: gang\n"
    )
    cfg = load_configuration(str(p))
    assert cfg.backend == "fake"
    assert cfg.enable_scheduler is False
    assert cfg.gang_scheduling_management.scheduler_provider == "gang"
    assert cfg.client_qps == 500  # defaulted (≈ defaults.go:35-36)


def test_config_rejects_unknown_fields(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("backnd: fake\n")  # typo must not pass silently
    with pytest.raises(ValueError, match="unknown configuration fields"):
        load_configuration(str(p))


def test_config_rejects_unknown_provider(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("gangSchedulingManagement:\n  schedulerProvider: volcano2\n")
    with pytest.raises(ValueError, match="unknown schedulerProvider"):
        load_configuration(str(p))


def test_manifest_roundtrip_and_apply(tmp_path):
    import yaml

    obj = from_manifest(yaml.safe_load(LWS_YAML))
    assert obj.spec.replicas == 2
    assert obj.spec.leader_worker_template.size == 4
    assert obj.spec.leader_worker_template.sub_group_policy.sub_group_size == 2
    assert obj.spec.rollout_strategy.rolling_update_configuration.max_surge == 1
    assert obj.spec.leader_worker_template.worker_template.spec.containers[0].tpu_chips() == 4

    cp = ControlPlane(auto_ready=True)
    cp.create(obj)
    cp.run_until_stable()
    pods = cp.store.list("Pod")
    assert len(pods) == 8

    manifest = to_manifest(cp.store.get("LeaderWorkerSet", "default", "vllm"))
    assert manifest["kind"] == "LeaderWorkerSet"
    assert manifest["status"]["replicas"] == 2


def test_load_manifests_multidoc(tmp_path):
    p = tmp_path / "m.yaml"
    p.write_text(LWS_YAML + "\n---\n" + LWS_YAML.replace("name: vllm", "name: vllm2"))
    objs = load_manifests(str(p))
    assert [o.meta.name for o in objs] == ["vllm", "vllm2"]


def test_http_api_server_lifecycle():
    cp = ControlPlane(auto_ready=True)
    server = ApiServer(cp, port=0)  # ephemeral port
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        def get(path):
            with urllib.request.urlopen(base + path) as r:
                return r.read().decode()

        def post(path, body: bytes):
            req = urllib.request.Request(base + path, data=body, method="POST")
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read().decode())

        assert get("/healthz") == "ok"

        out = post("/apply", LWS_YAML.encode())
        assert out["applied"] == ["LeaderWorkerSet/vllm"]
        cp.run_until_stable()

        listed = json.loads(get("/apis/LeaderWorkerSet"))
        assert listed[0]["metadata"]["name"] == "vllm"
        fetched = json.loads(get("/apis/Pod/default/vllm-0"))
        assert fetched["metadata"]["labels"]["leaderworkerset.lws.tpu/worker-index"] == "0"

        post("/scale/default/vllm", json.dumps({"replicas": 1}).encode())
        cp.run_until_stable()
        assert len(cp.store.list("Pod")) == 4

        metrics = get("/metrics")
        assert 'lws_reconcile_total{controller="lws"}' in metrics
        assert "lws_reconcile_duration_seconds_count" in metrics

        req = urllib.request.Request(f"{base}/apis/LeaderWorkerSet/default/vllm", method="DELETE")
        with urllib.request.urlopen(req):
            pass
        cp.run_until_stable()
        assert cp.store.list("Pod") == []
    finally:
        server.stop()


def test_http_apply_validation_422():
    cp = ControlPlane()
    server = ApiServer(cp, port=0)
    server.start()
    try:
        bad = LWS_YAML.replace("name: vllm", "name: Bad_Name")
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/apply", data=bad.encode(), method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 422
    finally:
        server.stop()


def test_typed_client_scale():
    cp = ControlPlane(auto_ready=True)
    client = Client(cp.store)
    client.create_lws(LWSBuilder().replicas(1).size(2).build())
    cp.run_until_stable()
    make_all_groups_ready(cp, "sample")
    assert client.get_lws("sample").status.ready_replicas == 1
    client.scale_lws("sample", 3)
    cp.run_until_stable()
    assert len(client.pods_of("sample")) == 6
    assert len(client.leader_pods_of("sample")) == 3


def test_plan_steps_cli(capsys):
    from lws_tpu.cli import main

    assert main(["plan-steps", "--initial", "2,2", "--target", "2,2"]) == 0
    out = capsys.readouterr().out
    assert "[2, 2]" in out and "[0, 0]" in out
    lines = [l for l in out.strip().splitlines()[1:]]
    assert lines[0].split()[0] == "0"
    assert "[0, 0]  [2, 2]" in lines[-1]


def test_config_rejects_nested_unknown_fields(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("metrics:\n  prot: 1234\n")  # typo inside a section
    with pytest.raises(ValueError, match="unknown configuration fields in metrics"):
        load_configuration(str(p))


def test_http_reapply_preserves_status():
    cp = ControlPlane(auto_ready=True)
    server = ApiServer(cp, port=0)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        def post(path, body: bytes):
            req = urllib.request.Request(base + path, data=body, method="POST")
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read().decode())

        post("/apply", LWS_YAML.encode())
        cp.run_until_stable()
        before = cp.store.get("LeaderWorkerSet", "default", "vllm").status.ready_replicas
        assert before == 2
        post("/apply", LWS_YAML.encode())  # unchanged re-apply
        after = cp.store.get("LeaderWorkerSet", "default", "vllm").status.ready_replicas
        assert after == before, "apply must never wipe live status"
    finally:
        server.stop()


def test_http_cordon_and_drain_endpoints():
    from lws_tpu.api.node import CLUSTER_NAMESPACE
    from lws_tpu.sched import make_slice_nodes

    cp = ControlPlane(enable_scheduler=True, auto_ready=True, require_binding=True)
    for s_ in range(2):
        cp.add_nodes(make_slice_nodes(f"slice-{s_}", topology="2x4"))
    cp.create(LWSBuilder().replicas(1).size(2).tpu_chips(4).build())
    cp.run_until_stable()
    server = ApiServer(cp, port=0)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        def post(path, body=b"{}"):
            req = urllib.request.Request(base + path, data=body, method="POST")
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read().decode())

        hosting = cp.store.get("Pod", "default", "sample-0").spec.node_name
        out = post(f"/drain/{hosting}")
        assert out["node"] == hosting and "sample" in " ".join(out["evicted"])
        cp.run_until_stable()
        # Group recreated away from the drained node.
        for p_ in cp.store.list("Pod", "default"):
            assert p_.spec.node_name != hosting
        assert cp.store.get("Node", CLUSTER_NAMESPACE, hosting).spec.unschedulable

        out = post(f"/cordon/{hosting}", json.dumps({"unschedulable": False}).encode())
        assert out["unschedulable"] is False
        assert not cp.store.get("Node", CLUSTER_NAMESPACE, hosting).spec.unschedulable

        with pytest.raises(urllib.error.HTTPError) as e:
            post("/drain/ghost")
        assert e.value.code == 404

        # Payload validation: a string "false" must be rejected, not coerced
        # to True (bool("false") is True) and silently cordon.
        with pytest.raises(urllib.error.HTTPError) as e:
            post(f"/cordon/{hosting}", json.dumps({"unschedulable": "false"}).encode())
        assert e.value.code == 422
        with pytest.raises(urllib.error.HTTPError) as e:
            post(f"/cordon/{hosting}", b"[1, 2]")
        assert e.value.code == 422
    finally:
        server.stop()


def test_http_kind_aliases_and_unknown_kind():
    """kubectl-style kind resolution on /apis: plural/lower aliases resolve,
    unknown kinds 404 with the alias list instead of silently returning []."""
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(2).build())
    cp.run_until_stable()
    server = ApiServer(cp, port=0)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        def get(path):
            with urllib.request.urlopen(base + path) as r:
                return json.loads(r.read().decode())

        assert len(get("/apis/pods")) == 2
        assert get("/apis/lws")[0]["metadata"]["name"] == "sample"
        assert get("/apis/leaderworkersets/default/sample")["kind"] == "LeaderWorkerSet"
        with pytest.raises(urllib.error.HTTPError) as e:
            get("/apis/widgets")
        assert e.value.code == 404 and "unknown kind" in e.value.read().decode()
    finally:
        server.stop()


def test_apply_accepts_k8s_nested_resource_quantities():
    """Reference-style manifests use resources.limits with quantity strings
    ("100m", "1Gi"); they must apply, with limits winning over requests."""
    from lws_tpu.manifest import from_manifest

    lws = from_manifest({
        "apiVersion": "leaderworkerset.x-k8s.io/v1",
        "kind": "LeaderWorkerSet",
        "metadata": {"name": "q"},
        "spec": {"leaderWorkerTemplate": {"size": 2, "workerTemplate": {"spec": {
            "containers": [{"name": "w", "resources": {
                "requests": {"cpu": "100m", "google.com/tpu": "2"},
                "limits": {"google.com/tpu": "4", "memory": "1Gi"},
            }}],
        }}}},
    })
    res = lws.spec.leader_worker_template.worker_template.spec.containers[0].resources
    assert res["google.com/tpu"] == 4      # limits win
    assert res["memory"] == 2**30
    assert res["cpu"] == 0                 # sub-unit floors; not scheduled here


def test_drain_skips_succeeded_pods():
    """Draining must not resurrect completed workloads (kubectl drain parity:
    succeeded pods are ignored, not failed-and-restarted)."""
    from lws_tpu.api.pod import PodPhase
    from lws_tpu.controllers.node_monitor import evict_pods_on_node
    from lws_tpu.sched import make_slice_nodes

    cp = ControlPlane(enable_scheduler=True, auto_ready=True, require_binding=True)
    cp.add_nodes(make_slice_nodes("s0", topology="2x4"))
    cp.create(LWSBuilder().replicas(1).size(2).tpu_chips(4).build())
    cp.run_until_stable()
    done = cp.store.get("Pod", "default", "sample-0-1")
    node = done.spec.node_name
    done.status.phase = PodPhase.SUCCEEDED
    done.status.ready = False
    cp.store.update_status(done)

    evicted = evict_pods_on_node(cp.store, node, "drain test")
    assert "sample-0-1" not in evicted
    assert cp.store.get("Pod", "default", "sample-0-1").status.phase == PodPhase.SUCCEEDED


def test_mixed_case_manifest_rejected():
    """A manifest mixing camelCase and snake_case field names is ambiguous
    between the k8s parser and the native round-trip path: reject loudly
    instead of guessing (guessing wrong silently drops spec fields)."""
    from lws_tpu.manifest import from_manifest

    with pytest.raises(ValueError, match="mixes"):
        from_manifest({
            "kind": "LeaderWorkerSet",
            "metadata": {"name": "x"},
            "spec": {"leaderWorkerTemplate": {"size": 2},
                     "startup_policy": "LeaderCreated"},
        })


def test_camelcase_manifest_with_resource_version_takes_k8s_parser():
    """kubectl-style exports keep metadata.resourceVersion; its presence must
    NOT shunt a camelCase manifest onto the snake_case path (which would
    silently produce an all-defaults spec)."""
    from lws_tpu.manifest import from_manifest

    lws = from_manifest({
        "kind": "LeaderWorkerSet",
        "metadata": {"name": "x", "resourceVersion": 42},
        "spec": {"replicas": 3, "leaderWorkerTemplate": {"size": 4}},
    })
    assert lws.spec.replicas == 3
    assert lws.spec.leader_worker_template.size == 4


def test_events_endpoint_exposes_controller_trace():
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(2).build())
    cp.run_until_stable()
    server = ApiServer(cp, port=0)
    server.start()
    try:
        from lws_tpu.client import RemoteClient

        client = RemoteClient(f"http://127.0.0.1:{server.port}")
        events = client.events()
        assert events, "reconcile should have recorded events"
        assert {"object", "type", "reason", "message", "timestamp"} <= set(events[0])
        named = client.events(name="sample")
        assert named and all(e["object"].endswith("/sample") for e in named)
        assert client.events(namespace="nope") == []
    finally:
        server.stop()
