"""Integration tier: full control plane against the in-process store
(≈ test/integration/controllers/leaderworkerset_test.go create/scale cases).
"""


from lws_tpu.api import contract
from lws_tpu.api.types import (
    CONDITION_AVAILABLE,
    CONDITION_PROGRESSING,
    StartupPolicy,
    SubdomainPolicy,
)
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import (
    LWSBuilder,
    assert_valid_lws,
    condition_status,
    expect_valid_leader_groupset,
    expect_valid_worker_groupsets,
    lws_pods,
    make_all_groups_ready,
    set_pod_ready,
)


def make_cp(**kw):
    return ControlPlane(**kw)


def test_create_materializes_groups():
    cp = make_cp()
    lws = cp.create(LWSBuilder().replicas(2).size(3).build())
    cp.run_until_stable()

    expect_valid_leader_groupset(cp.store, lws, replicas=2)
    expect_valid_worker_groupsets(cp.store, lws, count=2)
    assert_valid_lws(cp.store, "sample")
    pods = lws_pods(cp.store, "sample")
    names = sorted(p.meta.name for p in pods)
    assert names == sorted(
        ["sample-0", "sample-0-1", "sample-0-2", "sample-1", "sample-1-1", "sample-1-2"]
    )
    # Shared headless service exists and is the pods' subdomain.
    svc = cp.store.get("Service", "default", "sample")
    assert svc.spec.publish_not_ready_addresses
    for p in pods:
        assert p.spec.subdomain == "sample"


def test_pod_contract_injected():
    cp = make_cp()
    cp.create(LWSBuilder().replicas(1).size(2).tpu_chips(4).build())
    cp.run_until_stable()

    worker = cp.store.get("Pod", "default", "sample-0-1")
    assert worker.meta.labels[contract.WORKER_INDEX_LABEL_KEY] == "1"
    assert worker.meta.labels[contract.GROUP_INDEX_LABEL_KEY] == "0"
    env = {e.name: e.value for e in worker.spec.containers[0].env}
    assert env[contract.LWS_LEADER_ADDRESS] == "sample-0.sample.default"
    assert env[contract.LWS_GROUP_SIZE] == "2"
    assert env[contract.TPU_WORKER_ID] == "1"
    leader = cp.store.get("Pod", "default", "sample-0")
    assert leader.meta.labels[contract.GROUP_UNIQUE_HASH_LABEL_KEY]
    assert (
        worker.meta.labels[contract.GROUP_UNIQUE_HASH_LABEL_KEY]
        == leader.meta.labels[contract.GROUP_UNIQUE_HASH_LABEL_KEY]
    )


def test_status_becomes_available_when_ready():
    cp = make_cp()
    lws = cp.create(LWSBuilder().replicas(2).size(2).build())
    cp.run_until_stable()

    fetched = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert condition_status(fetched, CONDITION_PROGRESSING) is True
    assert fetched.status.replicas == 2

    make_all_groups_ready(cp, "sample")
    cp.run_until_stable()
    fetched = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert fetched.status.ready_replicas == 2
    assert fetched.status.updated_replicas == 2
    assert condition_status(fetched, CONDITION_AVAILABLE) is True
    assert condition_status(fetched, CONDITION_PROGRESSING) is False
    assert fetched.status.hpa_pod_selector


def test_scale_up_and_down():
    cp = make_cp(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(2).build())
    cp.run_until_stable()
    assert len(lws_pods(cp.store, "sample")) == 2

    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.replicas = 3
    cp.store.update(lws)
    cp.run_until_stable()
    assert len(lws_pods(cp.store, "sample")) == 6

    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.replicas = 0
    cp.store.update(lws)
    cp.run_until_stable()
    assert len(lws_pods(cp.store, "sample")) == 0


def test_scale_to_zero_and_back():
    cp = make_cp(auto_ready=True)
    cp.create(LWSBuilder().replicas(2).size(2).build())
    cp.run_until_stable()
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    lws.spec.replicas = 0
    cp.store.update(lws)
    cp.run_until_stable()
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert lws.status.replicas == 0
    lws.spec.replicas = 2
    cp.store.update(lws)
    cp.run_until_stable()
    assert len(lws_pods(cp.store, "sample")) == 4


def test_size_one_no_worker_groupsets():
    cp = make_cp(auto_ready=True)
    lws = cp.create(LWSBuilder().replicas(2).size(1).build())
    cp.run_until_stable()
    assert len(lws_pods(cp.store, "sample")) == 2
    expect_valid_worker_groupsets(cp.store, lws, count=0)
    fetched = cp.store.get("LeaderWorkerSet", "default", "sample")
    assert fetched.status.ready_replicas == 2
    assert condition_status(fetched, CONDITION_AVAILABLE) is True


def test_leader_ready_startup_policy_gates_workers():
    cp = make_cp()
    cp.create(LWSBuilder().replicas(1).size(3).startup_policy(StartupPolicy.LEADER_READY).build())
    cp.run_until_stable()
    assert len(lws_pods(cp.store, "sample")) == 1  # leader only

    set_pod_ready(cp.store, "default", "sample-0")
    cp.run_until_stable()
    assert len(lws_pods(cp.store, "sample")) == 3


def test_unique_per_replica_services_and_subdomains():
    cp = make_cp(auto_ready=True)
    cp.create(
        LWSBuilder().replicas(2).size(2).subdomain_policy(SubdomainPolicy.UNIQUE_PER_REPLICA).build()
    )
    cp.run_until_stable()
    # One service per replica, named after the leader pod.
    assert cp.store.try_get("Service", "default", "sample-0") is not None
    assert cp.store.try_get("Service", "default", "sample-1") is not None
    leader = cp.store.get("Pod", "default", "sample-0")
    assert leader.spec.subdomain == "sample-0"
    worker = cp.store.get("Pod", "default", "sample-0-1")
    assert worker.spec.subdomain == "sample-0"
    env = {e.name: e.value for e in worker.spec.containers[0].env}
    assert env[contract.LWS_LEADER_ADDRESS] == "sample-0.sample-0.default"


def test_deleted_worker_groupset_recreated():
    cp = make_cp(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(3).build())
    cp.run_until_stable()
    cp.store.delete("GroupSet", "default", "sample-0")
    cp.run_until_stable()
    assert cp.store.try_get("GroupSet", "default", "sample-0") is not None
    assert len(lws_pods(cp.store, "sample")) == 3


def test_deleted_service_recreated():
    cp = make_cp(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(2).build())
    cp.run_until_stable()
    cp.store.delete("Service", "default", "sample")
    cp.run_until_stable()
    assert cp.store.try_get("Service", "default", "sample") is not None


def test_lws_delete_cascades_everything():
    cp = make_cp(auto_ready=True)
    cp.create(LWSBuilder().replicas(2).size(3).build())
    cp.run_until_stable()
    cp.store.delete("LeaderWorkerSet", "default", "sample")
    cp.run_until_stable()
    assert cp.store.list("Pod") == []
    assert cp.store.list("GroupSet") == []
    assert cp.store.list("Service") == []
    assert cp.store.list("ControllerRevision") == []


def test_threaded_manager_mode():
    """The background-thread manager (live `serve` mode) reconciles to the
    same fixed point as run_until_stable."""
    import time

    cp = make_cp(auto_ready=True)
    cp.manager.start(poll_interval=0.005)
    try:
        cp.create(LWSBuilder("threaded").replicas(2).size(2).build())
        deadline = time.time() + 30
        while time.time() < deadline:
            lws = cp.store.get("LeaderWorkerSet", "default", "threaded")
            if lws.status.ready_replicas == 2:
                break
            time.sleep(0.05)
        else:
            raise AssertionError(f"never became ready: {lws.status}")
        assert len(lws_pods(cp.store, "threaded")) == 4
    finally:
        cp.manager.stop()


def test_requeue_after_is_honored():
    """Result.requeue_after re-runs the reconciler after the delay (timer
    heap), and flush_delays() promotes timers deterministically."""
    import time as _time

    from lws_tpu.core.manager import Manager, Result
    from lws_tpu.core.store import Store, new_meta
    from lws_tpu.api.pod import Pod

    store = Store()
    calls = []
    delay = {"value": 60}  # far future: "not yet due" can't race wall clock

    class Periodic:
        name = "periodic"

        def reconcile(self, key):
            calls.append(key)
            if delay["value"]:
                return Result(requeue_after=delay["value"])
            return None

    mgr = Manager(store)
    mgr.register(Periodic(), {"Pod": lambda o: [o.key()]})
    store.create(Pod(meta=new_meta("p0")))
    assert mgr.run_until_stable() == 1
    assert len(calls) == 1

    # Not yet due (timer parked 60s out): stable without a second call.
    assert mgr.run_until_stable() == 0

    # flush_delays() promotes the far-future timer without waiting.
    delay["value"] = 0.01  # next requeue is a short, real wall-clock timer
    mgr.flush_delays()
    assert mgr.run_until_stable() == 1
    assert len(calls) == 2

    # A short timer is promoted by real elapsed time (sleep strictly longer
    # than the delay — the due direction can't race the clock).
    delay["value"] = 0
    _time.sleep(0.05)
    assert mgr.run_until_stable() == 1
    assert len(calls) == 3
    assert mgr.run_until_stable() == 0


def test_deleted_per_replica_service_recreated():
    """UniquePerReplica services are owned by their leader pod; deleting one
    must requeue that pod (owner_pod_of_deleted DELETED edge) so the pod
    controller recreates it — the LWS status-churn side channel that used to
    repair this is generation-gated now."""
    cp = make_cp(auto_ready=True)
    cp.create(
        LWSBuilder().replicas(2).size(2)
        .subdomain_policy(SubdomainPolicy.UNIQUE_PER_REPLICA).build()
    )
    cp.run_until_stable()
    assert cp.store.try_get("Service", "default", "sample-1") is not None
    cp.store.delete("Service", "default", "sample-1")
    cp.run_until_stable()
    assert cp.store.try_get("Service", "default", "sample-1") is not None
    assert_valid_lws(cp.store, "sample")


def test_deleted_podgroup_recreated():
    """Gang PodGroups are owned by their leader pod; same DELETED repair
    edge as per-replica services."""
    from lws_tpu.sched import make_slice_nodes

    cp = make_cp(enable_scheduler=True, auto_ready=True, scheduler_provider="gang")
    for i in range(2):
        cp.add_nodes(make_slice_nodes(f"slice-{i}", topology="2x4"))
    cp.create(LWSBuilder().replicas(2).size(2).tpu_chips(4).build())
    cp.run_until_stable()
    groups = cp.store.list("PodGroup")
    assert len(groups) == 2
    victim = groups[0]
    cp.store.delete("PodGroup", victim.meta.namespace, victim.meta.name)
    cp.run_until_stable()
    assert cp.store.try_get("PodGroup", victim.meta.namespace, victim.meta.name) is not None
