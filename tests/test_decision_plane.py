"""Decision provenance + closed-loop actuation (ISSUE 19): the bounded
DecisionLedger (collapse, flap detection, convergence timing, eviction),
the scale/rollout actuators closing the loop through the STOCK machinery
(AnnotationAdapter -> Autoscaler -> DS writeback; RolloutActuationAdapter),
kill-switch mutation proofs per plane, DrainGate-mediated scale-in, the
`/debug/decisions` surface on both servers, `lws-tpu why` + the ACT column,
the loadgen closed-loop report fold, and the two deterministic end-to-end
sweeps with chaos overlays (flash crowd -> scale-out -> one drained
scale-in; degraded rollout -> automatic rollback).

Everything is clock-injected and seeded — no wall-clock sleeps outside the
socket-backed drain scenario (which reuses the chaos suite's bounded-wait
idiom)."""

import argparse
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from lws_tpu import loadgen, obs
from lws_tpu.api.pod import EnvVar
from lws_tpu.core import resilience
from lws_tpu.core.flightrecorder import FlightRecorder
from lws_tpu.core.metrics import MetricsRegistry
from lws_tpu.loadgen import closedloop
from lws_tpu.obs import decisions, rollout
from lws_tpu.obs.decisions import DecisionLedger, RolloutActuator, ScaleActuator
from lws_tpu.obs.history import HistoryRing
from lws_tpu.obs.recommend import Recommendation
from lws_tpu.obs.rollout import CanaryAnalyzer, RolloutLedger
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import LWSBuilder, make_all_groups_ready
from lws_tpu.utils import revision as revisionutils

WINDOWS = tuple(w.scaled(0.05) for w in obs.DEFAULT_BURN_WINDOWS)


def update_image(cp, name, image):
    lws = cp.store.get("LeaderWorkerSet", "default", name)
    for c in lws.spec.leader_worker_template.worker_template.spec.containers:
        c.image = image
    cp.store.update(lws)


def _revision_ring(baseline: str, canary: str, now_span=195.0):
    """Two-revision canary ring keyed on REAL revision hashes: the baseline
    delivers every token on time, the canary mints tokens with zero
    goodput (an all-late canary — absence of the goodput twin is a 100%
    error series, not a missing signal)."""
    ring = HistoryRing(interval_s=0.0, retention_s=3600.0)
    acc = 0.0
    for t in (0.0, 90.0, 180.0, now_span):
        acc += 500.0
        cum = MetricsRegistry()
        cum.inc("serving_tokens_total",
                {"engine": "paged", "revision": baseline}, acc * 2)
        cum.inc("serving_goodput_tokens_total",
                {"engine": "paged", "revision": baseline}, acc * 2)
        cum.inc("serving_tokens_total",
                {"engine": "paged", "revision": canary}, acc)
        ring.ingest(cum.render(), now=t)
    return ring


def _mid_update_cp():
    """A deployment caught mid-rolling-update: both revisions live, the
    canary template is current — the state a rollback restores from."""
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(3).size(2).image("img:v1").build())
    make_all_groups_ready(cp, "sample")
    update_image(cp, "sample", "img:v2")
    cp.run_until_stable()
    lws = cp.store.get("LeaderWorkerSet", "default", "sample")
    revs = revisionutils.list_revisions(cp.store, lws)
    assert len(revs) == 2
    return (cp, revisionutils.get_revision_key(revs[0]),
            revisionutils.get_revision_key(revs[-1]))


# ---------------------------------------------------------------------------
# DecisionLedger semantics


def test_ledger_collapse_repeats_and_verdict_edges():
    led = DecisionLedger(registry=MetricsRegistry(), recorder=FlightRecorder())
    guards = [{"name": "evidence", "passed": True, "detail": "steady"}]
    r1 = led.open("scale", "decode", "hold", guards=guards, now=1.0)
    r2 = led.open("scale", "decode", "hold", guards=guards, now=2.0)
    # Identical un-acted repeats fold onto one record: a steady "hold"
    # stream must not flush the scale-out that mattered out of the window.
    assert r2 is r1 and r1.repeats == 1 and r1.last_at == 2.0
    # A verdict (or guard-outcome) change breaks the collapse.
    r3 = led.open("scale", "decode", "scale_out", guards=guards, now=3.0)
    assert r3.id != r1.id
    # A different subject never collapses onto another's record.
    r4 = led.open("scale", "prefill", "hold", guards=guards, now=4.0)
    assert r4.id != r1.id
    # Acted records never absorb repeats: provenance of an actuation is
    # immutable history, not a counter.
    led.actuate(r3.id, "scale_out", "applied", now=3.5)
    r5 = led.open("scale", "decode", "scale_out", guards=guards, now=5.0)
    assert r5.id != r3.id and r3.repeats == 0


def test_ledger_capacity_evicts_oldest_and_snapshot_limits():
    led = DecisionLedger(capacity=3, registry=MetricsRegistry(),
                         recorder=FlightRecorder())
    ids = [led.open("scale", f"r{i}", "hold", now=float(i)).id
           for i in range(5)]
    snap = led.snapshot(limit=256)
    assert [d["id"] for d in snap] == ids[2:]  # newest-last, oldest evicted
    assert led.get(ids[0]) is None
    assert [d["id"] for d in led.snapshot(limit=1)] == [ids[-1]]


def test_ledger_actuate_metrics_flap_detection_and_convergence(monkeypatch):
    reg = MetricsRegistry()
    fr = FlightRecorder()
    led = DecisionLedger(registry=reg, recorder=fr)
    monkeypatch.setenv(decisions.FLAP_WINDOW_ENV, "100")

    out = led.open("scale", "decode", "scale_out", now=10.0)
    led.actuate(out.id, "scale_out", "applied", now=10.0,
                generation_before=3, lws="child", namespace="default",
                desired=4)
    assert reg.counter_value(
        "serving_actuations_total",
        {"plane": "scale", "action": "scale_out", "outcome": "applied"}) == 1.0
    # Applied-but-not-converged is what the convergence sweeps walk.
    assert [r.id for r in led.pending("scale")] == [out.id]
    led.converge(out.id, now=25.0, generation_after=7)
    assert out.convergence_s == 15.0 and out.generation_after == 7
    assert led.pending("scale") == []
    assert "serving_convergence_seconds" in reg.render()

    # Direction reversal INSIDE the window = a flap, counted and stamped.
    back = led.open("scale", "decode", "scale_in", now=40.0)
    led.actuate(back.id, "scale_in", "applied", now=40.0)
    assert back.detail.get("flap") is True
    assert reg.counter_value("serving_actuation_flaps_total",
                             {"plane": "scale"}) == 1.0
    # Reversal OUTSIDE the window is a normal correction.
    monkeypatch.setenv(decisions.FLAP_WINDOW_ENV, "5")
    fwd = led.open("scale", "decode", "scale_out", now=90.0)
    led.actuate(fwd.id, "scale_out", "applied", now=90.0)
    assert fwd.detail.get("flap") is None
    assert reg.counter_value("serving_actuation_flaps_total",
                             {"plane": "scale"}) == 1.0
    # Suppressed actuations cannot oscillate: no direction memory burned.
    sup = led.open("scale", "decode", "scale_in", now=91.0)
    led.actuate(sup.id, "scale_in", "suppressed", now=91.0)
    assert sup.detail.get("flap") is None

    # Supersede closes a stale pending decision without "converging" it.
    led.supersede(fwd.id, sup.id)
    assert fwd.converged_at == -1.0
    assert fwd.detail["superseded_by"] == sup.id
    # last_actuation is the newest acted record — the ACT column's source.
    assert led.last_actuation("scale").id == sup.id
    assert led.last_actuation("rollout") is None


# ---------------------------------------------------------------------------
# Kill-switch mutation proofs: with LWS_TPU_ACTUATION_DISABLE set, verdicts
# still publish but replicas/partitions provably never move — and flipping
# the switch back is the ONLY thing needed for the same evidence to act.


def test_scale_kill_switch_records_but_replicas_never_move():
    res = closedloop.run_sweep(seed=7, disable="scale,rollout")
    try:
        # The recommender still saw the crowd and still recommended.
        assert any(e["desired"] == 4 for e in res["evaluations"])
        # But nothing moved, ever: no autoscale, no drain.
        assert res["max_replicas_seen"] == 1
        assert all(r == 1 for _, r in res["replicas"])
        assert res["drains"] == []
        suppressed = [d for d in res["decisions"]
                      if d["outcome"] == "suppressed"]
        assert suppressed, res["decisions"]
        for d in suppressed:
            assert d["action"] == "scale_out"
            kill = next(g for g in d["guards"] if g["name"] == "kill_switch")
            assert kill["passed"] is False
            # The full burn evidence is still recorded — record-only mode
            # is the same flight recorder, minus the control surface.
            assert d["inputs"]["burns"]
        assert set(res["actuations"]) == {"scale_out/suppressed"}
        assert res["flaps"] == 0
    finally:
        rollout.LEDGER.clear()


def test_rollout_kill_switch_records_but_partition_never_moves(monkeypatch):
    cp, old_key, new_key = _mid_update_cp()
    try:
        reg = MetricsRegistry()
        fr = FlightRecorder()
        an = CanaryAnalyzer(_revision_ring(old_key, new_key),
                            lws="default/sample", attainment_target=0.99,
                            windows=WINDOWS, min_samples=100.0,
                            min_duration_s=50.0, delta=2.0,
                            ledger=RolloutLedger(registry=reg),
                            registry=reg, recorder=fr)
        led = DecisionLedger(registry=reg, recorder=fr)
        act = RolloutActuator(cp.store, ledger=led)
        monkeypatch.setenv(decisions.DISABLE_ENV, "scale,rollout")

        report = an.evaluate(now=195.0)
        assert report.baseline == old_key
        assert report.verdicts[new_key].verdict == "rollback"
        # The verdict gauge publishes regardless of the switch.
        assert reg.gauge_value("lws_rollout_canary_verdict",
                               {"lws": "default/sample",
                                "revision": new_key}) == -1.0

        before = cp.store.get("LeaderWorkerSet", "default", "sample")
        image_before = (before.spec.leader_worker_template.worker_template
                        .spec.containers[0].image)
        record = act.apply(report, now=195.0)
        assert record.action == "rollback" and record.outcome == "suppressed"
        kill = next(g for g in record.guards if g["name"] == "kill_switch")
        assert kill["passed"] is False
        cp.run_until_stable()
        after = cp.store.get("LeaderWorkerSet", "default", "sample")
        assert (after.spec.leader_worker_template.worker_template
                .spec.containers[0].image) == image_before == "img:v2"
        assert reg.counter_value(
            "serving_actuations_total",
            {"plane": "rollout", "action": "rollback",
             "outcome": "suppressed"}) == 1.0

        # The switch is load-bearing: clearing it is the only change, and
        # the SAME evidence now rolls the template back.
        monkeypatch.delenv(decisions.DISABLE_ENV)
        record2 = act.apply(report, now=196.0)
        assert record2.outcome == "applied"
        assert record2.detail["rolled_back_to"] == old_key
        lws = cp.store.get("LeaderWorkerSet", "default", "sample")
        assert (lws.spec.leader_worker_template.worker_template
                .spec.containers[0].image) == "img:v1"
    finally:
        rollout.LEDGER.clear()


# ---------------------------------------------------------------------------
# DrainGate-mediated scale-in: the victim's worker finishes in-flight work
# and parks the rest for a successor BEFORE the pod goes away.


def _make_ds_with_telemetry(port: int, decode_replicas: int = 2):
    from lws_tpu.api.disagg import (
        DisaggregatedRoleSpec,
        DisaggregatedSet,
        DisaggregatedSetSpec,
        LeaderWorkerSetTemplateSpec,
    )
    from lws_tpu.api.types import LeaderWorkerSetSpec, LeaderWorkerTemplate
    from lws_tpu.core.store import new_meta
    from lws_tpu.runtime.telemetry import METRICS_PORT_ENV
    from lws_tpu.testing import make_worker_template

    def role(name, replicas):
        tpl = make_worker_template("img:v1")
        tpl.spec.containers[0].env.append(
            EnvVar(name=METRICS_PORT_ENV, value=str(port)))
        return DisaggregatedRoleSpec(
            name=name, replicas=replicas,
            template=LeaderWorkerSetTemplateSpec(
                spec=LeaderWorkerSetSpec(
                    leader_worker_template=LeaderWorkerTemplate(
                        worker_template=tpl, size=1))))

    return DisaggregatedSet(
        meta=new_meta("llmd"),
        spec=DisaggregatedSetSpec(
            roles=[role("prefill", 1), role("decode", decode_replicas)]),
    )


def test_scale_in_drains_the_victim_before_the_pod_goes():
    """One-step scale-in through the REAL drain wire: the actuator POSTs
    /debug/drain at the victim's published telemetry endpoint, the process
    DrainGate latches, the worker loop finishes (and acks) its in-flight
    bundle, parks the rest for a successor — and only then does the
    autoscaler remove the replica. No token stream lost."""
    from lws_tpu.runtime.telemetry import TelemetryServer
    from lws_tpu.serving import kv_transport as kt

    tele = TelemetryServer(port=0)
    tele.start()
    server = kt.KVServer(port=0, host="127.0.0.1")
    try:
        cp = ControlPlane(auto_ready=True)
        cp.create(_make_ds_with_telemetry(tele.port))
        cp.run_until_stable()
        # The sim publishes headless-DNS pod addresses; point them at
        # loopback so the actuator's drain POST reaches the test server.
        for pod in cp.store.list("Pod", "default"):
            pod.status.address = "127.0.0.1"
            cp.store.update(pod)

        for i in range(3):
            server.offer_bundle({"id": f"d{i}"}, b"x")
        hold, done = threading.Event(), threading.Event()
        processed: list = []

        def worker():
            def process(meta, payload):
                processed.append(meta["id"])
                hold.wait(timeout=10)

            while not resilience.DRAIN.draining:
                try:
                    if kt.pull_bundle(("127.0.0.1", server.port), timeout=0.2,
                                      process=process) is None:
                        continue
                except OSError:
                    break
            done.set()

        threading.Thread(target=worker, daemon=True).start()
        deadline = time.time() + 5
        while not processed and time.time() < deadline:
            time.sleep(0.01)
        assert processed == ["d0"]  # one bundle in flight

        reg = MetricsRegistry()
        led = DecisionLedger(registry=reg, recorder=FlightRecorder())
        actuator = ScaleActuator(cp.store, ledger=led, min_replicas=1,
                                 max_replicas=4, stabilization=2)
        rec = Recommendation(
            at=100.0,
            desired={"prefill": 1, "decode": 1},
            current={"prefill": 1, "decode": 2},
            reasons={"prefill": "steady",
                     "decode": "calm: burn 0.00x, budget intact"},
        )
        records = actuator.apply(rec, now=100.0)
        scale_in = next(r for r in records if r.verdict == "scale_in")
        assert scale_in.outcome == "applied"
        # The drain hit the victim (highest group index) over HTTP and
        # latched the process gate MID-processing.
        assert resilience.DRAIN.draining
        drained = scale_in.detail["drained"]
        assert drained["ok"] is True and drained["pod"].endswith("-decode-1")
        hold.set()                    # in-flight work completes...
        assert done.wait(timeout=5)   # ...and the loop exits clean
        deadline = time.time() + 5
        while server.delivery_counts()[0] < 1 and time.time() < deadline:
            time.sleep(0.01)
        assert server.delivery_counts()[0] == 1  # the in-flight item ACKED
        assert processed == ["d0"]    # nothing new admitted past the drain
        # Parked work survives for a successor: both remaining bundles pull.
        survivors = {kt.pull_bundle(("127.0.0.1", server.port),
                                    timeout=1.0)[0]["id"] for _ in range(2)}
        assert survivors == {"d1", "d2"}

        # The pod removal itself rides the stock autoscaler's scale-down
        # stabilization: a second consecutive calm evaluation moves it.
        cp.run_until_stable()
        actuator.apply(rec, now=115.0)
        cp.run_until_stable()
        child = next(
            lws for lws in cp.store.list("LeaderWorkerSet", "default")
            if lws.meta.name.endswith("-decode"))
        assert child.spec.replicas == 1
        # ...and the DS writeback kept the role spec in lockstep.
        ds = cp.store.get("DisaggregatedSet", "default", "llmd")
        assert ds.role("decode").replicas == 1
        settled = actuator.observe(now=120.0)
        assert [r.id for r in settled] == [scale_in.id]
        assert scale_in.convergence_s == 20.0
        assert scale_in.repeats == 1  # the stabilization re-publish folded on
    finally:
        resilience.DRAIN.reset()
        tele.stop()
        server.close()
        rollout.LEDGER.clear()


# ---------------------------------------------------------------------------
# The /debug/decisions surface + `lws-tpu why`


def _seed_global_decision():
    rec = decisions.DECISIONS.open(
        "scale", "decode", "scale_out",
        inputs={"reason": "burn 20.0x over threshold 14.4", "current": 1,
                "desired": 4, "firing": ["paged/chat"],
                "burns": [{"series": "paged/chat", "instance": "w0",
                           "window": "fast", "short_burn": 20.0,
                           "long_burn": 18.0, "threshold": 14.4,
                           "firing": True}]},
        guards=[{"name": "evidence", "passed": True, "detail": "burn"},
                {"name": "kill_switch", "passed": True, "detail": "off"},
                {"name": "target", "passed": True, "detail": "child"}],
        now=100.0)
    decisions.DECISIONS.actuate(
        rec.id, "scale_out", "applied", now=100.0, generation_before=3,
        namespace="default", ds="llmd", lws="child", desired=4)
    decisions.DECISIONS.converge(rec.id, now=115.0, generation_after=5)
    return rec


def test_telemetry_server_decisions_endpoint_bearer_and_limit():
    from lws_tpu.runtime.telemetry import TelemetryServer

    decisions.DECISIONS.clear()
    rec = _seed_global_decision()
    server = TelemetryServer(port=0, token="s3cret")
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/debug/decisions", timeout=10)
        assert err.value.code == 401  # bearer parity with the other surfaces
        req = urllib.request.Request(
            f"{base}/debug/decisions",
            headers={"Authorization": "Bearer s3cret"})
        with urllib.request.urlopen(req, timeout=10) as r:
            body = json.loads(r.read().decode())
        assert [d["id"] for d in body] == [rec.id]
        assert body[0]["convergence_s"] == 15.0
        req = urllib.request.Request(
            f"{base}/debug/decisions?limit=wat",
            headers={"Authorization": "Bearer s3cret"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 400  # parse_limit contract: 400, never 500
    finally:
        server.stop()
        decisions.DECISIONS.clear()


def test_api_server_decisions_endpoint_and_why_cli(capsys):
    from lws_tpu import cli
    from lws_tpu.runtime.server import ApiServer

    decisions.DECISIONS.clear()
    cp = ControlPlane(auto_ready=True)
    rec = _seed_global_decision()
    api = ApiServer(cp, port=0)
    api.start()
    base = f"http://127.0.0.1:{api.port}"
    try:
        with urllib.request.urlopen(f"{base}/debug/decisions", timeout=10) as r:
            body = json.loads(r.read().decode())
        assert [d["id"] for d in body] == [rec.id]
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/debug/decisions?limit=-1",
                                   timeout=10)
        assert err.value.code == 400

        # `lws-tpu why last` against the live server renders the chain.
        ns = argparse.Namespace(server=f"127.0.0.1:{api.port}",
                                decision_id="last", limit=64, json=False)
        assert cli.cmd_why(ns) == 0
        out = capsys.readouterr().out
        assert f"DECISION {rec.id}" in out
        assert "EVIDENCE" in out and "GUARDS" in out
        assert "scale_out -> applied" in out
        assert "CONVERGENCE: fleet settled 15.00s after actuation" in out
        # --json round-trips the record; an unknown id is a 1, not a trace.
        ns = argparse.Namespace(server=f"127.0.0.1:{api.port}",
                                decision_id=rec.id, limit=64, json=True)
        assert cli.cmd_why(ns) == 0
        assert json.loads(capsys.readouterr().out)["id"] == rec.id
        ns = argparse.Namespace(server=f"127.0.0.1:{api.port}",
                                decision_id="scale-999999", limit=64,
                                json=False)
        assert cli.cmd_why(ns) == 1
        assert "not in the retained window" in capsys.readouterr().err
    finally:
        api.stop()
        decisions.DECISIONS.clear()
        rollout.LEDGER.clear()


def test_watchdog_dump_embeds_the_decision_window():
    decisions.DECISIONS.clear()
    try:
        rec = _seed_global_decision()
        dump = FlightRecorder().dump(reason="manual")
        assert any(d["id"] == rec.id for d in dump["decisions"])
    finally:
        decisions.DECISIONS.clear()


# ---------------------------------------------------------------------------
# CLI renders: the ACT column + `why`, from canned records


def _canned_records():
    return [
        {"id": "scale-000001", "plane": "scale", "subject": "decode",
         "at": 10.0, "verdict": "hold", "inputs": {}, "guards": [],
         "action": "", "outcome": "", "acted_at": None,
         "generation_before": None, "generation_after": None, "detail": {},
         "converged_at": None, "convergence_s": None, "repeats": 4,
         "last_at": 50.0},
        {"id": "scale-000002", "plane": "scale", "subject": "decode",
         "at": 60.0, "verdict": "scale_out",
         "inputs": {"reason": "burn 20.0x over threshold 14.4",
                    "current": 1, "desired": 4, "firing": ["paged/chat"],
                    "burns": [{"series": "paged/chat", "instance": "w0",
                               "window": "fast", "short_burn": 20.0,
                               "long_burn": 18.0, "threshold": 14.4,
                               "firing": True}]},
         "guards": [{"name": "evidence", "passed": True, "detail": "burn"},
                    {"name": "kill_switch", "passed": True, "detail": "off"},
                    {"name": "target", "passed": True, "detail": "child"}],
         "action": "scale_out", "outcome": "applied", "acted_at": 60.0,
         "generation_before": 3, "generation_after": 5,
         "detail": {"lws": "crowd-0-x-decode", "desired": 4, "from": 1},
         "converged_at": 75.0, "convergence_s": 15.0, "repeats": 2,
         "last_at": 75.0},
        {"id": "rollout-000001", "plane": "rollout",
         "subject": "default/sample", "at": 80.0, "verdict": "rollback",
         "inputs": {"baseline": "r1",
                    "verdicts": {"r2": {"verdict": "rollback",
                                        "reason": "fast burn 55.0x",
                                        "short_burn": 55.0,
                                        "long_burn": 40.0,
                                        "baseline_burn": 0.0}}},
         "guards": [{"name": "kill_switch", "passed": True, "detail": "off"}],
         "action": "rollback", "outcome": "applied", "acted_at": 80.0,
         "generation_before": 7, "generation_after": 8,
         "detail": {"rolled_back_to": "r1", "flap": True},
         "converged_at": None, "convergence_s": None, "repeats": 0,
         "last_at": None},
    ]


def test_act_lines_fold_newest_actuation_per_plane():
    from lws_tpu.cli import _act_lines

    lines = _act_lines(_canned_records(), now=100.0)
    assert len(lines) == 2  # one per plane; the un-acted hold never shows
    scale = next(ln for ln in lines if ln.startswith("ACT scale"))
    assert "scale_out" in scale and "applied" in scale
    assert "[scale-000002]" in scale and "converged 15.0s" in scale
    assert "40s ago" in scale
    roll = next(ln for ln in lines if ln.startswith("ACT rollout"))
    assert "[rollout-000001]" in roll
    assert "converging" in roll and "FLAP" in roll
    assert _act_lines([], now=100.0) == []


def test_monitor_and_rollout_frames_carry_the_act_column():
    from lws_tpu.cli import render_monitor, render_rollout

    ring = HistoryRing(interval_s=0.0, retention_s=600.0)
    for t, v in ((0.0, 1.0), (10.0, 100.0)):
        cum = MetricsRegistry()
        cum.inc("serving_tokens_total", {"engine": "paged"}, v)
        ring.ingest(cum.render(), now=t)
    frame = render_monitor(ring.snapshot(), {}, now=10.0,
                           decisions=_canned_records())
    assert "ACT scale" in frame and "[scale-000002]" in frame
    out = render_rollout([], {}, {}, decisions=_canned_records(), now=100.0)
    assert "ACT rollout" in out and "FLAP" in out


def test_render_why_scale_and_rollout_chains():
    from lws_tpu.cli import render_why

    out = render_why(_canned_records()[1], now=100.0)
    assert "DECISION scale-000002" in out and "repeats=2" in out
    assert "reason: burn 20.0x over threshold 14.4" in out
    assert "replicas: current=1 desired=4" in out
    assert "paged/chat@w0" in out and "20.0x" in out and "yes" in out
    assert "[pass] evidence" in out and "[pass] kill_switch" in out
    assert "scale_out -> applied" in out
    assert "target generation: 3 -> 5" in out
    assert "CONVERGENCE: fleet settled 15.00s after actuation" in out

    out = render_why(_canned_records()[2], now=100.0)
    assert "baseline: r1" in out and "rollback" in out and "55.0x" in out
    assert "rolled_back_to=r1" in out
    assert "FLAP: this actuation reversed direction" in out
    assert "CONVERGENCE: pending" in out

    # A record-only verdict renders the negative lanes, not a stub.
    out = render_why(_canned_records()[0], now=100.0)
    assert "(no recorded inputs)" in out
    assert "(not acted on — verdict recorded only)" in out
    assert "CONVERGENCE: n/a" in out


def test_fail_guard_renders_as_fail():
    from lws_tpu.cli import render_why

    rec = _canned_records()[1]
    rec["guards"][1] = {"name": "kill_switch", "passed": False,
                        "detail": "scale,rollout"}
    out = render_why(rec, now=100.0)
    assert "[FAIL] kill_switch" in out and "scale,rollout" in out


# ---------------------------------------------------------------------------
# Loadgen: the closed-loop report block


def test_fold_actuations_totals_flaps_and_trace():
    ring = HistoryRing(interval_s=0.0, retention_s=600.0)
    steps = [
        (0.0, {"scale/scale_out/applied": 1.0}, {}),
        (30.0, {"scale/scale_out/applied": 1.0,
                "scale/scale_in/applied": 1.0}, {}),
        (60.0, {"scale/scale_out/applied": 2.0,
                "scale/scale_in/applied": 1.0}, {"scale": 1.0}),
    ]
    for t, acts, flaps in steps:
        cum = MetricsRegistry()
        for key, v in acts.items():
            plane, action, outcome = key.split("/")
            cum.inc("serving_actuations_total",
                    {"plane": plane, "action": action, "outcome": outcome}, v)
        for plane, v in flaps.items():
            cum.inc("serving_actuation_flaps_total", {"plane": plane}, v)
        ring.ingest(cum.render(), now=t)
    act = loadgen.fold_actuations(ring)
    assert act["actuations"] == {"scale/scale_out/applied": 2.0,
                                 "scale/scale_in/applied": 1.0}
    assert act["flaps"] == {"scale": 1.0}
    # Run-relative trace of every count STEP, in time order.
    trace_keys = [(s["t"], s["what"]) for s in act["trace"]]
    assert (0.0, "scale/scale_out/applied") in trace_keys
    assert (30.0, "scale/scale_in/applied") in trace_keys
    assert (60.0, "scale/scale_out/applied") in trace_keys
    # No actuation series in the ring -> no block at all.
    assert loadgen.fold_actuations(
        HistoryRing(interval_s=0.0, retention_s=60.0)) is None


def test_render_report_closed_loop_block():
    report = {
        "scenario": "flash_crowd", "seed": 7, "horizon_s": 1.5,
        "wall_s": 1.6, "offered_rps": 30.0, "achieved_rps": 29.0,
        "classes": {},
        "all": {"count": 10, "completed": 10, "attainment": 0.9,
                "goodput_fraction": 0.8, "tokens": 60, "good_tokens": 48,
                "ttft_p50": 0.01, "ttft_p95": 0.05, "ttft_p99": 0.06,
                "itl_p50": 0.001, "itl_p95": 0.002, "itl_p99": 0.003},
        "actuations": {
            "actuations": {"scale/scale_out/applied": 1.0,
                           "scale/scale_in/applied": 1.0},
            "flaps": {},
            "trace": [{"t": 0.5, "what": "scale/scale_out/applied",
                       "count": 1.0}],
        },
    }
    out = loadgen.render_report(report)
    assert "closed loop:" in out
    assert "scale/scale_out/applied=1" in out
    assert "flaps: none" in out
    assert "actuation @0.50s: scale/scale_out/applied (count 1)" in out


# ---------------------------------------------------------------------------
# The two acceptance sweeps, chaos overlays included


def test_closed_loop_flash_crowd_sweep_with_chaos():
    """Acceptance sweep (a): seeded flash crowd -> decode scale-out within
    two evaluations -> burn clears -> exactly ONE DrainGate-mediated
    scale-in step -> converged, zero flaps, bounded replicas — while a
    chaos overlay kills a decode pod mid-crowd. Every replica change
    resolves to a full provenance record, rendered end-to-end by `why`."""
    from lws_tpu.cli import render_why

    deleted: list = []

    def chaos(cp, now, tick):
        if tick == 5:  # mid-crowd, post-scale-out
            pod = sorted(
                (p.meta.name for p in cp.store.list("Pod", "default")
                 if "-decode" in p.meta.name))[0]
            cp.store.delete("Pod", "default", pod)
            deleted.append(pod)

    res = closedloop.run_sweep(seed=7, chaos=chaos)
    try:
        assert deleted  # the overlay really fired
        first_bad = next(e["tick"] for e in res["evaluations"]
                         if e["over_capacity"])
        assert res["scale_out_tick"] is not None
        assert res["scale_out_tick"] - first_bad + 1 <= 2
        assert res["max_replicas_seen"] == 4  # the autoscaler clamp held
        assert res["scale_in_steps"] == 1 and res["converged"]
        assert len(res["drains"]) == 1
        assert res["drains"][0].endswith("-decode-3")  # highest group index
        assert res["flaps"] == 0
        assert set(res["actuations"]) == {"scale_out/applied",
                                          "scale_in/applied"}

        applied = [d for d in res["decisions"] if d["outcome"] == "applied"]
        assert len(applied) == 2
        for d in applied:  # full provenance on every replica change
            assert all(g["passed"] for g in d["guards"])
            assert d["inputs"]["burns"] and d["inputs"]["reason"]
            assert d["generation_before"] is not None
            assert d["converged_at"] is not None and d["converged_at"] >= 0
            assert d["convergence_s"] is not None
        scale_in = next(d for d in applied if d["verdict"] == "scale_in")
        assert scale_in["detail"]["drained"]["ok"] is True
        out = render_why(scale_in, now=300.0)
        assert "EVIDENCE" in out and "GUARDS" in out
        assert "calm" in out and "drained=" in out
        assert "CONVERGENCE: fleet settled" in out
    finally:
        rollout.LEDGER.clear()


def test_closed_loop_rollback_sweep_with_chaos():
    """Acceptance sweep (b): a rolling update to a degraded revision ->
    the canary analyzer's rollback verdict actuates through the STOCK
    rollout machinery -> the fleet walks back to the baseline and the
    decision converges — while a chaos overlay kills a pod mid-walk-back.
    The episode is edge-triggered: re-judging the same regression never
    actuates twice, and the flap counter stays zero."""
    from lws_tpu.cli import render_why

    cp, old_key, new_key = _mid_update_cp()
    try:
        reg = MetricsRegistry()
        fr = FlightRecorder()
        an = CanaryAnalyzer(_revision_ring(old_key, new_key),
                            lws="default/sample", attainment_target=0.99,
                            windows=WINDOWS, min_samples=100.0,
                            min_duration_s=50.0, delta=2.0,
                            ledger=RolloutLedger(registry=reg),
                            registry=reg, recorder=fr)
        led = DecisionLedger(registry=reg, recorder=fr)
        act = RolloutActuator(cp.store, ledger=led)

        report = an.evaluate(now=195.0)
        record = act.apply(report, now=195.0)
        assert record.outcome == "applied" and record.action == "rollback"
        assert record.detail["paused"] is True
        assert record.detail["rolled_back_to"] == old_key
        assert record.detail["offenders"] == [new_key]
        assert record.generation_before is not None
        assert record.generation_after is not None

        # Chaos overlay: a pod dies mid-walk-back; the stock controller
        # replaces it and the rollback still converges.
        victim = cp.store.list("Pod", "default")[0]
        cp.store.delete("Pod", "default", victim.meta.name)

        settled: list = []
        for _ in range(12):
            cp.run_until_stable()
            make_all_groups_ready(cp, "sample")
            settled = act.observe(now=210.0)
            if settled:
                break
        assert [r.id for r in settled] == [record.id]
        assert record.convergence_s == 15.0
        for pod in cp.store.list("Pod", "default"):
            assert pod.spec.containers[0].image == "img:v1", pod.meta.name

        # Edge-triggered: the same regression re-judged does NOT actuate
        # again — the repeat records as guard-skipped, counters stay put.
        record2 = act.apply(report, now=220.0)
        assert record2.id != record.id and record2.outcome == "skipped"
        edge = next(g for g in record2.guards
                    if g["name"] == "regression_edge")
        assert edge["passed"] is False
        assert reg.counter_value(
            "serving_actuations_total",
            {"plane": "rollout", "action": "rollback",
             "outcome": "applied"}) == 1.0
        assert reg.counter_value("serving_actuation_flaps_total",
                                 {"plane": "rollout"}) == 0.0

        out = render_why(record.to_dict(), now=300.0)
        assert "baseline:" in out and "rollback" in out
        assert "CONVERGENCE: fleet settled 15.00s after actuation" in out
    finally:
        rollout.LEDGER.clear()
