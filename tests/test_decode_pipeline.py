"""Pipelined decode (ISSUE 3): the bounded in-flight dispatch ring must
change WHEN tokens reach the host, never WHICH tokens — pipelined engines
are token-identical to the synchronous (`pipeline_depth=0`) loop under
mid-stream admission, eviction, early completion, sampling, and the
pallas→XLA fallback probe with a non-empty in-flight queue."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lws_tpu.models.llama import LlamaConfig, init_params
from lws_tpu.serving.batch_engine import BatchEngine
from lws_tpu.serving.paged_engine import PagedBatchEngine
from lws_tpu.serving.pipeline import DecodePipeline


@pytest.fixture(scope="module")
def small_model():
    cfg = LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, param_dtype=jnp.float32,
        remat=False,
    )
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    return cfg, params


def prompts(n, rng=3):
    r = np.random.RandomState(rng)
    return [r.randint(1, 255, size=r.randint(4, 40)).astype(np.int32) for _ in range(n)]


# ---------------------------------------------------------------------------
# DecodePipeline unit behavior (no engine, numpy payloads).


def test_pipeline_fifo_depth_and_flush():
    pipe = DecodePipeline(depth=2, engine="batch")
    order = []
    for i in range(4):
        pipe.push(3, np.asarray([i]), lambda h: order.append(int(h[0])))
    # depth 2: pushes 3 and 4 evicted chunks 0 and 1, in dispatch order.
    assert order == [0, 1]
    assert len(pipe) == 2 and pipe.inflight_steps() == 6
    pipe.flush()
    assert order == [0, 1, 2, 3]
    assert not pipe and pipe.inflight_steps() == 0
    assert pipe.stats["dispatched"] == pipe.stats["consumed"] == 4


def test_pipeline_depth_zero_is_synchronous():
    pipe = DecodePipeline(depth=0, engine="batch")
    seen = []
    pipe.push(1, np.asarray([7]), lambda h: seen.append(int(h[0])))
    assert seen == [7] and not pipe


def test_pipeline_discard_drops_without_commit():
    pipe = DecodePipeline(depth=4, engine="batch")
    seen = []
    pipe.push(1, np.asarray([1]), lambda h: seen.append(int(h[0])))
    pipe.discard()
    assert seen == [] and not pipe
    assert pipe.stats["discarded"] == 1


def test_pipeline_host_blocked_only_when_ring_empty():
    pipe = DecodePipeline(depth=2, engine="batch")
    with pipe.host_section():
        pass
    blocked_empty = pipe.stats["host_blocked_s"]
    assert blocked_empty >= 0.0
    pipe.push(1, np.asarray([0]), lambda h: None)
    before = pipe.stats["host_blocked_s"]
    with pipe.host_section():  # ring non-empty: overlapped, not blocked
        pass
    assert pipe.stats["host_blocked_s"] == before


# ---------------------------------------------------------------------------
# Engine equivalence: pipelined vs synchronous.


def _run_paged(cfg, params, depth, schedule, **engine_kw):
    eng = PagedBatchEngine(cfg, params, pipeline_depth=depth, **engine_kw)
    return schedule(eng), eng


def test_paged_pipelined_matches_sync_greedy_early_completion(small_model):
    """Mixed budgets: the soonest completion forces in-flight-aware bound
    re-clamping; every stream must match the synchronous loop exactly."""
    cfg, params = small_model
    ps = prompts(4)
    budgets = (12, 3, 7, 1)  # 1: completes at admission; 3/7: early retires

    def schedule(eng):
        ids = [eng.submit(p, max_new_tokens=m) for p, m in zip(ps, budgets)]
        eng.run_until_drained()
        return [eng.result(i) for i in ids]

    kw = dict(slots=4, max_len=64, block_size=8)
    sync, _ = _run_paged(cfg, params, 0, schedule, **kw)
    piped, eng = _run_paged(cfg, params, 3, schedule, **kw)
    assert sync == piped
    assert [len(t) for t in piped] == list(budgets)
    assert eng._pipeline.stats["max_inflight"] >= 2  # overlap actually happened


def test_paged_pipelined_matches_sync_midstream_admission(small_model):
    """Admission into slots/blocks freed by in-flight completions: submit
    flushes the ring instead of refusing, and later chunks' commits only
    touch requests active at their dispatch."""
    cfg, params = small_model
    ps = prompts(3, rng=7)

    def schedule(eng):
        a = eng.submit(ps[0], max_new_tokens=4)
        b = eng.submit(ps[1], max_new_tokens=20)
        third = None
        for _ in range(200):
            eng.step_n(2)
            if third is None and eng.active_count < 2:
                third = eng.submit(ps[2], max_new_tokens=10)
                assert third is not None
            if eng.active_count == 0 and third is not None:
                break
        return [eng.result(i) for i in (a, b, third)]

    # Pool sized so the third request NEEDS the first's released blocks.
    kw = dict(slots=2, max_len=64, block_size=8, num_blocks=2 * 8 + 1)
    sync, _ = _run_paged(cfg, params, 0, schedule, **kw)
    piped, _ = _run_paged(cfg, params, 2, schedule, **kw)
    assert sync == piped


def test_paged_pipelined_matches_sync_sampled(small_model):
    """Seeded sampling: the per-slot PRNG key schedule is one split per
    dispatched step regardless of when tokens are consumed — pipelined and
    sync streams must be identical, mixed greedy/sampled batch included."""
    cfg, params = small_model
    ps = prompts(3, rng=11)

    def schedule(eng):
        ids = [
            eng.submit(ps[0], max_new_tokens=10, temperature=0.8, seed=5),
            eng.submit(ps[1], max_new_tokens=10),  # greedy slot in the mix
            eng.submit(ps[2], max_new_tokens=6, temperature=1.2, top_k=20, seed=9),
        ]
        eng.run_until_drained()
        return [eng.result(i) for i in ids]

    kw = dict(slots=3, max_len=64, block_size=8)
    sync, _ = _run_paged(cfg, params, 0, schedule, **kw)
    piped, eng = _run_paged(cfg, params, 2, schedule, **kw)
    assert sync == piped
    assert eng._sampled_active == 0  # counter balanced after drain


def test_paged_pipelined_matches_sync_prefix_eviction(small_model):
    """Prefix cache: a pool sized so a later admission must EVICT parked
    prefix blocks while decode chunks are in flight (_alloc_blocks flushes
    the ring before evicting), with a prefix HIT pinned across the same
    window — streams must still match the synchronous engine."""
    cfg, params = small_model
    r = np.random.RandomState(13)
    prompt_a = r.randint(1, 255, size=24).astype(np.int32)
    prompt_b = r.randint(1, 255, size=24).astype(np.int32)
    b_variant = prompt_b.copy()
    b_variant[-1] = 1  # shares B's two full prefix blocks, distinct tail
    fresh = r.randint(1, 255, size=24).astype(np.int32)
    # All footprints = max(bucket 32, 24 + max_new<=8) = 4 blocks. Usable
    # pool = 9: after A and B park 2 blocks each and B-variant pins B's,
    # fresh's 4-block allocation finds 3 free + A's 2 parked -> eviction.

    def schedule(eng):
        a = eng.submit(prompt_a, max_new_tokens=6)
        eng.run_until_drained()  # A's prefix blocks park in the LRU
        b = eng.submit(prompt_b, max_new_tokens=6)
        eng.run_until_drained()  # B's park too
        bv = eng.submit(b_variant, max_new_tokens=8)  # HIT: pins B's blocks
        eng.step_n(2)  # chunks in flight when fresh's allocation evicts
        f = eng.submit(fresh, max_new_tokens=6)
        assert f is not None
        eng.run_until_drained()
        return [eng.result(i) for i in (a, b, bv, f)], dict(eng.stats_prefix)

    kw = dict(slots=2, max_len=64, block_size=8, num_blocks=10,
              prefix_cache=True)
    (sync, sync_stats), _ = _run_paged(cfg, params, 0, schedule, **kw)
    (piped, piped_stats), _ = _run_paged(cfg, params, 2, schedule, **kw)
    assert sync == piped
    assert piped_stats["hit_blocks"] >= 2  # the hit path engaged
    assert piped_stats["evictions"] >= 1   # the eviction path engaged
    assert piped_stats == sync_stats


def test_paged_fallback_probe_flushes_inflight_queue(small_model):
    """The pallas→XLA fallback probe with a NON-EMPTY in-flight queue: the
    probe dispatch flushes the ring first (rollback contract — a failed
    probe must leave nothing half-committed), falls back, and the final
    streams still match a synchronous no-kernel run."""
    cfg, params = small_model
    ps = prompts(3, rng=17)

    def reference():
        eng = PagedBatchEngine(cfg, params, slots=3, max_len=64, block_size=8,
                               pipeline_depth=0)
        ids = [eng.submit(p, max_new_tokens=12) for p in ps]
        eng.run_until_drained()
        return [eng.result(i) for i in ids]

    eng = PagedBatchEngine(cfg, params, slots=3, max_len=64, block_size=8,
                           pipeline_depth=2)
    ids = [eng.submit(p, max_new_tokens=12) for p in ps]
    eng.step_n(1)
    eng.step_n(1)
    assert len(eng._pipeline) == 2  # queue genuinely non-empty
    # Simulate the kernel's first real-backend contact happening mid-stream:
    # force probe mode; the pallas path cannot compile on CPU (no interpret
    # override), so the next dispatch must flush, fail, and fall back.
    eng._use_kernel = True
    eng._kernel_probed = False
    eng.stats["attention_path"] = "kernel"
    executed = eng.step_n(1)
    assert executed == 1
    assert eng.stats["attention_path"] == "xla_fallback"
    assert "kernel_error" in eng.stats
    eng.run_until_drained()
    assert [eng.result(i) for i in ids] == reference()


def test_paged_bound_never_overruns_budget_with_inflight(small_model):
    """step_n(32) back to back: in-flight steps count against the completion
    bound, so no request's token list can exceed max_new_tokens even before
    any flush."""
    cfg, params = small_model
    eng = PagedBatchEngine(cfg, params, slots=2, max_len=64, block_size=8,
                           pipeline_depth=2)
    ids = [eng.submit(p, max_new_tokens=5) for p in prompts(2, rng=23)]
    for _ in range(6):
        eng.step_n(32)
    eng._pipeline.flush()
    for i in ids:
        assert len(eng.result(i)) == 5


def test_batch_engine_pipelined_matches_sync(small_model):
    cfg, params = small_model
    ps = prompts(4, rng=29)
    budgets = (12, 3, 7, 12)

    def run(depth):
        eng = BatchEngine(cfg, params, slots=4, max_len=64, pipeline_depth=depth)
        ids = [eng.submit(p, max_new_tokens=m) for p, m in zip(ps, budgets)]
        eng.run_until_drained()
        return [eng.result(i) for i in ids]

    assert run(0) == run(2)


def test_batch_engine_pipelined_midstream_admission(small_model):
    """Slot freed by an in-flight completion is reclaimable: submit flushes
    the ring instead of returning None."""
    cfg, params = small_model
    ps = prompts(3, rng=31)

    def run(depth):
        eng = BatchEngine(cfg, params, slots=2, max_len=64, pipeline_depth=depth)
        a = eng.submit(ps[0], max_new_tokens=3)
        b = eng.submit(ps[1], max_new_tokens=15)
        third = None
        for _ in range(60):
            eng.step()
            if third is None:
                third = eng.submit(ps[2], max_new_tokens=8)  # None until a slot frees
            if eng.active_count == 0 and third is not None:
                break
        assert third is not None
        return [eng.result(i) for i in (a, b, third)]

    assert run(0) == run(2)


def test_dense_engine_generate_pipelined_matches_sync(small_model):
    """Engine.generate: bounded in-flight chunked decode must reproduce the
    synchronous per-chunk loop bit for bit (greedy and seeded sampling — the
    key schedule is per dispatch, not per consume)."""
    from lws_tpu.serving.engine import Engine, SamplingParams

    cfg, params = small_model
    prompt = jnp.asarray(prompts(1, rng=37)[0][None, :])

    for sampling in (SamplingParams(), SamplingParams(temperature=1.1)):
        outs = []
        for depth in (0, 2):
            eng = Engine(cfg, params, batch_size=1, max_len=128,
                         sampling=sampling, seed=4, pipeline_depth=depth)
            outs.append(np.asarray(eng.generate(prompt, 40).tokens))
        np.testing.assert_array_equal(outs[0], outs[1])


def test_speculative_flushes_pipeline(small_model):
    """step_speculative drafts from host token history: it must flush the
    in-flight ring first, and the spec+pipelined engine still matches the
    plain synchronous engine's greedy streams."""
    cfg, params = small_model
    ps = prompts(2, rng=41)

    def plain():
        eng = PagedBatchEngine(cfg, params, slots=2, max_len=64, block_size=8,
                               pipeline_depth=0)
        ids = [eng.submit(p, max_new_tokens=10) for p in ps]
        eng.run_until_drained()
        return [eng.result(i) for i in ids]

    eng = PagedBatchEngine(cfg, params, slots=2, max_len=64, block_size=8,
                           pipeline_depth=2)
    ids = [eng.submit(p, max_new_tokens=10) for p in ps]
    eng.step_n(1)  # put a chunk in flight before the speculative dispatch
    assert len(eng._pipeline) == 1
    eng.run_until_drained_speculative()
    assert not eng._pipeline
    assert [eng.result(i) for i in ids] == plain()


def test_inflight_metrics_surface(small_model):
    """The observability contract: the gauge tracks the ring and the
    host-blocked counter accumulates for the engine label."""
    from lws_tpu.core import metrics

    cfg, params = small_model
    eng = PagedBatchEngine(cfg, params, slots=2, max_len=64, block_size=8,
                           pipeline_depth=2)
    for p in prompts(2, rng=43):
        eng.submit(p, max_new_tokens=8)
    eng.step_n(1)
    assert metrics.REGISTRY.gauge_value(
        "serving_inflight_dispatches", {"engine": "paged"}
    ) == len(eng._pipeline) == 1
    eng.run_until_drained()
    assert metrics.REGISTRY.gauge_value(
        "serving_inflight_dispatches", {"engine": "paged"}
    ) == 0
    assert metrics.REGISTRY.counter_value(
        "serving_host_blocked_seconds", {"engine": "paged"}
    ) > 0.0
