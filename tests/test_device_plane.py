"""Device-runtime observability (ISSUE 20): the compile ledger (feeds,
storm heartbeats, journey annotation), HBM attribution (per-pool gauges,
fragmentation watermark, pressure heartbeats), the /debug/compile surfaces
on both servers, the fleet fold, and the `lws-tpu top`/`devices` views.

Every ledger test drives `CompileLedger.observe(...)` as the injectable
deterministic feed (the `StackSampler.sample_once(frames=...)` pattern) —
no dependence on when XLA actually compiles. One test arms a real
jax.monitoring listener to prove the production wire-up records genuine
CPU-backend compiles with ambient site attribution."""

import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lws_tpu.core import metrics
from lws_tpu.core.flightrecorder import FlightRecorder, Watchdog, default_rules
from lws_tpu.core.metrics import MetricsRegistry, parse_exposition
from lws_tpu.obs import device
from lws_tpu.obs import journey as journeymod
from lws_tpu.obs.device import CompileLedger, compile_site
from lws_tpu.obs.journey import JourneyVault, verdict

T0 = 1000.0
TARGETS = {"ttft_s": 1.0, "itl_s": 0.1, "queue_wait_s": 0.5}


def make_ledger(**kw):
    kw.setdefault("recorder", FlightRecorder())
    kw.setdefault("storm_n", 3)
    kw.setdefault("storm_window_s", 60.0)
    return CompileLedger(**kw)


def make_vault():
    return JourneyVault(sample_rate=0.0, slowest_k=0, rng=lambda: 1.0,
                        registry=MetricsRegistry())


class _pool_registry:
    """Save/clear/restore the process pool registry around a test — the
    kv_host_arena registers its arena_restore provider at import time and
    must survive this file."""

    def __enter__(self):
        with device._POOL_LOCK:
            self._bytes = dict(device._POOL_BYTES)
            self._providers = dict(device._POOL_PROVIDERS)
        device.clear_pools()
        return self

    def __exit__(self, *exc):
        with device._POOL_LOCK:
            device._POOL_BYTES.clear()
            device._POOL_BYTES.update(self._bytes)
            device._POOL_PROVIDERS.clear()
            device._POOL_PROVIDERS.update(self._providers)


# ---------------------------------------------------------------------------
# The ledger feed: kinds, bounds, attribution


def test_ledger_first_then_recompile_kinds_counts_and_metrics():
    led = make_ledger()
    before_first = metrics.REGISTRY.counter_value(
        "serving_compiles_total", {"engine": "paged", "kind": "first"})
    before_re = metrics.REGISTRY.counter_value(
        "serving_compiles_total", {"engine": "paged", "kind": "recompile"})
    r1 = led.observe(0.5, executable="paged.step_n", engine="paged",
                     shape="n4", now=T0, unix=1.0)
    r2 = led.observe(0.3, executable="paged.step_n", engine="paged",
                     shape="n8", now=T0 + 1, unix=2.0)
    assert r1["kind"] == "first" and r2["kind"] == "recompile"
    snap = led.snapshot()
    counts = snap["executables"]["paged.step_n"]
    assert counts["first"] == 1 and counts["recompiles"] == 1
    assert counts["seconds"] == pytest.approx(0.8)
    assert metrics.REGISTRY.counter_value(
        "serving_compiles_total", {"engine": "paged", "kind": "first"}
    ) == before_first + 1
    assert metrics.REGISTRY.counter_value(
        "serving_compiles_total", {"engine": "paged", "kind": "recompile"}
    ) == before_re + 1
    # Records carry full provenance, oldest-first, monotonically sequenced.
    recs = led.records()
    assert [r["shape"] for r in recs] == ["n4", "n8"]
    assert recs[0]["seq"] < recs[1]["seq"]
    json.dumps(snap)  # the /debug/compile body stays JSON-serializable


def test_ledger_ring_bound_and_executable_filter():
    led = make_ledger(ring=4)
    for i in range(6):
        led.observe(0.1, executable=f"exe{i % 2}", engine="paged",
                    now=T0 + i, unix=float(i))
    recs = led.records()
    assert len(recs) == 4  # bounded: oldest two fell off
    assert recs[0]["unix"] == 2.0
    only0 = led.records(executable="exe0")
    assert only0 and all(r["executable"] == "exe0" for r in only0)
    assert len(led.records(limit=2)) == 2


def test_ambient_site_attribution_nesting_and_explicit_override():
    led = make_ledger()
    with compile_site("paged.prefill", engine="paged", shape="b64",
                      request_id="r-outer"):
        with compile_site("paged.prefill_suffix", engine="paged",
                          shape="b64/s16", request_id="r-inner"):
            rec = led.observe(0.2, now=T0, unix=1.0)
        rec2 = led.observe(0.2, now=T0 + 1, unix=2.0)
        # Explicit kwargs (the injectable test feed) beat the ambient site.
        rec3 = led.observe(0.2, executable="explicit", engine="batch",
                           now=T0 + 2, unix=3.0)
    rec4 = led.observe(0.2, now=T0 + 3, unix=4.0)
    assert rec["executable"] == "paged.prefill_suffix"  # innermost wins
    assert rec["shape"] == "b64/s16" and rec["request_id"] == "r-inner"
    assert rec2["executable"] == "paged.prefill"
    assert rec3["executable"] == "explicit" and rec3["engine"] == "batch"
    assert rec4["executable"] == "unattributed"


def test_disarmed_ledger_records_nothing():
    led = make_ledger()
    led.disarm()
    assert led.observe(0.5, executable="x", now=T0, unix=1.0) is None
    assert led.records() == [] and led.armed is False


def test_armed_listener_records_real_cpu_backend_compiles():
    """The production wire-up: a real jax.monitoring duration listener
    records a genuine CPU-backend compile, attributed through the ambient
    site on the compiling thread."""
    led = make_ledger()
    if not led.arm():
        pytest.skip("jax unavailable")
    try:
        @jax.jit
        def _fresh(x):  # a new function object => a fresh backend compile
            return x * 3 + 1

        with compile_site("test.fresh", engine="test", shape="b8"):
            _fresh(jnp.arange(8))
        recs = led.records(executable="test.fresh")
        assert recs, "no compile event reached the armed ledger"
        assert recs[0]["kind"] == "first" and recs[0]["seconds"] > 0
        assert recs[0]["engine"] == "test" and recs[0]["shape"] == "b8"
    finally:
        led.disarm()  # listener stays registered but observes nothing
    n = len(led.records())

    @jax.jit
    def _after(x):
        return x - 7

    _after(jnp.arange(4))
    assert len(led.records()) == n  # disarm really disarms


def test_compile_storm_fires_once_per_episode():
    fr = FlightRecorder()
    wd = Watchdog(recorder=fr, rules=default_rules())
    led = make_ledger(recorder=fr)
    led.observe(0.4, executable="paged.prefill", engine="paged",
                now=T0, unix=1.0)  # the first compile never storms
    assert "compile_storm" not in wd.check_now(now=T0)
    for i in range(1, 4):  # three in-window recompiles = the storm edge
        led.observe(0.4, executable="paged.prefill", engine="paged",
                    now=T0 + i, unix=1.0 + i)
    firing = wd.check_now(now=T0 + 3)
    assert "compile_storm" in firing
    assert firing["compile_storm"][0]["source"] == \
        "compile_storm:paged.prefill"
    dump1 = wd.last_dump
    assert dump1 is not None and dump1["alert"]["watchdog"] == "compile_storm"
    # Steady firing state: no re-dump while the episode holds.
    assert "compile_storm" in wd.check_now(now=T0 + 4)
    assert wd.last_dump is dump1
    # The window drains (next observe prunes stale stamps) => episode ends.
    led.observe(0.4, executable="paged.prefill", engine="paged",
                now=T0 + 300, unix=400.0)
    assert "compile_storm" not in wd.check_now(now=T0 + 300)
    # A second storm is a second episode: a NEW edge, a NEW dump.
    for i in range(3):
        led.observe(0.4, executable="paged.prefill", engine="paged",
                    now=T0 + 400 + i, unix=500.0 + i)
    assert "compile_storm" in wd.check_now(now=T0 + 403)
    assert wd.last_dump is not dump1


# ---------------------------------------------------------------------------
# HBM attribution: the shared refresh helper


def test_refresh_injected_stats_pools_fragmentation_and_pressure():
    with _pool_registry():
        fr = FlightRecorder()
        wd = Watchdog(recorder=fr, rules=default_rules())
        device.set_pool_bytes("weights", 4e9)
        device.set_pool_bytes("kv", 3e9)
        device.register_pool_provider("arena_restore", lambda: 1e9)
        stats = [{"device": "tpu:0", "in_use": 9.3e9, "limit": 10e9,
                  "peak": 9.8e9}]
        assert device.refresh_device_memory(stats=stats, recorder=fr,
                                            now=T0) == 1
        g = metrics.REGISTRY.gauge_value
        assert g("serving_hbm_bytes_in_use", {"device": "tpu:0"}) == 9.3e9
        assert g("serving_hbm_bytes_limit", {"device": "tpu:0"}) == 10e9
        assert g("serving_hbm_peak_bytes", {"device": "tpu:0"}) == 9.8e9
        assert g("serving_hbm_fragmentation", {"device": "tpu:0"}) == \
            pytest.approx((9.8e9 - 9.3e9) / 9.8e9)
        assert g("serving_hbm_pool_bytes", {"pool": "weights"}) == 4e9
        assert g("serving_hbm_pool_bytes", {"pool": "kv"}) == 3e9
        # arena_restore is HOST-resident: reported, never subtracted.
        assert g("serving_hbm_pool_bytes", {"pool": "arena_restore"}) == 1e9
        assert g("serving_hbm_pool_bytes", {"pool": "workspace"}) == \
            pytest.approx(9.3e9 - 4e9 - 3e9)
        # 93% occupancy >= the 0.92 default => one pressure episode
        # (sustain_s=0.0 is a strict bound: check an instant later).
        firing = wd.check_now(now=T0 + 1)
        assert "hbm_pressure" in firing
        assert firing["hbm_pressure"][0]["source"] == "hbm_pressure:tpu:0"
        dump1 = wd.last_dump
        device.refresh_device_memory(stats=stats, recorder=fr, now=T0 + 5)
        assert "hbm_pressure" in wd.check_now(now=T0 + 5)
        assert wd.last_dump is dump1  # steady state: no re-dump
        # Pressure relieved: the heartbeat clears the episode.
        stats[0]["in_use"] = 5e9
        device.refresh_device_memory(stats=stats, recorder=fr, now=T0 + 10)
        assert "hbm_pressure" not in wd.check_now(now=T0 + 10)


def test_refresh_swallows_broken_pool_provider():
    with _pool_registry():
        device.set_pool_bytes("weights", 2e9)
        device.register_pool_provider(
            "arena_restore", lambda: (_ for _ in ()).throw(RuntimeError("x")))
        stats = [{"device": "tpu:0", "in_use": 3e9, "limit": 10e9,
                  "peak": 3e9}]
        assert device.refresh_device_memory(stats=stats,
                                            recorder=FlightRecorder(),
                                            now=T0) == 1
        assert metrics.REGISTRY.gauge_value(
            "serving_hbm_pool_bytes", {"pool": "weights"}) == 2e9


def test_refresh_live_path_is_cpu_safe():
    # The production seams pass nothing: whatever the local backend
    # reports (CPU backends usually report no allocator stats) must
    # refresh without raising.
    assert device.refresh_device_memory(recorder=FlightRecorder()) >= 0


def test_transfer_accounting_counts_bytes_and_seconds():
    before = metrics.REGISTRY.counter_value(
        "serving_transfer_bytes_total",
        {"site": "test.site", "direction": "h2d"})
    device.record_transfer("test.site", 1024)
    with device.transfer("test.site", 2048):
        pass
    assert metrics.REGISTRY.counter_value(
        "serving_transfer_bytes_total",
        {"site": "test.site", "direction": "h2d"}) == before + 3072


# ---------------------------------------------------------------------------
# /debug/compile HTTP surfaces: validation + auth parity + fleet fold


def test_worker_debug_compile_validation_and_token_parity(monkeypatch):
    from lws_tpu.runtime.telemetry import TelemetryServer

    led = make_ledger()
    led.observe(0.5, executable="paged.step_n", engine="paged",
                now=T0, unix=1.0)
    monkeypatch.setattr(device, "LEDGER", led)
    server = TelemetryServer(port=0)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        for bad in ("?limit=abc", "?limit=-5", "?limit=1.5"):
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"{base}/debug/compile{bad}",
                                       timeout=10)
            assert err.value.code == 400, bad
        with urllib.request.urlopen(f"{base}/debug/compile?limit=8",
                                    timeout=10) as resp:
            body = json.loads(resp.read().decode())
        assert body["records"][0]["executable"] == "paged.step_n"
        assert "paged.step_n" in body["executables"]
        assert {"armed", "storm_n", "storms"} <= set(body)
    finally:
        server.stop()
    token_server = TelemetryServer(port=0, token="s3cret")
    token_server.start()
    base = f"http://127.0.0.1:{token_server.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/debug/compile", timeout=10)
        assert err.value.code == 401
        req = urllib.request.Request(
            f"{base}/debug/compile",
            headers={"Authorization": "Bearer s3cret"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
    finally:
        token_server.stop()


def test_api_server_debug_compile_and_fleet_fold(monkeypatch):
    from lws_tpu.api.pod import Container, EnvVar, Pod, PodPhase, PodSpec
    from lws_tpu.core.store import new_meta
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.server import ApiServer
    from lws_tpu.runtime.telemetry import TelemetryServer

    led = make_ledger()
    led.observe(0.5, executable="paged.step_n", engine="paged",
                now=T0, unix=1.0)
    led.observe(0.3, executable="paged.step_n", engine="paged",
                now=T0 + 1, unix=2.0)
    monkeypatch.setattr(device, "LEDGER", led)
    worker = TelemetryServer(port=0)  # serves the same process ledger
    worker.start()
    cp = ControlPlane()
    api = ApiServer(cp, port=0)
    api.start()
    base = f"http://127.0.0.1:{api.port}"
    try:
        for path in ("/debug/compile", "/debug/compile/fleet"):
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"{base}{path}?limit=zz", timeout=10)
            assert err.value.code == 400, path
        with urllib.request.urlopen(f"{base}/debug/compile", timeout=10) as r:
            own = json.loads(r.read().decode())
        assert own["executables"]["paged.step_n"]["recompiles"] == 1
        pod = cp.store.create(Pod(
            meta=new_meta("dev-w0"),
            spec=PodSpec(containers=[Container(
                name="w", command=["sleep", "1"],
                env=[EnvVar("LWS_TPU_METRICS_PORT", str(worker.port))],
            )]),
        ))
        pod.status.phase = PodPhase.RUNNING
        pod.status.ready = True
        pod.status.address = "127.0.0.1"
        cp.store.update_status(pod)
        with urllib.request.urlopen(f"{base}/debug/compile/fleet",
                                    timeout=10) as r:
            fleet = json.loads(r.read().decode())
        by_instance = {
            e["labels"]["instance"]: e["compile"]
            for e in fleet["instances"]
        }
        assert {"control-plane", "dev-w0"} <= set(by_instance)
        assert by_instance["dev-w0"]["records"]
        agg = fleet["executables"]["paged.step_n"]
        # Both legs serve the same process ledger: the fold sums them.
        assert agg["instances"] == 2
        assert agg["first"] == 2 and agg["recompiles"] == 2
    finally:
        api.stop()
        worker.stop()


# ---------------------------------------------------------------------------
# The acceptance proof: paged-engine workload, unbounded bucket schedule,
# storm -> dump embeds the ledger window -> explain blames the compile.


def test_compile_storm_to_explain_blame_end_to_end(monkeypatch):
    from lws_tpu.cli import render_explain
    from lws_tpu.models.llama import LlamaConfig, init_params
    from lws_tpu.serving.paged_engine import PagedBatchEngine

    fr = FlightRecorder()
    wd = Watchdog(recorder=fr, rules=default_rules())
    led = make_ledger(recorder=fr)
    vault = make_vault()
    monkeypatch.setattr(device, "LEDGER", led)  # the dump embeds THIS ledger
    monkeypatch.setattr(journeymod, "VAULT", vault)

    cfg = LlamaConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq_len=128, dtype=jnp.float32,
        param_dtype=jnp.float32, remat=False,
    )
    params = jax.jit(lambda: init_params(cfg, jax.random.key(0)))()
    engine = PagedBatchEngine(cfg, params, max_len=128, block_size=16,
                              slots=4, num_blocks=40)
    # An unbounded-bucket shape schedule: every prompt lands in a NEW
    # power-of-two bucket, so every prefill after the first is a shape-miss
    # recompile of the same executable — the storm signature the bucket
    # bound exists to prevent. The injected feed mirrors what the armed
    # listener would observe for this schedule, deterministically.
    lengths = (8, 24, 40, 72)  # buckets 16 / 32 / 64 / 128
    rids = []
    for i, n in enumerate(lengths):
        rid = engine.submit(np.arange(1, n + 1, dtype=np.int32), 4)
        assert rid is not None
        bucket = 16
        while bucket < n:
            bucket *= 2
        req = f"req-{bucket}"
        rids.append(req)
        led.observe(0.6, executable="paged.prefill", engine="paged",
                    shape=f"b{bucket}", request_id=req,
                    now=T0 + i, unix=1.0 + i)
    engine.run_until_drained()

    # The storm fires EXACTLY once for the episode.
    firing = wd.check_now(now=T0 + len(lengths))
    assert firing["compile_storm"][0]["source"] == \
        "compile_storm:paged.prefill"
    dump = wd.last_dump
    assert dump["alert"]["watchdog"] == "compile_storm"
    assert wd.check_now(now=T0 + len(lengths) + 1)  # still firing...
    assert wd.last_dump is dump                     # ...but dumped once

    # The dump embeds the offending executable's ledger window.
    embedded = [r for r in dump["compiles"]["records"]
                if r["executable"] == "paged.prefill"]
    assert len(embedded) == 4
    assert [r["kind"] for r in embedded] == \
        ["first", "recompile", "recompile", "recompile"]
    assert dump["compiles"]["storms"].get("paged.prefill", 0) >= 3
    json.dumps(dump)

    # The affected request's journey carries the compile annotation, the
    # verdict names recompilation as the TTFT-blaming phase, and the
    # explain frame renders the compile row.
    hot = rids[-1]
    out = vault.complete(hot, trace={"trace_id": "t-hot"}, engine="paged",
                         ok=False, phases={"ttft_s": 1.8}, targets=TARGETS)
    assert out == "breached"
    j = vault.get(hot)
    notes = j["annotations"]["compiles"]
    assert notes and notes[0]["executable"] == "paged.prefill"
    v = verdict(j)
    assert v["phase"] == "compile"
    assert "XLA compilation" in v["text"] and "buckets" in v["text"]
    frame = render_explain(j)
    assert "compile recompile: paged.prefill" in frame
    assert "VERDICT" in frame and "XLA compilation" in frame


def test_request_annotation_budget_is_bounded():
    led = make_ledger(max_request_annotations=4)
    for i in range(8):
        led.observe(0.1, executable="e", engine="paged",
                    request_id=f"r{i}", now=T0 + i, unix=float(i))
    with led._lock:
        assert len(led._per_request) == 4  # oldest rids evicted
        assert set(led._per_request) == {"r4", "r5", "r6", "r7"}


# ---------------------------------------------------------------------------
# lws-tpu top: HBM% + CMP columns; lws-tpu devices


DEVICE_EXPOSITION = """\
# TYPE serving_requests_total counter
serving_requests_total{engine="paged",instance="w0"} 42
# TYPE serving_slo_attainment gauge
serving_slo_attainment{engine="paged",instance="w0"} 0.88
# TYPE serving_compiles_total counter
serving_compiles_total{engine="paged",kind="first",instance="w0"} 2
serving_compiles_total{engine="paged",kind="recompile",instance="w0"} 4
# TYPE serving_hbm_bytes_in_use gauge
serving_hbm_bytes_in_use{device="tpu:0",instance="w0"} 9300000000.0
# TYPE serving_hbm_bytes_limit gauge
serving_hbm_bytes_limit{device="tpu:0",instance="w0"} 10000000000.0
# TYPE serving_hbm_pool_bytes gauge
serving_hbm_pool_bytes{pool="weights",instance="w0"} 4200000000.0
serving_hbm_pool_bytes{pool="kv",instance="w0"} 3000000000.0
serving_hbm_pool_bytes{pool="arena_restore",instance="w0"} 200000000.0
serving_hbm_pool_bytes{pool="workspace",instance="w0"} 300000000.0
"""


def test_top_rows_fold_hbm_and_compiles():
    from lws_tpu.cli import _top_rows, render_top

    fams = parse_exposition(DEVICE_EXPOSITION)
    rows = _top_rows(fams)
    assert rows[("w0", "paged")]["cmp_first"] == 2.0
    assert rows[("w0", "paged")]["cmp_recompile"] == 4.0
    assert rows[("w0", "-")]["hbm_in_use"] == 9.3e9
    assert rows[("w0", "-")]["hbm_limit"] == 10e9
    frame = render_top(fams)
    assert "HBM%" in frame and "CMP" in frame
    row = next(l for l in frame.splitlines() if l.startswith("w0"))
    assert "93%" in row   # HBM in_use/limit rides the instance `-` row
    assert row.rstrip().endswith("4")  # lifetime recompiles (no ring)


def test_history_rates_cmp_counts_windowed_recompiles():
    from lws_tpu.cli import history_rates
    from lws_tpu.obs.history import HistoryRing

    ring = HistoryRing(interval_s=0.0, retention_s=600.0)
    for t, n in ((0.0, 1.0), (30.0, 5.0)):
        reg = MetricsRegistry()
        reg.inc("serving_compiles_total",
                {"engine": "paged", "kind": "recompile", "instance": "w0"}, n)
        reg.inc("serving_compiles_total",
                {"engine": "paged", "kind": "first", "instance": "w0"}, 2.0)
        ring.ingest(reg.render(), now=t)
    rates = history_rates(ring, now=30.0, window_s=60.0)
    # Only the recompile series counts — first compiles are warm-up cost.
    assert rates[("w0", "paged")]["cmp"] == pytest.approx(4.0)


def test_render_devices_tables_and_pool_rows():
    from lws_tpu.cli import _pool_rows, render_devices

    pools = _pool_rows(parse_exposition(DEVICE_EXPOSITION))
    assert pools["w0"]["weights"] == 4.2e9
    body = {
        "instances": [
            {"labels": {"instance": "w0"}, "compile": {
                "records": [
                    {"unix": 2.0, "executable": "paged.prefill",
                     "kind": "recompile", "shape": "b128", "seconds": 0.61},
                ],
                "storms": {"paged.prefill": 3},
            }},
        ],
        "executables": {
            "paged.prefill": {"first": 1, "recompiles": 3, "seconds": 2.4,
                              "instances": 1},
            "paged.step_n": {"first": 1, "recompiles": 0, "seconds": 0.8,
                             "instances": 1},
        },
    }
    frame = render_devices(body, pools=pools)
    lines = frame.splitlines()
    assert lines[0].startswith("DEVICES  instances=1  executables=2")
    assert "storms=paged.prefill" in lines[0]
    assert any("w0" in l and "4200" in l for l in lines)  # pool MB cells
    # Recompile-heavy executables sort first.
    exe_rows = [l for l in lines if l.startswith("paged.")]
    assert exe_rows[0].startswith("paged.prefill")
    assert any(l.startswith("w0") and "recompile" in l and "b128" in l
               for l in lines)  # the forensic tail row


def test_cmd_devices_one_shot_against_live_server(monkeypatch, capsys):
    from lws_tpu import cli
    from lws_tpu.runtime import ControlPlane
    from lws_tpu.runtime.server import ApiServer

    led = make_ledger()
    led.observe(0.5, executable="paged.step_n", engine="paged",
                now=T0, unix=1.0)
    monkeypatch.setattr(device, "LEDGER", led)
    cp = ControlPlane()
    api = ApiServer(cp, port=0)
    api.start()
    try:
        rc = cli.main(["devices", "--server", f"127.0.0.1:{api.port}"])
        assert rc == 0
        out = capsys.readouterr().out
        assert out.startswith("DEVICES")
        assert "paged.step_n" in out
        rc = cli.main(["devices", "--server", f"127.0.0.1:{api.port}",
                       "--json"])
        assert rc == 0
        body = json.loads(capsys.readouterr().out)
        assert "paged.step_n" in body["executables"]
    finally:
        api.stop()


def test_simfleet_emits_schema_faithful_device_series():
    from lws_tpu.runtime.simfleet import SimFleet

    with SimFleet(n_instances=2, seed=7) as fleet:
        for _ in range(16):
            fleet.tick(1)
        fams = parse_exposition(fleet.instances[0].registry.render())
    compiles = {
        labels["kind"]
        for name, labels, _, _ in fams["serving_compiles_total"]["samples"]
        if name == "serving_compiles_total"
    }
    assert "first" in compiles  # the warm-up compile always lands
    pools = {
        labels["pool"]: v
        for name, labels, v, _ in fams["serving_hbm_pool_bytes"]["samples"]
        if name == "serving_hbm_pool_bytes"
    }
    assert set(pools) == {"weights", "kv", "arena_restore", "workspace"}
    g = {name: s for name, s in fams.items()}
    assert "serving_hbm_bytes_in_use" in g and "serving_hbm_bytes_limit" in g
