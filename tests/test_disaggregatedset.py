"""DisaggregatedSet end-to-end: simple path, N-dimensional lockstep rolling
update with coordinated drain, revision-aware services, role add/remove
(≈ test/e2e/disaggregatedset/e2e_test.go flows, driven in-process)."""

import pytest

from lws_tpu.api import disagg
from lws_tpu.api.disagg import (
    DisaggregatedRoleSpec,
    DisaggregatedSet,
    DisaggregatedSetSpec,
    LeaderWorkerSetTemplateSpec,
)
from lws_tpu.api.types import LeaderWorkerSetSpec, LeaderWorkerTemplate
from lws_tpu.core.store import AdmissionError, new_meta
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import make_worker_template
from lws_tpu.controllers.disagg import utils as dsutils


def role(name, replicas=2, size=2, image="img:v1"):
    return DisaggregatedRoleSpec(
        name=name,
        replicas=replicas,
        template=LeaderWorkerSetTemplateSpec(
            spec=LeaderWorkerSetSpec(
                leader_worker_template=LeaderWorkerTemplate(
                    worker_template=make_worker_template(image), size=size
                )
            )
        ),
    )


def make_ds(roles=None, name="llmd"):
    return DisaggregatedSet(
        meta=new_meta(name),
        spec=DisaggregatedSetSpec(roles=roles or [role("prefill"), role("decode")]),
    )


def child_lws(cp, ds_name="llmd"):
    return {
        l.meta.name: l
        for l in cp.store.list("LeaderWorkerSet", "default", labels={disagg.DS_NAME_LABEL_KEY: ds_name})
    }


def test_simple_create_builds_role_lws():
    cp = ControlPlane(auto_ready=True)
    ds = cp.create(make_ds())
    cp.run_until_stable()
    revision = dsutils.compute_revision(ds.spec.roles)
    children = child_lws(cp)
    assert set(children) == {f"llmd-0-{revision}-prefill", f"llmd-0-{revision}-decode"}
    for name, lws in children.items():
        assert lws.spec.replicas == 2
        assert lws.meta.labels[disagg.DS_REVISION_LABEL_KEY] == revision
    # Pods carry DS identity labels (selectable by role services).
    pods = cp.store.list("Pod", "default", labels={disagg.DS_NAME_LABEL_KEY: "llmd"})
    assert len(pods) == 8  # 2 roles x 2 replicas x size 2
    # Private services appear once all roles ready.
    svc = cp.store.try_get("Service", "default", f"llmd-0-{revision}-prefill-prv")
    assert svc is not None
    assert svc.spec.selector[disagg.DS_ROLE_LABEL_KEY] == "prefill"
    # Status aggregated.
    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    assert {r.name: r.ready_replicas for r in fetched.status.roles} == {"prefill": 2, "decode": 2}


def test_scale_role_is_not_a_new_revision():
    cp = ControlPlane(auto_ready=True)
    ds = cp.create(make_ds())
    cp.run_until_stable()
    rev1 = dsutils.compute_revision(ds.spec.roles)
    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    fetched.spec.roles[0].replicas = 4
    cp.store.update(fetched)
    cp.run_until_stable()
    children = child_lws(cp)
    assert set(children) == {f"llmd-0-{rev1}-prefill", f"llmd-0-{rev1}-decode"}
    assert children[f"llmd-0-{rev1}-prefill"].spec.replicas == 4


def test_rolling_update_lockstep_and_drain():
    cp = ControlPlane(auto_ready=True)
    ds = cp.create(make_ds())
    cp.run_until_stable()
    rev1 = dsutils.compute_revision(ds.spec.roles)

    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    for r in fetched.spec.roles:
        for c in r.template.spec.leader_worker_template.worker_template.spec.containers:
            c.image = "img:v2"
    cp.store.update(fetched)
    rev2 = dsutils.compute_revision(fetched.spec.roles)
    assert rev2 != rev1

    cp.run_until_stable()

    # Old revision fully drained + GC'd; new revision at target on both roles.
    children = child_lws(cp)
    assert set(children) == {f"llmd-0-{rev2}-prefill", f"llmd-0-{rev2}-decode"}, children.keys()
    for lws in children.values():
        assert lws.spec.replicas == 2
        assert lws.status.ready_replicas == 2
    # Old services gone, new services exist.
    assert cp.store.try_get("Service", "default", f"llmd-0-{rev1}-prefill-prv") is None
    assert cp.store.try_get("Service", "default", f"llmd-0-{rev2}-prefill-prv") is not None
    assert cp.store.try_get("Service", "default", f"llmd-0-{rev2}-decode-prv") is not None
    reasons = {e.reason for e in cp.recorder.events}
    assert {"RollingUpdateStarted", "ScalingUp", "ScalingDown", "LWSDeleted"} <= reasons
    status = cp.store.get("DisaggregatedSet", "default", "llmd")
    assert status.status.current_revision == rev2
    assert {r.name: r.updated_replicas for r in status.status.roles} == {"prefill": 2, "decode": 2}


def test_rolling_update_role_added_and_removed():
    cp = ControlPlane(auto_ready=True)
    ds = cp.create(make_ds())
    cp.run_until_stable()
    rev1 = dsutils.compute_revision(ds.spec.roles)

    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    # Rename decode -> worker (a remove + add) and bump the template.
    fetched.spec.roles = [role("prefill", image="img:v2"), role("worker", image="img:v2")]
    cp.store.update(fetched)
    rev2 = dsutils.compute_revision(fetched.spec.roles)
    cp.run_until_stable()

    children = child_lws(cp)
    assert set(children) == {f"llmd-0-{rev2}-prefill", f"llmd-0-{rev2}-worker"}, children.keys()
    for lws in children.values():
        assert lws.status.ready_replicas == 2


def test_ds_validation():
    cp = ControlPlane()
    with pytest.raises(AdmissionError):
        cp.create(make_ds(roles=[role("only")]))  # < 2 roles
    with pytest.raises(AdmissionError):
        cp.create(make_ds(roles=[role("a"), role("a")]))  # duplicate names
    with pytest.raises(AdmissionError):
        cp.create(make_ds(roles=[role("a", replicas=0), role("b", replicas=2)]))  # mixed zero
    bad = make_ds()
    bad.spec.roles[0].template.spec.rollout_strategy.rolling_update_configuration = (
        __import__("lws_tpu.api.types", fromlist=["RollingUpdateConfiguration"]).RollingUpdateConfiguration(partition=1)
    )
    with pytest.raises(AdmissionError):
        cp.create(bad)


def test_ds_delete_cascades():
    cp = ControlPlane(auto_ready=True)
    cp.create(make_ds())
    cp.run_until_stable()
    cp.store.delete("DisaggregatedSet", "default", "llmd")
    cp.run_until_stable()
    assert cp.store.list("LeaderWorkerSet") == []
    assert cp.store.list("Pod") == []
    assert cp.store.list("Service") == []


def test_ds_name_length_bounded_at_admission():
    cp = ControlPlane()
    with pytest.raises(AdmissionError):
        cp.create(make_ds(name="a" * 50))  # derived service name would exceed 63


def test_per_role_percentage_budgets_drive_step_size():
    """Per-role maxSurge as a percentage (ref executor.go:235-260): 50% of 4
    replicas -> surge batches of 2, so the rollout takes fewer steps."""
    from lws_tpu.api.types import RollingUpdateConfiguration, RolloutStrategy

    cp = ControlPlane(auto_ready=True)
    roles = [role("prefill", replicas=4), role("decode", replicas=4)]
    for r in roles:
        r.template.spec.rollout_strategy = RolloutStrategy(
            rolling_update_configuration=RollingUpdateConfiguration(max_surge="50%")
        )
    ds = cp.create(make_ds(roles=roles))
    cp.run_until_stable()
    rev1 = dsutils.compute_revision(ds.spec.roles)

    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    for r in fetched.spec.roles:
        for c in r.template.spec.leader_worker_template.worker_template.spec.containers:
            c.image = "img:v2"
    cp.store.update(fetched)
    rev2 = dsutils.compute_revision(fetched.spec.roles)
    cp.run_until_stable()
    children = child_lws(cp)
    assert set(children) == {f"llmd-0-{rev2}-prefill", f"llmd-0-{rev2}-decode"}
    assert all(l.spec.replicas == 4 and l.status.ready_replicas == 4 for l in children.values())
    # Surge of 2 per step: scale-up events should show jumps of 2.
    ups = [e.message for e in cp.recorder.events if e.reason == "ScalingUp" and "prefill" in e.message]
    assert any("from 0 to 2" in m for m in ups), ups


def test_slices_fan_out_and_roll_independently():
    """KEP-846: slices replicate the whole role topology; each slice is its
    own rollout domain with slice-scoped services."""
    cp = ControlPlane(auto_ready=True)
    ds = make_ds()
    ds.spec.slices = 3
    ds = cp.create(ds)
    cp.run_until_stable()
    rev1 = dsutils.compute_revision(ds.spec.roles)

    children = child_lws(cp)
    assert set(children) == {
        f"llmd-{s}-{rev1}-{r}" for s in range(3) for r in ("prefill", "decode")
    }
    # Per-slice services, slice-scoped selectors (KV pairing stays in-slice).
    for s in range(3):
        svc = cp.store.get("Service", "default", f"llmd-{s}-{rev1}-prefill-prv")
        assert svc.spec.selector[disagg.DS_SLICE_LABEL_KEY] == str(s)
    # Pods carry the slice identity through their templates.
    pods = cp.store.list("Pod", "default", labels={disagg.DS_SLICE_LABEL_KEY: "2"})
    assert len(pods) == 8  # 2 roles x 2 replicas x size 2
    # Status aggregates across slices.
    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    assert {r.name: r.ready_replicas for r in fetched.status.roles} == {"prefill": 6, "decode": 6}

    # Template change: every slice converges to the new revision.
    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    for r in fetched.spec.roles:
        for c in r.template.spec.leader_worker_template.worker_template.spec.containers:
            c.image = "img:v2"
    cp.store.update(fetched)
    rev2 = dsutils.compute_revision(fetched.spec.roles)
    cp.run_until_stable()
    children = child_lws(cp)
    assert set(children) == {
        f"llmd-{s}-{rev2}-{r}" for s in range(3) for r in ("prefill", "decode")
    }


def test_slice_scale_down_is_plain_deletion():
    cp = ControlPlane(auto_ready=True)
    ds = make_ds()
    ds.spec.slices = 3
    ds = cp.create(ds)
    cp.run_until_stable()
    rev = dsutils.compute_revision(ds.spec.roles)

    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    fetched.spec.slices = 1
    cp.store.update(fetched)
    cp.run_until_stable()
    children = child_lws(cp)
    assert set(children) == {f"llmd-0-{rev}-prefill", f"llmd-0-{rev}-decode"}
    # Lower slice untouched (same uids), higher slices' services gone.
    assert cp.store.try_get("Service", "default", f"llmd-2-{rev}-prefill-prv") is None
    assert cp.store.try_get("Service", "default", f"llmd-0-{rev}-prefill-prv") is not None
    assert len(cp.store.list("Pod", "default", labels={disagg.DS_NAME_LABEL_KEY: "llmd"})) == 8


def test_slices_change_is_not_a_rollout():
    """Changing slices is a scale operation: existing slices' LWS keep their
    uids (no recreation) and the revision is unchanged."""
    cp = ControlPlane(auto_ready=True)
    ds = cp.create(make_ds())
    cp.run_until_stable()
    rev = dsutils.compute_revision(ds.spec.roles)
    before = {n: l.meta.uid for n, l in child_lws(cp).items()}

    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    fetched.spec.slices = 2
    cp.store.update(fetched)
    cp.run_until_stable()
    after = child_lws(cp)
    assert set(after) == set(before) | {f"llmd-1-{rev}-prefill", f"llmd-1-{rev}-decode"}
    for name, uid in before.items():
        assert after[name].meta.uid == uid, f"{name} was recreated"


def test_observed_rollout_steps_match_planner_predictions():
    """Step-sequence tracking (≈ test/e2e/disaggregatedset/e2e_test.go:618):
    watch every child-LWS scale during a live rolling update and assert the
    observed (old, new) replica vectors are EXACTLY the planner's
    ComputeAllSteps prediction, in order — the executor must never take a
    step the pure-math planner didn't predict."""
    from lws_tpu.controllers.disagg.executor import RollingUpdateExecutor
    from lws_tpu.controllers.disagg.planner import ComputeAllSteps

    cp = ControlPlane(auto_ready=True)
    ds = cp.create(make_ds(roles=[role("prefill", replicas=3), role("decode", replicas=2)]))
    cp.run_until_stable()
    rev1 = dsutils.compute_revision(ds.spec.roles)

    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    for r in fetched.spec.roles:
        for c in r.template.spec.leader_worker_template.worker_template.spec.containers:
            c.image = "img:v2"
    role_names = [r.name for r in fetched.spec.roles]
    rev2 = dsutils.compute_revision(fetched.spec.roles)

    observed = []

    def snapshot(_event) -> None:
        if _event.obj.kind != "LeaderWorkerSet":
            return
        old_vec, new_vec = [], []
        for rn in role_names:
            old = cp.store.try_get("LeaderWorkerSet", "default", f"llmd-0-{rev1}-{rn}")
            new = cp.store.try_get("LeaderWorkerSet", "default", f"llmd-0-{rev2}-{rn}")
            old_vec.append(old.spec.replicas if old is not None else 0)
            new_vec.append(new.spec.replicas if new is not None else 0)
        state = (tuple(old_vec), tuple(new_vec))
        if not observed or observed[-1] != state:
            observed.append(state)

    cp.store.watch(snapshot)
    cp.store.update(fetched)
    cp.run_until_stable()

    config = RollingUpdateExecutor._extract_config(fetched, role_names)
    predicted = [
        (tuple(s.past), tuple(s.new))
        for s in ComputeAllSteps([3, 2], [3, 2], config)
    ]
    # The executor may pass through each predicted state over several
    # reconciles (dedup'd above) but must visit exactly the predicted states
    # in the predicted order. The 0-replica new-revision creation is the
    # planner's initial state, so sequences align from the start.
    predicted_set = set(predicted)
    relevant = [s for s in observed if s in predicted_set]
    assert relevant == predicted, f"observed={observed}\npredicted={predicted}"
    # Scale steps span several store writes (one per role LWS), so watchers
    # can also see half-applied vectors — but every one of those must lie
    # componentwise BETWEEN two adjacent predicted steps; anything outside
    # that envelope is a step the planner never sanctioned.
    def between(obs, a, b):
        return all(
            min(a[k][i], b[k][i]) <= obs[k][i] <= max(a[k][i], b[k][i])
            for k in (0, 1)
            for i in range(len(obs[0]))
        )

    for obs in observed:
        if obs in predicted_set:
            continue
        ok = any(between(obs, predicted[i], predicted[i + 1]) for i in range(len(predicted) - 1))
        assert ok, f"executor state {obs} outside every predicted transition\npredicted={predicted}"


def test_abc_mid_rollout_drains_newest_first():
    """Mid-rollout A->B->C (ref e2e_test.go:978 'drain B before A'): B is a
    bad intermediate that never goes ready; pushing C mid-rollout must drain
    B (newest old revision) to zero while stable A still holds capacity, and
    only then drain A."""
    from lws_tpu.testing import make_all_groups_ready

    cp = ControlPlane(auto_ready=False)
    ds = cp.create(make_ds(roles=[role("prefill"), role("decode")]))
    cp.run_until_stable()
    rev_a = dsutils.compute_revision(ds.spec.roles)
    for name in child_lws(cp):
        make_all_groups_ready(cp, name, max_rounds=30)
    cp.run_until_stable()

    def total_replicas(rev):
        return sum(
            l.spec.replicas for l in child_lws(cp).values()
            if l.meta.labels[disagg.DS_REVISION_LABEL_KEY] == rev
        )

    # B: bad deploy — its pods never become ready.
    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    for r in fetched.spec.roles:
        for c in r.template.spec.leader_worker_template.worker_template.spec.containers:
            c.image = "img:broken"
    cp.store.update(fetched)
    rev_b = dsutils.compute_revision(fetched.spec.roles)
    cp.run_until_stable()
    assert total_replicas(rev_b) >= 0 and total_replicas(rev_a) > 0

    # C: the fix, pushed mid-rollout.
    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    for r in fetched.spec.roles:
        for c in r.template.spec.leader_worker_template.worker_template.spec.containers:
            c.image = "img:fixed"
    cp.store.update(fetched)
    rev_c = dsutils.compute_revision(fetched.spec.roles)

    b_zero_seen_while_a_alive = False
    for _ in range(60):
        cp.run_until_stable()
        for name, lws in child_lws(cp).items():
            if lws.meta.labels[disagg.DS_REVISION_LABEL_KEY] == rev_c:
                make_all_groups_ready(cp, name, max_rounds=30)
        cp.run_until_stable()
        a, b, c = total_replicas(rev_a), total_replicas(rev_b), total_replicas(rev_c)
        if b == 0 and a > 0:
            b_zero_seen_while_a_alive = True  # newest-first: B dies before A
        if a == 0 and b == 0 and c == 4:
            break
    assert b_zero_seen_while_a_alive, "B (newest old) must drain before A"
    children = child_lws(cp)
    assert {l.meta.labels[disagg.DS_REVISION_LABEL_KEY] for l in children.values()} == {rev_c}
    assert all(l.status.ready_replicas == l.spec.replicas for l in children.values())


@pytest.mark.parametrize("surge,expected_first_jump", [("25%", 1), ("100%", 4)])
def test_per_role_percentage_grid(surge, expected_first_jump):
    """Percentage budgets at more grid points (ref executor.go:235-260 +
    VERDICT r2 missing #4): 25% of 4 -> steps of 1; 100% of 4 -> one jump."""
    from lws_tpu.api.types import RollingUpdateConfiguration, RolloutStrategy

    cp = ControlPlane(auto_ready=True)
    roles = [role("prefill", replicas=4), role("decode", replicas=4)]
    for r in roles:
        r.template.spec.rollout_strategy = RolloutStrategy(
            rolling_update_configuration=RollingUpdateConfiguration(max_surge=surge)
        )
    ds = cp.create(make_ds(roles=roles))
    cp.run_until_stable()

    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    for r in fetched.spec.roles:
        for c in r.template.spec.leader_worker_template.worker_template.spec.containers:
            c.image = "img:v2"
    cp.store.update(fetched)
    rev2 = dsutils.compute_revision(fetched.spec.roles)
    cp.run_until_stable()

    children = child_lws(cp)
    assert set(children) == {f"llmd-0-{rev2}-prefill", f"llmd-0-{rev2}-decode"}
    assert all(l.status.ready_replicas == 4 for l in children.values())
    ups = [e.message for e in cp.recorder.events
           if e.reason == "ScalingUp" and "prefill" in e.message]
    assert any(f"from 0 to {expected_first_jump}" in m for m in ups), ups


@pytest.mark.parametrize(
    "surge,unavailable,replicas",
    [
        # The reference's own e2e shape: 50% surge + 25% unavailable of 4
        # (e2e_test.go:243-259).
        ("50%", "25%", 4),
        ("25%", "25%", 8),
        ("100%", "50%", 4),
    ],
)
def test_per_role_percentage_grid_surge_and_unavailable(surge, unavailable, replicas):
    """Percentage budgets on BOTH axes at three grid points (VERDICT r3 #8).
    Surge resolves by ceil (never 0 for a nonzero percent), so every
    intermediate child-LWS replica count stays admissible — the reference's
    e2e sweep pairs the axes the same way for the same reason (a pure
    percentage-maxUnavailable with surge 0 is rejected by both webhooks the
    moment it floors to 0, leaderworkerset_webhook.go:171-174). Every
    observed drain must be a step the pure-math planner predicted for the
    RESOLVED budgets — the percentage parsing is the layer under test. Ref
    executor.go:235-260, test/e2e/disaggregatedset/e2e_test.go:243-259."""
    from lws_tpu.api.types import RollingUpdateConfiguration, RolloutStrategy
    from lws_tpu.controllers.disagg.executor import RollingUpdateExecutor
    from lws_tpu.controllers.disagg.planner import ComputeAllSteps

    cp = ControlPlane(auto_ready=True)
    roles = [role("prefill", replicas=replicas), role("decode", replicas=replicas)]
    for r in roles:
        r.template.spec.rollout_strategy = RolloutStrategy(
            rolling_update_configuration=RollingUpdateConfiguration(
                max_unavailable=unavailable, max_surge=surge
            )
        )
    cp.create(make_ds(roles=roles))
    cp.run_until_stable()

    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    for r in fetched.spec.roles:
        for c in r.template.spec.leader_worker_template.worker_template.spec.containers:
            c.image = "img:v2"
    cp.store.update(fetched)
    rev2 = dsutils.compute_revision(fetched.spec.roles)
    cp.run_until_stable()

    # Converged on the new revision at target.
    children = child_lws(cp)
    assert set(children) == {f"llmd-0-{rev2}-prefill", f"llmd-0-{rev2}-decode"}
    assert all(l.status.ready_replicas == replicas for l in children.values())

    # The executor's drains followed the planner's predicted old-replica
    # sequence for the budgets RESOLVED from the percentages.
    role_names = [r.name for r in fetched.spec.roles]
    config = RollingUpdateExecutor._extract_config(fetched, role_names)
    init = [replicas] * len(role_names)
    predicted_old = [s.past[0] for s in ComputeAllSteps(init, init, config)]
    predicted_pairs = {
        (predicted_old[i], predicted_old[i + 1])
        for i in range(len(predicted_old) - 1)
        if predicted_old[i] != predicted_old[i + 1]
    }
    downs = [e.message for e in cp.recorder.events
             if e.reason == "ScalingDown" and "prefill" in e.message]
    assert downs, "no drain events recorded"
    import re

    for m in downs:
        frm, to = map(int, re.search(r"from (\d+) to (\d+)", m).groups())
        assert (frm, to) in predicted_pairs, (m, sorted(predicted_pairs))


def test_template_metadata_propagates_to_child_lws():
    """Role template metadata (the Kueue-style queue labels a cluster admin
    sets) must land on each child LWS — per role, and re-applied on every new
    revision's children across a rolling update (ref
    test/e2e/disaggregatedset/e2e_test.go:477-518 kueue.x-k8s.io/queue-name
    propagation)."""
    cp = ControlPlane(auto_ready=True)
    roles = [role("prefill"), role("decode")]
    roles[0].template.metadata.labels["kueue.x-k8s.io/queue-name"] = "prefill-queue"
    roles[0].template.metadata.annotations["team"] = "serving"
    roles[1].template.metadata.labels["kueue.x-k8s.io/queue-name"] = "decode-queue"
    ds = cp.create(make_ds(roles=roles))
    cp.run_until_stable()
    rev1 = dsutils.compute_revision(ds.spec.roles)

    children = child_lws(cp)
    pre = children[f"llmd-0-{rev1}-prefill"]
    dec = children[f"llmd-0-{rev1}-decode"]
    assert pre.meta.labels["kueue.x-k8s.io/queue-name"] == "prefill-queue"
    assert pre.meta.annotations["team"] == "serving"
    assert dec.meta.labels["kueue.x-k8s.io/queue-name"] == "decode-queue"
    assert "team" not in dec.meta.annotations

    # Rolling update: the NEW revision's children carry the same metadata.
    fetched = cp.store.get("DisaggregatedSet", "default", "llmd")
    for r in fetched.spec.roles:
        for c in r.template.spec.leader_worker_template.worker_template.spec.containers:
            c.image = "img:v2"
    cp.store.update(fetched)
    rev2 = dsutils.compute_revision(fetched.spec.roles)
    cp.run_until_stable()
    children = child_lws(cp)
    assert set(children) == {f"llmd-0-{rev2}-prefill", f"llmd-0-{rev2}-decode"}
    assert children[f"llmd-0-{rev2}-prefill"].meta.labels["kueue.x-k8s.io/queue-name"] == "prefill-queue"
    assert children[f"llmd-0-{rev2}-decode"].meta.labels["kueue.x-k8s.io/queue-name"] == "decode-queue"
