"""Rendezvous DNS view + metrics registry units."""

from lws_tpu.api import contract
from lws_tpu.core import DnsView
from lws_tpu.core.metrics import MetricsRegistry
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import LWSBuilder


def test_dns_resolves_group_members_before_ready():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(1).size(3).build())
    cp.run_until_stable()
    dns = DnsView(cp.store)
    # Publish-before-ready: every member resolvable while still Pending.
    for name in ("sample-0", "sample-0-1", "sample-0-2"):
        pod = dns.resolve(f"{name}.sample.default")
        assert pod is not None and not pod.status.ready
    # The exact name the injected env points at resolves too.
    leader = cp.store.get("Pod", "default", "sample-0")
    env = {e.name: e.value for e in leader.spec.containers[0].env}
    assert dns.resolve(env[contract.LWS_LEADER_ADDRESS]) is not None


def test_dns_negative_lookups():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(1).size(2).build())
    cp.run_until_stable()
    dns = DnsView(cp.store)
    assert dns.resolve("nope.sample.default") is None          # no such pod
    assert dns.resolve("sample-0.nosvc.default") is None       # no such service
    assert dns.resolve("sample-0.sample.other") is None        # wrong namespace
    assert dns.resolve("garbage") is None                      # malformed


def test_dns_endpoints_span_selector():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(2).size(2).build())
    cp.run_until_stable()
    dns = DnsView(cp.store)
    svc = cp.store.get("Service", "default", "sample")
    assert len(dns.endpoints(svc)) == 4  # all pods, ready or not


def test_metrics_render_prometheus_text():
    reg = MetricsRegistry()
    reg.inc("lws_reconcile_total", {"controller": "lws"})
    reg.inc("lws_reconcile_total", {"controller": "lws"})
    reg.observe("lws_reconcile_duration_seconds", 0.003, {"controller": "lws"})
    reg.observe("lws_reconcile_duration_seconds", 2.0, {"controller": "lws"})
    text = reg.render()
    assert 'lws_reconcile_total{controller="lws"} 2.0' in text
    assert 'lws_reconcile_duration_seconds_bucket{controller="lws",le="0.005"} 1' in text
    assert 'lws_reconcile_duration_seconds_bucket{controller="lws",le="+Inf"} 2' in text
    assert 'lws_reconcile_duration_seconds_count{controller="lws"} 2' in text
    assert reg.counter_value("lws_reconcile_total", {"controller": "lws"}) == 2.0


def test_reconcile_metrics_flow_through_control_plane():
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(2).build())
    cp.run_until_stable()
    assert cp.metrics.counter_value("lws_reconcile_total", {"controller": "lws"}) > 0
    assert cp.metrics.counter_value("lws_reconcile_total", {"controller": "groupset"}) > 0
    assert cp.metrics.counter_value("lws_reconcile_errors_total", {"controller": "lws"}) == 0
