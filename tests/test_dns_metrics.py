"""Rendezvous DNS view + metrics registry units, including a minimal
Prometheus text-exposition parser that validates the registry's output the
way a real scraper would (HELP/TYPE blocks, label syntax, histogram
invariants)."""

import re

from lws_tpu.api import contract
from lws_tpu.core import DnsView
from lws_tpu.core.metrics import MetricsRegistry, render_exposition
from lws_tpu.runtime import ControlPlane
from lws_tpu.testing import LWSBuilder

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>(?:[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\",?)*)\})?"
    r" (?P<value>[0-9.+\-eEInf]+)"
    # OpenMetrics exemplar on bucket lines (` # {trace_id="..."} 0.004`):
    # classic scrapers treat everything after # as a comment; ours validates
    # the shape so a malformed exemplar can't hide in the suffix.
    r"(?P<exemplar> # \{(?:[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\",?)*\} [0-9.+\-eEInf]+)?$"
)


def parse_exposition(text: str) -> dict:
    """Minimal Prometheus text parser: returns {family: {"type": t,
    "samples": [(name, labels_dict, value)]}}. Raises AssertionError on
    anything a real scraper would reject: samples before their TYPE line,
    duplicate TYPE for a family, malformed sample lines, or histogram
    bucket counts that are not cumulative."""
    families: dict = {}
    current = None
    for line in text.strip().split("\n"):
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, name, ftype = line.split(" ", 3)
            assert name not in families, f"duplicate TYPE for {name}"
            assert ftype in ("counter", "gauge", "histogram"), line
            families[name] = {"type": ftype, "samples": []}
            current = name
            continue
        if line == "# EOF":  # OpenMetrics terminator (negotiated responses)
            continue
        assert not line.startswith("#"), f"unknown comment line: {line}"
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        name = m.group("name")
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix) and base[: -len(suffix)] in families:
                base = base[: -len(suffix)]
        assert current is not None and base == current, (
            f"sample {name} outside its family block ({current})"
        )
        labels = dict(
            kv.split("=", 1) for kv in
            (m.group("labels") or "").split(",") if kv
        )
        labels = {k: v.strip('"') for k, v in labels.items()}
        families[base]["samples"].append((name, labels, float(m.group("value"))))
    for fam, data in families.items():
        if data["type"] != "histogram":
            continue
        # Bucket counts must be cumulative per label set, ending at +Inf.
        series: dict = {}
        for name, labels, value in data["samples"]:
            if name.endswith("_bucket"):
                key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
                series.setdefault(key, []).append((labels["le"], value))
        for key, buckets in series.items():
            values = [v for _, v in buckets]
            assert values == sorted(values), f"{fam}{key}: non-cumulative buckets"
            assert buckets[-1][0] == "+Inf", f"{fam}{key}: missing +Inf bucket"
    return families


def test_dns_resolves_group_members_before_ready():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(1).size(3).build())
    cp.run_until_stable()
    dns = DnsView(cp.store)
    # Publish-before-ready: every member resolvable while still Pending.
    for name in ("sample-0", "sample-0-1", "sample-0-2"):
        pod = dns.resolve(f"{name}.sample.default")
        assert pod is not None and not pod.status.ready
    # The exact name the injected env points at resolves too.
    leader = cp.store.get("Pod", "default", "sample-0")
    env = {e.name: e.value for e in leader.spec.containers[0].env}
    assert dns.resolve(env[contract.LWS_LEADER_ADDRESS]) is not None


def test_dns_negative_lookups():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(1).size(2).build())
    cp.run_until_stable()
    dns = DnsView(cp.store)
    assert dns.resolve("nope.sample.default") is None          # no such pod
    assert dns.resolve("sample-0.nosvc.default") is None       # no such service
    assert dns.resolve("sample-0.sample.other") is None        # wrong namespace
    assert dns.resolve("garbage") is None                      # malformed


def test_dns_endpoints_span_selector():
    cp = ControlPlane()
    cp.create(LWSBuilder().replicas(2).size(2).build())
    cp.run_until_stable()
    dns = DnsView(cp.store)
    svc = cp.store.get("Service", "default", "sample")
    assert len(dns.endpoints(svc)) == 4  # all pods, ready or not


def test_metrics_render_prometheus_text():
    reg = MetricsRegistry()
    reg.inc("lws_reconcile_total", {"controller": "lws"})
    reg.inc("lws_reconcile_total", {"controller": "lws"})
    reg.observe("lws_reconcile_duration_seconds", 0.003, {"controller": "lws"})
    reg.observe("lws_reconcile_duration_seconds", 2.0, {"controller": "lws"})
    text = reg.render()
    assert 'lws_reconcile_total{controller="lws"} 2.0' in text
    assert 'lws_reconcile_duration_seconds_bucket{controller="lws",le="0.005"} 1' in text
    assert 'lws_reconcile_duration_seconds_bucket{controller="lws",le="+Inf"} 2' in text
    assert 'lws_reconcile_duration_seconds_count{controller="lws"} 2' in text
    assert reg.counter_value("lws_reconcile_total", {"controller": "lws"}) == 2.0


def test_metrics_exposition_is_parser_valid():
    reg = MetricsRegistry()
    reg.inc("lws_reconcile_total", {"controller": "lws"})
    reg.set("lws_rollout_progress", 0.5, {"lws": "default/sample", "revision": "abc"})
    reg.observe("lws_reconcile_duration_seconds", 0.003,
                {"controller": "lws", "result": "success"})
    reg.observe("lws_reconcile_duration_seconds", 2.0,
                {"controller": "lws", "result": "success"})
    fams = parse_exposition(reg.render())
    assert fams["lws_reconcile_total"]["type"] == "counter"
    assert fams["lws_rollout_progress"]["type"] == "gauge"
    assert fams["lws_rollout_progress"]["samples"][0][2] == 0.5
    assert fams["lws_reconcile_duration_seconds"]["type"] == "histogram"
    count = [
        v for name, labels, v in fams["lws_reconcile_duration_seconds"]["samples"]
        if name.endswith("_count")
    ]
    assert count == [2.0]


def test_gauge_set_last_value_wins():
    reg = MetricsRegistry()
    reg.set("g", 1.0, {"k": "a"})
    reg.set("g", 7.0, {"k": "a"})
    assert reg.gauge_value("g", {"k": "a"}) == 7.0
    assert reg.gauge_value("g", {"k": "missing"}) is None


def test_label_cardinality_cap_drops_and_counts():
    reg = MetricsRegistry(max_label_sets=3)
    for i in range(10):
        reg.inc("per_replica_total", {"replica": str(i)})
    # First 3 label sets admitted, 7 dropped and counted.
    assert reg.counter_value("per_replica_total", {"replica": "0"}) == 1.0
    assert reg.counter_value("per_replica_total", {"replica": "5"}) == 0.0
    assert reg.counter_value(
        "lws_metric_label_sets_dropped_total", {"metric": "per_replica_total"}
    ) == 7.0
    # Known label sets keep accumulating after the cap trips.
    reg.inc("per_replica_total", {"replica": "0"})
    assert reg.counter_value("per_replica_total", {"replica": "0"}) == 2.0
    # The drop counter renders, so the loss is scrape-visible.
    assert "lws_metric_label_sets_dropped_total" in reg.render()


def test_clear_gauge_retires_superseded_series():
    reg = MetricsRegistry(max_label_sets=2)
    reg.set("rollout", 0.5, {"lws": "a", "revision": "r1"})
    reg.clear_gauge("rollout", {"lws": "a"})
    reg.set("rollout", 0.1, {"lws": "a", "revision": "r2"})
    assert reg.gauge_value("rollout", {"lws": "a", "revision": "r1"}) is None
    assert reg.gauge_value("rollout", {"lws": "a", "revision": "r2"}) == 0.1
    # Retiring frees cardinality slots: revision churn can't exhaust the cap.
    for i in range(10):
        reg.clear_gauge("rollout", {"lws": "a"})
        reg.set("rollout", i / 10, {"lws": "a", "revision": f"r{i}"})
    assert reg.gauge_value("rollout", {"lws": "a", "revision": "r9"}) == 0.9
    assert reg.counter_value(
        "lws_metric_label_sets_dropped_total", {"metric": "rollout"}
    ) == 0.0


def test_render_exposition_merges_registries():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.inc("shared_total", {"src": "a"})
    b.inc("shared_total", {"src": "b"})
    b.set("only_b", 1.0)
    fams = parse_exposition(render_exposition(a, b))
    # One family block with BOTH registries' samples (duplicate TYPE lines
    # would be scraper-invalid; parse_exposition enforces that).
    assert len(fams["shared_total"]["samples"]) == 2
    assert "only_b" in fams


def test_reconcile_metrics_flow_through_control_plane():
    cp = ControlPlane(auto_ready=True)
    cp.create(LWSBuilder().replicas(1).size(2).build())
    cp.run_until_stable()
    assert cp.metrics.counter_value("lws_reconcile_total", {"controller": "lws"}) > 0
    assert cp.metrics.counter_value("lws_reconcile_total", {"controller": "groupset"}) > 0
    assert cp.metrics.counter_value("lws_reconcile_errors_total", {"controller": "lws"}) == 0
    # The duration histogram is result-labeled and the whole exposition
    # stays parser-valid end to end.
    fams = parse_exposition(cp.metrics.render())
    samples = fams["lws_reconcile_duration_seconds"]["samples"]
    assert any(labels.get("result") == "success" for _, labels, _ in samples)
