"""Planner invariants over full simulated rollouts
(≈ pkg/controllers/disaggregatedset/planner_test.go, 1068 LoC of cases —
here as property checks over a config matrix plus pinned step sequences)."""

import pytest

from lws_tpu.controllers.disagg.planner import (
    ComputeAllSteps,
    ComputeNextStep,
    RollingUpdateConfig,
    UpdateStep,
    default_rolling_update_config,
)


def check_invariants(steps, initial_old, target, config):
    assert steps[0].past == initial_old
    assert steps[0].new == [0] * len(initial_old)
    final = steps[-1]
    assert final.past == [0] * len(initial_old), f"old not drained: {final}"
    assert final.new == target, f"new not at target: {final}"
    # Decoupling holds on non-growing rollouts; when target > initialOld the
    # force-drain fallback (ref planner.go:296-318) legitimately couples an
    # old-drain with the blocked new-scale in one step.
    enforce_decoupling = all(target[i] <= initial_old[i] for i in range(len(target)))
    for prev, cur in zip(steps, steps[1:]):
        old_changed = cur.past != prev.past
        new_changed = cur.new != prev.new
        if enforce_decoupling:
            assert not (old_changed and new_changed), f"coupled step {prev} -> {cur}"
        assert old_changed or new_changed, f"no-op step {prev} -> {cur}"
        for i in range(len(initial_old)):
            # Monotonic: old only down, new only up.
            assert cur.past[i] <= prev.past[i]
            assert cur.new[i] >= prev.new[i]
            # Capacity constraint: never exceed the larger of start/target
            # plus the surge budget.
            if target[i] > 0:
                cap = max(initial_old[i], target[i]) + config[i].max_surge
                assert cur.past[i] + cur.new[i] <= cap, f"surge violated at role {i}: {cur}"
            # Availability floor (only binding when not scaling from/to zero).
            if initial_old[i] >= target[i] > 0:
                assert cur.past[i] + cur.new[i] >= target[i] - config[i].max_unavailable, (
                    f"availability violated at role {i}: {cur}"
                )
        # Orphan prevention: no role at 0 while a sibling (that had replicas)
        # still serves old.
        served = [cur.past[i] for i in range(len(initial_old)) if initial_old[i] > 0]
        if served and any(v == 0 for v in served):
            # allowed only when new covers availability for all roles
            for i in range(len(initial_old)):
                if initial_old[i] >= target[i]:
                    assert cur.new[i] >= target[i] - config[i].max_unavailable or all(
                        v == 0 for v in served
                    ), f"orphan at step {cur}"


MATRIX = [
    ([4, 4], [4, 4], None),
    ([3, 6], [3, 6], None),
    ([4, 4], [8, 8], None),
    ([8, 8], [4, 4], None),
    ([5, 3], [2, 7], None),
    ([1, 1], [1, 1], None),
    ([10, 2], [2, 10], None),
    ([4, 4, 4], [4, 4, 4], None),
    ([2, 3, 4], [4, 3, 2], None),
    # custom budgets
    ([6, 6], [6, 6], [RollingUpdateConfig(2, 0), RollingUpdateConfig(2, 0)]),
    ([6, 6], [6, 6], [RollingUpdateConfig(0, 2), RollingUpdateConfig(0, 2)]),
    ([4, 8], [4, 8], [RollingUpdateConfig(1, 0), RollingUpdateConfig(2, 1)]),
]


@pytest.mark.parametrize("initial_old,target,config", MATRIX)
def test_full_rollout_invariants(initial_old, target, config):
    if config is None:
        config = default_rolling_update_config(len(initial_old))
    steps = ComputeAllSteps(initial_old, target, config)
    check_invariants(steps, initial_old, target, config)


def test_pinned_two_role_sequence():
    """Pinned sequence for the default config (surge 1), 2x2 -> 2x2."""
    steps = ComputeAllSteps([2, 2], [2, 2], default_rolling_update_config(2))
    as_tuples = [(s.past, s.new) for s in steps]
    assert as_tuples[0] == ([2, 2], [0, 0])
    assert as_tuples[-1] == ([0, 0], [2, 2])
    # Scale-up precedes any drain of the same magnitude step.
    assert as_tuples[1] == ([2, 2], [1, 1])


def test_complete_returns_none():
    assert ComputeNextStep([2, 2], [0, 0], [2, 2], [2, 2], default_rolling_update_config(2)) is None


def test_abnormal_state_corrected():
    # currentOld exceeds initialOld (someone scaled old up mid-rollout).
    step = ComputeNextStep([2, 2], [5, 2], [1, 1], [2, 2], default_rolling_update_config(2))
    assert step == UpdateStep(past=[2, 2], new=[1, 1])


def test_new_at_target_drains_everything():
    step = ComputeNextStep([2, 2], [1, 1], [2, 2], [2, 2], default_rolling_update_config(2))
    assert step.past == [0, 0]
    assert step.new == [2, 2]


def test_role_removed_drains_to_zero():
    # Role 1 exists only in old (removed from spec): target 0.
    config = default_rolling_update_config(2)
    steps = ComputeAllSteps([3, 3], [3, 0], config)
    final = steps[-1]
    assert final.past == [0, 0]
    assert final.new[0] == 3
    assert final.new[1] == 0


def test_role_added_scales_from_zero():
    config = default_rolling_update_config(2)
    steps = ComputeAllSteps([3, 0], [3, 3], config)
    final = steps[-1]
    assert final.new == [3, 3]
    assert final.past == [0, 0]


def test_stateless_resume_mid_rollout():
    """The planner must derive the step from observed replicas: replaying from
    any intermediate state reaches the same terminal state."""
    config = default_rolling_update_config(2)
    steps = ComputeAllSteps([4, 4], [4, 4], config)
    mid = steps[len(steps) // 2]
    current_old, current_new = list(mid.past), list(mid.new)
    for _ in range(50):
        nxt = ComputeNextStep([4, 4], current_old, current_new, [4, 4], config)
        if nxt is None:
            break
        current_old, current_new = nxt.past, nxt.new
    assert current_old == [0, 0]
    assert current_new == [4, 4]
